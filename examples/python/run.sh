#!/usr/bin/env bash
# End-to-end walkthrough of the Python language leg (BASELINE config 5):
# Python sources -> path contexts (ast walker) -> trained model -> exported
# code vectors -> predictions. Run from this directory. CPU-friendly (~2 min).
set -euo pipefail
cd "$(dirname "$0")"
REPO_ROOT="$(cd ../.. && pwd)"
export PYTHONPATH="$REPO_ROOT${PYTHONPATH:+:$PYTHONPATH}"

# 1. Extract path contexts. dataset/methods.txt lists "<py-file>\t<name>"
#    rows ("*" = every function); .py rows route through the pure-Python
#    ast extractor (code2vec_tpu/pyextract.py), which applies the same
#    anonymization/path conventions as the native Java extractor and can
#    merge both languages into one vocab space (mixed methods.txt).
python -m code2vec_tpu.extractor dataset/ .

# 2. Train method-name prediction on the extracted corpus. Each function
#    name is implemented twice (string_ops/number_ops mirror
#    text_utils/math_utils), so the held-out split shares labels with
#    training and the final test F1 is meaningfully nonzero.
python "$REPO_ROOT/main.py" \
  --corpus_path dataset/corpus.txt \
  --path_idx_path dataset/path_idxs.txt \
  --terminal_idx_path dataset/terminal_idxs.txt \
  --batch_size 4 --encode_size 64 --max_epoch 8 --lr 0.01 \
  --model_path output --vectors_path output/code.vec --no_cuda

# 3. Inspect the exported vectors (one "label\tfloats" row per method).
head -3 output/code.vec
echo "---"

# 4. Predict method names for a Python source file from the trained
#    checkpoint: top-k labels with probabilities and the
#    highest-attention path-contexts.
python -m code2vec_tpu.predict src/util/math_utils.py \
  --model_path output \
  --terminal_idx_path dataset/terminal_idxs.txt \
  --path_idx_path dataset/path_idxs.txt \
  --top_k 3 --show_attention 1
echo "---"
echo "artifacts: dataset/{corpus,terminal_idxs,path_idxs,params}.txt, output/code.vec"
echo "visualize: python $REPO_ROOT/visualize_code_vec.py --code_vec_path output/code.vec"
