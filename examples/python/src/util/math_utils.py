"""Numeric helpers (mirrored by number_ops.py — see text_utils.py)."""


def find_max(values):
    best = values[0]
    for v in values[1:]:
        if v > best:
            best = v
    return best


def sum_of_squares(values):
    total = 0
    for v in values:
        total += v * v
    return total


def is_prime(number):
    if number < 2:
        return False
    factor = 2
    while factor * factor <= number:
        if number % factor == 0:
            return False
        factor += 1
    return True


def clamp_value(value, low, high):
    if value < low:
        return low
    if value > high:
        return high
    return value
