"""Second implementations of the text_utils.py method names — different
bodies, same labels, so test-split methods have in-vocabulary names."""


def count_words(sentence):
    pieces = [p for p in sentence.split() if len(p) > 0]
    return len(pieces)


def reverse_text(value):
    chars = list(value)
    lo, hi = 0, len(chars) - 1
    while lo < hi:
        chars[lo], chars[hi] = chars[hi], chars[lo]
        lo += 1
        hi -= 1
    return "".join(chars)


def is_palindrome(value):
    kept = [c.lower() for c in value if c.isalnum()]
    return kept == kept[::-1]


def capitalize_words(sentence):
    out = []
    for token in sentence.split(" "):
        out.append(token.capitalize() if token else token)
    return " ".join(out)
