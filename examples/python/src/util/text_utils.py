"""Text helpers (mirrored by string_ops.py so each method name appears
twice in the corpus — the held-out split then shares labels with training)."""


def count_words(text):
    total = 0
    for chunk in text.split():
        if chunk:
            total += 1
    return total


def reverse_text(text):
    result = ""
    for ch in text:
        result = ch + result
    return result


def is_palindrome(text):
    cleaned = ""
    for ch in text:
        if ch.isalnum():
            cleaned += ch.lower()
    left, right = 0, len(cleaned) - 1
    while left < right:
        if cleaned[left] != cleaned[right]:
            return False
        left += 1
        right -= 1
    return True


def capitalize_words(text):
    parts = []
    for word in text.split(" "):
        if word:
            parts.append(word[0].upper() + word[1:])
        else:
            parts.append(word)
    return " ".join(parts)
