"""Second implementations of the math_utils.py method names."""


def find_max(items):
    result = None
    for item in items:
        if result is None or item > result:
            result = item
    return result


def sum_of_squares(items):
    return sum(item ** 2 for item in items)


def is_prime(candidate):
    if candidate < 2:
        return False
    for divisor in range(2, int(candidate ** 0.5) + 1):
        if candidate % divisor == 0:
            return False
    return True


def clamp_value(amount, minimum, maximum):
    return max(minimum, min(amount, maximum))
