package util;

import java.util.ArrayList;
import java.util.List;

public class TextUtils {

    public static String capitalize(String input) {
        if (input == null || input.isEmpty()) {
            return input;
        }
        char first = Character.toUpperCase(input.charAt(0));
        return first + input.substring(1);
    }

    public static List<String> splitLines(String text) {
        List<String> lines = new ArrayList<>();
        int start = 0;
        for (int i = 0; i < text.length(); i++) {
            if (text.charAt(i) == '\n') {
                lines.add(text.substring(start, i));
                start = i + 1;
            }
        }
        if (start < text.length()) {
            lines.add(text.substring(start));
        }
        return lines;
    }

    public static int countOccurrences(String haystack, char needle) {
        int count = 0;
        for (int i = 0; i < haystack.length(); i++) {
            if (haystack.charAt(i) == needle) {
                count++;
            }
        }
        return count;
    }

    public static String joinWith(List<String> parts, String separator) {
        StringBuilder builder = new StringBuilder();
        for (int i = 0; i < parts.size(); i++) {
            if (i > 0) {
                builder.append(separator);
            }
            builder.append(parts.get(i));
        }
        return builder.toString();
    }

    public static boolean isBlank(String value) {
        if (value == null) {
            return true;
        }
        for (int i = 0; i < value.length(); i++) {
            if (!Character.isWhitespace(value.charAt(i))) {
                return false;
            }
        }
        return true;
    }

    public static String reverse(String input) {
        StringBuilder builder = new StringBuilder(input.length());
        for (int i = input.length() - 1; i >= 0; i--) {
            builder.append(input.charAt(i));
        }
        return builder.toString();
    }
}
