package util;

public class NumberOps {

    public static double clamp(double value, double floor, double ceiling) {
        return Math.min(Math.max(value, floor), ceiling);
    }

    public static long factorial(long n) {
        if (n <= 1) {
            return 1;
        }
        return n * factorial(n - 1);
    }

    public static long gcd(long first, long second) {
        if (second == 0) {
            return first;
        }
        return gcd(second, first % second);
    }

    public static boolean isPrime(long number) {
        if (number < 2) {
            return false;
        }
        if (number % 2 == 0) {
            return number == 2;
        }
        long divisor = 3;
        while (divisor * divisor <= number) {
            if (number % divisor == 0) {
                return false;
            }
            divisor += 2;
        }
        return true;
    }

    public static double mean(double[] samples) {
        double total = 0.0;
        for (double sample : samples) {
            total += sample;
        }
        return total / samples.length;
    }

    public static int maxIndex(double[] values) {
        int best = 0;
        for (int i = 1; i < values.length; i++) {
            if (values[i] > values[best]) {
                best = i;
            }
        }
        return best;
    }
}
