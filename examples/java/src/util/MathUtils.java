package util;

public class MathUtils {

    public static int clamp(int value, int low, int high) {
        if (value < low) {
            return low;
        }
        if (value > high) {
            return high;
        }
        return value;
    }

    public static long factorial(int n) {
        long result = 1;
        for (int i = 2; i <= n; i++) {
            result *= i;
        }
        return result;
    }

    public static int gcd(int a, int b) {
        while (b != 0) {
            int remainder = a % b;
            a = b;
            b = remainder;
        }
        return a;
    }

    public static boolean isPrime(int candidate) {
        if (candidate < 2) {
            return false;
        }
        for (int divisor = 2; (long) divisor * divisor <= candidate; divisor++) {
            if (candidate % divisor == 0) {
                return false;
            }
        }
        return true;
    }

    public static double mean(double[] values) {
        double total = 0.0;
        for (double value : values) {
            total += value;
        }
        return total / values.length;
    }

    public static int maxIndex(int[] values) {
        int best = 0;
        for (int i = 1; i < values.length; i++) {
            if (values[i] > values[best]) {
                best = i;
            }
        }
        return best;
    }
}
