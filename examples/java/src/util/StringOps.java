package util;

public class StringOps {

    public static String capitalize(String word) {
        char[] chars = word.toCharArray();
        if (chars.length > 0) {
            chars[0] = Character.toUpperCase(chars[0]);
        }
        return new String(chars);
    }

    public static String[] splitLines(String document) {
        java.util.List<String> lines = new java.util.ArrayList<String>();
        int start = 0;
        for (int i = 0; i < document.length(); i++) {
            if (document.charAt(i) == '\n') {
                lines.add(document.substring(start, i));
                start = i + 1;
            }
        }
        lines.add(document.substring(start));
        return lines.toArray(new String[0]);
    }

    public static int countOccurrences(String haystack, String needle) {
        int total = 0;
        int from = haystack.indexOf(needle);
        while (from >= 0) {
            total++;
            from = haystack.indexOf(needle, from + needle.length());
        }
        return total;
    }

    public static String joinWith(String[] parts, String glue) {
        StringBuilder out = new StringBuilder();
        for (int i = 0; i < parts.length; i++) {
            if (i > 0) {
                out.append(glue);
            }
            out.append(parts[i]);
        }
        return out.toString();
    }

    public static boolean isBlank(String text) {
        if (text == null) {
            return true;
        }
        for (int i = 0; i < text.length(); i++) {
            if (!Character.isWhitespace(text.charAt(i))) {
                return false;
            }
        }
        return true;
    }

    public static String reverse(String input) {
        StringBuilder builder = new StringBuilder(input.length());
        for (int i = input.length() - 1; i >= 0; i--) {
            builder.append(input.charAt(i));
        }
        return builder.toString();
    }
}
