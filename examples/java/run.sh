#!/usr/bin/env bash
# End-to-end walkthrough: Java sources -> path contexts -> trained model ->
# exported code vectors. Run from this directory. CPU-friendly (~2 min).
set -euo pipefail
cd "$(dirname "$0")"
REPO_ROOT="$(cd ../.. && pwd)"
export PYTHONPATH="$REPO_ROOT${PYTHONPATH:+:$PYTHONPATH}"

# 1. Extract path contexts (builds the C++ extractor on first use).
#    dataset/methods.txt lists "<java-file>\t<method-name>" rows; "*" = all.
python -m code2vec_tpu.extractor dataset/ . --method-declarations method_declarations.txt

# 2. Train method-name prediction on the extracted corpus. The corpus is
#    tiny but each method name is implemented twice (StringOps/NumberOps
#    mirror TextUtils/MathUtils), so the held-out split shares labels with
#    training and the final test F1 is meaningfully nonzero (~0.5+).
python "$REPO_ROOT/main.py" \
  --corpus_path dataset/corpus.txt \
  --path_idx_path dataset/path_idxs.txt \
  --terminal_idx_path dataset/terminal_idxs.txt \
  --batch_size 4 --encode_size 64 --max_epoch 8 --lr 0.01 \
  --model_path output --vectors_path output/code.vec --no_cuda

# 3. Inspect the exported vectors (one "label\tfloats" row per method).
head -3 output/code.vec
echo "---"

# 4. Predict method names for source code from the trained checkpoint
#    (the inference surface the reference lacks): top-k labels with
#    probabilities and the highest-attention path-contexts.
python -m code2vec_tpu.predict src/util/MathUtils.java \
  --model_path output \
  --terminal_idx_path dataset/terminal_idxs.txt \
  --path_idx_path dataset/path_idxs.txt \
  --top_k 3 --show_attention 1
echo "---"
echo "artifacts: dataset/{corpus,terminal_idxs,path_idxs,params}.txt, output/code.vec"
echo "visualize: python $REPO_ROOT/visualize_code_vec.py --code_vec_path output/code.vec"
