"""Wheel build hook: ship the native extractor's C++ sources as package data.

The extractor (extractor/src, dependency-free C++17) lives outside the
package tree in a checkout, so plain [tool.setuptools] package-data can't
reach it. This build_py override copies CMakeLists.txt + src/ into
code2vec_tpu/_native inside the wheel; code2vec_tpu.extractor builds it on
first use into the user cache dir (see extractor._locate_sources).
"""

import os
import shutil

from setuptools import setup
from setuptools.command.build_py import build_py


class build_py_with_native_sources(build_py):
    def run(self):
        super().run()
        root = os.path.dirname(os.path.abspath(__file__))
        src = os.path.join(root, "extractor")
        dest = os.path.join(self.build_lib, "code2vec_tpu", "_native")
        os.makedirs(os.path.join(dest, "src"), exist_ok=True)
        shutil.copy2(os.path.join(src, "CMakeLists.txt"), dest)
        for name in os.listdir(os.path.join(src, "src")):
            if name.endswith((".cc", ".h")):
                shutil.copy2(
                    os.path.join(src, "src", name),
                    os.path.join(dest, "src", name),
                )


setup(cmdclass={"build_py": build_py_with_native_sources})
