"""Drop-in entry-point shim: ``python main.py <flags>`` works exactly like
the reference repo's invocation (reference: main.py:494-502); the real
driver lives in :mod:`code2vec_tpu.cli`.
"""

from code2vec_tpu.cli import main

if __name__ == "__main__":
    main()
