"""code2vec_tpu — a TPU-native (JAX/XLA/Pallas) code2vec framework.

A from-scratch reimplementation of the capabilities of sonoisa/code2vec
(reference at /root/reference), designed TPU-first:

- Flax model compiled under XLA (``code2vec_tpu.models``)
- jit/pjit train step over a ``jax.sharding.Mesh`` (``code2vec_tpu.parallel``)
- vectorized host-side input pipeline (``code2vec_tpu.data``)
- exact artifact-format compatibility with the reference's text interchange
  files (``code2vec_tpu.formats``) so existing corpora keep working
- a native C++ path-context extractor (``extractor/``) replacing the
  reference's Scala/JVM notebook pipeline

Reference layer map: SURVEY.md §1; component inventory: SURVEY.md §2.
"""

__version__ = "0.1.0"

PAD_INDEX = 0
PAD_NAME = "<PAD/>"
QUESTION_TOKEN_NAME = "@question"
# The terminal vocab injects "@question" at index 1 and shifts all file
# indices > 0 up by one (reference: model/dataset_reader.py:11-12,29-41).
QUESTION_TOKEN_INDEX = 1
