"""The AOT executable ladder: zero tracing on the serving hot path.

A jitted forward re-traces (and re-compiles, seconds of XLA) the first
time each shape arrives — precisely the latency spike an online service
cannot take mid-traffic ("Compiler-First … Portable O(1) Autoregressive
Caching for Inference", PAPERS.md: compile ahead of time, keep per-request
state O(1)). The bucket ladder (PR 4) makes that affordable here: request
shapes are a SMALL STATIC set — ``len(ladder) × len(micro-batch sizes)``
— so the engine lowers and compiles every one of them at startup via
``jax.jit(fn).lower(...).compile()`` and the hot path is a dict lookup
into finished executables.

Ladder sources, in order:

1. the ladder recorded in ``model_meta.json`` at train time
   (``predict.save_inference_meta``) — the serving host never needs the
   corpus;
2. absent that (older checkpoints), a width histogram of the live request
   stream: until ``warmup_requests`` requests have been observed every
   request runs at the top width, then the ladder is derived from the
   observed width histogram (``data/pipeline.derive_bucket_ladder_hist``
   — the same histogram->ladder rule the CSR corpus container's footer
   and tools/corpus_stats.py use) and its executables compiled once.

Schedule provenance: startup consults the PR-8 autotune cache for every
(batch, width) shape (``ops/autotune.consult_schedules`` — the
``--expect-cached``-style warmup) and keeps the per-executable records for
the run manifest.

Observability: ``serve_executable_compile`` / ``serve_forward`` counters
on the shared registry, and a ``_cache_size`` probe (the executable-table
size) so the obs :class:`RecompileDetector` can assert zero post-warmup
compiles exactly as it does for the training step functions.
"""

from __future__ import annotations

import logging
import threading

import numpy as np

from code2vec_tpu import PAD_INDEX
from code2vec_tpu.data.pipeline import (
    derive_bucket_ladder_hist,
    nearest_bucket_width,
)
from code2vec_tpu.obs.runtime import RuntimeHealth, global_health
from code2vec_tpu.obs.sync import make_rlock
from code2vec_tpu.obs.trace import current_trace_scope, get_tracer

logger = logging.getLogger(__name__)

DEFAULT_BATCH_SIZES = (1, 8)


class ServingEngine:
    """Compiled forwards for every (micro-batch, bucket width) shape.

    ``state``: a restored/initialized TrainState (its ``apply_fn`` is the
    model). ``quant_tables``: optional pre-quantized ``(terminal, path)``
    tables (quantize ONCE at load — ``ops/quant.py``). ``ladder``: bag
    widths ending at ``max_width``; None = histogram fallback. The engine
    serializes device work behind one lock: the micro-batcher is its only
    steady-state caller, but startup warmup and ad-hoc single calls must
    not interleave with it.
    """

    def __init__(
        self,
        state,
        *,
        max_width: int,
        model_dims: tuple[int, int, int] | None = None,
        ladder: tuple[int, ...] | None = None,
        batch_sizes: tuple[int, ...] = DEFAULT_BATCH_SIZES,
        quant_tables=None,
        table_dtype: str = "f32",
        autotune_cache: str | None = None,
        warmup_requests: int = 64,
        health: RuntimeHealth | None = None,
        events=None,
        version: str = "v0",
    ) -> None:
        if not batch_sizes or any(b < 1 for b in batch_sizes):
            raise ValueError(f"batch_sizes must be >= 1, got {batch_sizes!r}")
        self._state = state
        # which model version this ladder was compiled for — hot-swap
        # (serve/swap.py) builds one engine per generation and the
        # compile events/provenance must say whose executables they are
        self.version = str(version)
        self.max_width = int(max_width)
        # the training bag width (requests up to here always serve); kept
        # distinct from max_width, which longbag rungs may raise below
        self.base_width = self.max_width
        self.batch_sizes = tuple(sorted({int(b) for b in batch_sizes}))
        self.ladder: tuple[int, ...] | None = (
            tuple(int(w) for w in ladder) if ladder else None
        )
        if self.ladder and self.ladder[-1] < self.max_width:
            raise ValueError(
                f"ladder must reach max_width ({self.max_width}), got "
                f"{self.ladder}"
            )
        if self.ladder and self.ladder[-1] > self.max_width:
            # longbag rungs (PR 13): the training run fed unbounded bags
            # (--max_contexts 0) and recorded rungs above the base bag
            # width. Oversized requests route through these compiled
            # executables instead of being rejected at submit; the loud
            # reject now applies only beyond the TOP rung.
            logger.info(
                "ladder carries longbag rungs above the base bag width "
                "%d: oversized requests up to %d serve through the "
                "chunked executables", self.max_width, self.ladder[-1],
            )
            self.max_width = int(self.ladder[-1])
        self._model_dims = model_dims
        self._quant_tables = quant_tables
        self.table_dtype = table_dtype
        self._autotune_cache = autotune_cache or None
        self.warmup_requests = int(warmup_requests)
        self._health = health or global_health()
        self._events = events
        self._lock = make_rlock("engine")
        self._compiled: dict[tuple[int, int], object] = {}
        self._width_histogram: dict[int, int] = {}
        self._warmed = False  # True once the ladder's executables exist
        self.provenance: list[dict] = []
        self._jit = None
        self._costs = None  # CostAccountant, created at first compile
        self._n_labels = None  # label head width, derived lazily

        # per-engine tallies (the health counters are process-global and
        # would alias across engines); mirrored into the registry below
        self._n_post_warmup = 0
        self._compile_counter = self._health.counter("serve_executable_compile")
        self._forward_counter = self._health.counter("serve_forward")
        self._post_warmup_counter = self._health.counter(
            "serve_post_warmup_compile"
        )

    # ---- construction helpers ------------------------------------------
    @classmethod
    def from_predictor(cls, predictor, **kw) -> "ServingEngine":
        """Build from a loaded :class:`predict.Predictor` (checkpoint +
        meta): the meta's recorded ladder, quantized tables, and model dims
        flow through automatically unless overridden."""
        meta = predictor.meta
        # only a ladder the checkpoint actually recorded flows through;
        # the Predictor's geometric fallback guess is for its own offline
        # single forwards — the server instead learns its ladder from the
        # live request stream (the documented histogram fallback)
        kw.setdefault(
            "ladder", predictor.ladder if predictor.ladder_recorded else None
        )
        kw.setdefault("quant_tables", predictor._quant_tables)
        kw.setdefault("table_dtype", predictor.table_dtype)
        kw.setdefault(
            "model_dims",
            (
                int(meta["terminal_embed_size"]),
                int(meta["path_embed_size"]),
                int(meta["encode_size"]),
            ),
        )
        # the TRAINING bag (base_bag), not predictor.bag: the Predictor
        # raises its own bag to the ladder top for offline padding, but the
        # engine owns the base-vs-longbag split itself (ladder rungs above
        # max_width raise it in __init__, with base_width kept honest)
        return cls(
            predictor.state,
            max_width=getattr(predictor, "base_bag", predictor.bag),
            **kw,
        )

    # ---- forward construction ------------------------------------------
    def _forward_fn(self):
        if self._jit is None:
            import jax

            quant_tables = self._quant_tables

            def forward(state, starts, paths, ends):
                logits, code_vector, attention = state.apply_fn(
                    {"params": state.params},
                    starts, paths, ends,
                    labels=None, deterministic=True,
                    quant_tables=quant_tables,
                )
                return logits, code_vector, attention

            self._jit = jax.jit(forward)
        return self._jit

    # ---- the RecompileDetector probe -----------------------------------
    def _cache_size(self) -> int:
        """Executable-table size — grows by exactly one per compile, so the
        obs RecompileDetector can track the engine like a jitted fn."""
        return len(self._compiled)

    @property
    def post_warmup_compiles(self) -> int:
        """Compiles after :meth:`prepare` finished (or after the fallback
        ladder froze) — a correctly-warmed server holds this at zero."""
        return self._n_post_warmup

    # ---- ladder resolution ---------------------------------------------
    @property
    def active_ladder(self) -> tuple[int, ...]:
        """The ladder requests pad to RIGHT NOW: the resolved ladder, or
        just the top width while the histogram fallback is still
        observing."""
        return self.ladder if self.ladder else (self.max_width,)

    def observe_width(self, count: int) -> None:
        """Histogram fallback: record one request's real context count;
        once ``warmup_requests`` are seen, derive and compile the ladder.

        The stream is accumulated AS a width histogram and the ladder comes
        from ``derive_bucket_ladder_hist`` — the same histogram->ladder
        entry point the CSR corpus container's footer and
        tools/corpus_stats.py use (one derivation rule everywhere, and the
        engine's memory stays O(distinct widths) however long warmup runs).
        """
        if self.ladder is not None:
            return
        with self._lock:
            if self.ladder is not None:  # froze while we waited on the lock
                return
            width = min(int(count), self.max_width)
            self._width_histogram[width] = (
                self._width_histogram.get(width, 0) + 1
            )
            n_seen = sum(self._width_histogram.values())
            if n_seen < self.warmup_requests:
                return
            ladder = derive_bucket_ladder_hist(
                np.asarray(sorted(self._width_histogram), np.int64),
                np.asarray(
                    [
                        self._width_histogram[w]
                        for w in sorted(self._width_histogram)
                    ],
                    np.int64,
                ),
                self.max_width,
            )
            logger.info(
                "request-stream histogram froze the serving ladder at %s "
                "(%d samples)", list(ladder), n_seen,
            )
            self.ladder = ladder
            self._warmed = False
            self.prepare()

    # ---- startup: consult + compile ------------------------------------
    def _consult(self, shapes: list[tuple[int, int]]) -> dict[tuple[int, int], dict]:
        """Autotune-cache consultation for every executable shape; misses
        are recorded, never searched (search belongs to the offline
        autotune pass)."""
        if self._model_dims is None:
            return {}
        from code2vec_tpu.ops.autotune import (
            ShapeKey,
            consult_schedules,
            device_kind,
            get_cache,
        )

        cache = get_cache(self._autotune_cache)
        te, pe, enc = self._model_dims
        kind = device_kind()
        keys = [
            ShapeKey(
                device_kind=kind, batch=b, width=w, terminal_embed=te,
                path_embed=pe, encode=enc, table_dtype=self.table_dtype,
            )
            for b, w in shapes
        ]
        records = consult_schedules(keys, cache=cache)
        return dict(zip(shapes, records))

    def prepare(self) -> list[dict]:
        """Lower + compile the full executable ladder (idempotent): every
        (micro-batch size, bucket width) pair. Returns one provenance
        record per executable — shape, schedule, cache hit — which the
        server writes into the run manifest."""
        with self._lock:
            shapes = [
                (b, w) for w in self.active_ladder for b in self.batch_sizes
            ]
            schedules = self._consult(shapes)
            kernel_backend = self._kernel_backend_label()
            for b, w in shapes:
                if (b, w) in self._compiled:
                    continue
                record = {
                    "batch": b,
                    "width": w,
                    "version": self.version,
                    "table_dtype": self.table_dtype,
                    "kernel_backend": kernel_backend,
                    "compile_ms": self._compile(b, w),
                    "schedule": schedules.get((b, w), {}).get("schedule"),
                    "schedule_cached": schedules.get((b, w), {}).get("cached"),
                }
                record["cost"] = self._executable_cost(b, w)
                self.provenance.append(record)
                if self._events is not None:
                    self._events.emit("serve_executable", **record)
            self._warmed = True
            self._health.gauge("serve_executables").set(len(self._compiled))
            return list(self.provenance)

    def _kernel_backend_label(self) -> str:
        """Resolved default lowering-strategy label (ops/backend.py) for
        this process — what a schedule with ``backend="auto"`` lowers to.
        Provenance only: per-schedule overrides ride in the schedule dict
        itself (its ``backend`` field)."""
        from code2vec_tpu.ops.backend import resolve as resolve_backend

        return resolve_backend().label

    def _compile(self, b: int, w: int) -> float:
        """AOT-compile one (batch, width) executable; returns compile ms."""
        import time

        import jax

        fn = self._forward_fn()
        struct = jax.ShapeDtypeStruct((b, w), np.int32)
        t0 = time.perf_counter()
        with get_tracer().span(
            "serve_compile", category="serve", batch=b, width=w
        ):
            self._compiled[(b, w)] = fn.lower(
                self._state, struct, struct, struct
            ).compile()
        self._compile_counter.inc()
        if self._warmed:
            self._n_post_warmup += 1
            self._post_warmup_counter.inc()
            logger.warning(
                "post-warmup executable compile for shape (%d, %d): a "
                "request shape missed the AOT ladder — the ladder or batch "
                "sizes do not cover the traffic", b, w,
            )
        return round((time.perf_counter() - t0) * 1e3, 3)

    # ---- cost accounting ------------------------------------------------
    def _label_width(self) -> int | None:
        """Label-head width via ``jax.eval_shape`` on the jitted forward —
        abstract evaluation only, no compile, no device work."""
        if self._n_labels is None:
            try:
                import jax

                struct = jax.ShapeDtypeStruct((1, 1), np.int32)
                out = jax.eval_shape(
                    self._forward_fn(), self._state, struct, struct, struct
                )
                self._n_labels = int(out[0].shape[-1])
            except Exception:  # pragma: no cover - exotic head shapes
                self._n_labels = 0
        return self._n_labels or None

    def _executable_cost(self, b: int, w: int) -> dict:
        """Static cost record for one compiled shape (XLA ``cost_analysis``
        with analytic fallback), registered with the accountant so later
        device-time records fold into MFU."""
        from code2vec_tpu.obs import costs as obs_costs

        if self._costs is None:
            self._costs = obs_costs.CostAccountant(
                device_kind=obs_costs.detect_device_kind(),
                health=self._health,
            )
        analytic = None
        if self._model_dims is not None:
            te, pe, enc = self._model_dims
            labels = self._label_width()
            if labels:
                analytic = obs_costs.analytic_forward_cost(
                    b, w,
                    terminal_embed=te, path_embed=pe, encode=enc,
                    labels=labels, table_dtype=self.table_dtype,
                )
        cost = obs_costs.executable_cost(self._compiled.get((b, w)), analytic)
        self._costs.register((b, w), cost)
        return cost

    def record_device_time(
        self, batch: int, width: int, device_ms: float, requests: int = 1
    ) -> None:
        """Fold one fenced device span into the perf accounting (called by
        the batcher with its existing ``device_ms`` measurement — O(1),
        no new timers or syncs on the hot path)."""
        if self._costs is not None:
            self._costs.record((batch, width), device_ms, requests=requests)

    def perf_summary(self) -> dict | None:
        """The perf block (device time, achieved FLOP/s, MFU, per-exec
        breakdown) for health payloads and bench detail; None before the
        first compile."""
        return self._costs.snapshot() if self._costs is not None else None

    # ---- hot path -------------------------------------------------------
    def width_for(self, count: int) -> int:
        """Nearest bucket width for one request's real context count."""
        return nearest_bucket_width(
            min(max(int(count), 1), self.max_width), self.active_ladder
        )

    def batch_size_for(self, n_requests: int) -> int:
        """Smallest micro-batch size holding ``n_requests`` (callers split
        anything larger than the top size)."""
        for b in self.batch_sizes:
            if n_requests <= b:
                return b
        return self.batch_sizes[-1]

    def run(self, starts: np.ndarray, paths: np.ndarray, ends: np.ndarray):
        """One device call at an exact ``[B, L]`` shape. A shape outside
        the compiled table compiles on the spot — counted as a post-warmup
        compile (the thing a warmed server must never do)."""
        key = (int(starts.shape[0]), int(starts.shape[1]))
        with self._lock:
            compiled = self._compiled.get(key)
            if compiled is None:
                # a shape miss gets the same provenance/event treatment as
                # startup compiles — the event log must show every compile
                # an audit of post_warmup_compiles would ask about
                was_warmed = self._warmed
                record = {
                    "batch": key[0],
                    "width": key[1],
                    "version": self.version,
                    "table_dtype": self.table_dtype,
                    "kernel_backend": self._kernel_backend_label(),
                    "compile_ms": self._compile(*key),
                    "schedule": None,
                    "schedule_cached": None,
                    "post_warmup": was_warmed,
                }
                record["cost"] = self._executable_cost(*key)
                self.provenance.append(record)
                if self._events is not None:
                    self._events.emit("serve_executable", **record)
                self._health.gauge("serve_executables").set(len(self._compiled))
                compiled = self._compiled[key]
            self._forward_counter.inc()
            # the engine's own device-call span: tagged with the caller's
            # trace scope (the batcher publishes the group's trace_ids
            # there), so a stitched trace shows router -> worker ->
            # batcher -> THIS executable call under one trace id
            with get_tracer().span(
                "engine_run", category="serve",
                batch=key[0], width=key[1], version=self.version,
                **current_trace_scope(),
            ):
                logits, code_vector, attention = compiled(
                    self._state, starts, paths, ends
                )
        return logits, code_vector, attention

    def pad_requests(
        self, contexts: list[np.ndarray]
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray, int, int]:
        """Pack per-request ``[n_i, 3]`` id arrays into one padded batch.

        Returns ``(starts, paths, ends, batch, width)`` where width is the
        nearest bucket width for the LONGEST member and batch the smallest
        micro-batch size holding them all; spare rows are all-PAD. The
        shared padding rule means a coalesced batch and a one-at-a-time
        replay land on the same executables (and, per the PR-4 invariant,
        the same row values: PAD lanes carry exactly-zero attention)."""
        n = len(contexts)
        if n > self.batch_sizes[-1]:
            raise ValueError(
                f"{n} requests exceed the top micro-batch size "
                f"{self.batch_sizes[-1]}; the batcher must split the group"
            )
        longest = max(len(c) for c in contexts)
        if longest > self.max_width:
            raise ValueError(
                f"a request has {longest} contexts, more than the model's "
                f"max bag width {self.max_width}; subsample before packing "
                "(the batcher rejects these at submit)"
            )
        width = self.width_for(longest)
        batch = self.batch_size_for(n)
        starts = np.full((batch, width), PAD_INDEX, np.int32)
        paths = np.full((batch, width), PAD_INDEX, np.int32)
        ends = np.full((batch, width), PAD_INDEX, np.int32)
        for i, arr in enumerate(contexts):
            arr = np.asarray(arr, np.int32).reshape(-1, 3)
            m = arr.shape[0]
            starts[i, :m] = arr[:, 0]
            paths[i, :m] = arr[:, 1]
            ends[i, :m] = arr[:, 2]
        return starts, paths, ends, batch, width
