"""Transport-thin request handling: dict in, dict out.

The protocol layer owns everything between a parsed request object and a
response object — extraction, vocab mapping, batcher submission, softmax/
top-k postprocessing — and NOTHING about bytes on a wire. Both transports
are adapters over the same :class:`CodeServer`:

- **stdio-JSONL** (:func:`serve_stdio`): one JSON object per line in, one
  per line out, responses in request order. The reader thread submits
  requests as fast as they arrive while the writer resolves them in FIFO
  order — pipelined clients therefore get real micro-batch coalescing
  over a pipe, no sockets involved (what the tests and the CI smoke
  drive).
- **HTTP** (:func:`serve_http`): stdlib ``ThreadingHTTPServer``; each
  concurrent POST maps to one handler thread blocking on its future, so
  concurrency again becomes coalescing.

Request schema (one ``op`` per object; unknown fields ignored)::

    {"op": "predict",    "source": str, "language": "java"|"python",
     "method_name": "*", "top_k": 5, "include_vector": false}
    {"op": "embed",      ... same selectors ...}
    # predict/embed alternatively take a PRE-MAPPED path-context bag in
    # place of "source": extraction and vocab mapping are skipped — the
    # form an indexing pipeline resends, and the form the fleet router's
    # content-addressed result cache digests order-invariantly (a
    # permuted resend of the same bag is a cache hit)
    {"op": "embed",      "contexts": [[start, path, end], ...]}
    {"op": "embed_file", ... same selectors ...}   # one pooled vector for
                                                   # the whole source (the
                                                   # hierarchical head)
    {"op": "neighbors",  "vector": [...] | source selectors, "top_k": 5,
     "granularity": "method"|"file"}   # file = pool the source's method
                                       # vectors first (whole-file search
                                       # against an exported file.vec)
    {"op": "health"}
    {"op": "reload",    "model_path": str, "wait": false}   # hot-swap
    {"op": "rollback"}
    {"op": "swap_status"}
    {"op": "shutdown"}

The three control ops drive **live checkpoint hot-swap**
(:mod:`code2vec_tpu.serve.swap`): ``reload`` shadow-compiles the target
checkpoint's full executable ladder on a background thread, validates it
against the golden request set, and atomically swaps the serving pointer;
``rollback`` swaps back to the still-resident previous generation;
``swap_status`` reports the state machine. Every data request snapshots
its generation AT SUBMISSION, so in-flight requests drain through the
generation they were submitted to — a swap never drops them.

Responses echo an optional ``"id"`` field (client-side correlation) and
carry ``"error"`` instead of results on failure; :class:`~code2vec_tpu
.serve.batcher.ServeOverloaded` maps to ``"error_kind": "overloaded"``
(retryable).
"""

from __future__ import annotations

import json
import logging
import queue
import threading
import time
from typing import Callable

import numpy as np

from code2vec_tpu.obs.handles import handles_snapshot
from code2vec_tpu.obs.sync import sync_snapshot
from code2vec_tpu.obs.trace import TraceContext, get_tracer, new_trace_id
from code2vec_tpu.serve.swap import Generation, SwapController

logger = logging.getLogger(__name__)

__all__ = [
    "CodeServer",
    "make_http_server",
    "run_transport",
    "serve_http",
    "serve_stdio",
]

# ops that get per-op obs metrics (`serve.op.<op>.e2e_ms` latency +
# `serve.op.<op>.requests`/`.errors` counters — one schema for dashboards
# and the fleet router's shedding decisions); unknown ops are excluded so
# garbage requests cannot grow the registry unboundedly
def _validate_context_rows(
    rows, n_terminals: int, n_paths: int
) -> list[tuple[int, int, int]]:
    """Validate a pre-mapped ``"contexts"`` field: a non-empty list of
    ``[start, path, end]`` integer triples within the checkpoint's vocab
    table bounds. Bad rows are the CLIENT's mistake (bad_request), never
    a silent out-of-bounds gather on device."""
    if not isinstance(rows, (list, tuple)) or not rows:
        raise ValueError(
            "'contexts' must be a non-empty list of [start, path, end] "
            "id triples"
        )
    mapped = []
    for row in rows:
        if not isinstance(row, (list, tuple)) or len(row) != 3:
            raise ValueError(
                f"each context must be a [start, path, end] triple, "
                f"got {row!r}"
            )
        try:
            s, p, e = (int(v) for v in row)
        except (TypeError, ValueError):
            raise ValueError(
                f"context triple {row!r} is not integer-valued"
            ) from None
        if not (
            0 <= s < n_terminals and 0 <= p < n_paths and 0 <= e < n_terminals
        ):
            raise ValueError(
                f"context triple {row!r} is outside the vocab tables "
                f"({n_terminals} terminals, {n_paths} paths)"
            )
        mapped.append((s, p, e))
    return mapped


INSTRUMENTED_OPS = (
    "predict", "embed", "embed_file", "neighbors", "health",
    "reload", "rollback", "swap_status", "flights",
)


def _topk_predictions(logits: np.ndarray, label_vocab, top_k: int) -> list[dict]:
    """Top-k names+probs for one logits row — the SAME numerics as offline
    prediction, by construction: both call ``predict.softmax_top_k``."""
    from code2vec_tpu.predict import softmax_top_k

    return [
        {"name": label_vocab.itos[i], "prob": prob}
        for i, prob in softmax_top_k(logits, len(label_vocab), top_k)
    ]


class CodeServer:
    """The serving facade: extraction + mapping on the caller thread,
    device work through the micro-batcher, postprocess on resolve.

    ``predictor`` supplies vocab mapping and extraction (it already knows
    the corpus's extraction params and the ``@question`` framing);
    ``engine``/``batcher`` run the compiled forwards; ``retrieval`` is
    optional (the ``neighbors`` op errors cleanly without it). The four
    live in one :class:`~code2vec_tpu.serve.swap.Generation` behind a
    :class:`~code2vec_tpu.serve.swap.SwapController`; ``factory`` (a
    ``build(target) -> Generation`` callable) plus ``golden`` enable the
    ``reload``/``rollback`` hot-swap control ops.
    """

    def __init__(
        self, predictor, engine, batcher, retrieval=None, health=None,
        *, version: str = "v0", factory=None, golden=None, events=None,
        flight=None, generation=None,
    ) -> None:
        from code2vec_tpu.obs.runtime import global_health

        self.health = health or global_health()
        # slow-request flight recorder (obs.runtime.FlightRecorder): the
        # batcher feeds it per-request breakdowns; kept on the server so
        # the health payload and the CLI's exit-time dump can reach it
        self.flight = flight
        # adopt the caller's Generation when it already built one (the
        # CLI's gen0): wrapping the same pieces in a second Generation
        # here would orphan the first on the handle ledger — only one of
        # the two wrappers would ever be closed
        if generation is None:
            generation = Generation(
                version=version, predictor=predictor, engine=engine,
                batcher=batcher, retrieval=retrieval,
            )
        self.swap = SwapController(
            generation,
            build=factory, golden=golden, health=self.health, events=events,
        )
        self._shutdown = threading.Event()

    # ---- the active generation (swap-aware accessors) -------------------
    # setters write into the CURRENT generation — existing callers (and
    # tests) that monkeypatch e.g. `server.batcher` keep working
    @property
    def predictor(self):
        return self.swap.active.predictor

    @predictor.setter
    def predictor(self, value) -> None:
        self.swap.active.predictor = value

    @property
    def engine(self):
        return self.swap.active.engine

    @engine.setter
    def engine(self, value) -> None:
        self.swap.active.engine = value

    @property
    def batcher(self):
        return self.swap.active.batcher

    @batcher.setter
    def batcher(self, value) -> None:
        self.swap.active.batcher = value

    @property
    def retrieval(self):
        return self.swap.active.retrieval

    @retrieval.setter
    def retrieval(self, value) -> None:
        self.swap.active.retrieval = value

    # ---- lifecycle ------------------------------------------------------
    @property
    def shutdown_requested(self) -> bool:
        return self._shutdown.is_set()

    def request_shutdown(self) -> None:
        """Mark the server as shutting down (the SIGTERM handler's hook:
        transports stop accepting, drain what was accepted, then exit)."""
        self._shutdown.set()

    def close(self) -> None:
        """Drain in-flight requests and stop every resident generation."""
        self.swap.close()
        if self.flight is not None:
            self.flight.close()

    # ---- request handling ----------------------------------------------
    def handle(self, request: dict) -> dict:
        """Synchronous convenience: submit + wait (the HTTP path).
        Resolve-time failures (a future carrying the device call's
        exception) become error payloads here too — handle_async's try
        only covers submission."""
        resolver = self.handle_async(request)
        try:
            return resolver()
        except Exception as exc:  # noqa: BLE001 - protocol boundary
            return self._error_payload(exc)

    def handle_async(self, request: dict) -> Callable[[], dict]:
        """Submit any device work NOW; return a resolver that blocks for
        the results and builds the response. The stdio loop calls
        resolvers in FIFO order on its writer thread while the reader
        keeps submitting — which is exactly what lets the micro-batcher
        coalesce a pipelined request stream."""
        req_id = request.get("id")

        def finish(payload: dict) -> dict:
            if req_id is not None:
                payload = {"id": req_id, **payload}
            return payload

        op = request.get("op")
        # install the request's trace context: honor the one the router
        # (or a client) stamped into the "trace" field; mint one locally
        # only when a real tracer is recording — the untraced hot path
        # stays allocation-free
        trace = TraceContext.from_request(request)
        if trace is None and get_tracer().enabled and op in INSTRUMENTED_OPS:
            trace = TraceContext(trace_id=new_trace_id())
        try:
            # data requests snapshot the generation HERE: a swap that
            # commits between submission and resolve must not reroute an
            # in-flight request — it drains through the generation it was
            # submitted to (whose batcher stays alive until retirement)
            gen = self.swap.active
            if op == "health":
                # resolve-time snapshot: in a pipelined stream the health
                # line reports the state AFTER the requests ahead of it,
                # not the instant it was read off the wire
                resolver = self._health_payload
            elif op == "shutdown":
                self._shutdown.set()
                payload = {"ok": True, "shutting_down": True}
                resolver = lambda: payload  # noqa: E731
            elif op in ("predict", "embed"):
                resolver = self._submit_methods(request, op, gen, trace)
            elif op == "embed_file":
                resolver = self._submit_file(request, gen, trace)
            elif op == "neighbors":
                resolver = self._submit_neighbors(request, gen, trace)
            elif op == "reload":
                status = self.swap.reload(
                    request.get("model_path"),
                    wait=bool(request.get("wait", False)),
                )
                resolver = self._swap_resolver(status)
            elif op == "rollback":
                status = self.swap.rollback()
                resolver = self._swap_resolver(status)
            elif op == "swap_status":
                status = self.swap.status()
                resolver = lambda: {"ok": True, "swap": status}  # noqa: E731
            elif op == "flights":
                payload = self._flights_payload()
                resolver = lambda: payload  # noqa: E731
            else:
                payload = {
                    "error": f"unknown op {op!r}",
                    "error_kind": "bad_request",
                }
                resolver = lambda: payload  # noqa: E731
        except Exception as exc:  # noqa: BLE001 - protocol boundary
            payload = self._error_payload(exc)
            resolver = lambda: payload  # noqa: E731
        return self._instrument(op, resolver, finish, trace)

    def _instrument(
        self, op, resolver: Callable[[], dict],
        finish: Callable[[dict], dict], trace: TraceContext | None = None,
    ) -> Callable[[], dict]:
        """Per-op obs metrics around the resolver: one latency histogram +
        request/error counters per SLO-relevant op, on the same registry
        as the batcher's phase histograms (ONE metric schema). With a
        trace context, the whole submit->resolve interval is also recorded
        as a ``serve_request`` span tagged with the trace id — the
        worker-side anchor of the cross-process request trace."""
        if op not in INSTRUMENTED_OPS:
            return lambda: finish(resolver())
        t0 = time.perf_counter()
        self.health.counter(f"serve.op.{op}.requests").inc()

        def span_done(error: bool) -> None:
            tracer = get_tracer()
            if trace is not None and tracer.enabled:
                tracer.span_complete(
                    "serve_request", category="serve",
                    start_s=t0, end_s=time.perf_counter(),
                    trace_id=trace.trace_id, op=op, error=error,
                )

        def run() -> dict:
            try:
                payload = resolver()
            except Exception:
                # resolve-time failures (a future carrying the device
                # call's exception, a retired generation's closed batcher)
                # are exactly what error dashboards must see — count them
                # before the transport maps the exception to a payload
                self.health.latency(f"serve.op.{op}.e2e_ms").record(
                    (time.perf_counter() - t0) * 1e3
                )
                self.health.counter(f"serve.op.{op}.errors").inc()
                span_done(error=True)
                raise
            self.health.latency(f"serve.op.{op}.e2e_ms").record(
                (time.perf_counter() - t0) * 1e3
            )
            if "error" in payload:
                self.health.counter(f"serve.op.{op}.errors").inc()
            span_done(error="error" in payload)
            return finish(payload)

        return run

    @staticmethod
    def _swap_resolver(status: dict) -> Callable[[], dict]:
        # a swap still running (wait=false) is an accepted request, not a
        # failure — only an idle state whose latest outcome is "failed"
        # reports the error (and then it IS this reload's: reload() flips
        # the state to building before the status snapshot, so an idle
        # snapshot means the started swap already finished)
        failed = (
            status.get("state") == "idle"
            and (status.get("last_swap") or {}).get("outcome") == "failed"
        )
        payload: dict = {"ok": not failed, "swap": status}
        if failed:
            payload["error"] = status["last_swap"].get("error", "swap failed")
            payload["error_kind"] = "swap_failed"
        return lambda: payload

    @staticmethod
    def _error_payload(exc: BaseException) -> dict:
        from code2vec_tpu.serve.batcher import ServeOverloaded, ServerClosed

        if isinstance(exc, ServeOverloaded):
            kind = "overloaded"
        elif isinstance(exc, ServerClosed):
            kind = "closed"
        elif isinstance(exc, (ValueError, KeyError, TypeError)):
            kind = "bad_request"
        else:
            kind = "internal"
            logger.exception("request failed")
        return {"error": f"{type(exc).__name__}: {exc}", "error_kind": kind}

    # transports map error kinds to HTTP statuses with this table; the
    # fleet router adds "deadline"/"unavailable" kinds of its own
    HTTP_STATUS = {
        None: 200,
        "bad_request": 400,
        "overloaded": 429,
        "deadline": 429,
        "closed": 503,
        "unavailable": 503,
        "swap_failed": 500,
        "internal": 500,
    }

    # ---- metrics --------------------------------------------------------
    def metrics_text(self) -> str:
        """Prometheus text exposition (0.0.4) of the health registry —
        what ``GET /metrics`` serves. A lock-light snapshot serialize:
        never touches the engine, the batcher queue, or device state."""
        from code2vec_tpu.obs.runtime import build_info_text, prometheus_text

        return build_info_text() + prometheus_text(
            [({}, self.health.snapshot())]
        )

    # ---- ops ------------------------------------------------------------
    def _flights_payload(self) -> dict:
        """Live flight-recorder contents — the mid-incident view the
        exit-time ``flight_*.json`` dumps cannot give. JSON-sanitized so
        numpy scalars inside captured span breakdowns survive the wire."""
        from code2vec_tpu.obs.events import sanitize

        flight = self.flight
        if flight is None:
            return {"ok": True, "recorded": 0, "seen": 0, "flights": []}
        return {
            "ok": True,
            "recorded": flight.count,
            "seen": flight.seen,
            "threshold_ms": flight.threshold_ms,
            "flights": [sanitize(r) for r in flight.snapshot()],
        }

    def _health_payload(self) -> dict:
        gen = self.swap.active
        engine = gen.engine
        return {
            "ok": True,
            "version": gen.version,
            "ladder": list(engine.active_ladder),
            "batch_sizes": list(engine.batch_sizes),
            "executables": engine._cache_size(),
            "post_warmup_compiles": engine.post_warmup_compiles,
            "table_dtype": engine.table_dtype,
            # the retrieval backend mirrors the engine's executable
            # provenance: exact reports size + compiled query fns; ann
            # adds n_list/n_probe/shortlist and its LUT-kernel schedule
            "retrieval": (
                gen.retrieval.describe()
                if gen.retrieval is not None
                else None
            ),
            "swap": self.swap.status(),
            # slow-request flight recorder: how many tail requests have a
            # captured per-request timeline (None = recorder not wired)
            "flight_recorded": (
                self.flight.count if self.flight is not None else None
            ),
            # static costs × accumulated device time: per-executable
            # device-ms, achieved FLOP/s, MFU — what the router's capacity
            # model reads off each replica (guarded: duck-typed engines)
            "perf": (
                engine.perf_summary()
                if hasattr(engine, "perf_summary")
                else None
            ),
            # lock sanitizer: enabled flag + order-violation count + graph
            # size — zero violations under load is the health criterion
            "sync": sync_snapshot(),
            # handle ledger: per-kind open-handle counts — the router
            # relays this per replica, so a slow leak shows as a count
            # climbing across swaps before the replica dies of it
            "handles": handles_snapshot(),
            **self.health.snapshot(),
        }

    def _submit_methods(
        self, request: dict, op: str, gen: Generation,
        trace: TraceContext | None = None,
    ) -> Callable[[], dict]:
        predictor, engine, batcher = gen.predictor, gen.engine, gen.batcher
        source = request.get("source")
        contexts_field = request.get("contexts")
        if contexts_field is None and (
            not isinstance(source, str) or not source.strip()
        ):
            raise ValueError(
                f"{op!r} needs a non-empty 'source' string or a "
                "'contexts' list of [start, path, end] id triples"
            )
        if op == "predict" and not predictor.meta.get(
            "infer_method_name", True
        ):
            # same guard as Predictor.predict_source: a variable-task-only
            # head would serve confident nonsense as method names
            raise ValueError(
                "this checkpoint was trained for the variable-name task "
                "only; 'predict' is unavailable (embed/neighbors still work)"
            )
        language = request.get("language", "java")
        method_name = request.get("method_name", "*")
        top_k = int(request.get("top_k", 5))
        include_vector = bool(request.get("include_vector", op == "embed"))

        # extraction + vocab mapping on THIS thread (CPU-bound, no device):
        # the batcher only ever sees mapped id arrays
        submitted = []  # (label, n_oov, future | None, n_contexts)
        if contexts_field is not None:
            # pre-mapped path-context bag: [[start, path, end], ...]
            # vocab-id triples, one method. The form an indexing pipeline
            # resends (it mapped the bag once, at index time) — extraction
            # and vocab mapping are skipped entirely, and it is the form
            # the fleet router's content-addressed result cache digests
            # order-invariantly, so a permuted resend of the same bag is
            # a cache hit
            mapped = _validate_context_rows(
                contexts_field,
                int(predictor.meta["terminal_count"]),
                int(predictor.meta["path_count"]),
            )
            if len(mapped) > engine.max_width:
                # same seeded subsample rule as the offline Predictor
                rng = np.random.default_rng(0)
                keep = rng.choice(
                    len(mapped), engine.max_width, replace=False
                )
                mapped = [mapped[i] for i in sorted(keep)]
            label = (
                method_name
                if isinstance(method_name, str) and method_name != "*"
                else "<contexts>"
            )
            arr = np.asarray(mapped, np.int32).reshape(-1, 3)
            future = (
                batcher.submit(arr, trace=trace)
                if trace is not None
                else batcher.submit(arr)
            )
            submitted.append((label, 0, future, len(mapped)))
        else:
            for label, contexts, _ in predictor._extract(
                source, method_name, language
            ):
                mapped, n_oov = predictor._map_contexts(contexts)
                if len(mapped) > engine.max_width:
                    # same seeded subsample rule as the offline Predictor
                    rng = np.random.default_rng(0)
                    keep = rng.choice(
                        len(mapped), engine.max_width, replace=False
                    )
                    mapped = [mapped[i] for i in sorted(keep)]
                if not mapped:
                    submitted.append((label, n_oov, None, 0))
                    continue
                arr = np.asarray(mapped, np.int32).reshape(-1, 3)
                # the trace kwarg only when a context exists: untraced
                # paths keep the 1-arg submit surface duck-typed batchers
                # rely on
                future = (
                    batcher.submit(arr, trace=trace)
                    if trace is not None
                    else batcher.submit(arr)
                )
                submitted.append((label, n_oov, future, len(mapped)))

        label_vocab = predictor.label_vocab

        def resolve() -> dict:
            methods = []
            for label, n_oov, future, n_contexts in submitted:
                entry: dict = {
                    "method_name": label,
                    "n_contexts": n_contexts,
                    "n_oov": n_oov,
                }
                if future is None:
                    entry["error"] = (
                        "every context is OOV against the training vocab"
                    )
                    methods.append(entry)
                    continue
                result = future.result()
                if op == "predict":
                    entry["predictions"] = _topk_predictions(
                        result.logits, label_vocab, top_k
                    )
                if include_vector:
                    entry["code_vector"] = [
                        float(v) for v in result.code_vector
                    ]
                entry["timing"] = {
                    "queue_wait_ms": result.queue_wait_ms,
                    "device_ms": result.device_ms,
                    "coalesced": result.coalesced,
                    "batch": result.batch,
                    "width": result.width,
                }
                methods.append(entry)
            return {"ok": True, "methods": methods}

        return resolve

    def _submit_file(
        self, request: dict, gen: Generation,
        trace: TraceContext | None = None,
    ) -> Callable[[], dict]:
        """The hierarchical two-level head online: embed every method of
        the source through the micro-batcher, then attention-pool the
        method vectors with the checkpoint's trained attention param
        (models/hierarchical.py) into ONE file vector — whole-file
        embedding with the same per-method device path as ``embed``."""
        predictor = gen.predictor
        embed_resolver = self._submit_methods(
            {**request, "include_vector": True}, "embed", gen, trace
        )

        def resolve() -> dict:
            from code2vec_tpu.models.hierarchical import pool_vectors

            embedded = embed_resolver()
            names, vectors = [], []
            for entry in embedded["methods"]:
                cv = entry.get("code_vector")
                if cv is not None:
                    names.append(entry["method_name"])
                    vectors.append(cv)
            if not vectors:
                return {
                    "error": "no method in the source produced an "
                    "embedding (nothing extracted, or every context is "
                    "OOV against the training vocab)",
                    "error_kind": "bad_request",
                }
            attn = np.asarray(
                predictor.state.params["attention"], np.float32
            )
            file_vector = pool_vectors(
                np.asarray(vectors, np.float32), attn
            )
            return {
                "ok": True,
                "file_vector": [float(v) for v in file_vector],
                "n_methods": len(vectors),
                "method_names": names,
            }

        return resolve

    def _submit_neighbors(
        self, request: dict, gen: Generation,
        trace: TraceContext | None = None,
    ) -> Callable[[], dict]:
        retrieval = gen.retrieval
        if retrieval is None:
            raise ValueError(
                "no retrieval index loaded — start the server with "
                "--code_vec_path (an exported code.vec)"
            )
        trace_args = {"trace_id": trace.trace_id} if trace else {}

        def retrieve(vec: np.ndarray, k: int):
            # retrieval spans carry the originating trace id too — the
            # third worker-side hop of the cross-process request trace
            with get_tracer().span(
                "serve_retrieval", category="serve", top_k=k, **trace_args
            ):
                return retrieval.top_k(vec, k)

        top_k = int(request.get("top_k", 5))
        granularity = request.get("granularity", "method")
        if granularity not in ("method", "file"):
            raise ValueError(
                f"granularity must be 'method' or 'file', got "
                f"{granularity!r}"
            )
        vector = request.get("vector")
        if vector is not None:
            vec = np.asarray(vector, np.float32)
            if vec.shape != (retrieval.dim,):
                raise ValueError(
                    f"'vector' must have dim {retrieval.dim}, got "
                    f"{vec.shape}"
                )
            neighbors = retrieve(vec, top_k)
            payload = {
                "ok": True,
                "neighbors": [
                    {"name": n, "similarity": s} for n, s in neighbors
                ],
            }
            return lambda: payload

        # source-form at FILE granularity: pool the source's method
        # vectors into one file vector (the hierarchical head), then
        # retrieve — whole-file search against a file.vec-backed index
        # (export.export_file_vectors) through the unchanged stack
        if granularity == "file":
            want_vector = bool(request.get("include_vector", False))
            file_resolver = self._submit_file(request, gen, trace)

            def resolve_file() -> dict:
                payload = file_resolver()
                if "error" in payload:
                    return payload
                vec = np.asarray(payload["file_vector"], np.float32)
                out = {
                    "ok": True,
                    "n_methods": payload["n_methods"],
                    "neighbors": [
                        {"name": n, "similarity": s}
                        for n, s in retrieve(vec, top_k)
                    ],
                }
                if want_vector:
                    out["file_vector"] = payload["file_vector"]
                return out

            return resolve_file

        # source-form: embed through the micro-batcher, then retrieve.
        # include_vector=True here is internal plumbing — remember whether
        # the CLIENT also asked for the vector so their flag survives
        want_vector = bool(request.get("include_vector", False))
        embed_resolver = self._submit_methods(
            {**request, "include_vector": True}, "embed", gen, trace
        )

        def resolve() -> dict:
            embedded = embed_resolver()
            for entry in embedded["methods"]:
                cv = entry.get("code_vector")
                if cv is not None:
                    entry["neighbors"] = [
                        {"name": n, "similarity": s}
                        for n, s in retrieve(
                            np.asarray(cv, np.float32), top_k
                        )
                    ]
                if not want_vector:
                    entry.pop("code_vector", None)
            return embedded

        return resolve


# ---------------------------------------------------------------------------
# transports
# ---------------------------------------------------------------------------


def serve_stdio(
    server: CodeServer, in_stream, out_stream, stop_event=None
) -> None:
    """JSONL over any line-iterable/writable stream pair (stdin/stdout in
    production, in-memory pipes in tests). Responses keep request order;
    submission outpaces resolution, so pipelined clients coalesce.

    ``stop_event`` (the SIGTERM path — ``__main__`` wires its handler to
    it): when set, the loop stops WAITING for new requests but still
    RESOLVES everything already accepted — every submitted request gets
    its response written before the process exits (the drain contract
    fleet eviction relies on; without it queued requests die with the
    process)."""
    pending: "queue.Queue" = queue.Queue()
    _EOF = object()
    # set while the reader holds a line it has not yet enqueued a resolver
    # for — the SIGTERM drain must not declare the stream empty while a
    # read-but-unsubmitted request is still in the reader's hands (source
    # extraction inside handle_async can take well over the poll window)
    reader_busy = threading.Event()

    def reader() -> None:
        try:
            for line in in_stream:
                reader_busy.set()
                try:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        request = json.loads(line)
                        if not isinstance(request, dict):
                            raise ValueError("request must be a JSON object")
                    except ValueError as exc:
                        # malformed JSONL — including a mid-stream EOF's
                        # truncated final line — answers with a structured
                        # error and the stream keeps serving
                        payload = {
                            "error": f"bad request line: {exc}",
                            "error_kind": "bad_request",
                        }
                        pending.put(lambda payload=payload: payload)
                        continue
                    pending.put(server.handle_async(request))
                finally:
                    reader_busy.clear()
                if server.shutdown_requested:
                    break
        finally:
            pending.put(_EOF)

    thread = threading.Thread(target=reader, name="c2v-serve-stdin", daemon=True)
    thread.start()
    empty_strikes = 0
    try:
        while True:
            try:
                resolver = pending.get(timeout=0.1)
            except queue.Empty:
                if (
                    stop_event is not None
                    and stop_event.is_set()
                    and not reader_busy.is_set()
                ):
                    # SIGTERM drain: the reader holds nothing and two
                    # consecutive empty polls (200 ms) passed — everything
                    # accepted has been resolved and written; exit cleanly
                    empty_strikes += 1
                    if empty_strikes >= 2:
                        break
                continue
            empty_strikes = 0
            if resolver is _EOF:
                break
            try:
                response = resolver()
            except Exception as exc:  # noqa: BLE001 - keep serving
                response = CodeServer._error_payload(exc)
            out_stream.write(json.dumps(response) + "\n")
            out_stream.flush()
    finally:
        server.close()
        thread.join(timeout=5.0)


def make_http_server(server: CodeServer, host: str, port: int):
    """Build (but don't run) the stdlib threading HTTP server: POST /
    (or /v1/<op>) with a JSON body; GET /healthz for the health payload.
    Split from :func:`serve_http` so tests can bind port 0 and read the
    chosen port before starting ``serve_forever`` on a thread."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def _respond(self, code: int, payload: dict) -> None:
            body = json.dumps(payload).encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler API
            path = self.path.rstrip("/")
            if path in ("", "/healthz".rstrip("/")):
                self._respond(200, server.handle({"op": "health"}))
            elif path == "/metrics":
                # Prometheus text exposition — the scrape plane. Served by
                # both the single worker (its own registry) and the fleet
                # router (aggregated across replicas with a `replica`
                # label); either way a lock-light snapshot serialize.
                metrics_text = getattr(server, "metrics_text", None)
                if metrics_text is None:
                    self._respond(404, {"error": "no metrics exporter"})
                    return
                body = metrics_text().encode("utf-8")
                self.send_response(200)
                self.send_header(
                    "Content-Type",
                    "text/plain; version=0.0.4; charset=utf-8",
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                self._respond(404, {"error": "unknown path"})

        def do_POST(self):  # noqa: N802 - BaseHTTPRequestHandler API
            try:
                length = int(self.headers.get("Content-Length", 0))
                request = json.loads(self.rfile.read(length) or b"{}")
                op = self.path.strip("/").split("/")[-1]
                if op and "op" not in request and op != "v1":
                    request["op"] = op
            except (ValueError, TypeError) as exc:
                self._respond(
                    400,
                    {"error": f"bad body: {exc}", "error_kind": "bad_request"},
                )
                return
            response = server.handle(request)
            kind = response.get("error_kind")
            code = CodeServer.HTTP_STATUS.get(kind, 200)
            self._respond(code, response)
            if server.shutdown_requested:
                threading.Thread(
                    target=httpd.shutdown, daemon=True
                ).start()

        def log_message(self, fmt, *args):  # quiet: obs carries the metrics
            logger.debug("http: " + fmt, *args)

    httpd = ThreadingHTTPServer((host, port), Handler)
    return httpd


def serve_http(server: CodeServer, host: str, port: int) -> None:
    """Run the HTTP transport until shutdown; drains the batcher on exit."""
    httpd = make_http_server(server, host, port)
    try:
        logger.info("serving HTTP on %s:%d", *httpd.server_address[:2])
        httpd.serve_forever(poll_interval=0.1)
    finally:
        server.close()
        httpd.server_close()


def run_transport(server, transport: str, host: str, port: int) -> None:
    """The SIGTERM-draining transport loop shared by the serve and fleet
    CLIs: SIGTERM stops ACCEPTING, resolves + writes a response for
    everything already accepted (stdio writer drain + server close drain),
    and exits 0 — the contract fleet eviction and rolling restarts rely
    on. ``server`` is anything with the CodeServer surface (CodeServer
    itself, or the fleet router)."""
    import signal
    import sys

    stop_event = threading.Event()
    httpd_box: list = []

    def _on_sigterm(signum, frame):  # noqa: ARG001 - signal API
        logger.info("SIGTERM: draining accepted requests, then exiting")
        stop_event.set()
        server.request_shutdown()
        for httpd in httpd_box:
            threading.Thread(target=httpd.shutdown, daemon=True).start()

    previous_handler = signal.signal(signal.SIGTERM, _on_sigterm)
    try:
        if transport == "stdio":
            serve_stdio(server, sys.stdin, sys.stdout, stop_event=stop_event)
        else:
            httpd = make_http_server(server, host, port)
            httpd_box.append(httpd)
            try:
                logger.info(
                    "serving HTTP on %s:%d", *httpd.server_address[:2]
                )
                httpd.serve_forever(poll_interval=0.1)
            finally:
                server.close()
                httpd.server_close()
    finally:
        signal.signal(signal.SIGTERM, previous_handler)
