"""Transport-thin request handling: dict in, dict out.

The protocol layer owns everything between a parsed request object and a
response object — extraction, vocab mapping, batcher submission, softmax/
top-k postprocessing — and NOTHING about bytes on a wire. Both transports
are adapters over the same :class:`CodeServer`:

- **stdio-JSONL** (:func:`serve_stdio`): one JSON object per line in, one
  per line out, responses in request order. The reader thread submits
  requests as fast as they arrive while the writer resolves them in FIFO
  order — pipelined clients therefore get real micro-batch coalescing
  over a pipe, no sockets involved (what the tests and the CI smoke
  drive).
- **HTTP** (:func:`serve_http`): stdlib ``ThreadingHTTPServer``; each
  concurrent POST maps to one handler thread blocking on its future, so
  concurrency again becomes coalescing.

Request schema (one ``op`` per object; unknown fields ignored)::

    {"op": "predict",   "source": str, "language": "java"|"python",
     "method_name": "*", "top_k": 5, "include_vector": false}
    {"op": "embed",     ... same selectors ...}
    {"op": "neighbors", "vector": [...] | source selectors, "top_k": 5}
    {"op": "health"}
    {"op": "shutdown"}

Responses echo an optional ``"id"`` field (client-side correlation) and
carry ``"error"`` instead of results on failure; :class:`~code2vec_tpu
.serve.batcher.ServeOverloaded` maps to ``"error_kind": "overloaded"``
(retryable).
"""

from __future__ import annotations

import json
import logging
import queue
import threading
from typing import Callable

import numpy as np

logger = logging.getLogger(__name__)

__all__ = ["CodeServer", "serve_stdio", "serve_http", "make_http_server"]


def _topk_predictions(logits: np.ndarray, label_vocab, top_k: int) -> list[dict]:
    """Top-k names+probs for one logits row — the SAME numerics as offline
    prediction, by construction: both call ``predict.softmax_top_k``."""
    from code2vec_tpu.predict import softmax_top_k

    return [
        {"name": label_vocab.itos[i], "prob": prob}
        for i, prob in softmax_top_k(logits, len(label_vocab), top_k)
    ]


class CodeServer:
    """The serving facade: extraction + mapping on the caller thread,
    device work through the micro-batcher, postprocess on resolve.

    ``predictor`` supplies vocab mapping and extraction (it already knows
    the corpus's extraction params and the ``@question`` framing);
    ``engine``/``batcher`` run the compiled forwards; ``retrieval`` is
    optional (the ``neighbors`` op errors cleanly without it).
    """

    def __init__(
        self, predictor, engine, batcher, retrieval=None, health=None,
    ) -> None:
        from code2vec_tpu.obs.runtime import global_health

        self.predictor = predictor
        self.engine = engine
        self.batcher = batcher
        self.retrieval = retrieval
        self.health = health or global_health()
        self._shutdown = threading.Event()

    # ---- lifecycle ------------------------------------------------------
    @property
    def shutdown_requested(self) -> bool:
        return self._shutdown.is_set()

    def close(self) -> None:
        """Drain in-flight requests and stop the batcher."""
        self.batcher.close()

    # ---- request handling ----------------------------------------------
    def handle(self, request: dict) -> dict:
        """Synchronous convenience: submit + wait (the HTTP path).
        Resolve-time failures (a future carrying the device call's
        exception) become error payloads here too — handle_async's try
        only covers submission."""
        resolver = self.handle_async(request)
        try:
            return resolver()
        except Exception as exc:  # noqa: BLE001 - protocol boundary
            return self._error_payload(exc)

    def handle_async(self, request: dict) -> Callable[[], dict]:
        """Submit any device work NOW; return a resolver that blocks for
        the results and builds the response. The stdio loop calls
        resolvers in FIFO order on its writer thread while the reader
        keeps submitting — which is exactly what lets the micro-batcher
        coalesce a pipelined request stream."""
        req_id = request.get("id")

        def finish(payload: dict) -> dict:
            if req_id is not None:
                payload = {"id": req_id, **payload}
            return payload

        try:
            op = request.get("op")
            if op == "health":
                # resolve-time snapshot: in a pipelined stream the health
                # line reports the state AFTER the requests ahead of it,
                # not the instant it was read off the wire
                return lambda: finish(self._health_payload())
            if op == "shutdown":
                self._shutdown.set()
                return lambda: finish({"ok": True, "shutting_down": True})
            if op in ("predict", "embed"):
                resolver = self._submit_methods(request, op)
                return lambda: finish(resolver())
            if op == "neighbors":
                resolver = self._submit_neighbors(request)
                return lambda: finish(resolver())
            return lambda: finish(
                {"error": f"unknown op {op!r}", "error_kind": "bad_request"}
            )
        except Exception as exc:  # noqa: BLE001 - protocol boundary
            payload = self._error_payload(exc)
            return lambda: finish(payload)

    @staticmethod
    def _error_payload(exc: BaseException) -> dict:
        from code2vec_tpu.serve.batcher import ServeOverloaded, ServerClosed

        if isinstance(exc, ServeOverloaded):
            kind = "overloaded"
        elif isinstance(exc, ServerClosed):
            kind = "closed"
        elif isinstance(exc, (ValueError, KeyError, TypeError)):
            kind = "bad_request"
        else:
            kind = "internal"
            logger.exception("request failed")
        return {"error": f"{type(exc).__name__}: {exc}", "error_kind": kind}

    # ---- ops ------------------------------------------------------------
    def _health_payload(self) -> dict:
        engine = self.engine
        return {
            "ok": True,
            "ladder": list(engine.active_ladder),
            "batch_sizes": list(engine.batch_sizes),
            "executables": engine._cache_size(),
            "post_warmup_compiles": engine.post_warmup_compiles,
            "table_dtype": engine.table_dtype,
            # the retrieval backend mirrors the engine's executable
            # provenance: exact reports size + compiled query fns; ann
            # adds n_list/n_probe/shortlist and its LUT-kernel schedule
            "retrieval": (
                self.retrieval.describe()
                if self.retrieval is not None
                else None
            ),
            **self.health.snapshot(),
        }

    def _submit_methods(self, request: dict, op: str) -> Callable[[], dict]:
        source = request.get("source")
        if not isinstance(source, str) or not source.strip():
            raise ValueError(f"{op!r} needs a non-empty 'source' string")
        if op == "predict" and not self.predictor.meta.get(
            "infer_method_name", True
        ):
            # same guard as Predictor.predict_source: a variable-task-only
            # head would serve confident nonsense as method names
            raise ValueError(
                "this checkpoint was trained for the variable-name task "
                "only; 'predict' is unavailable (embed/neighbors still work)"
            )
        language = request.get("language", "java")
        method_name = request.get("method_name", "*")
        top_k = int(request.get("top_k", 5))
        include_vector = bool(request.get("include_vector", op == "embed"))

        # extraction + vocab mapping on THIS thread (CPU-bound, no device):
        # the batcher only ever sees mapped id arrays
        submitted = []  # (label, n_oov, future | None, n_contexts)
        for label, contexts, _ in self.predictor._extract(
            source, method_name, language
        ):
            mapped, n_oov = self.predictor._map_contexts(contexts)
            if len(mapped) > self.engine.max_width:
                # same seeded subsample rule as the offline Predictor
                rng = np.random.default_rng(0)
                keep = rng.choice(
                    len(mapped), self.engine.max_width, replace=False
                )
                mapped = [mapped[i] for i in sorted(keep)]
            if not mapped:
                submitted.append((label, n_oov, None, 0))
                continue
            arr = np.asarray(mapped, np.int32).reshape(-1, 3)
            submitted.append((label, n_oov, self.batcher.submit(arr), len(mapped)))

        label_vocab = self.predictor.label_vocab

        def resolve() -> dict:
            methods = []
            for label, n_oov, future, n_contexts in submitted:
                entry: dict = {
                    "method_name": label,
                    "n_contexts": n_contexts,
                    "n_oov": n_oov,
                }
                if future is None:
                    entry["error"] = (
                        "every context is OOV against the training vocab"
                    )
                    methods.append(entry)
                    continue
                result = future.result()
                if op == "predict":
                    entry["predictions"] = _topk_predictions(
                        result.logits, label_vocab, top_k
                    )
                if include_vector:
                    entry["code_vector"] = [
                        float(v) for v in result.code_vector
                    ]
                entry["timing"] = {
                    "queue_wait_ms": result.queue_wait_ms,
                    "device_ms": result.device_ms,
                    "coalesced": result.coalesced,
                    "batch": result.batch,
                    "width": result.width,
                }
                methods.append(entry)
            return {"ok": True, "methods": methods}

        return resolve

    def _submit_neighbors(self, request: dict) -> Callable[[], dict]:
        if self.retrieval is None:
            raise ValueError(
                "no retrieval index loaded — start the server with "
                "--code_vec_path (an exported code.vec)"
            )
        top_k = int(request.get("top_k", 5))
        vector = request.get("vector")
        if vector is not None:
            vec = np.asarray(vector, np.float32)
            if vec.shape != (self.retrieval.dim,):
                raise ValueError(
                    f"'vector' must have dim {self.retrieval.dim}, got "
                    f"{vec.shape}"
                )
            neighbors = self.retrieval.top_k(vec, top_k)
            payload = {
                "ok": True,
                "neighbors": [
                    {"name": n, "similarity": s} for n, s in neighbors
                ],
            }
            return lambda: payload

        # source-form: embed through the micro-batcher, then retrieve.
        # include_vector=True here is internal plumbing — remember whether
        # the CLIENT also asked for the vector so their flag survives
        want_vector = bool(request.get("include_vector", False))
        embed_resolver = self._submit_methods(
            {**request, "include_vector": True}, "embed"
        )
        retrieval = self.retrieval

        def resolve() -> dict:
            embedded = embed_resolver()
            for entry in embedded["methods"]:
                cv = entry.get("code_vector")
                if cv is not None:
                    entry["neighbors"] = [
                        {"name": n, "similarity": s}
                        for n, s in retrieval.top_k(
                            np.asarray(cv, np.float32), top_k
                        )
                    ]
                if not want_vector:
                    entry.pop("code_vector", None)
            return embedded

        return resolve


# ---------------------------------------------------------------------------
# transports
# ---------------------------------------------------------------------------


def serve_stdio(server: CodeServer, in_stream, out_stream) -> None:
    """JSONL over any line-iterable/writable stream pair (stdin/stdout in
    production, in-memory pipes in tests). Responses keep request order;
    submission outpaces resolution, so pipelined clients coalesce."""
    pending: "queue.Queue" = queue.Queue()
    _EOF = object()

    def reader() -> None:
        try:
            for line in in_stream:
                line = line.strip()
                if not line:
                    continue
                try:
                    request = json.loads(line)
                    if not isinstance(request, dict):
                        raise ValueError("request must be a JSON object")
                except ValueError as exc:
                    payload = {
                        "error": f"bad request line: {exc}",
                        "error_kind": "bad_request",
                    }
                    pending.put(lambda payload=payload: payload)
                    continue
                pending.put(server.handle_async(request))
                if server.shutdown_requested:
                    break
        finally:
            pending.put(_EOF)

    thread = threading.Thread(target=reader, name="c2v-serve-stdin", daemon=True)
    thread.start()
    try:
        while True:
            resolver = pending.get()
            if resolver is _EOF:
                break
            try:
                response = resolver()
            except Exception as exc:  # noqa: BLE001 - keep serving
                response = CodeServer._error_payload(exc)
            out_stream.write(json.dumps(response) + "\n")
            out_stream.flush()
    finally:
        server.close()
        thread.join(timeout=5.0)


def make_http_server(server: CodeServer, host: str, port: int):
    """Build (but don't run) the stdlib threading HTTP server: POST /
    (or /v1/<op>) with a JSON body; GET /healthz for the health payload.
    Split from :func:`serve_http` so tests can bind port 0 and read the
    chosen port before starting ``serve_forever`` on a thread."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def _respond(self, code: int, payload: dict) -> None:
            body = json.dumps(payload).encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler API
            if self.path.rstrip("/") in ("", "/healthz".rstrip("/")):
                self._respond(200, server.handle({"op": "health"}))
            else:
                self._respond(404, {"error": "unknown path"})

        def do_POST(self):  # noqa: N802 - BaseHTTPRequestHandler API
            try:
                length = int(self.headers.get("Content-Length", 0))
                request = json.loads(self.rfile.read(length) or b"{}")
                op = self.path.strip("/").split("/")[-1]
                if op and "op" not in request and op != "v1":
                    request["op"] = op
            except (ValueError, TypeError) as exc:
                self._respond(
                    400,
                    {"error": f"bad body: {exc}", "error_kind": "bad_request"},
                )
                return
            response = server.handle(request)
            kind = response.get("error_kind")
            code = {
                None: 200,
                "bad_request": 400,
                "overloaded": 429,
                "closed": 503,
                "internal": 500,
            }.get(kind, 200)
            self._respond(code, response)
            if server.shutdown_requested:
                threading.Thread(
                    target=httpd.shutdown, daemon=True
                ).start()

        def log_message(self, fmt, *args):  # quiet: obs carries the metrics
            logger.debug("http: " + fmt, *args)

    httpd = ThreadingHTTPServer((host, port), Handler)
    return httpd


def serve_http(server: CodeServer, host: str, port: int) -> None:
    """Run the HTTP transport until shutdown; drains the batcher on exit."""
    httpd = make_http_server(server, host, port)
    try:
        logger.info("serving HTTP on %s:%d", *httpd.server_address[:2])
        httpd.serve_forever(poll_interval=0.1)
    finally:
        server.close()
        httpd.server_close()
