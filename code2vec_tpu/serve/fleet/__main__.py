"""``python -m code2vec_tpu.serve.fleet`` — launch router + N replicas.

The router process is jax-free; each replica is a full
``python -m code2vec_tpu.serve --transport stdio`` subprocess that
AOT-compiles its executable ladder before the router counts it placeable.
Client-facing transports are the same stdio-JSONL/HTTP adapters the
single-process server uses — a client cannot tell a fleet from one
worker, except that ``health`` returns the fleet topology and ``reload``
performs a ROLLING hot-swap across the replicas.

    python -m code2vec_tpu.serve.fleet --replicas 4 \\
        --model_path out \\
        --terminal_idx_path ds/terminal_idxs.txt \\
        --path_idx_path ds/path_idxs.txt \\
        --transport http --port 8080 \\
        --slo embed=512:1500,neighbors=64:8000

    # zero-downtime rollout + instant rollback (any transport):
    {"op": "reload", "model_path": "out_v2"}
    {"op": "swap_status"}
    {"op": "rollback"}
"""

from __future__ import annotations

import argparse
import logging
import os
import sys

logger = logging.getLogger(__name__)


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="code2vec_tpu.serve.fleet",
        description="fleet serving: replica router, tiered load shedding, "
        "rolling live checkpoint hot-swap",
    )
    parser.add_argument("--replicas", type=int, default=2,
                        help="worker subprocess count")
    parser.add_argument("--model_path", required=True)
    parser.add_argument("--terminal_idx_path", required=True)
    parser.add_argument("--path_idx_path", required=True)
    parser.add_argument("--transport", default="stdio",
                        choices=("stdio", "http"))
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8080)
    parser.add_argument("--slo", default="",
                        help="per-class budget/deadline overrides: "
                        "class=budget:deadline_ms comma-separated over "
                        "defaults health=16:1000,embed=256:2000,"
                        "neighbors=64:5000")
    parser.add_argument("--per_replica_inflight", type=int, default=8,
                        help="max requests in flight per replica (the "
                        "per-replica bounded queue)")
    parser.add_argument("--probe_interval_s", type=float, default=2.0)
    parser.add_argument("--probe_timeout_s", type=float, default=60.0)
    parser.add_argument("--max_probe_failures", type=int, default=3,
                        help="consecutive missed health probes before a "
                        "replica is evicted and respawned")
    parser.add_argument("--boot_timeout_s", type=float, default=900.0,
                        help="per-replica AOT-compile + readiness budget")
    parser.add_argument("--events_dir", default=None,
                        help="router event log (fleet manifest, spawn/"
                        "evict/swap events); replicas log under "
                        "<events_dir>/r<slot>")
    parser.add_argument("--trace_dir", default=None,
                        help="fleet-wide Chrome tracing: the router "
                        "writes its trace here and each replica writes "
                        "under <trace_dir>/r<slot>; every request is "
                        "stamped with a trace id at admission and "
                        "tools/trace_stitch.py merges the per-process "
                        "files into one viewable trace")
    parser.add_argument("--slo_objective", type=float, default=0.999,
                        help="per-class availability objective for "
                        "error-budget burn accounting (0.999 = 0.1%% "
                        "error budget over the rolling window)")
    parser.add_argument("--slo_window_s", type=float, default=60.0,
                        help="rolling error-budget window length")
    parser.add_argument("--result_cache_mb", type=float, default=0.0,
                        help="router-level content-addressed result cache "
                        "capacity in MB (0 = off): repeat requests for the "
                        "same canonical path-context bag are served from "
                        "router memory in O(1) — no queue budget, no "
                        "replica, no device call — with S3-FIFO eviction, "
                        "miss coalescing, and swap-versioned invalidation")
    parser.add_argument("--flight_threshold_ms", type=float, default=0.0,
                        help="capture a full per-request flight record "
                        "for any request slower than this (0 = p99 "
                        "sampling only)")
    # worker passthrough (same semantics as code2vec_tpu.serve)
    parser.add_argument("--table_dtype", default=None,
                        choices=("f32", "bf16", "int8"))
    parser.add_argument("--batch_sizes", default="1,8")
    parser.add_argument("--deadline_ms", type=float, default=2.0,
                        help="per-worker micro-batcher coalescing window")
    parser.add_argument("--max_pending", type=int, default=256,
                        help="per-worker micro-batcher queue bound")
    parser.add_argument("--warmup_requests", type=int, default=64)
    parser.add_argument("--golden_min_recall", type=float, default=0.9)
    parser.add_argument("--autotune_cache", default="")
    parser.add_argument("--code_vec_path", default=None)
    parser.add_argument("--retrieval_backend", default="exact",
                        choices=("exact", "ann"))
    parser.add_argument("--ann_index_path", default=None)
    parser.add_argument("--ann_n_probe", type=int, default=None)
    parser.add_argument("--ann_shortlist", type=int, default=None)
    parser.add_argument("--accelerator", action="store_true", default=False)
    parser.add_argument("--sync_debug", action="store_true", default=False,
                        help="lock sanitizer on the router AND every "
                        "worker (the flag is forwarded down the replica "
                        "command line); equivalent to C2V_SYNC_DEBUG=1")
    parser.add_argument("--handle_debug", action="store_true", default=False,
                        help="handle ledger on the router AND every "
                        "worker (forwarded like --sync_debug): per-kind "
                        "open-handle gauges, per-replica handles health "
                        "blocks, open-handle counts on eviction events, "
                        "and a handle_leak shutdown report; equivalent "
                        "to C2V_HANDLE_DEBUG=1")
    return parser


def worker_argv(args, slot: int) -> list[str]:
    """The replica subprocess command line (one worker, stdio)."""
    argv = [
        sys.executable, "-m", "code2vec_tpu.serve",
        "--transport", "stdio",
        "--model_path", args.model_path,
        "--terminal_idx_path", args.terminal_idx_path,
        "--path_idx_path", args.path_idx_path,
        "--batch_sizes", str(args.batch_sizes),
        "--deadline_ms", str(args.deadline_ms),
        "--max_pending", str(args.max_pending),
        "--warmup_requests", str(args.warmup_requests),
        "--golden_min_recall", str(args.golden_min_recall),
        "--retrieval_backend", args.retrieval_backend,
    ]
    if args.table_dtype:
        argv += ["--table_dtype", args.table_dtype]
    if args.autotune_cache:
        argv += ["--autotune_cache", args.autotune_cache]
    if args.code_vec_path:
        argv += ["--code_vec_path", args.code_vec_path]
    if args.ann_index_path:
        argv += ["--ann_index_path", args.ann_index_path]
    if args.ann_n_probe is not None:
        argv += ["--ann_n_probe", str(args.ann_n_probe)]
    if args.ann_shortlist is not None:
        argv += ["--ann_shortlist", str(args.ann_shortlist)]
    if args.accelerator:
        argv += ["--accelerator"]
    if args.events_dir:
        argv += ["--events_dir", os.path.join(args.events_dir, f"r{slot}")]
    if getattr(args, "trace_dir", None):
        argv += ["--trace_dir", os.path.join(args.trace_dir, f"r{slot}")]
    threshold = getattr(args, "flight_threshold_ms", 0.0)
    if threshold:
        argv += ["--flight_threshold_ms", str(threshold)]
    if getattr(args, "sync_debug", False):
        argv += ["--sync_debug"]
    if getattr(args, "handle_debug", False):
        argv += ["--handle_debug"]
    return argv


def build_router(args):
    """Assemble the router (spawns + readies every replica); importable so
    tests can drive a real fleet without the transport loop."""
    from code2vec_tpu.serve.fleet.replica import ReplicaHandle
    from code2vec_tpu.serve.fleet.router import FleetRouter
    from code2vec_tpu.serve.fleet.slo import parse_slo_spec

    # flip the sanitizer BEFORE the router/cache/SLO locks are built; the
    # replica subprocesses inherit the env AND get the explicit flag
    if getattr(args, "sync_debug", False):
        from code2vec_tpu.obs.sync import SYNC_DEBUG_ENV

        os.environ[SYNC_DEBUG_ENV] = "1"
    # same ordering rule for the handle ledger: the env must be live before
    # the first lifecycle owner (event log, flight recorder, replicas)
    if getattr(args, "handle_debug", False):
        from code2vec_tpu.obs.handles import HANDLE_DEBUG_ENV

        os.environ[HANDLE_DEBUG_ENV] = "1"

    events = None
    if args.events_dir:
        from code2vec_tpu.obs.events import EventLog

        events = EventLog(args.events_dir)
        events.write_manifest(
            fleet={
                "replicas": args.replicas,
                "model_path": args.model_path,
                "transport": args.transport,
                "slo": args.slo or None,
                "per_replica_inflight": args.per_replica_inflight,
            }
        )
        from code2vec_tpu.obs.sync import register_event_log, sync_debug_enabled

        if sync_debug_enabled():
            # router-side lock_order_violation events land in the fleet log
            register_event_log(events)
        from code2vec_tpu.obs.handles import handle_debug_enabled
        from code2vec_tpu.obs.handles import register_event_log as register_handle_log

        if handle_debug_enabled():
            # router-side handle_leak events land in the fleet log too
            register_handle_log(events)

    def factory(slot: int, incarnation: int) -> ReplicaHandle:
        return ReplicaHandle(
            slot, worker_argv(args, slot), incarnation=incarnation,
        )

    from code2vec_tpu.obs.runtime import FlightRecorder, global_health

    threshold = getattr(args, "flight_threshold_ms", 0.0)
    flight = FlightRecorder(
        threshold_ms=threshold if threshold > 0 else None,
        events=events, health=global_health(),
    )
    cache = None
    cache_mb = getattr(args, "result_cache_mb", 0.0) or 0.0
    if cache_mb > 0:
        from code2vec_tpu.serve.fleet.cache import ResultCache

        cache = ResultCache(
            int(cache_mb * 2**20), health=global_health()
        )
    router = FleetRouter(
        factory,
        args.replicas,
        slo=parse_slo_spec(args.slo),
        events=events,
        per_replica_inflight=args.per_replica_inflight,
        probe_interval_s=args.probe_interval_s,
        probe_timeout_s=args.probe_timeout_s,
        max_probe_failures=args.max_probe_failures,
        boot_timeout_s=args.boot_timeout_s,
        slo_objective=getattr(args, "slo_objective", 0.999),
        slo_window_s=getattr(args, "slo_window_s", 60.0),
        flight=flight,
        result_cache=cache,
    )
    return router, events


def main(argv: list[str] | None = None) -> None:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s: %(message)s",
        datefmt="%m/%d/%Y %I:%M:%S %p",
    )
    args = build_parser().parse_args(argv)

    tracer = None
    if args.trace_dir:
        from code2vec_tpu.obs.trace import Tracer, set_tracer

        # the router is jax-free: pin its trace pid/row explicitly instead
        # of letting export probe a backend that was never initialized
        tracer = Tracer(process_index=0, process_name="fleet-router")
        set_tracer(tracer)

    router, events = build_router(args)
    logger.info("fleet of %d replica(s) is ready", args.replicas)

    # same SIGTERM-draining transport loop as the single worker — a
    # client cannot tell a fleet from one process, shutdown included
    from code2vec_tpu.serve.protocol import run_transport

    try:
        run_transport(router, args.transport, args.host, args.port)
    finally:
        if tracer is not None:
            from code2vec_tpu.obs.trace import set_tracer

            set_tracer(None)
            try:
                tracer.export_dir(args.trace_dir)
            except Exception:
                logger.warning("could not write chrome trace", exc_info=True)
        if args.events_dir and router._flight is not None:
            try:
                router._flight.dump(os.path.join(args.events_dir, "flight"))
            except Exception:
                logger.warning("could not dump flight records", exc_info=True)
        from code2vec_tpu.obs.handles import handle_debug_enabled, report_leaks

        if handle_debug_enabled():
            exclude = (events,) if events is not None else ()
            report_leaks("fleet.shutdown", events=events, exclude=exclude)
        if events is not None:
            try:
                events.close()
            except Exception:
                logger.warning("could not close event log", exc_info=True)


if __name__ == "__main__":
    main()
