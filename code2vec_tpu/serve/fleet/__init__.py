"""Fleet serving: one router, N replica workers, zero-downtime rollouts.

``python -m code2vec_tpu.serve`` is one process pinned to one model
generation; this package is the layer that makes it a FLEET (ROADMAP
item 2 — heavy traffic from millions of users):

- :mod:`replica` — one worker subprocess (``python -m code2vec_tpu.serve
  --transport stdio``) behind a JSONL pipe client: FIFO request/response
  matching (the stdio transport guarantees response order), bounded
  in-flight accounting, and a graceful stop that rides the worker's
  SIGTERM drain contract (every accepted request gets its response before
  the process exits).
- :mod:`slo` — per-op SLO classes (``embed`` / ``neighbors`` /
  ``health``) with DISTINCT queue budgets and deadlines, replacing the
  single global ``max_pending``: tiered load shedding means overload
  degrades the cheap-to-retry tiers first while the control plane stays
  responsive.
- :mod:`cache` — router-level content-addressed result cache: repeat
  requests (order-invariant canonical bag digest + op knobs + generation
  version) are served from router memory in O(1), ahead of SLO admission
  — S3-FIFO eviction with byte-accounted capacity, concurrent-miss
  coalescing, and swap-versioned invalidation (a committed rolling swap
  flips the active version key; ``rollback`` flips it back and the old
  generation's entries are instantly valid again, bitwise).
- :mod:`router` — the fan-out: per-class bounded queues feed a dispatcher
  that places each request on the least-loaded healthy replica (bounded
  per-replica in-flight — the micro-batcher backpressure idea, one level
  up), sheds on budget exhaustion or deadline expiry, health-probes every
  replica and evicts/respawns the unresponsive, retries requests stranded
  on a dead replica, and orchestrates ROLLING hot-swaps: ``reload`` walks
  the replicas one at a time (each keeps serving while its shadow
  generation compiles — that is the point of in-process hot-swap), so a
  fleet-wide model rollout never takes capacity below N-0.

The router is deliberately **jax-free**: it moves JSON dicts, never
tensors, so it adds microseconds — all device work stays in the workers.
``python -m code2vec_tpu.serve.fleet`` (or ``tools/fleet_serve.py``)
launches router + replicas; the client-facing transports are the same
stdio-JSONL/HTTP adapters single-process serving uses.
"""

from code2vec_tpu.serve.fleet.cache import (
    ResultCache,
    canonical_bag_digest,
    canonical_request_key,
)
from code2vec_tpu.serve.fleet.replica import ReplicaDied, ReplicaHandle
from code2vec_tpu.serve.fleet.router import FleetRouter
from code2vec_tpu.serve.fleet.slo import (
    DEFAULT_SLO,
    SloClass,
    classify_op,
    parse_slo_spec,
)

__all__ = [
    "DEFAULT_SLO",
    "FleetRouter",
    "ReplicaDied",
    "ReplicaHandle",
    "ResultCache",
    "SloClass",
    "canonical_bag_digest",
    "canonical_request_key",
    "classify_op",
    "parse_slo_spec",
]
