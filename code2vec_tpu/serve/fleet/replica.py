"""One replica worker behind a JSONL pipe: spawn, send, match, stop.

A replica is ``python -m code2vec_tpu.serve --transport stdio`` as a
subprocess. The stdio transport writes responses IN REQUEST ORDER, so the
client side needs no correlation ids: a FIFO deque of futures, appended
at write time and popped by the reader thread per response line, is the
whole matching protocol (the same discipline the stdio transport's own
tests pin). What this module owns:

- **spawn + readiness**: the worker compiles its AOT ladder before
  accepting traffic; :meth:`ReplicaHandle.wait_ready` rides a ``health``
  request through the pipe so the router only counts a replica as
  placeable once its executables exist.
- **bounded in-flight accounting**: ``in_flight`` is the pending-future
  count — the router's per-replica backpressure bound (the micro-batcher
  ``max_pending`` idea, one level up) and its least-loaded placement key.
- **death detection**: stdout EOF or a failed write marks the handle dead
  and fails every pending future with :class:`ReplicaDied` — the router
  retries those on a sibling and the prober respawns the slot.
- **graceful stop**: a ``shutdown`` op rides the FIFO behind everything
  already submitted (so the worker drains before exiting); a stubborn
  process gets SIGTERM (the worker's drain handler — satellite fix of
  this PR) and only then SIGKILL.

Trace contexts need no handling here: the router stamps the ``"trace"``
field into the request dict at admission and this client forwards the
dict verbatim over the pipe — the worker's resolver picks the id up on
the far side. ``last_health`` (refreshed by every probe) doubles as the
router's lock-light ``/metrics`` source for this replica;
``last_health_unix`` records when it was captured so scrapers can judge
staleness.

Per-replica metrics live under the ``fleet.r<slot>.`` namespace of the
shared obs registry (``RuntimeHealth.namespaced``): ``dispatched`` /
``responses`` / ``in_flight`` / ``deaths`` — one schema for the router's
decisions and the fleet health op.
"""

from __future__ import annotations

import collections
import json
import logging
import subprocess
import threading
import time
from concurrent.futures import Future

from code2vec_tpu.obs import handles
from code2vec_tpu.obs.runtime import RuntimeHealth, global_health
from code2vec_tpu.obs.sync import make_lock

logger = logging.getLogger(__name__)

__all__ = ["ReplicaDied", "ReplicaHandle"]


class ReplicaDied(RuntimeError):
    """The worker process is gone; pending requests need a new home."""


class ReplicaHandle:
    """Pipe client for one worker subprocess (see module docstring)."""

    def __init__(
        self,
        slot: int,
        argv: list[str],
        *,
        incarnation: int = 0,
        env: dict | None = None,
        health: RuntimeHealth | None = None,
        stderr=None,
    ) -> None:
        self.slot = int(slot)
        self.incarnation = int(incarnation)
        self.argv = list(argv)
        self._health = (health or global_health()).namespaced(
            f"fleet.r{self.slot}"
        )
        self._pending: collections.deque[Future] = collections.deque()
        self._plock = make_lock(f"replica.r{self.slot}.pending")
        self._wlock = make_lock(f"replica.r{self.slot}.write")
        self._dead = threading.Event()
        self.death_reason: str | None = None
        # prober bookkeeping (owned by the router's probe thread)
        self.probe_failures = 0
        self.last_health: dict | None = None
        self.last_health_unix: float | None = None
        self.started_unix = time.time()
        self._dispatched = self._health.counter("dispatched")
        self._responses = self._health.counter("responses")
        self._deaths = self._health.counter("deaths")
        self._inflight_gauge = self._health.gauge("in_flight")
        self._inflight_gauge.set(0)
        self._proc = subprocess.Popen(
            self.argv,
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            stderr=stderr,
            text=True,
            bufsize=1,  # line-buffered pipes: one request/response per line
            env=env,
        )
        self._reader = threading.Thread(
            target=self._read_loop,
            name=f"c2v-fleet-r{self.slot}-reader",
            daemon=True,
        )
        self._reader.start()
        handles.track(
            self, "replica", name=f"r{self.slot}#i{self.incarnation}"
        )

    # ---- state ----------------------------------------------------------
    @property
    def alive(self) -> bool:
        return not self._dead.is_set() and self._proc.poll() is None

    @property
    def in_flight(self) -> int:
        return len(self._pending)

    @property
    def pid(self) -> int:
        return self._proc.pid

    # ---- request path ---------------------------------------------------
    def send(self, request: dict) -> Future:
        """Write one request line; returns a Future resolving to the
        worker's response dict. Raises :class:`ReplicaDied` if the worker
        is gone (including a write that discovers it just died)."""
        future: Future = Future()
        line = json.dumps(request)
        with self._wlock:
            if not self.alive:
                raise ReplicaDied(
                    f"replica r{self.slot} is not running"
                    f" ({self.death_reason or 'process exited'})"
                )
            # append BEFORE the write: the reader matches responses FIFO,
            # and a response cannot precede its request's write
            with self._plock:
                self._pending.append(future)
            try:
                # pipe write under _wlock is the point of _wlock: it exists
                # to serialize writers so request lines interleave whole.
                # Blocking is bounded by the pipe buffer and the worker's
                # reader, which drains continuously; nothing that resolves
                # this write ever needs _wlock.
                self._proc.stdin.write(line + "\n")  # jaxlint: disable=CX003
                self._proc.stdin.flush()  # jaxlint: disable=CX003
            except (BrokenPipeError, OSError, ValueError) as exc:
                # nothing was (fully) written for THIS request — it is the
                # newest pending entry; remove it before failing the rest
                with self._plock:
                    if self._pending and self._pending[-1] is future:
                        self._pending.pop()
                self._fail(f"stdin write failed: {exc}")
                raise ReplicaDied(
                    f"replica r{self.slot} died on write: {exc}"
                ) from exc
        self._dispatched.inc()
        self._inflight_gauge.set(self.in_flight)
        return future

    def wait_ready(self, timeout: float) -> dict:
        """Block until the worker answers a health probe (its AOT ladder
        is compiled and it is accepting traffic)."""
        payload = self.send({"op": "health"}).result(timeout)
        self.last_health = payload
        self.last_health_unix = time.time()
        return payload

    # ---- reader ---------------------------------------------------------
    def _read_loop(self) -> None:
        try:
            for line in self._proc.stdout:
                line = line.strip()
                if not line:
                    continue
                try:
                    payload = json.loads(line)
                    if not isinstance(payload, dict):
                        raise ValueError("not an object")
                except ValueError:
                    payload = {
                        "error": f"unparseable replica line: {line[:200]}",
                        "error_kind": "internal",
                    }
                with self._plock:
                    future = (
                        self._pending.popleft() if self._pending else None
                    )
                if future is None:
                    logger.warning(
                        "replica r%d wrote an unsolicited line: %.120s",
                        self.slot, line,
                    )
                    continue
                self._responses.inc()
                self._inflight_gauge.set(self.in_flight)
                if not future.done():
                    future.set_result(payload)
        finally:
            self._fail("stdout closed")

    def _fail(self, reason: str) -> None:
        if self._dead.is_set():
            return
        self._dead.set()
        self.death_reason = reason
        self._deaths.inc()
        # every path out of a replica's life funnels through here exactly
        # once (stop/kill/crash all set _dead) — the ledger close point
        handles.untrack(self)
        with self._plock:
            stranded = list(self._pending)
            self._pending.clear()
        self._inflight_gauge.set(0)
        for future in stranded:
            if not future.done():
                future.set_exception(
                    ReplicaDied(f"replica r{self.slot}: {reason}")
                )
        if stranded:
            logger.warning(
                "replica r%d died (%s) with %d request(s) in flight",
                self.slot, reason, len(stranded),
            )

    # ---- stop -----------------------------------------------------------
    def stop(self, timeout: float = 30.0) -> None:
        """Graceful: shutdown op (drains the FIFO ahead of it), then
        SIGTERM (the worker's drain handler), then SIGKILL."""
        try:
            self.send({"op": "shutdown"})
        except ReplicaDied:
            pass
        try:
            self._proc.wait(timeout)
        except subprocess.TimeoutExpired:
            self.kill(timeout)
        self._fail("stopped")

    def kill(self, timeout: float = 10.0) -> None:
        """Eviction path: SIGTERM first — the worker drains accepted
        requests and exits 0 — escalate to SIGKILL only on a hang."""
        if self._proc.poll() is None:
            self._proc.terminate()
            try:
                self._proc.wait(timeout)
            except subprocess.TimeoutExpired:  # pragma: no cover - hung jax
                self._proc.kill()
                self._proc.wait()
        self._fail("killed")
