"""The fleet router: SLO-classed queues -> least-loaded healthy replica.

Request life cycle (all jax-free; the router moves dicts, never tensors):

0. **cache** (``--result_cache_mb``, cache.py): data requests first hit
   the content-addressed result cache — a repeat of a cached request
   (same canonical bag/source/vector + knobs, same generation version)
   resolves HERE, ahead of SLO admission: it consumes no queue budget and
   touches no replica. Concurrent identical misses coalesce onto the
   first one's future; a committed rolling swap flips the cache's active
   version (old entries stay resident) and ``rollback`` flips it back.
1. **admit**: ``handle_async`` classifies the op into its SLO class and
   enqueues into that class's bounded queue — a full queue sheds with a
   retryable ``overloaded`` error (the class's budget IS the admission
   bound; there is no global ``max_pending`` anymore).
2. **dispatch**: one dispatcher thread drains the class queues in tier
   priority (embed before neighbors; health-class control ops never
   queue — the router handles them inline at admission, which is how
   they cut through saturation), placing each request on the
   healthy replica with the fewest in-flight requests, bounded by
   ``per_replica_inflight`` (per-replica backpressure — the
   micro-batcher's bounded-queue idea one level up). A request still
   undispatched past its class deadline is shed with a ``deadline``
   error: serving it anyway would poison the queue for requests whose
   clients are still waiting.
3. **resolve**: the replica's FIFO future resolves the router future;
   per-class latency histograms and counters land in the shared obs
   registry (``slo.<class>.*``). A request stranded on a dying replica is
   retried on a sibling (inference ops are idempotent) up to
   ``retry_limit`` times before failing with ``unavailable``.

A **prober** thread health-checks every replica each
``probe_interval_s`` through the same pipes traffic uses (a probe stuck
behind a wedged queue is exactly the signal wanted); ``max_probe_failures``
consecutive misses evicts the replica — SIGTERM first, so its drain
handler resolves whatever it accepted — and respawns the slot with a
fresh incarnation.

**Rolling hot-swap**: the ``reload`` op walks replicas ONE AT A TIME,
driving each worker's in-process shadow-build/validate/commit
(``serve/swap.py``) and polling its ``swap_status`` until the commit —
each replica keeps serving its incumbent generation while its shadow
compiles, so fleet capacity never drops during a rollout; a replica that
fails validation aborts the roll with the rest of the fleet untouched.
``rollback`` fans the instant pointer-swap to every replica.
"""

from __future__ import annotations

import collections
import logging
import queue
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field

from code2vec_tpu.obs.runtime import (
    FlightRecorder,
    RuntimeHealth,
    global_health,
)
from code2vec_tpu.obs.handles import handles_snapshot
from code2vec_tpu.obs.sync import make_lock, sync_snapshot
from code2vec_tpu.obs.trace import ensure_trace, get_tracer
from code2vec_tpu.serve.fleet.cache import ResultCache
from code2vec_tpu.serve.fleet.replica import ReplicaDied
from code2vec_tpu.serve.fleet.slo import (
    DEFAULT_SLO,
    PRIORITY,
    SloBurnTracker,
    SloClass,
    classify_op,
)

logger = logging.getLogger(__name__)

__all__ = ["FleetRouter"]

# outcome kinds that burn SLO error budget: the fleet failed the client
# (shed, expired, unavailable, or a server-side error — wherever it arose);
# a bad_request is the client's mistake and burns nothing. Distinct from
# per-op error counting: the router only counts errors IT minted (the
# _Queued.router_error flag) — a worker-relayed error already counted in
# that replica's own registry, and counting it again here would make the
# aggregated /metrics series double-count
_BUDGET_BURNING_KINDS = frozenset(
    ("overloaded", "deadline", "unavailable", "closed", "internal",
     "swap_failed")
)


@dataclass
class _Queued:
    request: dict
    future: Future
    cls: str
    op: str | None = None
    trace_id: str | None = None
    enqueued: float = field(default_factory=time.perf_counter)
    depth: int = 0  # class-queue depth observed at admission
    dispatched: float | None = None
    slot: int | None = None
    attempts: int = 0
    # True when the ROUTER resolved this item with an error it minted
    # (deadline shed, unavailable, drain) — the per-op error counter
    # counts exactly these; worker-relayed errors are already counted in
    # the replica's own registry
    router_error: bool = False
    # result-cache bookkeeping: the versioned key this item leads or
    # coalesces on (None = cache off / uncacheable / mid-roll) and its
    # role — "miss" leads (fills on success), "coalesced" rides the
    # leader's future without ever touching a queue or replica
    cache_key: tuple | None = None
    cache_state: str | None = None

    @property
    def age_ms(self) -> float:
        return (time.perf_counter() - self.enqueued) * 1e3


class FleetRouter:
    """Fan requests over N replica slots (see module docstring).

    ``replica_factory(slot, incarnation) -> handle`` builds one worker
    client (:class:`~code2vec_tpu.serve.fleet.replica.ReplicaHandle` in
    production; tests inject in-process fakes). The router exposes the
    same ``handle``/``handle_async``/``shutdown_requested``/``close``
    surface as :class:`~code2vec_tpu.serve.protocol.CodeServer`, so the
    stdio/HTTP transport adapters work unchanged.
    """

    def __init__(
        self,
        replica_factory,
        n_replicas: int,
        *,
        slo: dict[str, SloClass] | None = None,
        health: RuntimeHealth | None = None,
        events=None,
        per_replica_inflight: int = 8,
        probe_interval_s: float = 2.0,
        probe_timeout_s: float = 60.0,
        max_probe_failures: int = 3,
        boot_timeout_s: float = 900.0,
        swap_timeout_s: float = 1800.0,
        retry_limit: int = 2,
        slo_objective: float = 0.999,
        slo_window_s: float = 60.0,
        flight: FlightRecorder | None = None,
        result_cache: ResultCache | None = None,
    ) -> None:
        if n_replicas < 1:
            raise ValueError(f"n_replicas must be >= 1, got {n_replicas}")
        if per_replica_inflight < 1:
            raise ValueError(
                f"per_replica_inflight must be >= 1, got "
                f"{per_replica_inflight}"
            )
        self._factory = replica_factory
        self._slo = dict(slo if slo is not None else DEFAULT_SLO)
        self.health = health or global_health()
        self._events = events
        self._cap = int(per_replica_inflight)
        self._probe_interval_s = float(probe_interval_s)
        self._probe_timeout_s = float(probe_timeout_s)
        self._max_probe_failures = int(max_probe_failures)
        self._boot_timeout_s = float(boot_timeout_s)
        self._swap_timeout_s = float(swap_timeout_s)
        self._retry_limit = int(retry_limit)

        self._queues: dict[str, queue.Queue] = {
            name: queue.Queue(maxsize=cls.budget)
            for name, cls in self._slo.items()
        }
        self._heads: dict[str, _Queued | None] = {
            name: None for name in self._slo
        }
        self._retries: collections.deque[_Queued] = collections.deque()
        self._wake = threading.Event()
        self._closed = threading.Event()
        self._shutdown = threading.Event()
        self._stop_probe = threading.Event()

        self._swap_lock = make_lock("router.swap")
        self._rolling: dict = {"state": "idle", "target": None,
                               "outcome": None, "replicas": []}
        self._rolling_thread: threading.Thread | None = None

        self._evictions = self.health.counter("fleet.evictions")
        self._respawns = self.health.counter("fleet.respawns")
        self._retried = self.health.counter("fleet.retries")
        self.health.gauge("fleet.replicas").set(int(n_replicas))

        # SLO error-budget burn accounting: every finished data request
        # records good/bad into its class's rolling window (slo.py) —
        # burn-rate gauges + the slo_budget_exhausted event ride the same
        # registry/event log as everything else
        self._burn = SloBurnTracker(
            [name for name in self._slo if name != "health"],
            objective=slo_objective, window_s=slo_window_s,
            health=self.health, events=events,
        )
        # slow-request flight recorder: a shed or tail-latency request
        # leaves a concrete per-request timeline, not just a histogram
        self._flight = flight
        # content-addressed result cache (cache.py): hits resolve at
        # admission, ahead of SLO queues and replicas; None = disabled
        self._cache = result_cache
        self._version_seq = 0

        # ---- boot the fleet (parallel: each worker compiles its ladder)
        self._slots: list = [None] * int(n_replicas)
        errors: list = [None] * int(n_replicas)

        def boot(slot: int) -> None:
            try:
                self._slots[slot] = self._spawn(slot, incarnation=0)
            except Exception as exc:  # noqa: BLE001 - re-raised below
                errors[slot] = exc

        threads = [
            threading.Thread(target=boot, args=(i,), daemon=True)
            for i in range(int(n_replicas))
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        failed = [i for i, e in enumerate(errors) if e is not None]
        if failed:
            for handle in self._slots:
                if handle is not None:
                    try:
                        handle.stop(timeout=10.0)
                    except Exception:  # noqa: BLE001 - teardown best-effort
                        pass
            raise RuntimeError(
                f"replica slot(s) {failed} failed to boot: "
                f"{[str(errors[i]) for i in failed]}"
            )

        if self._cache is not None:
            # seed the cache's version from the fleet's actual serving
            # generation (every replica booted the same checkpoint); a
            # factory whose readiness payload carries no version keeps
            # the cache's own default
            for handle in self._slots:
                version = (getattr(handle, "last_health", None) or {}).get(
                    "version"
                )
                if version:
                    self._cache.set_version(version)
                    break

        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, name="c2v-fleet-dispatch", daemon=True
        )
        self._dispatcher.start()
        self._prober = threading.Thread(
            target=self._probe_loop, name="c2v-fleet-probe", daemon=True
        )
        self._prober.start()

    # ---- spawn / respawn ------------------------------------------------
    def _spawn(self, slot: int, incarnation: int):
        handle = self._factory(slot, incarnation)
        handle.wait_ready(self._boot_timeout_s)
        logger.info(
            "replica r%d (incarnation %d) is ready", slot, incarnation
        )
        self._emit(
            "fleet_replica_spawned", slot=slot, incarnation=incarnation,
            pid=getattr(handle, "pid", None),
        )
        return handle

    def _emit(self, event: str, **fields) -> None:
        if self._events is not None:
            try:
                self._events.emit(event, **fields)
            except Exception:  # pragma: no cover - closed log
                logger.warning("could not emit %s", event, exc_info=True)

    # ---- CodeServer-compatible surface ----------------------------------
    @property
    def shutdown_requested(self) -> bool:
        return self._shutdown.is_set()

    def request_shutdown(self) -> None:
        self._shutdown.set()

    def handle(self, request: dict) -> dict:
        resolver = self.handle_async(request)
        try:
            return resolver()
        except Exception as exc:  # noqa: BLE001 - protocol boundary
            from code2vec_tpu.serve.protocol import CodeServer

            return CodeServer._error_payload(exc)

    def handle_async(self, request: dict):
        req_id = request.get("id")

        def finish(payload: dict) -> dict:
            if req_id is not None:
                payload = {"id": req_id, **payload}
            return payload

        op = request.get("op")
        cls_name = classify_op(op)
        if cls_name is None:
            payload = {"error": f"unknown op {op!r}",
                       "error_kind": "bad_request"}
            return lambda: finish(payload)
        if self._closed.is_set():
            payload = {"error": "fleet router is shutting down",
                       "error_kind": "closed"}
            return lambda: finish(payload)

        # control plane handled in the router itself
        if op == "health":
            # resolve-time snapshot, like the single-process server
            return lambda: finish(self._fleet_health())
        if op == "shutdown":
            self._shutdown.set()
            return lambda: finish({"ok": True, "shutting_down": True})
        if op == "reload":
            try:
                payload = self._start_rolling(request)
            except ValueError as exc:
                payload = {"error": str(exc), "error_kind": "bad_request"}
            return lambda: finish(payload)
        if op == "rollback":
            payload = self._fleet_rollback()
            return lambda: finish(payload)
        if op == "swap_status":
            payload = self._fleet_swap_status()
            return lambda: finish(payload)
        if op == "flights":
            payload = self._fleet_flights()
            return lambda: finish(payload)

        # data plane: stamp (or honor) the request's trace context FIRST —
        # the same dict crosses the replica pipe, so the worker's spans
        # inherit the id with no extra wiring — then consult the result
        # cache AHEAD of SLO admission (a hit never consumes queue budget
        # or touches a replica; sheds cannot starve cacheable traffic) —
        # then admit into the class queue (budget = admission bound)
        t0 = time.perf_counter()
        trace = ensure_trace(request)
        self.health.counter(f"serve.op.{op}.requests").inc()
        cache_key = (
            self._cache.key_for(request) if self._cache is not None else None
        )
        cache_state = None
        if cache_key is not None:
            state, held = self._cache.begin(cache_key)
            if state == "hit":
                payload = held
                self.health.counter(f"slo.{cls_name}.cache_hits").inc()
                self._burn.record(cls_name, good=True)
                now = time.perf_counter()
                tracer = get_tracer()
                if tracer.enabled:
                    tracer.span_complete(
                        "fleet_request", category="fleet",
                        start_s=t0, end_s=now,
                        trace_id=trace.trace_id, op=op, slo_class=cls_name,
                        outcome="ok", cache_hit=True,
                        cache_version=cache_key[0],
                    )
                if self._flight is not None:
                    self._flight.observe((now - t0) * 1e3, {
                        "kind": "router",
                        "trace_id": trace.trace_id,
                        "op": op,
                        "slo_class": cls_name,
                        "outcome": "ok",
                        "cache_hit": True,
                        "cache_version": cache_key[0],
                    })
                return lambda: finish(payload)
            if state == "join":
                # coalesced miss: ride the leader's in-flight future —
                # no queue budget, no replica, one device call for the
                # whole herd. _finalize still runs for burn accounting
                # and the span/flight breakdown.
                item = _Queued(
                    request=request, future=Future(), cls=cls_name, op=op,
                    trace_id=trace.trace_id, cache_key=cache_key,
                    cache_state="coalesced",
                )
                item.future.add_done_callback(
                    lambda fut, item=item: self._finalize(item, fut)
                )
                held.add_done_callback(
                    lambda fut, item=item: (
                        item.future.set_result(fut.result())
                        if not item.future.done() else None
                    )
                )
                return lambda: finish(item.future.result())
            cache_state = "miss"  # this request leads; _finalize fills
        item = _Queued(
            request=request, future=Future(), cls=cls_name, op=op,
            trace_id=trace.trace_id,
            depth=self._queues[cls_name].qsize(),
            cache_key=cache_key, cache_state=cache_state,
        )
        self.health.counter(f"slo.{cls_name}.submitted").inc()
        try:
            self._queues[cls_name].put_nowait(item)
        except queue.Full:
            self.health.counter(f"slo.{cls_name}.shed_budget").inc()
            # the shed never reaches a worker's resolver: count it into
            # the per-op error counter HERE or 429s stay invisible per op
            self.health.counter(f"serve.op.{op}.errors").inc()
            self._burn.record(cls_name, good=False)
            slo = self._slo[cls_name]
            payload = {
                "error": (
                    f"{cls_name} queue budget ({slo.budget}) exhausted — "
                    "shed; retry with backoff"
                ),
                "error_kind": "overloaded",
                "slo_class": cls_name,
            }
            if item.cache_key is not None:
                # this item led a coalesced miss: hand joiners the shed
                # payload (they attached to THIS attempt) without caching
                self._cache.abandon(item.cache_key, payload)
            tracer = get_tracer()
            if tracer.enabled:
                tracer.span_complete(
                    "fleet_request", category="fleet",
                    start_s=item.enqueued, end_s=time.perf_counter(),
                    trace_id=trace.trace_id, op=op, slo_class=cls_name,
                    outcome="overloaded",
                )
            return lambda: finish(payload)
        item.future.add_done_callback(
            lambda fut, item=item: self._finalize(item, fut)
        )
        self.health.gauge(f"slo.{cls_name}.queued").set(
            self._queues[cls_name].qsize()
        )
        self._wake.set()
        return lambda: finish(item.future.result())

    def _finalize(self, item: _Queued, fut: Future) -> None:
        """One exit point for every admitted data request (served, shed on
        deadline, failed, drained): per-request router span tagged with
        the trace id, SLO burn accounting, per-op error visibility, and
        the flight-recorder breakdown. O(1) dict work per request."""
        payload = fut.result()  # router futures always resolve to a dict
        kind = payload.get("error_kind") if isinstance(payload, dict) else None
        now = time.perf_counter()
        if item.cache_key is not None and item.cache_state == "miss":
            # leader exit: cache the exact payload (pre-"id" — every
            # future hit re-stamps its own correlation id) or, on any
            # error, resolve joiners without caching so the next
            # identical request retries cold
            if kind is None and isinstance(payload, dict) and not payload.get(
                "error"
            ):
                self._cache.fill(item.cache_key, payload)
            else:
                self._cache.abandon(item.cache_key, payload)
        if item.router_error:
            # ROUTER-minted outcomes never reached a worker resolver —
            # without this the per-op error counters undercount sheds.
            # Worker-relayed errors are deliberately NOT counted here:
            # the replica already counted them in its own registry, and
            # the /metrics aggregation would otherwise show them twice
            self.health.counter(f"serve.op.{item.op}.errors").inc()
        self._burn.record(item.cls, good=kind not in _BUDGET_BURNING_KINDS)
        cache_tags = {}
        if self._cache is not None:
            cache_tags = {
                "cache_hit": False,
                "cache_version": (
                    item.cache_key[0] if item.cache_key is not None else None
                ),
            }
            if item.cache_state == "coalesced":
                cache_tags["cache_coalesced"] = True
        tracer = get_tracer()
        if tracer.enabled:
            tracer.span_complete(
                "fleet_request", category="fleet",
                start_s=item.enqueued, end_s=now,
                trace_id=item.trace_id, op=item.op, slo_class=item.cls,
                outcome=kind or "ok", slot=item.slot, **cache_tags,
            )
        if self._flight is not None:
            dispatch_wait_ms = (
                (item.dispatched - item.enqueued) * 1e3
                if item.dispatched is not None
                else None
            )
            self._flight.observe((now - item.enqueued) * 1e3, {
                "kind": "router",
                "trace_id": item.trace_id,
                "op": item.op,
                "slo_class": item.cls,
                "outcome": kind or "ok",
                "queue_depth_at_admission": item.depth,
                "dispatch_wait_ms": (
                    round(dispatch_wait_ms, 3)
                    if dispatch_wait_ms is not None else None
                ),
                "replica_slot": item.slot,
                "attempts": item.attempts,
                **cache_tags,
            })

    # ---- dispatch -------------------------------------------------------
    def _pick_replica(self):
        """Healthy replica with the fewest in-flight requests, below the
        per-replica bound; None when every replica is full or dead."""
        best = None
        for handle in self._slots:
            if handle is None or not handle.alive:
                continue
            if handle.in_flight >= self._cap:
                continue
            if best is None or handle.in_flight < best.in_flight:
                best = handle
        return best

    def _any_alive(self) -> bool:
        return any(h is not None and h.alive for h in self._slots)

    def _shed_deadline(self, item: _Queued) -> None:
        self.health.counter(f"slo.{item.cls}.shed_deadline").inc()
        slo = self._slo[item.cls]
        item.router_error = True
        item.future.set_result({
            "error": (
                f"{item.cls} deadline ({slo.deadline_ms:.0f} ms) exceeded "
                f"before dispatch (waited {item.age_ms:.0f} ms) — shed"
            ),
            "error_kind": "deadline",
            "slo_class": item.cls,
        })

    def _fail_item(
        self, item: _Queued, reason: str, kind: str = "unavailable"
    ) -> None:
        self.health.counter(f"slo.{item.cls}.failed").inc()
        item.router_error = True
        if not item.future.done():
            item.future.set_result({
                "error": reason,
                "error_kind": kind,
                "slo_class": item.cls,
            })

    def _next_item(self, cls: str) -> _Queued | None:
        head = self._heads[cls]
        if head is not None:
            return head
        try:
            item = self._queues[cls].get_nowait()
        except queue.Empty:
            return None
        self.health.gauge(f"slo.{cls}.queued").set(
            self._queues[cls].qsize()
        )
        self._heads[cls] = item
        return item

    def _dispatch_once(self) -> bool:
        """One placement attempt across the tiers; True if any progress
        (dispatch or shed) was made."""
        # stranded retries first — their original admission already waited
        while self._retries:
            item = self._retries.popleft()
            if item.age_ms > self._slo[item.cls].deadline_ms:
                self._shed_deadline(item)
                return True
            if item.attempts > self._retry_limit:
                self._fail_item(
                    item,
                    f"request failed on {item.attempts} replica(s) — "
                    "fleet unavailable",
                )
                return True
            replica = self._pick_replica()
            if replica is None:
                if self._closed.is_set() and not self._any_alive():
                    self._fail_item(item, "no replica alive during drain")
                    return True
                self._retries.appendleft(item)
                break
            if self._dispatch(item, replica):
                return True
            # the picked replica died at write time — it is no longer
            # `alive`, so the next pass picks a sibling
            self._retries.appendleft(item)
        for cls in PRIORITY:
            if cls not in self._heads:
                continue
            item = self._next_item(cls)
            if item is None:
                continue
            if item.age_ms > self._slo[cls].deadline_ms:
                self._heads[cls] = None
                self._shed_deadline(item)
                return True
            replica = self._pick_replica()
            if replica is None:
                if self._closed.is_set() and not self._any_alive():
                    # draining with a dead fleet: failing loudly beats a
                    # future that never resolves
                    self._heads[cls] = None
                    self._fail_item(item, "no replica alive during drain")
                    return True
                continue
            if self._dispatch(item, replica):
                self._heads[cls] = None
                return True
        return False

    def _dispatch(self, item: _Queued, replica) -> bool:
        try:
            inner = replica.send(item.request)
        except ReplicaDied:
            # no work reached a worker — not a retry attempt; the deadline
            # bounds how long the item can keep looking for a replica
            return False
        item.dispatched = time.perf_counter()
        item.slot = getattr(replica, "slot", None)
        inner.add_done_callback(
            lambda fut, item=item, replica=replica: self._on_reply(
                item, replica, fut
            )
        )
        return True

    def _on_reply(self, item: _Queued, replica, fut) -> None:
        exc = fut.exception()
        if exc is not None:
            # stranded on a dying replica — inference ops are idempotent,
            # so retry on a sibling instead of surfacing the eviction
            item.attempts += 1
            self._retried.inc()
            self._retries.append(item)
            self._wake.set()
            return
        payload = fut.result()
        self.health.latency(f"slo.{item.cls}.e2e_ms").record(item.age_ms)
        self.health.counter(f"slo.{item.cls}.completed").inc()
        if not item.future.done():
            item.future.set_result(payload)

    def _queues_empty(self) -> bool:
        return (
            not self._retries
            and all(h is None for h in self._heads.values())
            and all(q.qsize() == 0 for q in self._queues.values())
        )

    def _dispatch_loop(self) -> None:
        while True:
            if self._dispatch_once():
                continue
            if self._closed.is_set() and self._queues_empty():
                return
            self._wake.wait(0.005)
            self._wake.clear()

    # ---- health probing / eviction --------------------------------------
    def _probe_loop(self) -> None:
        # one probe thread PER SLOT per cycle: a wedged replica blocks its
        # own probe (up to probe_timeout_s) without delaying detection on
        # any sibling; a slot whose probe/respawn is still running is
        # simply skipped this cycle
        busy = [False] * len(self._slots)

        def probe(slot: int) -> None:
            try:
                self._probe_slot(slot)
            finally:
                busy[slot] = False

        while not self._stop_probe.wait(self._probe_interval_s):
            for slot in range(len(self._slots)):
                if self._stop_probe.is_set():
                    return
                if busy[slot]:
                    continue
                busy[slot] = True
                threading.Thread(
                    target=probe, args=(slot,),
                    name=f"c2v-fleet-probe-r{slot}", daemon=True,
                ).start()

    def _probe_slot(self, slot: int) -> None:
        handle = self._slots[slot]
        if handle is None:
            return
        if not handle.alive:
            self._evict(slot, reason=handle.death_reason or "process exited")
            return
        try:
            payload = handle.send({"op": "health"}).result(
                self._probe_timeout_s
            )
            handle.last_health = payload
            handle.last_health_unix = time.time()
            handle.probe_failures = 0
        except Exception as exc:  # noqa: BLE001 - timeout or death
            handle.probe_failures += 1
            logger.warning(
                "replica r%d missed health probe %d/%d: %s",
                slot, handle.probe_failures, self._max_probe_failures, exc,
            )
            if handle.probe_failures >= self._max_probe_failures:
                self._evict(slot, reason=f"missed {handle.probe_failures} "
                            "consecutive health probes")

    def _evict(self, slot: int, reason: str) -> None:
        handle = self._slots[slot]
        self._evictions.inc()
        # leak-on-crash preflight: the dead incarnation's last prober-cached
        # handle-ledger block rides the eviction event, so a replica that
        # died leaking shows its open-handle count without a log dive
        last = getattr(handle, "last_health", None) or {}
        dead_handles = last.get("handles") or {}
        if dead_handles.get("open_total"):
            logger.warning(
                "replica r%d died with %d ledger-open handle(s): %s",
                slot, dead_handles["open_total"], dead_handles.get("open"),
            )
        logger.warning("evicting replica r%d: %s", slot, reason)
        self._emit(
            "fleet_replica_evicted", slot=slot,
            incarnation=getattr(handle, "incarnation", None), reason=reason,
            open_handles=dead_handles.get("open_total"),
            open_handles_by_kind=dead_handles.get("open"),
        )
        try:
            handle.kill()  # SIGTERM first: the worker drains, then exits
        except Exception:  # noqa: BLE001 - already gone
            pass
        if self._closed.is_set():
            return
        incarnation = getattr(handle, "incarnation", 0) + 1
        try:
            self._slots[slot] = self._spawn(slot, incarnation)
            self._respawns.inc()
            self._wake.set()
        except Exception as exc:  # noqa: BLE001 - retried next probe cycle
            logger.error(
                "respawn of replica r%d failed (%s); retrying next probe "
                "cycle", slot, exc,
            )

    # ---- fleet control plane --------------------------------------------
    def _fleet_health(self) -> dict:
        replicas = []
        for slot, handle in enumerate(self._slots):
            if handle is None:
                replicas.append({"slot": slot, "alive": False})
                continue
            last = handle.last_health or {}
            replicas.append({
                "slot": slot,
                "incarnation": handle.incarnation,
                "pid": getattr(handle, "pid", None),
                "alive": handle.alive,
                "in_flight": handle.in_flight,
                "probe_failures": handle.probe_failures,
                "last_health_unix": getattr(
                    handle, "last_health_unix", None
                ),
                "version": last.get("version"),
                "post_warmup_compiles": last.get("post_warmup_compiles"),
                "executables": last.get("executables"),
                # device-time/MFU block (engine.perf_summary, cached by
                # the prober) — the per-replica truth behind fleet.capacity
                "perf": last.get("perf"),
                # lock-sanitizer block from the worker's own health
                # payload: enabled flag + order-violation count
                "sync": last.get("sync"),
                # handle-ledger block from the worker: per-kind open
                # counts — a count climbing across swaps is a leak
                "handles": last.get("handles"),
            })
        return {
            "ok": all(r.get("alive") for r in replicas),
            "fleet": {
                "replicas": replicas,
                "slo": {
                    name: {
                        "budget": cls.budget,
                        "deadline_ms": cls.deadline_ms,
                        "queued": self._queues[name].qsize(),
                    }
                    for name, cls in self._slo.items()
                },
                # rolling error-budget state per class: burn rate, window
                # good/bad, exhaustion — the numbers /metrics exports as
                # slo.<class>.burn_rate / budget_remaining gauges
                "slo_burn": self._burn.snapshot(),
                "rolling": self._rolling_status(),
                # result-cache block: hit/miss/coalesced counters, byte
                # accounting, and per-version resident entry counts (the
                # same numbers /metrics exports as c2v_cache_* series)
                "cache": (
                    self._cache.stats() if self._cache is not None else None
                ),
                "flight_recorded": (
                    self._flight.count if self._flight is not None else None
                ),
                # max-sustainable-QPS model from the replicas' perf blocks
                # (device-ms/request × observed mix) — the autoscaling
                # control signal; None until device time has been observed
                "capacity": self._capacity_block(),
                # the ROUTER's own lock-sanitizer snapshot (router.swap /
                # fleet.cache / fleet.slo locks); each replica row above
                # carries the worker-side block
                "sync": sync_snapshot(),
                # the ROUTER's own handle ledger (replica handles, the
                # flight recorder, the event log)
                "handles": handles_snapshot(),
            },
            **self.health.snapshot(),
        }

    def _capacity_block(self) -> dict | None:
        """Fleet capacity from the prober's cached per-replica ``perf``
        blocks — never crosses a pipe. Mirrored into router gauges so
        /metrics carries ``c2v_fleet_capacity_qps`` alongside health."""
        from code2vec_tpu.obs.costs import fleet_capacity

        perfs = []
        alive = 0
        for handle in self._slots:
            if handle is None or not handle.alive:
                continue
            alive += 1
            last = handle.last_health
            perfs.append(last.get("perf") if isinstance(last, dict) else None)
        capacity = fleet_capacity(perfs, alive=alive)
        if capacity is not None:
            self.health.gauge("fleet.capacity_qps").set(
                capacity["max_qps_fleet"]
            )
            self.health.gauge("fleet.capacity_qps_per_replica").set(
                capacity["max_qps_per_replica"]
            )
            self.health.gauge("fleet.capacity_device_ms_per_request").set(
                capacity["device_ms_per_request"]
            )
        return capacity

    def metrics_text(self) -> str:
        """Prometheus text exposition for ``GET /metrics`` on the router:
        the router's own registry unlabeled, plus each replica's last
        health snapshot under a ``replica="r<slot>"`` label. Lock-light by
        construction — replica blocks come from the prober's cached
        ``last_health`` payloads (already plain dicts), so a scrape never
        crosses the pipe, takes a replica lock, or touches device state.
        Each replica block carries its own ``started_unix`` /
        ``snapshot_seq``, so scrapers can detect counter resets across
        respawns."""
        from code2vec_tpu.obs.runtime import build_info_text, prometheus_text

        # refresh the capacity gauges from the cached perf blocks so a
        # metrics-only consumer sees the same signal as /health
        self._capacity_block()
        sources = [({}, self.health.snapshot())]
        for slot, handle in enumerate(self._slots):
            if handle is None:
                continue
            last = handle.last_health
            if not isinstance(last, dict) or "counters" not in last:
                continue
            snap = {
                key: last[key]
                for key in (
                    "started_unix", "snapshot_seq", "counters",
                    "gauges", "latencies_ms",
                )
                if key in last
            }
            captured_unix = getattr(handle, "last_health_unix", None)
            if captured_unix is not None:
                # when this replica's block was captured — the scrape's
                # staleness signal (probe-refreshed, not scrape-time)
                snap["gauges"] = {
                    **(snap.get("gauges") or {}),
                    "replica_last_health_unix": captured_unix,
                }
            sources.append(({"replica": f"r{slot}"}, snap))
        return build_info_text({"role": "router"}) + prometheus_text(sources)

    def _rolling_status(self) -> dict:
        with self._swap_lock:
            return {
                "state": self._rolling["state"],
                "target": self._rolling["target"],
                "outcome": self._rolling["outcome"],
                "replicas": list(self._rolling["replicas"]),
            }

    def _start_rolling(self, request: dict) -> dict:
        target = request.get("model_path")
        wait = bool(request.get("wait", False))
        with self._swap_lock:
            if (
                self._rolling_thread is not None
                and self._rolling_thread.is_alive()
            ):
                raise ValueError(
                    "a rolling swap is already in progress "
                    f"(target={self._rolling['target']!r})"
                )
            self._rolling = {"state": "running", "target": target,
                             "outcome": None, "replicas": []}
            if self._cache is not None:
                # mid-roll the fleet is mixed-version: the cache stands
                # down (no hits, no fills) until the outcome is known
                self._cache.begin_swap()
            self._rolling_thread = threading.Thread(
                target=self._rolling_swap, args=(target,),
                name="c2v-fleet-rolling-swap", daemon=True,
            )
            thread = self._rolling_thread
        self._emit("fleet_swap_started", target=target)
        thread.start()
        if wait:
            thread.join()
        status = self._rolling_status()
        payload: dict = {"ok": status["outcome"] != "failed",
                         "rolling": status}
        if status["outcome"] == "failed":
            failures = [
                r for r in status["replicas"] if r.get("outcome") == "failed"
            ]
            payload["error"] = (
                failures[0].get("error", "rolling swap failed")
                if failures else "rolling swap failed"
            )
            payload["error_kind"] = "swap_failed"
        return payload

    def _rolling_swap(self, target) -> None:
        """ONE replica at a time: drive its in-process hot-swap and poll
        its state machine to completion before touching the next — the
        fleet never has more than one replica compiling a shadow, and a
        validation failure stops the roll with the rest untouched."""
        outcome = "committed"
        per_replica: list[dict] = []
        for slot in range(len(self._slots)):
            handle = self._slots[slot]
            if handle is None or not handle.alive:
                per_replica.append({"slot": slot, "outcome": "skipped_dead"})
                continue
            entry: dict = {"slot": slot, "incarnation": handle.incarnation}
            try:
                response = handle.send(
                    {"op": "reload", "model_path": target}
                ).result(self._swap_timeout_s)
                if response.get("error"):
                    raise RuntimeError(response["error"])
                deadline = time.monotonic() + self._swap_timeout_s
                while True:
                    status = handle.send({"op": "swap_status"}).result(
                        self._swap_timeout_s
                    )
                    swap = status.get("swap", {})
                    if swap.get("state") == "idle":
                        last = swap.get("last_swap") or {}
                        if last.get("outcome") != "committed":
                            raise RuntimeError(
                                "replica swap failed: "
                                f"{last.get('error', 'unknown error')}"
                            )
                        entry["outcome"] = "committed"
                        entry["version"] = last.get("version")
                        entry["build_ms"] = last.get("build_ms")
                        entry["validate_ms"] = last.get("validate_ms")
                        break
                    if time.monotonic() > deadline:
                        raise RuntimeError(
                            f"swap did not finish within "
                            f"{self._swap_timeout_s:.0f} s"
                        )
                    time.sleep(0.25)
            except Exception as exc:  # noqa: BLE001 - abort the roll
                entry["outcome"] = "failed"
                entry["error"] = str(exc)
                per_replica.append(entry)
                outcome = "failed"
                logger.warning(
                    "rolling swap aborted at replica r%d: %s", slot, exc
                )
                break
            per_replica.append(entry)
            with self._swap_lock:
                self._rolling["replicas"] = list(per_replica)
        with self._swap_lock:
            self._rolling = {"state": "idle", "target": target,
                             "outcome": outcome, "replicas": per_replica}
        if self._cache is not None:
            if outcome == "committed":
                # flip the active version forward: the old generation's
                # entries stay resident (rollback revalidates them
                # bitwise) but stop being visible. A commit whose version
                # is unreported gets a fresh unique label — serving the
                # OLD entries against NEW weights would be wrong.
                versions = [
                    e.get("version") for e in per_replica if e.get("version")
                ]
                self._cache.end_swap(
                    version=versions[-1] if versions
                    else self._fresh_version(target)
                )
            else:
                # the roll failed with the incumbent generation intact:
                # its entries never stopped being true
                self._cache.end_swap()
        self._emit(
            "fleet_swap_committed" if outcome == "committed"
            else "fleet_swap_failed",
            target=target, replicas=per_replica,
        )

    def _fleet_rollback(self) -> dict:
        """Fan the instant pointer-swap to every live replica."""
        with self._swap_lock:
            if (
                self._rolling_thread is not None
                and self._rolling_thread.is_alive()
            ):
                return {
                    "error": "cannot roll back during a rolling swap",
                    "error_kind": "bad_request",
                }
        results = []
        ok = True
        for slot, handle in enumerate(self._slots):
            if handle is None or not handle.alive:
                results.append({"slot": slot, "outcome": "skipped_dead"})
                continue
            try:
                response = handle.send({"op": "rollback"}).result(
                    self._probe_timeout_s
                )
            except Exception as exc:  # noqa: BLE001 - per-replica report
                response = {"error": str(exc)}
            if response.get("error"):
                ok = False
                results.append({"slot": slot, "outcome": "failed",
                                "error": response["error"]})
            else:
                results.append({
                    "slot": slot,
                    "outcome": "rolled_back",
                    "version": (response.get("swap") or {}).get(
                        "active_version"
                    ),
                })
        if self._cache is not None:
            versions = {
                r.get("version")
                for r in results
                if r.get("outcome") == "rolled_back"
            }
            if ok and len(versions) == 1 and None not in versions:
                # the whole fleet agreed on the restored generation: flip
                # the cache back — that generation's entries (retained
                # across the commit) are instantly valid again, bitwise
                self._cache.set_version(versions.pop())
            else:
                # partial/ambiguous rollback: no version label is
                # truthful for the whole fleet — go cold under a fresh
                # unique version rather than risk a wrong hit
                self._cache.set_version(
                    self._fresh_version("post_rollback")
                )
        self._emit("fleet_rollback", replicas=results)
        return {"ok": ok, "replicas": results}

    def _fresh_version(self, hint) -> str:
        """A unique never-hits-anything version label for states where
        the fleet's true generation is unknown (unreported commit,
        partial rollback): correctness over hit rate."""
        self._version_seq += 1
        return f"{hint or 'unknown'}@seq{self._version_seq}"

    def _fleet_swap_status(self) -> dict:
        per_replica = []
        for slot, handle in enumerate(self._slots):
            if handle is None or not handle.alive:
                per_replica.append({"slot": slot, "alive": False})
                continue
            try:
                status = handle.send({"op": "swap_status"}).result(
                    self._probe_timeout_s
                )
                per_replica.append({"slot": slot,
                                    "swap": status.get("swap")})
            except Exception as exc:  # noqa: BLE001 - per-replica report
                per_replica.append({"slot": slot, "error": str(exc)})
        return {
            "ok": True,
            "rolling": self._rolling_status(),
            "replicas": per_replica,
        }

    def _fleet_flights(self) -> dict:
        """Live flight-recorder fan-out: the router's own captured
        records plus each alive replica's, fetched over the control pipe
        (same per-replica error isolation as ``swap_status``)."""
        per_replica = []
        for slot, handle in enumerate(self._slots):
            if handle is None or not handle.alive:
                per_replica.append({"slot": slot, "alive": False})
                continue
            try:
                payload = handle.send({"op": "flights"}).result(
                    self._probe_timeout_s
                )
                per_replica.append({
                    "slot": slot,
                    "recorded": payload.get("recorded"),
                    "seen": payload.get("seen"),
                    "flights": payload.get("flights") or [],
                })
            except Exception as exc:  # noqa: BLE001 - per-replica report
                per_replica.append({"slot": slot, "error": str(exc)})
        router_flights = (
            self._flight.snapshot() if self._flight is not None else []
        )
        return {
            "ok": True,
            "router": {
                "recorded": (
                    self._flight.count if self._flight is not None else 0
                ),
                "flights": router_flights,
            },
            "replicas": per_replica,
        }

    # ---- lifecycle ------------------------------------------------------
    def close(self, timeout: float = 60.0) -> None:
        """Drain the class queues through the fleet, then stop every
        replica gracefully. Idempotent."""
        self._closed.set()
        self._wake.set()
        self._dispatcher.join(timeout)
        self._stop_probe.set()
        self._prober.join(self._probe_interval_s + 5.0)
        rolling = self._rolling_thread
        if rolling is not None and rolling.is_alive():
            rolling.join(timeout)
        threads = []
        for handle in self._slots:
            if handle is None:
                continue
            t = threading.Thread(
                target=handle.stop, kwargs={"timeout": timeout}, daemon=True
            )
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout)
        # final sweep: an item admitted in the close race, or re-queued by
        # a late replica-death callback AFTER the dispatcher exited, can
        # never be dispatched — resolve it loudly instead of stranding its
        # caller on a future that never completes (the same poll-gap class
        # the micro-batcher's close fix covers one level down)
        leftovers = list(self._retries)
        self._retries.clear()
        # lockless by design: the dispatcher (the only other _heads writer)
        # was joined above, so this sweep runs single-threaded
        for cls, head in self._heads.items():  # jaxlint: disable=CX001
            if head is not None:
                leftovers.append(head)
                self._heads[cls] = None
        for q in self._queues.values():
            while True:
                try:
                    leftovers.append(q.get_nowait())
                except queue.Empty:
                    break
        for item in leftovers:
            self._fail_item(
                item, "fleet router closed before dispatch", kind="closed"
            )
        if self._flight is not None:
            self._flight.close()

    def __enter__(self) -> "FleetRouter":
        return self

    def __exit__(self, *exc_info) -> None:
        self.close()
