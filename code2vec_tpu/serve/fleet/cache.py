"""Router-level content-addressed result cache.

Real embedding traffic is heavily Zipfian — popular files re-indexed,
retried requests, fan-out consumers asking for the same method — yet
without this module every repeat request through the fleet costs a
device call. This is the "compute once, O(1) thereafter" serving thesis
applied at the REQUEST tier: the router already moves exact response
payloads (plain dicts, never tensors), so a repeat request can be served
from router memory without consuming SLO queue budget or touching a
replica. Three properties make that safe and effective:

- **content addressing, not request addressing.** The key is a canonical
  digest of what the request MEANS, not of its bytes: a path-context bag
  is digested as an order-invariant MULTISET of ``[start, path, end]``
  triples (sorted rows), so a permuted resend of the same method hits;
  op-relevant knobs (``top_k``, ``granularity``, ``include_vector``, …)
  fold into the key while correlation fields (``id``, ``trace``) never
  do — the router re-stamps those per response.

- **S3-FIFO eviction, byte-accounted.** A small probationary FIFO
  (~10% of capacity) absorbs new keys; only entries re-referenced while
  probationary promote into the main queue, so one-hit wonders — the
  bulk of a Zipf tail — wash through without displacing the hot set.
  Keys evicted from the small queue leave a GHOST (key only, no value);
  a ghost's return re-inserts directly into main. Main evicts lazily:
  a re-referenced entry gets its frequency decremented and re-queued
  instead of dying. Capacity is bytes of cached payloads
  (``--result_cache_mb``), not entry count — responses vary 100x in
  size and a count bound would be meaningless.

- **versioned invalidation, never a flush.** Every key embeds the fleet
  generation version active AT ADMISSION. A committed rolling swap flips
  the active version — old entries become instantly invisible (misses
  recompute against the new weights) but stay resident until eviction;
  ``rollback`` flips the version back and the old generation's entries
  are valid again BITWISE, because entries are the exact payloads those
  weights produced. While a roll is in progress the cache stands down
  entirely (no hits, no fills): mid-roll the fleet is mixed-version and
  no single version label would be truthful.

Concurrent identical misses COALESCE: the first becomes the leader (it
is the one that enqueues and reaches a replica), later arrivals attach
to the leader's future — a thundering herd of retries costs one device
call. Errors are never cached; the leader hands its error payload to
followers and the next request retries cold.

Everything here is jax-free, numpy-free, stdlib-only — it runs in the
router process and must add microseconds, not milliseconds. Metrics ride
the shared obs registry under the ``cache.`` namespace
(:meth:`~code2vec_tpu.obs.runtime.RuntimeHealth.namespaced`), so
``/metrics`` exports ``c2v_cache_*`` series with no new schema.
"""

from __future__ import annotations

import hashlib
import json
import struct
import threading
from collections import OrderedDict, deque
from concurrent.futures import Future
from dataclasses import dataclass

from code2vec_tpu.obs.sync import make_lock

__all__ = [
    "ResultCache",
    "canonical_bag_digest",
    "canonical_request_key",
    "payload_nbytes",
]

_DIGEST_SIZE = 16  # 128-bit blake2b: collision-safe at any realistic scale

# op-relevant knobs folded into the canonical key, per op. A knob absent
# from the request and a knob sent at its default value produce DIFFERENT
# keys — deliberately conservative: the worst case is a redundant miss,
# never a wrong hit. ``id`` and ``trace`` are correlation fields, not
# request content, and are excluded by construction (only listed fields
# are read).
_KNOB_FIELDS = {
    "predict": ("language", "method_name", "top_k", "include_vector"),
    "embed": ("language", "method_name", "include_vector"),
    "embed_file": ("language", "method_name"),
    "neighbors": (
        "language", "method_name", "top_k", "granularity", "include_vector",
    ),
}


def canonical_bag_digest(contexts) -> str:
    """Order-invariant MULTISET digest of a path-context bag.

    ``contexts`` is any iterable of ``[start, path, end]`` integer rows
    (lists, tuples, or an ``[n, 3]`` array — rows are coerced through
    ``int``). Rows are sorted lexicographically before hashing, so any
    permutation of the same bag digests identically, while duplicate
    rows (a legal multiset) still count: ``[a, a, b] != [a, b]``.
    """
    rows = sorted((int(r[0]), int(r[1]), int(r[2])) for r in contexts)
    h = hashlib.blake2b(digest_size=_DIGEST_SIZE)
    h.update(len(rows).to_bytes(8, "little"))
    for row in rows:
        for v in row:
            h.update(v.to_bytes(8, "little", signed=True))
    return h.hexdigest()


def _vector_digest(vector) -> str:
    floats = [float(v) for v in vector]
    return hashlib.blake2b(
        struct.pack(f"<{len(floats)}d", *floats), digest_size=_DIGEST_SIZE
    ).hexdigest()


def canonical_request_key(request: dict) -> str | None:
    """The version-free canonical key for one data-plane request, or
    ``None`` when the request is not cacheable (control ops, malformed
    bodies, unknown ops — the router then serves it uncached).

    Body identity, in precedence order (mirroring the protocol layer):
    a pre-mapped ``"contexts"`` bag digests as an order-invariant
    multiset; a ``"vector"`` (neighbors) digests its float64 wire values;
    a ``"source"`` string digests its UTF-8 bytes (extraction is
    deterministic, so source identity implies response identity).
    """
    op = request.get("op")
    knobs = _KNOB_FIELDS.get(op)
    if knobs is None:
        return None
    contexts = request.get("contexts")
    if contexts is not None:
        try:
            body = "bag:" + canonical_bag_digest(contexts)
        except (TypeError, ValueError, IndexError):
            return None
    elif isinstance(request.get("vector"), (list, tuple)):
        try:
            body = "vec:" + _vector_digest(request["vector"])
        except (TypeError, ValueError, struct.error):
            return None
    elif isinstance(request.get("source"), str):
        body = "src:" + hashlib.blake2b(
            request["source"].encode("utf-8"), digest_size=_DIGEST_SIZE
        ).hexdigest()
    else:
        return None
    try:
        knob_repr = json.dumps(
            {k: request[k] for k in knobs if k in request}, sort_keys=True
        )
    except (TypeError, ValueError):
        return None
    return f"{op}|{body}|{knob_repr}"


def payload_nbytes(payload) -> int | None:
    """Byte cost of one cached payload: its compact-JSON wire size (what
    a transport would actually send). ``None`` for non-serializable
    values — the caller skips caching those."""
    try:
        return len(
            json.dumps(payload, separators=(",", ":")).encode("utf-8")
        )
    except (TypeError, ValueError):
        return None


@dataclass
class _Entry:
    value: object
    nbytes: int
    freq: int = 0  # capped reference counter (S3-FIFO's 2-bit clock)
    in_main: bool = False


class ResultCache:
    """Bounded-memory S3-FIFO result cache with versioned keys and miss
    coalescing (see module docstring). One lock guards all state — every
    operation is O(1) dict/deque work plus amortized eviction.

    Admission protocol (the router's contract)::

        key = cache.key_for(request)          # None -> serve uncached
        state, held = cache.begin(key)
        # "hit"  -> held IS the cached payload; respond immediately
        # "join" -> held is the in-flight leader's Future; wait on it
        # "lead" -> dispatch, then EXACTLY ONE of:
        cache.fill(key, payload)              # cache + resolve joiners
        cache.abandon(key, payload)           # resolve joiners, no cache

    A leader that never calls ``fill``/``abandon`` strands its joiners —
    the router guarantees one of the two on every admitted request's
    single exit point (including close-time drains and admission sheds).
    """

    def __init__(
        self,
        capacity_bytes: int,
        *,
        small_fraction: float = 0.1,
        ghost_entries: int = 4096,
        health=None,
        version: str = "v0",
    ) -> None:
        if capacity_bytes <= 0:
            raise ValueError(
                f"capacity_bytes must be > 0, got {capacity_bytes}"
            )
        self._capacity = int(capacity_bytes)
        self._small_target = max(1, int(self._capacity * small_fraction))
        self._ghost_cap = int(ghost_entries)
        self._lock = make_lock("fleet.cache")
        self._entries: dict[tuple, _Entry] = {}
        self._small: deque[tuple] = deque()
        self._main: deque[tuple] = deque()
        self._ghost: OrderedDict[tuple, None] = OrderedDict()
        self._inflight: dict[tuple, Future] = {}
        self._bytes = 0
        self._small_bytes = 0
        self._version = str(version)
        self._swapping = False
        self._per_version: dict[str, int] = {}
        # plain ints for the health/stats() block; the namespaced obs
        # counters below feed /metrics — same numbers, two consumers
        self._hits = self._misses = self._coalesced = 0
        self._inserts = self._evictions = self._rejected = 0
        ns = health.namespaced("cache") if health is not None else None
        self._c_hits = ns.counter("hits") if ns else None
        self._c_misses = ns.counter("misses") if ns else None
        self._c_coalesced = ns.counter("coalesced") if ns else None
        self._c_evictions = ns.counter("evictions") if ns else None
        self._c_inserts = ns.counter("inserts") if ns else None
        self._c_rejected = ns.counter("rejected_oversize") if ns else None
        self._g_bytes = ns.gauge("bytes") if ns else None
        self._g_entries = ns.gauge("entries") if ns else None
        self._g_inflight = ns.gauge("in_flight") if ns else None
        self._g_versions = ns.gauge("versions_resident") if ns else None
        self._sync_gauges()

    # ---- version lifecycle ----------------------------------------------
    @property
    def active_version(self) -> str | None:
        """The version new keys are minted under; None mid-roll (the
        fleet is mixed-version — nothing is cacheable)."""
        with self._lock:
            return None if self._swapping else self._version

    def set_version(self, version: str) -> None:
        """Flip the active version (commit forward OR rollback — entries
        under every version stay resident; only visibility flips)."""
        with self._lock:
            self._version = str(version)
            self._swapping = False

    def begin_swap(self) -> None:
        """A rolling swap started: stand down (no hits, no fills) until
        :meth:`end_swap` — replicas disagree on weights mid-roll."""
        with self._lock:
            self._swapping = True

    def end_swap(self, version: str | None = None) -> None:
        """The roll finished. ``version`` is the committed generation
        (new keys mint under it); None means the roll failed and the
        incumbent version — whose entries never stopped being true —
        resumes."""
        with self._lock:
            if version is not None:
                self._version = str(version)
            self._swapping = False

    # ---- key derivation --------------------------------------------------
    def key_for(self, request: dict) -> tuple | None:
        """Full versioned key ``(version, canonical_key)`` for one
        request, or None when the request is uncacheable or the cache is
        standing down mid-roll. The version is captured HERE, at
        admission: a swap committing while the request is in flight must
        not relabel its eventual fill."""
        with self._lock:
            if self._swapping:
                return None
            version = self._version
        ckey = canonical_request_key(request)
        if ckey is None:
            return None
        return (version, ckey)

    # ---- admission / coalescing -----------------------------------------
    def begin(self, key: tuple):
        """See the class docstring: ``("hit", payload)``,
        ``("join", leader_future)``, or ``("lead", leader_future)``."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                entry.freq = min(entry.freq + 1, 3)
                self._hits += 1
                if self._c_hits:
                    self._c_hits.inc()
                return ("hit", entry.value)
            leader = self._inflight.get(key)
            if leader is not None:
                self._coalesced += 1
                if self._c_coalesced:
                    self._c_coalesced.inc()
                return ("join", leader)
            future: Future = Future()
            self._inflight[key] = future
            self._misses += 1
            if self._c_misses:
                self._c_misses.inc()
            if self._g_inflight:
                self._g_inflight.set(len(self._inflight))
            return ("lead", future)

    def fill(self, key: tuple, value, nbytes: int | None = None) -> None:
        """Leader completion: insert the payload and resolve joiners.
        ``nbytes`` defaults to the payload's JSON wire size; a payload
        that cannot be sized (non-JSON value without an explicit size)
        resolves joiners but is not cached."""
        if nbytes is None:
            nbytes = payload_nbytes(value)
        with self._lock:
            leader = self._inflight.pop(key, None)
            if nbytes is not None:
                self._insert(key, value, int(nbytes))
            if self._g_inflight:
                self._g_inflight.set(len(self._inflight))
        # resolve OUTSIDE the lock: joiner callbacks run synchronously
        # here and must be free to re-enter the cache
        if leader is not None and not leader.done():
            leader.set_result(value)

    def abandon(self, key: tuple, value) -> None:
        """Leader completion WITHOUT caching (error payloads, shed-at-
        admission, close-time drains): joiners still get the payload —
        they attached to this attempt and inherit its outcome verbatim —
        but the next identical request retries cold."""
        with self._lock:
            leader = self._inflight.pop(key, None)
            if self._g_inflight:
                self._g_inflight.set(len(self._inflight))
        if leader is not None and not leader.done():
            leader.set_result(value)

    # ---- S3-FIFO internals (lock held) ----------------------------------
    def _insert(self, key: tuple, value, nbytes: int) -> None:
        if nbytes > self._capacity:
            self._rejected += 1
            if self._c_rejected:
                self._c_rejected.inc()
            return
        entry = self._entries.get(key)
        if entry is not None:
            # refill of a resident key (race between two version flips):
            # update in place, keep queue position
            delta = nbytes - entry.nbytes
            entry.value, entry.nbytes = value, nbytes
            self._bytes += delta
            if not entry.in_main:
                self._small_bytes += delta
        else:
            in_main = self._ghost.pop(key, "__missing__") is None
            entry = _Entry(value=value, nbytes=nbytes, in_main=in_main)
            self._entries[key] = entry
            (self._main if in_main else self._small).append(key)
            self._bytes += nbytes
            if not in_main:
                self._small_bytes += nbytes
            self._per_version[key[0]] = self._per_version.get(key[0], 0) + 1
            self._inserts += 1
            if self._c_inserts:
                self._c_inserts.inc()
        while self._bytes > self._capacity:
            if self._small and (
                self._small_bytes > self._small_target or not self._main
            ):
                self._evict_small()
            elif self._main:
                self._evict_main()
            else:  # pragma: no cover - capacity > 0 guarantees progress
                break
        self._sync_gauges()

    def _evict_small(self) -> None:
        key = self._small.popleft()
        entry = self._entries[key]
        self._small_bytes -= entry.nbytes
        if entry.freq > 0:
            # re-referenced while probationary: promote (this is what
            # keeps one-hit wonders from ever displacing the hot set)
            entry.freq = 0
            entry.in_main = True
            self._main.append(key)
        else:
            self._drop(key, entry)
            self._ghost[key] = None
            while len(self._ghost) > self._ghost_cap:
                self._ghost.popitem(last=False)

    def _evict_main(self) -> None:
        key = self._main.popleft()
        entry = self._entries[key]
        if entry.freq > 0:
            entry.freq -= 1
            self._main.append(key)  # lazy promotion: second chance
        else:
            self._drop(key, entry)

    def _drop(self, key: tuple, entry: _Entry) -> None:
        del self._entries[key]
        self._bytes -= entry.nbytes
        remaining = self._per_version.get(key[0], 1) - 1
        if remaining > 0:
            self._per_version[key[0]] = remaining
        else:
            self._per_version.pop(key[0], None)
        self._evictions += 1
        if self._c_evictions:
            self._c_evictions.inc()

    def _sync_gauges(self) -> None:
        if self._g_bytes:
            self._g_bytes.set(self._bytes)
        if self._g_entries:
            self._g_entries.set(len(self._entries))
        if self._g_versions:
            self._g_versions.set(len(self._per_version))

    # ---- introspection ---------------------------------------------------
    def stats(self) -> dict:
        """The fleet-health cache block: counters, byte accounting, the
        active version, and per-version resident entry counts (the
        rollback story made visible — old generations' entries survive a
        commit)."""
        with self._lock:
            return {
                "capacity_bytes": self._capacity,
                "bytes": self._bytes,
                "entries": len(self._entries),
                "active_version": (
                    None if self._swapping else self._version
                ),
                "swapping": self._swapping,
                "hits": self._hits,
                "misses": self._misses,
                "coalesced": self._coalesced,
                "inserts": self._inserts,
                "evictions": self._evictions,
                "rejected_oversize": self._rejected,
                "in_flight": len(self._inflight),
                "ghost_entries": len(self._ghost),
                "versions": dict(self._per_version),
            }
