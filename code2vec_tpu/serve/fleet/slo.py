"""SLO classes: per-op queue budgets and deadlines, tiered shedding.

The single-process batcher bounds load with ONE global ``max_pending`` —
correct for one queue, wrong for a fleet where a burst of expensive
``neighbors`` scans must not starve cheap ``embed`` calls or the health
probes that decide evictions. Every op maps to one of three classes:

==========  ======================================  ==================
class       ops                                      default budget/deadline
==========  ======================================  ==================
health      health, swap_status, reload, rollback,   16 queued / 1000 ms
            shutdown (the control plane)
embed       predict, embed                           256 queued / 2000 ms
neighbors   neighbors                                 64 queued / 5000 ms
==========  ======================================  ==================

Each DATA class owns a bounded router queue (its **budget** — admission
control: a full queue sheds new arrivals with a retryable ``overloaded``
error) and a **deadline**: a request still undispatched past its
deadline is shed with a ``deadline`` error instead of being served
uselessly late (its client has typically given up — serving it anyway is
pure queue poison). Dispatch priority is the tier order above; under
sustained overload the lowest tier backs up and sheds first. The
``health`` tier is how the control plane cuts through saturated traffic:
the router answers/orchestrates those ops INLINE at admission — they
never enter a data queue, so no data-plane backlog can delay a probe or
a swap (its budget/deadline numbers are accepted for config symmetry but
currently have nothing to bound).

``--slo`` grammar: ``class=budget:deadline_ms`` comma-separated, e.g.
``embed=512:1500,neighbors=32:8000`` (unnamed classes keep defaults).
"""

from __future__ import annotations

from dataclasses import dataclass

__all__ = [
    "DEFAULT_SLO",
    "PRIORITY",
    "SloClass",
    "classify_op",
    "parse_slo_spec",
]


@dataclass(frozen=True)
class SloClass:
    """One service tier: admission budget + usefulness deadline."""

    name: str
    budget: int  # max queued (not yet dispatched) requests router-wide
    deadline_ms: float  # shed instead of dispatching past this age

    def __post_init__(self) -> None:
        if self.budget < 1:
            raise ValueError(f"{self.name}: budget must be >= 1, got "
                             f"{self.budget}")
        if self.deadline_ms <= 0:
            raise ValueError(f"{self.name}: deadline_ms must be > 0, got "
                             f"{self.deadline_ms}")


DEFAULT_SLO: dict[str, SloClass] = {
    "health": SloClass("health", budget=16, deadline_ms=1000.0),
    "embed": SloClass("embed", budget=256, deadline_ms=2000.0),
    "neighbors": SloClass("neighbors", budget=64, deadline_ms=5000.0),
}

# dispatch order under contention: control plane > embed > neighbors
PRIORITY: tuple[str, ...] = ("health", "embed", "neighbors")

_OP_CLASS = {
    "predict": "embed",
    "embed": "embed",
    "neighbors": "neighbors",
    "health": "health",
    "swap_status": "health",
    "reload": "health",
    "rollback": "health",
    "shutdown": "health",
}


def classify_op(op) -> str | None:
    """SLO class name for one request op; None = unknown op."""
    return _OP_CLASS.get(op)


def parse_slo_spec(
    spec: str | None, base: dict[str, SloClass] | None = None
) -> dict[str, SloClass]:
    """Parse ``class=budget:deadline_ms,...`` over ``base`` defaults."""
    classes = dict(base if base is not None else DEFAULT_SLO)
    if not spec:
        return classes
    for clause in spec.split(","):
        clause = clause.strip()
        if not clause:
            continue
        try:
            name, rest = clause.split("=", 1)
            budget, deadline = rest.split(":", 1)
        except ValueError:
            raise ValueError(
                f"bad --slo clause {clause!r}: expected "
                "class=budget:deadline_ms"
            ) from None
        name = name.strip()
        if name not in classes:
            raise ValueError(
                f"unknown SLO class {name!r}; have {sorted(classes)}"
            )
        classes[name] = SloClass(
            name, budget=int(budget), deadline_ms=float(deadline)
        )
    return classes
