"""SLO classes: per-op queue budgets and deadlines, tiered shedding.

The single-process batcher bounds load with ONE global ``max_pending`` —
correct for one queue, wrong for a fleet where a burst of expensive
``neighbors`` scans must not starve cheap ``embed`` calls or the health
probes that decide evictions. Every op maps to one of three classes:

==========  ======================================  ==================
class       ops                                      default budget/deadline
==========  ======================================  ==================
health      health, swap_status, reload, rollback,   16 queued / 1000 ms
            shutdown (the control plane)
embed       predict, embed                           256 queued / 2000 ms
neighbors   neighbors                                 64 queued / 5000 ms
==========  ======================================  ==================

Each DATA class owns a bounded router queue (its **budget** — admission
control: a full queue sheds new arrivals with a retryable ``overloaded``
error) and a **deadline**: a request still undispatched past its
deadline is shed with a ``deadline`` error instead of being served
uselessly late (its client has typically given up — serving it anyway is
pure queue poison). Dispatch priority is the tier order above; under
sustained overload the lowest tier backs up and sheds first. The
``health`` tier is how the control plane cuts through saturated traffic:
the router answers/orchestrates those ops INLINE at admission — they
never enter a data queue, so no data-plane backlog can delay a probe or
a swap (its budget/deadline numbers are accepted for config symmetry but
currently have nothing to bound).

``--slo`` grammar: ``class=budget:deadline_ms`` comma-separated, e.g.
``embed=512:1500,neighbors=32:8000`` (unnamed classes keep defaults).

**Error-budget burn accounting** (:class:`SloBurnTracker`): each class
additionally carries a rolling availability window. Every finished
request is recorded as *good* or *bad* (bad = shed on budget, shed on
deadline, or a server-side failure — client mistakes like
``bad_request`` do not burn budget); the tracker maintains per-second
ring buckets over ``window_s`` with running totals, so recording is O(1)
and a snapshot never scans history. The **burn rate** is the SRE
convention: observed error fraction divided by the allowed error
fraction ``1 - objective`` — burn 1.0 means the window is consuming its
budget exactly as fast as allowed; above 1.0 the budget depletes.
Crossing into exhaustion (burn >= 1 with enough traffic to mean it)
emits one ``slo_budget_exhausted`` event per episode and flips the
``slo.<class>.budget_exhausted`` gauge; recovery flips it back. Gauges
(``burn_rate`` / ``budget_remaining``) land in the shared registry on
every record, so ``health`` and ``GET /metrics`` surface them with no
extra bookkeeping.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass

from code2vec_tpu.obs.sync import make_lock

__all__ = [
    "DEFAULT_SLO",
    "PRIORITY",
    "SloBurnTracker",
    "SloClass",
    "classify_op",
    "parse_slo_spec",
]


@dataclass(frozen=True)
class SloClass:
    """One service tier: admission budget + usefulness deadline."""

    name: str
    budget: int  # max queued (not yet dispatched) requests router-wide
    deadline_ms: float  # shed instead of dispatching past this age

    def __post_init__(self) -> None:
        if self.budget < 1:
            raise ValueError(f"{self.name}: budget must be >= 1, got "
                             f"{self.budget}")
        if self.deadline_ms <= 0:
            raise ValueError(f"{self.name}: deadline_ms must be > 0, got "
                             f"{self.deadline_ms}")


DEFAULT_SLO: dict[str, SloClass] = {
    "health": SloClass("health", budget=16, deadline_ms=1000.0),
    "embed": SloClass("embed", budget=256, deadline_ms=2000.0),
    "neighbors": SloClass("neighbors", budget=64, deadline_ms=5000.0),
}

# dispatch order under contention: control plane > embed > neighbors
PRIORITY: tuple[str, ...] = ("health", "embed", "neighbors")

_OP_CLASS = {
    "predict": "embed",
    "embed": "embed",
    "neighbors": "neighbors",
    "health": "health",
    "swap_status": "health",
    "reload": "health",
    "rollback": "health",
    "shutdown": "health",
    "flights": "health",
}


def classify_op(op) -> str | None:
    """SLO class name for one request op; None = unknown op."""
    return _OP_CLASS.get(op)


def parse_slo_spec(
    spec: str | None, base: dict[str, SloClass] | None = None
) -> dict[str, SloClass]:
    """Parse ``class=budget:deadline_ms,...`` over ``base`` defaults."""
    classes = dict(base if base is not None else DEFAULT_SLO)
    if not spec:
        return classes
    for clause in spec.split(","):
        clause = clause.strip()
        if not clause:
            continue
        try:
            name, rest = clause.split("=", 1)
            budget, deadline = rest.split(":", 1)
        except ValueError:
            raise ValueError(
                f"bad --slo clause {clause!r}: expected "
                "class=budget:deadline_ms"
            ) from None
        name = name.strip()
        if name not in classes:
            raise ValueError(
                f"unknown SLO class {name!r}; have {sorted(classes)}"
            )
        classes[name] = SloClass(
            name, budget=int(budget), deadline_ms=float(deadline)
        )
    return classes


class _BurnWindow:
    """One class's rolling availability window: per-second (good, bad)
    ring buckets + running totals, advanced lazily on record/snapshot."""

    __slots__ = (
        "good", "bad", "_buckets", "_head", "_head_second", "exhausted",
    )

    def __init__(self, n_buckets: int) -> None:
        self.good = 0
        self.bad = 0
        self._buckets = [[0, 0] for _ in range(n_buckets)]
        self._head = 0
        self._head_second: int | None = None
        self.exhausted = False

    def advance(self, now_second: int) -> None:
        if self._head_second is None:
            self._head_second = now_second
            return
        steps = now_second - self._head_second
        if steps <= 0:
            return
        # expire at most a full ring of buckets (amortized O(1): each
        # recorded second is expired exactly once)
        for _ in range(min(steps, len(self._buckets))):
            self._head = (self._head + 1) % len(self._buckets)
            expired = self._buckets[self._head]
            self.good -= expired[0]
            self.bad -= expired[1]
            expired[0] = expired[1] = 0
        self._head_second = now_second

    def record(self, now_second: int, good: bool) -> None:
        self.advance(now_second)
        bucket = self._buckets[self._head]
        if good:
            bucket[0] += 1
            self.good += 1
        else:
            bucket[1] += 1
            self.bad += 1


class SloBurnTracker:
    """Rolling error-budget accounting per SLO class (module docstring).

    ``classes``: the class names to track (the keys of an SLO dict, or
    any iterable of names — ``bench.py --serve`` tracks one synthetic
    ``serve`` class over its own outcome stream). ``objective`` is the
    availability target (0.999 = 0.1% error budget); ``window_s`` the
    rolling window; ``min_requests`` stops a single early failure from
    declaring a near-empty window exhausted. ``health``/``events`` are
    the shared obs registry and event log the gauges/exhaustion events
    land on; ``clock`` is injectable for tests.
    """

    def __init__(
        self,
        classes,
        *,
        objective: float = 0.999,
        window_s: float = 60.0,
        min_requests: int = 10,
        health=None,
        events=None,
        clock=time.monotonic,
    ) -> None:
        if not 0.0 < objective < 1.0:
            raise ValueError(
                f"objective must be in (0, 1), got {objective}"
            )
        if window_s < 1.0:
            raise ValueError(f"window_s must be >= 1, got {window_s}")
        self.objective = float(objective)
        self.window_s = float(window_s)
        self.min_requests = int(min_requests)
        self._health = health
        self._events = events
        self._clock = clock
        self._lock = make_lock("fleet.slo")
        n_buckets = int(window_s) + 1
        self._windows: dict[str, _BurnWindow] = {
            name: _BurnWindow(n_buckets) for name in classes
        }
        if not self._windows:
            raise ValueError("SloBurnTracker needs at least one class")

    def _burn(self, window: _BurnWindow) -> tuple[float, int]:
        total = window.good + window.bad
        if total == 0:
            return 0.0, 0
        error_fraction = window.bad / total
        return error_fraction / (1.0 - self.objective), total

    def record(self, cls: str, good: bool) -> None:
        """O(1) per finished request: bucket update + two gauge writes;
        emits ``slo_budget_exhausted`` on the transition into burn >= 1."""
        window = self._windows.get(cls)
        if window is None:
            return
        newly_exhausted = False
        with self._lock:
            window.record(int(self._clock()), good)
            burn, total = self._burn(window)
            exhausted = burn >= 1.0 and total >= self.min_requests
            if exhausted and not window.exhausted:
                newly_exhausted = True
            window.exhausted = exhausted
            good_n, bad_n = window.good, window.bad
        if self._health is not None:
            self._health.gauge(f"slo.{cls}.burn_rate").set(round(burn, 4))
            self._health.gauge(f"slo.{cls}.budget_remaining").set(
                round(max(0.0, 1.0 - burn), 4)
            )
            self._health.gauge(f"slo.{cls}.budget_exhausted").set(
                1 if exhausted else 0
            )
        if newly_exhausted and self._events is not None:
            try:
                self._events.emit(
                    "slo_budget_exhausted", slo_class=cls,
                    burn_rate=round(burn, 4), objective=self.objective,
                    window_s=self.window_s, good=good_n, bad=bad_n,
                )
            except Exception:  # pragma: no cover - closed log
                pass

    def snapshot(self) -> dict:
        """Per-class burn block for ``health`` payloads and bench detail:
        window totals, burn rate, remaining budget, exhaustion flag."""
        out = {}
        with self._lock:
            for cls, window in self._windows.items():
                window.advance(int(self._clock()))
                burn, total = self._burn(window)
                out[cls] = {
                    "good": window.good,
                    "bad": window.bad,
                    "burn_rate": round(burn, 4),
                    "budget_remaining": round(max(0.0, 1.0 - burn), 4),
                    "exhausted": bool(
                        burn >= 1.0 and total >= self.min_requests
                    ),
                    "objective": self.objective,
                    "window_s": self.window_s,
                }
        return out
