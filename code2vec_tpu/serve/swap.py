"""Live checkpoint hot-swap: shadow-compiled generations, validated, atomic.

The serving unit of rollout is a **generation**: one checkpoint's worth of
serving state — predictor, AOT executable ladder, micro-batcher, retrieval
index — bundled so it can be swapped as one pointer. The thesis is the
compile-first one ("Compiler-First … Portable O(1) Autoregressive Caching
for Inference", PAPERS.md): serving state is compiled ahead of time and
swapped atomically, never traced on the hot path. A ``reload`` therefore:

1. **builds a shadow generation on a background thread** — loads the new
   checkpoint, AOT-compiles its FULL bucket ladder
   (``ServingEngine.prepare``), loads its retrieval backend — while the
   active generation keeps serving untouched;
2. **validates it against a golden request set**
   (:func:`validate_generation`): every golden request must come out of
   the shadow ladder's coalesced executables BITWISE equal to its own
   batch-1 dispatch (the serving invariant pinned since PR 9 — the check
   that catches a miscompiled/misquantized ladder), all outputs finite,
   ZERO post-warmup compiles during validation (the golden set sweeps
   every ladder rung, so a hole in the shadow ladder fails here, not in
   traffic), and — when a retrieval backend is present — recall@k against
   a brute-force NumPy reference over the same vectors bounded below
   (exact backends must hit 1.0; ANN backends their configured floor);
3. **swaps the serving pointer atomically** — one reference assignment
   under the controller lock. In-flight requests hold their OWN generation
   reference (``CodeServer.handle_async`` snapshots it at submission), so
   nothing is dropped: requests already submitted drain through the old
   generation's still-running batcher while new arrivals dispatch into the
   new one;
4. **keeps the old generation resident** — engine, compiled executables,
   batcher thread and all — so ``rollback`` is one pointer swap back and
   the next request reproduces the prior version's BITWISE-identical
   embeddings (same executables, same quantized tables; nothing is
   rebuilt). Only when a LATER swap commits is the oldest generation
   finally drained and released.

State machine (reported by the ``swap_status`` op)::

    idle --reload--> building --> validating --commit--> idle
                        |              |
                        +---failure----+--> idle (active unchanged,
                                            last_swap.outcome = "failed")

Failures never touch the active pointer: a build error or validation
miss closes the half-built shadow and records the error; serving
continues on the incumbent version.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field

import numpy as np

from code2vec_tpu.obs import handles
from code2vec_tpu.obs.runtime import RuntimeHealth, global_health
from code2vec_tpu.obs.sync import make_rlock

logger = logging.getLogger(__name__)

__all__ = [
    "Generation",
    "GoldenSet",
    "SwapController",
    "SwapValidationError",
    "validate_generation",
]


class SwapValidationError(RuntimeError):
    """The shadow generation failed golden validation — not swapped in."""


@dataclass
class Generation:
    """One checkpoint's worth of serving state, swappable as a unit."""

    version: str
    engine: object  # ServingEngine (full AOT ladder compiled)
    batcher: object  # MicroBatcher bound to that engine
    predictor: object | None = None  # None for state-built benches/tests
    retrieval: object | None = None
    provenance: list = field(default_factory=list)
    created_unix: float = field(default_factory=time.time)

    def __post_init__(self) -> None:
        handles.track(self, "generation", name=str(self.version))

    def close(self, timeout: float | None = None) -> None:
        """Drain and stop this generation's batcher and release its
        retrieval backend (idempotent). Argument-free call keeps
        duck-typed batcher stand-ins (tests, CI smokes) working;
        MicroBatcher's own default drain timeout applies.
        """
        del timeout
        self.batcher.close()
        close_retrieval = getattr(self.retrieval, "close", None)
        if close_retrieval is not None:
            close_retrieval()
        handles.untrack(self)


@dataclass
class GoldenSet:
    """Deterministic validation workload swept across the shadow ladder.

    Requests are synthesized per-validated-generation (seeded rng, ids
    bounded by THAT generation's vocab tables) with ``n_per_width``
    requests at and just under every ladder rung — so every shadow
    executable the traffic could hit is exercised before it serves.
    ``n_terminals``/``n_paths`` override the id bounds for generations
    without a predictor (bench/tests build engines straight from a train
    state); with a predictor they come from its ``model_meta.json``.
    """

    n_per_width: int = 2
    seed: int = 0
    min_recall: float = 0.9
    recall_k: int = 10
    n_queries: int = 8
    n_terminals: int | None = None
    n_paths: int | None = None

    def requests_for(self, gen: Generation) -> list[np.ndarray]:
        """The ``[n, 3]`` mapped-context arrays to validate ``gen`` with."""
        n_terminals = self.n_terminals
        n_paths = self.n_paths
        if n_terminals is None or n_paths is None:
            if gen.predictor is None:
                raise ValueError(
                    "GoldenSet needs n_terminals/n_paths when the "
                    "generation has no predictor to read them from"
                )
            n_terminals = n_terminals or int(
                gen.predictor.meta["terminal_count"]
            )
            n_paths = n_paths or int(gen.predictor.meta["path_count"])
        rng = np.random.default_rng(self.seed)
        requests = []
        for width in gen.engine.active_ladder:
            for j in range(self.n_per_width):
                n = max(1, int(width) - j)
                requests.append(
                    np.stack(
                        [
                            rng.integers(1, n_terminals, n),
                            rng.integers(1, n_paths, n),
                            rng.integers(1, n_terminals, n),
                        ],
                        axis=1,
                    ).astype(np.int32)
                )
        return requests


def _retrieval_recall(retrieval, k: int, n_queries: int) -> float:
    """Mean recall@k of the backend vs brute-force NumPy cosine over the
    SAME unit rows (both backends keep them: the exact index device-side,
    the ANN index as the re-rank mmap)."""
    rows = np.asarray(retrieval._rows, np.float32)[: retrieval.n]
    labels = retrieval.labels
    k = min(int(k), retrieval.n)
    rng = np.random.default_rng(0)
    queries = rng.choice(
        retrieval.n, size=min(int(n_queries), retrieval.n), replace=False
    )
    hits, total = 0, 0
    for qi in queries:
        got = {name for name, _ in retrieval.top_k(rows[qi], k)}
        reference = {
            labels[i] for i in np.argsort(-(rows @ rows[qi]))[:k]
        }
        hits += len(got & reference)
        total += k
    return hits / total if total else 1.0


def validate_generation(gen: Generation, golden: GoldenSet | None) -> dict:
    """Run the golden set through a freshly-built shadow generation.

    Returns a report dict for the swap event log; raises
    :class:`SwapValidationError` on any miss. Runs ONLY against the shadow
    engine directly (never its batcher), so a validating swap cannot
    contend with live traffic for the active generation's queue.
    """
    report: dict = {"golden_requests": 0, "checks": []}
    if golden is None:
        report["checks"].append("skipped: no golden set configured")
        return report
    requests = golden.requests_for(gen)
    engine = gen.engine

    # batch-1 reference pass: every golden request through its own width's
    # single-request executable
    singles = []
    for arr in requests:
        starts, paths, ends, _, _ = engine.pad_requests([arr])
        logits, vectors, _ = engine.run(starts, paths, ends)
        logits = np.asarray(logits)[0]
        vectors = np.asarray(vectors)[0]
        if not (np.isfinite(logits).all() and np.isfinite(vectors).all()):
            raise SwapValidationError(
                f"shadow engine produced non-finite outputs for a "
                f"{len(arr)}-context golden request"
            )
        singles.append((logits, vectors))

    # coalesced pass: the same requests grouped to the top micro-batch
    # size must reproduce the batch-1 EMBEDDINGS bitwise (the PR-9
    # serving invariant — a miscompiled ladder or broken PAD masking
    # fails here). Logits get a tight tolerance instead: XLA's codegen
    # for the label-head dot may pick a different reduction strategy per
    # batch size at some (encode, label) dims, shifting the last bit —
    # the embedding path is what the bitwise rollout contract covers.
    top = engine.batch_sizes[-1]
    for base in range(0, len(requests), top):
        chunk = requests[base : base + top]
        starts, paths, ends, _, _ = engine.pad_requests(chunk)
        logits, vectors, _ = engine.run(starts, paths, ends)
        logits = np.asarray(logits)
        vectors = np.asarray(vectors)
        for i in range(len(chunk)):
            ref_logits, ref_vectors = singles[base + i]
            if not np.array_equal(vectors[i], ref_vectors):
                raise SwapValidationError(
                    "shadow engine's coalesced embeddings diverge bitwise "
                    f"from batch-1 dispatch (request {base + i}, width "
                    f"{len(chunk[i])})"
                )
            if not np.allclose(
                logits[i], ref_logits, rtol=1e-5, atol=1e-6
            ):
                raise SwapValidationError(
                    "shadow engine's coalesced logits diverge beyond "
                    "reduction-order noise from batch-1 dispatch (request "
                    f"{base + i}, width {len(chunk[i])})"
                )
    report["golden_requests"] = len(requests)
    report["checks"].append(
        "embeddings: coalesced == batch-1 bitwise (logits within "
        "reduction-order tolerance), all finite"
    )

    if engine.post_warmup_compiles:
        raise SwapValidationError(
            f"golden validation triggered {engine.post_warmup_compiles} "
            "post-warmup compile(s): the shadow ladder does not cover its "
            "own rungs"
        )
    report["checks"].append("zero post-warmup compiles across validation")

    if gen.retrieval is not None:
        recall = _retrieval_recall(
            gen.retrieval, golden.recall_k, golden.n_queries
        )
        report["recall"] = round(recall, 4)
        if recall < golden.min_recall:
            raise SwapValidationError(
                f"shadow retrieval recall@{golden.recall_k} = {recall:.4f} "
                f"below the {golden.min_recall} floor"
            )
        report["checks"].append(
            f"neighbors: recall@{golden.recall_k} = {recall:.4f} >= "
            f"{golden.min_recall}"
        )
    return report


class SwapController:
    """Owns the active/previous generation pointers and the swap thread.

    ``build(target) -> Generation`` is the generation factory (loads a
    checkpoint, compiles the full ladder, builds batcher + retrieval); it
    runs on the controller's background thread so the reload control op
    returns immediately and the active generation never stalls. At most
    one swap runs at a time; ``rollback`` is pointer-swap-instant and
    refuses to race an in-progress swap.
    """

    def __init__(
        self,
        active: Generation,
        *,
        build=None,
        golden: GoldenSet | None = None,
        health: RuntimeHealth | None = None,
        events=None,
        close_timeout: float = 30.0,
    ) -> None:
        self.active = active
        self.previous: Generation | None = None
        self._build = build
        self.golden = golden
        self._health = health or global_health()
        self._events = events
        self._close_timeout = close_timeout
        self._lock = make_rlock("swap.controller")
        self._state = "idle"  # idle | building | validating
        self._target: str | None = None
        self._last: dict | None = None
        self._thread: threading.Thread | None = None
        self._swaps = self._health.counter("serve_swaps_committed")
        self._failed = self._health.counter("serve_swaps_failed")
        self._rollbacks = self._health.counter("serve_rollbacks")
        self._health.gauge("serve_active_version").set(active.version)

    # ---- status ---------------------------------------------------------
    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def status(self) -> dict:
        with self._lock:
            return {
                "state": self._state,
                "target": self._target,
                "active_version": self.active.version,
                "previous_version": (
                    self.previous.version if self.previous else None
                ),
                "last_swap": dict(self._last) if self._last else None,
            }

    def _emit(self, event: str, **fields) -> None:
        if self._events is not None:
            try:
                self._events.emit(event, **fields)
            except Exception:  # pragma: no cover - closed log mid-swap
                logger.warning("could not emit %s event", event, exc_info=True)

    # ---- reload ---------------------------------------------------------
    def reload(self, target: str | None, wait: bool = False) -> dict:
        """Start a shadow build + validate + swap toward ``target`` (a
        model path, or whatever token the factory understands). Returns
        the status snapshot — final when ``wait``, in-progress otherwise.
        """
        if self._build is None:
            raise ValueError(
                "this server has no generation factory — reload is only "
                "available through the serve CLI (or a SwapController "
                "constructed with build=...)"
            )
        with self._lock:
            if self._state != "idle":
                raise ValueError(
                    f"a swap is already in progress (state={self._state}, "
                    f"target={self._target!r}); wait for it or roll back "
                    "after it commits"
                )
            self._state = "building"
            self._target = target
            self._thread = threading.Thread(
                target=self._swap_thread, args=(target,),
                name="c2v-swap-build", daemon=True,
            )
            thread = self._thread
        self._emit("swap_started", target=target,
                   active_version=self.active.version)
        thread.start()
        if wait:
            thread.join()
        return self.status()

    def wait(self, timeout: float | None = None) -> dict:
        """Block until any in-progress swap finishes; returns status."""
        thread = self._thread
        if thread is not None and thread.is_alive():
            thread.join(timeout)
        return self.status()

    def _swap_thread(self, target: str | None) -> None:
        t0 = time.perf_counter()
        gen: Generation | None = None
        try:
            gen = self._build(target)
            build_ms = (time.perf_counter() - t0) * 1e3
            with self._lock:
                self._state = "validating"
            t1 = time.perf_counter()
            report = validate_generation(gen, self.golden)
            validate_ms = (time.perf_counter() - t1) * 1e3
        except BaseException as exc:  # noqa: BLE001 - recorded, not raised
            if gen is not None:
                try:
                    gen.close(self._close_timeout)
                except Exception:  # pragma: no cover - half-built batcher
                    pass
            with self._lock:
                self._state = "idle"
                self._target = None
                self._last = {
                    "target": target,
                    "outcome": "failed",
                    "error": f"{type(exc).__name__}: {exc}",
                }
            self._failed.inc()
            logger.warning("swap to %r failed: %s", target, exc)
            self._emit("swap_failed", target=target,
                       error=f"{type(exc).__name__}: {exc}",
                       active_version=self.active.version)
            return
        with self._lock:
            retired = self.previous
            self.previous = self.active
            self.active = gen
            self._state = "idle"
            self._target = None
            self._last = {
                "target": target,
                "outcome": "committed",
                "version": gen.version,
                "build_ms": round(build_ms, 1),
                "validate_ms": round(validate_ms, 1),
                **report,
            }
            last = dict(self._last)
        self._swaps.inc()
        self._health.gauge("serve_active_version").set(gen.version)
        logger.info(
            "swap committed: %s is live (built %.0f ms, validated %.0f ms "
            "over %d golden requests); %s resident for rollback",
            gen.version, build_ms, validate_ms, last.get("golden_requests", 0),
            self.previous.version,
        )
        self._emit("swap_committed", **last)
        if retired is not None:
            # only now does the oldest generation go away — and it drains:
            # anything still in its queue resolves before close returns
            retired.close(self._close_timeout)
            self._emit("generation_retired", version=retired.version)

    # ---- rollback -------------------------------------------------------
    def rollback(self) -> dict:
        """Instant pointer swap back to the previous resident generation —
        its executables and tables were never torn down, so the next
        request reproduces that version's bitwise-identical outputs."""
        with self._lock:
            if self._state != "idle":
                raise ValueError(
                    f"cannot roll back while a swap is in progress "
                    f"(state={self._state})"
                )
            if self.previous is None:
                raise ValueError(
                    "no previous generation resident — nothing to roll "
                    "back to"
                )
            self.active, self.previous = self.previous, self.active
            self._last = {
                "target": self.active.version,
                "outcome": "rolled_back",
                "version": self.active.version,
            }
            # snapshot under the lock: a reload racing this rollback could
            # repoint active/previous between release and the log/emit below
            restored, demoted = self.active.version, self.previous.version
        self._rollbacks.inc()
        self._health.gauge("serve_active_version").set(restored)
        logger.info("rolled back to %s (%s stays resident)",
                    restored, demoted)
        self._emit("rollback", version=restored, demoted_version=demoted)
        return self.status()

    # ---- lifecycle ------------------------------------------------------
    def close(self) -> None:
        """Join any in-progress swap, then drain every resident
        generation's batcher."""
        thread = self._thread
        if thread is not None and thread.is_alive():
            thread.join(self._close_timeout)
        with self._lock:
            generations = (self.active, self.previous)
        for gen in generations:
            if gen is not None:
                try:
                    gen.close(self._close_timeout)
                except Exception:  # pragma: no cover - already closed
                    pass
