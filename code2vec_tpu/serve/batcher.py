"""Continuous micro-batcher: coalesce concurrent requests into one device call.

Same machinery family as ``train/prefetch.py`` — one background thread,
a bounded FIFO queue, explicit shutdown semantics — but inverted: the
prefetcher runs a *known* batch stream ahead of one consumer, while the
batcher gathers *unknown* concurrent requests behind one device. The loop:

1. block until a request arrives;
2. coalesce more arrivals for at most ``deadline_ms`` (or until the
   engine's top micro-batch size fills) — under low load the deadline
   expires with a single request, which dispatches alone through the
   batch-1 executable: the deterministic single-request fallback;
3. pad the group to its nearest bucket width + micro-batch size
   (``ServingEngine.pad_requests`` — the trainer's padding rule, so every
   group hits a warm AOT executable);
4. ONE device call; scatter rows back to per-request futures.

Batched and one-at-a-time execution are bitwise-equal per request: every
per-row op in the forward (gather, matmul-per-row, layernorm, masked
softmax, pool) is independent of the other rows, and PAD lanes contribute
exact zeros (the PR-4 bucketing invariant, pinned by tests/test_serve.py).

Backpressure is explicit: the queue holds at most ``max_pending``
requests and :meth:`submit` raises :class:`ServeOverloaded` instead of
buffering unboundedly — the transport maps it to a retryable 429-class
error. Shutdown drains: queued and in-flight requests complete before
:meth:`close` returns; submissions after close raise
:class:`ServerClosed`.

Every phase is measured per request/group: ``queue_wait`` / ``pad`` /
``device`` / ``postprocess`` spans on the tracer, the same buckets as
latency histograms on the health registry (``serve.queue_wait_ms`` etc.),
plus ``serve_requests`` / ``serve_batches`` / ``serve_coalesced`` /
``serve_rejected`` counters and a live ``serve_queue_depth`` gauge —
``bench.py --serve`` reads p50/p99 straight from these, and the fleet
router's shedding decisions read the same schema (no ad-hoc state).
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from contextlib import nullcontext
from dataclasses import dataclass

import numpy as np

from code2vec_tpu.obs.runtime import (
    FlightRecorder,
    RuntimeHealth,
    global_health,
)
from code2vec_tpu.obs import handles
from code2vec_tpu.obs.sync import make_lock
from code2vec_tpu.obs.trace import TraceContext, get_tracer, trace_scope

__all__ = ["MicroBatcher", "ServeOverloaded", "ServerClosed", "ServeResult"]


class ServeOverloaded(RuntimeError):
    """The pending queue is full — shed load instead of buffering."""


class ServerClosed(RuntimeError):
    """submit() after close(): the server is shutting down."""


@dataclass
class ServeResult:
    """One request's slice of a device call, host-side."""

    logits: np.ndarray  # [label_count_padded] f32
    code_vector: np.ndarray  # [encode_size] f32
    attention: np.ndarray  # [n_contexts] f32 (PAD lanes stripped)
    n_contexts: int
    batch: int  # the executable's micro-batch size
    width: int  # the executable's bucket width
    coalesced: int  # how many requests shared the device call
    queue_wait_ms: float
    device_ms: float


class _Pending:
    __slots__ = ("contexts", "future", "enqueued", "trace", "depth")

    def __init__(self, contexts: np.ndarray, trace=None):
        self.contexts = contexts
        self.future: Future = Future()
        self.enqueued = time.perf_counter()
        self.trace = trace  # TraceContext | None (cross-process tracing)
        self.depth = 0  # queue depth observed at admission


class MicroBatcher:
    """Bounded-queue request coalescer in front of a :class:`ServingEngine`.

    ``deadline_ms``: how long the first request of a group waits for
    company — the latency/efficiency dial (0 = dispatch immediately,
    strictly one request per device call). ``max_batch`` defaults to the
    engine's top micro-batch size; ``max_pending`` bounds queued (not yet
    dispatched) requests.
    """

    _POLL_S = 0.05  # close-check cadence while idle

    def __init__(
        self,
        engine,
        deadline_ms: float = 2.0,
        max_batch: int | None = None,
        max_pending: int = 256,
        health: RuntimeHealth | None = None,
        flight: FlightRecorder | None = None,
    ) -> None:
        if deadline_ms < 0:
            raise ValueError(f"deadline_ms must be >= 0, got {deadline_ms}")
        self._engine = engine
        self._deadline_s = float(deadline_ms) / 1e3
        # groups never exceed the top compiled micro-batch size — a larger
        # cap would force the engine onto an uncompiled shape
        top = max(engine.batch_sizes)
        self._max_batch = min(int(max_batch or top), top)
        if self._max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {self._max_batch}")
        self._health = health or global_health()
        self._flight = flight
        self._queue: queue.Queue = queue.Queue(maxsize=int(max_pending))
        self._closed = threading.Event()
        # serializes submit's closed-check+enqueue against close's
        # flag-set: without it a submit could pass the check, lose the
        # CPU, and enqueue after close() already swept the queue —
        # leaving its future pending forever
        self._submit_lock = make_lock("batcher.submit")
        self._requests = self._health.counter("serve_requests")
        self._batches = self._health.counter("serve_batches")
        self._coalesced = self._health.counter("serve_coalesced")
        self._rejected = self._health.counter("serve_rejected")
        # live queue depth for the router's shedding decisions and
        # dashboards — same registry/schema as the shed counter and the
        # per-phase latency histograms (one obs schema, no ad-hoc state)
        self._depth = self._health.gauge("serve_queue_depth")
        self._depth.set(0)
        self._thread = threading.Thread(
            target=self._loop, name="c2v-micro-batcher", daemon=True
        )
        self._thread.start()
        handles.track(self, "batcher")

    # ---- caller side ----------------------------------------------------
    def submit(self, contexts, trace: TraceContext | None = None) -> Future:
        """Enqueue one request (an ``[n, 3]`` array of mapped
        (start, path, end) vocab ids); resolves to a :class:`ServeResult`.
        ``trace`` is the request's cross-process trace context: the
        coalesced device call's span records every member's trace id.
        Raises :class:`ServerClosed` after close, :class:`ServeOverloaded`
        when ``max_pending`` requests are already waiting."""
        pending = _Pending(
            np.asarray(contexts, np.int32).reshape(-1, 3), trace=trace
        )
        max_width = getattr(self._engine, "max_width", None)
        if max_width is not None and len(pending.contexts) > max_width:
            # reject loudly instead of silently truncating the bag: the
            # caller (the protocol layer, predict-style subsampling) owns
            # the decision of WHICH contexts to drop
            raise ValueError(
                f"request has {len(pending.contexts)} contexts, more than "
                f"the model's max bag width {max_width}; subsample before "
                "submitting"
            )
        with self._submit_lock:
            if self._closed.is_set():
                raise ServerClosed("micro-batcher is closed")
            # depth BEFORE this request joined: the flight recorder's
            # "what did the queue look like at admission" field
            pending.depth = self._queue.qsize()
            try:
                self._queue.put_nowait(pending)
            except queue.Full:
                self._rejected.inc()
                raise ServeOverloaded(
                    f"serving queue is full ({self._queue.maxsize} pending); "
                    "retry with backoff"
                ) from None
            self._depth.set(self._queue.qsize())
        self._requests.inc()
        return pending.future

    def close(self, timeout: float = 30.0) -> None:
        """Stop accepting requests, DRAIN everything already queued (every
        accepted future resolves), and join the thread. Idempotent."""
        with self._submit_lock:
            self._closed.set()
        self._thread.join(timeout)
        if self._thread.is_alive():  # pragma: no cover - hung device call
            raise TimeoutError("micro-batcher did not drain in time")
        # anything enqueued before the flag flipped but after the drain
        # loop's last empty poll — fail it loudly rather than leave its
        # future pending forever (the submit lock guarantees nothing can
        # enqueue after this sweep)
        while True:
            try:
                leftover = self._queue.get_nowait()
            except queue.Empty:
                break
            if not leftover.future.done():
                leftover.future.set_exception(
                    ServerClosed("micro-batcher closed before dispatch")
                )
        handles.untrack(self)

    def __enter__(self) -> "MicroBatcher":
        return self

    def __exit__(self, *exc_info) -> None:
        self.close()

    # ---- batcher thread -------------------------------------------------
    def _loop(self) -> None:
        while True:
            try:
                first = self._queue.get(timeout=self._POLL_S)
            except queue.Empty:
                if self._closed.is_set():
                    # closed observed: submit serializes its closed-check +
                    # enqueue against close's flag-set, so every ACCEPTED
                    # request is already visible in the queue — but an item
                    # can land in the gap between this poll's timeout
                    # expiring and the flag check. One final non-blocking
                    # drain before exiting, or that accepted request would
                    # be failed by close()'s sweep instead of served (the
                    # drop the fleet's SIGTERM-eviction path would hit).
                    try:
                        first = self._queue.get_nowait()
                    except queue.Empty:
                        return
                else:
                    continue
            self._depth.set(self._queue.qsize())
            group = [first]
            t_end = time.perf_counter() + self._deadline_s
            while len(group) < self._max_batch:
                if self._closed.is_set():
                    # draining: take whatever is already queued, never wait
                    try:
                        group.append(self._queue.get_nowait())
                        continue
                    except queue.Empty:
                        break
                remaining = t_end - time.perf_counter()
                if remaining <= 0:
                    break
                try:
                    group.append(self._queue.get(timeout=remaining))
                except queue.Empty:
                    break
            try:
                self._run_group(group)
            except BaseException as exc:  # noqa: BLE001 - scattered to callers
                for pending in group:
                    if not pending.future.done():
                        pending.future.set_exception(exc)

    def _run_group(self, group: list[_Pending]) -> None:
        tracer = get_tracer()
        engine = self._engine
        t_start = time.perf_counter()
        # the coalesce-aware link: a batched device span records the N
        # trace ids it served, so a stitched trace can walk from any one
        # request's id into the shared device call (and see who else rode
        # it). Built only when someone traced — the untraced hot path
        # stays an empty-list comprehension.
        trace_ids = [p.trace.trace_id for p in group if p.trace is not None]
        span_trace = {"trace_ids": trace_ids} if trace_ids else {}
        for pending in group:
            engine.observe_width(len(pending.contexts))
        with tracer.span(
            "serve_pad", category="serve", requests=len(group), **span_trace
        ):
            t0 = time.perf_counter()
            starts, paths, ends, batch, width = engine.pad_requests(
                [p.contexts for p in group]
            )
            pad_ms = (time.perf_counter() - t0) * 1e3
        with tracer.span(
            "serve_device", category="serve",
            batch=batch, width=width, requests=len(group), **span_trace,
        ):
            t0 = time.perf_counter()
            # thread-local scope, not a signature change: the engine's own
            # device-call span picks the trace ids up without widening
            # run() (duck-typed engines keep their 3-arg surface)
            with trace_scope(**span_trace) if span_trace else nullcontext():
                logits, vectors, attention = engine.run(starts, paths, ends)
            # the scatter below reads host values anyway; fencing here
            # attributes the wait to the device phase, not postprocess
            logits = np.asarray(logits)
            vectors = np.asarray(vectors)
            attention = np.asarray(attention)
            device_ms = (time.perf_counter() - t0) * 1e3
        # perf accounting rides the span we already timed — O(1) counter
        # math in the engine's accountant, guarded so duck-typed engines
        # without the hook keep working
        record_perf = getattr(engine, "record_device_time", None)
        if record_perf is not None:
            record_perf(batch, width, device_ms, requests=len(group))
        t_device_end = time.perf_counter()
        with tracer.span("serve_postprocess", category="serve", **span_trace):
            for i, pending in enumerate(group):
                n = int(pending.contexts.shape[0])
                queue_wait_ms = (t_start - pending.enqueued) * 1e3
                pending.future.set_result(
                    ServeResult(
                        logits=logits[i],
                        code_vector=vectors[i],
                        attention=attention[i, : min(n, width)],
                        n_contexts=n,
                        batch=batch,
                        width=width,
                        coalesced=len(group),
                        queue_wait_ms=round(queue_wait_ms, 3),
                        device_ms=round(device_ms, 3),
                    )
                )
                now = time.perf_counter()
                e2e_ms = (now - pending.enqueued) * 1e3
                self._health.latency("serve.queue_wait_ms").record(queue_wait_ms)
                self._health.latency("serve.e2e_ms").record(e2e_ms)
                if self._flight is not None:
                    # full span breakdown for the tail: the recorder
                    # decides (threshold / p99) whether to keep it
                    self._flight.observe(e2e_ms, {
                        "kind": "serve",
                        "trace_id": (
                            pending.trace.trace_id if pending.trace else None
                        ),
                        "n_contexts": n,
                        "queue_wait_ms": round(queue_wait_ms, 3),
                        "pad_ms": round(pad_ms, 3),
                        "device_ms": round(device_ms, 3),
                        "postprocess_ms": round(
                            (now - t_device_end) * 1e3, 3
                        ),
                        "batch": batch,
                        "width": width,
                        "coalesced": len(group),
                        "queue_depth_at_admission": pending.depth,
                    })
        self._health.latency("serve.pad_ms").record(pad_ms)
        self._health.latency("serve.device_ms").record(device_ms)
        self._batches.inc()
        if len(group) > 1:
            self._coalesced.inc(len(group))
