"""``python -m code2vec_tpu.serve`` — start the online server.

Startup order matters and is the whole point: pin the backend, pin the
autotune cache, load the checkpoint (quantizing tables once), AOT-compile
the full executable ladder, write the run manifest WITH per-executable
schedule provenance — and only then accept traffic, so the first request
is as fast as the millionth.

    python -m code2vec_tpu.serve --model_path out \\
        --terminal_idx_path ds/terminal_idxs.txt \\
        --path_idx_path ds/path_idxs.txt \\
        --transport stdio        # or: --transport http --port 8080
"""

from __future__ import annotations

import argparse
import logging
import os

logger = logging.getLogger(__name__)


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="code2vec_tpu.serve",
        description="code2vec-as-a-service: compiled online inference + "
        "nearest-method retrieval",
    )
    parser.add_argument("--model_path", required=True,
                        help="train output dir (checkpoint + model_meta.json)")
    parser.add_argument("--terminal_idx_path", required=True)
    parser.add_argument("--path_idx_path", required=True)
    parser.add_argument("--transport", default="stdio",
                        choices=("stdio", "http"),
                        help="stdio = JSONL request/response over "
                        "stdin/stdout; http = stdlib threading server")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8080)
    parser.add_argument("--table_dtype", default=None,
                        choices=("f32", "bf16", "int8"),
                        help="embedding-table storage for the serving "
                        "forward (default: the checkpoint's meta)")
    parser.add_argument("--batch_sizes", default="1,8",
                        help="comma list of micro-batch sizes to compile "
                        "executables for (the batcher pads request groups "
                        "to the smallest fitting size)")
    parser.add_argument("--deadline_ms", type=float, default=2.0,
                        help="micro-batcher coalescing window: how long "
                        "the first request of a group waits for company "
                        "(0 = dispatch immediately, one request per call)")
    parser.add_argument("--max_pending", type=int, default=256,
                        help="queued-request bound; beyond it submissions "
                        "are rejected as overloaded (shed, don't buffer)")
    parser.add_argument("--warmup_requests", type=int, default=64,
                        help="histogram-fallback sample size when the "
                        "checkpoint's meta has no recorded bucket ladder")
    parser.add_argument("--longbag_widths", default="",
                        help="comma list of longbag rungs to compile ABOVE "
                        "the checkpoint's bag width (e.g. 512,2048): "
                        "oversized requests then serve through these "
                        "executables instead of being rejected. Runs "
                        "trained with --max_contexts 0 record their rungs "
                        "in model_meta.json and need no flag")
    parser.add_argument("--golden_min_recall", type=float, default=0.9,
                        help="hot-swap validation: minimum neighbors "
                        "recall@k the shadow generation's retrieval "
                        "backend must hit against a brute-force reference "
                        "before a reload may commit (serve/swap.py)")
    parser.add_argument("--autotune_cache", default="",
                        help="kernel-schedule cache consulted per compiled "
                        "executable (ops/autotune.py; default "
                        "$C2V_AUTOTUNE_CACHE or the user cache path)")
    parser.add_argument("--code_vec_path", default=None,
                        help="exported code.vec for the neighbors op "
                        "(default: <model_path>/code.vec when present)")
    parser.add_argument("--retrieval_backend", default="exact",
                        choices=("exact", "ann"),
                        help="neighbors backend: 'exact' = full O(N) "
                        "matmul over code.vec (default, bitwise-stable); "
                        "'ann' = IVF-PQ index with exact re-rank "
                        "(tools/ann_build.py)")
    parser.add_argument("--ann_index_path", default=None,
                        help="ANN index container for --retrieval_backend "
                        "ann (default: <model_path>/ann.index when "
                        "present)")
    parser.add_argument("--ann_n_probe", type=int, default=None,
                        help="cells probed per ANN query (default: the "
                        "index container's baked-in value)")
    parser.add_argument("--ann_shortlist", type=int, default=None,
                        help="ANN shortlist re-ranked exactly per query "
                        "(default: the container's baked-in value)")
    parser.add_argument("--accelerator", action="store_true", default=False,
                        help="serve from the default device backend; off = "
                        "pin CPU (same contract as the predict CLI)")
    parser.add_argument("--events_dir", default=None,
                        help="JSONL event log (run manifest with the "
                        "executable ladder + schedule provenance, then "
                        "serve_executable/... events)")
    parser.add_argument("--trace_dir", default=None,
                        help="Chrome trace of the serve spans "
                        "(queue_wait/pad/device/postprocess); spans carry "
                        "the request's trace id when one rides the "
                        "request's 'trace' field (the fleet router stamps "
                        "it at admission) — tools/trace_stitch.py merges "
                        "per-process files into one fleet-wide trace")
    parser.add_argument("--flight_threshold_ms", type=float, default=0.0,
                        help="slow-request flight recorder: capture a "
                        "full per-request span breakdown for any request "
                        "slower than this many ms (0 = p99 sampling "
                        "only); records land as `flight` events and as "
                        "flight_*.json dumps under <events_dir>/flight")
    parser.add_argument("--sync_debug", action="store_true", default=False,
                        help="lock sanitizer: trace every factory-built "
                        "lock (acquisition-order cycle detection, "
                        "hold/wait/contention metrics); equivalent to "
                        "C2V_SYNC_DEBUG=1. Off by default — the factory "
                        "then returns plain threading primitives")
    parser.add_argument("--handle_debug", action="store_true", default=False,
                        help="handle ledger: track every lifecycle object "
                        "(batchers, generations, mmap readers, event "
                        "logs) with creation-site stacks; per-kind "
                        "c2v_handles_open gauges, a handles health "
                        "block, and a handle_leak shutdown report. "
                        "Equivalent to C2V_HANDLE_DEBUG=1; off by "
                        "default — track() is then a no-op")
    return parser


def _build_retrieval(args, model_path: str):
    """The retrieval backend for one generation — resolved against THAT
    generation's model dir (a reloaded checkpoint brings its own exported
    code.vec / ann.index along)."""
    from code2vec_tpu.serve.retrieval import RetrievalIndex

    if args.retrieval_backend == "ann":
        from code2vec_tpu.serve.retrieval import load_retrieval_index

        ann_path = args.ann_index_path
        if ann_path is None:
            default = os.path.join(model_path, "ann.index")
            ann_path = default if os.path.exists(default) else None
        return load_retrieval_index(
            "ann",
            ann_index_path=ann_path,
            n_probe=args.ann_n_probe,
            shortlist=args.ann_shortlist,
        )
    code_vec_path = args.code_vec_path
    if code_vec_path is None:
        default = os.path.join(model_path, "code.vec")
        code_vec_path = default if os.path.exists(default) else None
    if code_vec_path:
        return RetrievalIndex.from_code_vec(code_vec_path)
    return None


def make_generation_factory(args, events=None, start=0, flight=None):
    """``build(target) -> Generation``: load a checkpoint (``target`` is
    its model dir; None = the CLI's ``--model_path``), AOT-compile its
    full executable ladder, load retrieval, stand up a micro-batcher.
    Called once at startup for generation 0 and again — on the swap
    controller's background thread — for every ``reload``. ``flight`` is
    the process-wide slow-request recorder; every generation's batcher
    feeds the same one (a swap must not reset tail forensics)."""
    import itertools

    from code2vec_tpu.predict import Predictor
    from code2vec_tpu.serve.batcher import MicroBatcher
    from code2vec_tpu.serve.engine import ServingEngine
    from code2vec_tpu.serve.swap import Generation

    batch_sizes = tuple(
        int(tok) for tok in str(args.batch_sizes).split(",") if tok.strip()
    )
    counter = itertools.count(start)

    def build(target: str | None) -> "Generation":
        model_path = target or args.model_path
        if not os.path.isdir(model_path):
            raise ValueError(f"model_path {model_path!r} is not a directory")
        version = f"{model_path}#g{next(counter)}"
        predictor = Predictor(
            model_path, args.terminal_idx_path, args.path_idx_path,
            table_dtype=args.table_dtype,
        )
        engine_kw = {}
        longbag = tuple(sorted({
            int(tok)
            for tok in str(getattr(args, "longbag_widths", "") or "").split(",")
            if tok.strip()
        }))
        if longbag:
            # operator-pinned longbag rungs (old checkpoints without
            # recorded rungs): extend whatever ladder the meta carries
            base = (
                predictor.ladder if predictor.ladder_recorded
                else (predictor.bag,)
            )
            extra = tuple(w for w in longbag if w > base[-1])
            if len(extra) != len(longbag):
                raise ValueError(
                    f"--longbag_widths must all exceed the ladder top "
                    f"{base[-1]}, got {list(longbag)}"
                )
            engine_kw["ladder"] = tuple(base) + extra
        engine = ServingEngine.from_predictor(
            predictor,
            batch_sizes=batch_sizes,
            autotune_cache=args.autotune_cache or None,
            warmup_requests=args.warmup_requests,
            events=events,
            version=version,
            **engine_kw,
        )
        provenance = engine.prepare()
        logger.info(
            "[%s] compiled %d executables over ladder %s x batch sizes %s",
            version, len(provenance), list(engine.active_ladder),
            list(engine.batch_sizes),
        )
        retrieval = _build_retrieval(args, model_path)
        batcher = MicroBatcher(
            engine,
            deadline_ms=args.deadline_ms,
            max_pending=args.max_pending,
            flight=flight,
        )
        return Generation(
            version=version, predictor=predictor, engine=engine,
            batcher=batcher, retrieval=retrieval, provenance=provenance,
        )

    return build


def build_server(args):
    """Everything between arg parsing and the transport loop, importable
    so tests can drive a fully-assembled server without a subprocess."""
    from code2vec_tpu.obs.runtime import global_health
    from code2vec_tpu.serve.protocol import CodeServer
    from code2vec_tpu.serve.swap import GoldenSet

    # the sanitizer switch must flip BEFORE any lock is constructed below
    # (batcher, engine, swap controller all build their locks here);
    # make_lock reads the env at call time, so this is the whole wiring
    if getattr(args, "sync_debug", False):
        from code2vec_tpu.obs.sync import SYNC_DEBUG_ENV

        os.environ[SYNC_DEBUG_ENV] = "1"
    # likewise the ledger switch, BEFORE any lifecycle owner (flight
    # recorder, batcher, generation 0) is constructed below
    if getattr(args, "handle_debug", False):
        from code2vec_tpu.obs.handles import HANDLE_DEBUG_ENV

        os.environ[HANDLE_DEBUG_ENV] = "1"

    # pin the schedule cache BEFORE the first trace, exactly like train()
    # and export_from_checkpoint do
    if args.autotune_cache:
        from code2vec_tpu.ops.autotune import get_cache

        get_cache(args.autotune_cache)

    events = None
    if args.events_dir:
        from code2vec_tpu.obs.events import EventLog

        events = EventLog(args.events_dir)
        from code2vec_tpu.obs.sync import register_event_log, sync_debug_enabled

        if sync_debug_enabled():
            # lock_order_violation events land in this worker's own log
            register_event_log(events)
        from code2vec_tpu.obs.handles import handle_debug_enabled
        from code2vec_tpu.obs.handles import (
            register_event_log as register_handle_log,
        )

        if handle_debug_enabled():
            # handle_leak events from the shutdown report land here too
            register_handle_log(events)

    # slow-request flight recorder: one per process, shared by every
    # generation's batcher (constructed without the event log for the
    # same manifest-first reason as the factory below; attached after)
    from code2vec_tpu.obs.runtime import FlightRecorder

    threshold = getattr(args, "flight_threshold_ms", 0.0)
    flight = FlightRecorder(
        threshold_ms=threshold if threshold > 0 else None,
        health=global_health(),
    )

    # the factory builds generation 0 WITHOUT the event log attached (the
    # manifest must stay the log's first line), then every later
    # generation with it
    factory = make_generation_factory(args, events=None, flight=flight)
    gen0 = factory(None)
    engine, retrieval = gen0.engine, gen0.retrieval

    if events is not None:
        events.write_manifest(
            serve={
                "model_path": args.model_path,
                "transport": args.transport,
                "version": gen0.version,
                "table_dtype": engine.table_dtype,
                "ladder": list(engine.active_ladder),
                "batch_sizes": list(engine.batch_sizes),
                "deadline_ms": args.deadline_ms,
                # per-executable schedule provenance: which tuned kernel
                # schedule each compiled shape consulted, and whether the
                # cache covered it (the --expect-cached-style warmup)
                "executables": gen0.provenance,
                # retrieval-backend provenance, mirroring the executables:
                # backend kind, index geometry, and (ann) the LUT-kernel
                # schedule the searcher consulted
                "retrieval": (
                    retrieval.describe() if retrieval is not None else None
                ),
            }
        )
        # attach the log only AFTER the manifest so it stays the first
        # line; later compiles (histogram-freeze, shape misses, shadow
        # builds) still get their own serve_executable events
        engine._events = events
        flight._events = events
        factory = make_generation_factory(
            args, events=events, start=1, flight=flight
        )

    server = CodeServer(
        gen0.predictor, engine, gen0.batcher, retrieval=retrieval,
        version=gen0.version, factory=factory,
        golden=GoldenSet(min_recall=args.golden_min_recall),
        events=events, flight=flight, generation=gen0,
    )
    health = global_health()
    health.gauge("serve_transport").set(args.transport)
    return server, events


def main(argv: list[str] | None = None) -> None:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s: %(message)s",
        datefmt="%m/%d/%Y %I:%M:%S %p",
    )
    args = build_parser().parse_args(argv)

    from code2vec_tpu.cli import pin_platform

    pin_platform(not args.accelerator)

    tracer = None
    if args.trace_dir:
        from code2vec_tpu.obs.trace import Tracer, set_tracer

        # name the process row so a stitched fleet trace reads
        # router/worker at a glance (the stitcher prefixes the source dir)
        tracer = Tracer(process_name=f"serve-worker-{os.getpid()}")
        set_tracer(tracer)

    server, events = build_server(args)

    # SIGTERM = graceful drain, not an abrupt exit (run_transport): the
    # path fleet eviction and rolling restarts hit — a worker that drops
    # queued requests on SIGTERM turns every eviction into client-visible
    # failures.
    from code2vec_tpu.serve.protocol import run_transport

    try:
        run_transport(server, args.transport, args.host, args.port)
    finally:
        if tracer is not None:
            from code2vec_tpu.obs.trace import set_tracer

            set_tracer(None)
            try:
                tracer.export_dir(args.trace_dir)
            except Exception:
                logger.warning("could not write chrome trace", exc_info=True)
        if args.events_dir and server.flight is not None:
            # tail forensics survive the process: every captured record
            # as its own flight_<seq>.json next to the event log
            try:
                server.flight.dump(os.path.join(args.events_dir, "flight"))
            except Exception:
                logger.warning("could not dump flight records", exc_info=True)
        # shutdown leak report: run_transport already closed the server
        # (generations, batchers, flight recorder all untracked), so any
        # handle still open here is a leak — named by its creation site.
        # The event log itself is legitimately open until the line below.
        from code2vec_tpu.obs.handles import handle_debug_enabled, report_leaks

        if handle_debug_enabled():
            exclude = (events,) if events is not None else ()
            report_leaks("serve.shutdown", events=events, exclude=exclude)
        if events is not None:
            try:
                events.close()
            except Exception:
                logger.warning("could not close event log", exc_info=True)


if __name__ == "__main__":
    main()
