"""Compiled online serving: code2vec-as-a-service.

The training side of this repo ends at a checkpoint plus an exported
code-vector matrix; this package is the online path in front of them
(ROADMAP item 1 — "the single biggest step toward the millions-of-users
north star"). Three pieces, composable and individually testable:

- :mod:`engine` — the **AOT executable ladder**: at server start the
  predict forward is lowered and compiled once per (micro-batch size,
  bucket width) from the ladder recorded at train time
  (``model_meta.json``), consulting the PR-8 autotuned schedule cache and
  quantized tables, so every request shape dispatches into a warm
  ``jax.jit(...).lower().compile()`` executable and the hot path performs
  ZERO tracing (asserted via the obs ``RecompileDetector``: the engine
  exposes a ``_cache_size`` probe over its executable table).
- :mod:`batcher` — the **continuous micro-batcher**: a bounded-queue
  coalescer (the ``train/prefetch.py`` machinery family) that gathers
  concurrent requests within a deadline, pads them to the nearest bucket
  width (``data/pipeline.nearest_bucket_width`` — the same rule the
  bucketed trainer and ``predict.Predictor`` use), runs ONE device call,
  and scatters rows back to per-request futures. Under low load it
  degrades to a deterministic single-request dispatch.
- :mod:`retrieval` — **top-k nearest-method search** over the exported
  ``code.vec`` matrix, the query→matrix matmul sharded across the mesh by
  the ``parallel/shardings.retrieval_shardings`` rule (row-sharded like
  the embedding tables).

:mod:`protocol` wires them behind a transport-thin server (stdio-JSONL or
stdlib HTTP — the request handling is a plain ``dict -> dict`` function,
testable without sockets), and ``python -m code2vec_tpu.serve`` is the
CLI. Every phase is measured: per-request queue_wait / pad / device /
postprocess spans and ``serve_*`` counters via ``obs``, with
``bench.py --serve`` as the open-loop p50/p99 + QPS load harness.
"""

from code2vec_tpu.serve.batcher import MicroBatcher, ServeOverloaded, ServerClosed
from code2vec_tpu.serve.engine import ServingEngine
from code2vec_tpu.serve.retrieval import RetrievalIndex

__all__ = [
    "MicroBatcher",
    "RetrievalIndex",
    "ServeOverloaded",
    "ServerClosed",
    "ServingEngine",
]
