"""Compiled online serving: code2vec-as-a-service.

The training side of this repo ends at a checkpoint plus an exported
code-vector matrix; this package is the online path in front of them
(ROADMAP item 1 — "the single biggest step toward the millions-of-users
north star"). Three pieces, composable and individually testable:

- :mod:`engine` — the **AOT executable ladder**: at server start the
  predict forward is lowered and compiled once per (micro-batch size,
  bucket width) from the ladder recorded at train time
  (``model_meta.json``), consulting the PR-8 autotuned schedule cache and
  quantized tables, so every request shape dispatches into a warm
  ``jax.jit(...).lower().compile()`` executable and the hot path performs
  ZERO tracing (asserted via the obs ``RecompileDetector``: the engine
  exposes a ``_cache_size`` probe over its executable table).
- :mod:`batcher` — the **continuous micro-batcher**: a bounded-queue
  coalescer (the ``train/prefetch.py`` machinery family) that gathers
  concurrent requests within a deadline, pads them to the nearest bucket
  width (``data/pipeline.nearest_bucket_width`` — the same rule the
  bucketed trainer and ``predict.Predictor`` use), runs ONE device call,
  and scatters rows back to per-request futures. Under low load it
  degrades to a deterministic single-request dispatch.
- :mod:`retrieval` — **top-k nearest-method search** over the exported
  ``code.vec`` matrix, the query→matrix matmul sharded across the mesh by
  the ``parallel/shardings.retrieval_shardings`` rule (row-sharded like
  the embedding tables).

- :mod:`swap` — **live checkpoint hot-swap**: serving state is bundled
  into swappable *generations* (predictor + AOT ladder + batcher +
  retrieval); a ``reload`` control op shadow-compiles the new version on
  a background thread, validates it against a golden request set (bitwise
  embeddings, recall-bounded neighbors), and atomically swaps the serving
  pointer without dropping in-flight requests — the old generation stays
  resident for an instant ``rollback``.
- :mod:`fleet` — **fleet serving**: a jax-free router process fanning
  requests over N replica workers (subprocesses of this very CLI on
  stdio), with per-SLO-class queue budgets/deadlines (tiered load
  shedding), health-probe-driven eviction/respawn, and rolling hot-swap
  across the fleet. ``python -m code2vec_tpu.serve.fleet`` is its CLI.

:mod:`protocol` wires them behind a transport-thin server (stdio-JSONL or
stdlib HTTP — the request handling is a plain ``dict -> dict`` function,
testable without sockets), and ``python -m code2vec_tpu.serve`` is the
CLI. Every phase is measured: per-request queue_wait / pad / device /
postprocess spans, per-op latency histograms, and ``serve_*`` counters
via ``obs``, with ``bench.py --serve`` as the open-loop p50/p99 + QPS
load harness (``--rolling-swap`` adds a mid-stream hot-swap + rollback).
"""

# PEP 562 lazy exports (the analysis package's pattern): importing any
# serve submodule must not drag in the whole stack — in particular the
# fleet ROUTER process imports serve.protocol for its transports and is
# deliberately jax-free (it moves dicts, never tensors); an eager
# `from .engine import ...` here would cost it the full jax import.
_EXPORTS = {
    "Generation": "code2vec_tpu.serve.swap",
    "GoldenSet": "code2vec_tpu.serve.swap",
    "MicroBatcher": "code2vec_tpu.serve.batcher",
    "RetrievalIndex": "code2vec_tpu.serve.retrieval",
    "ServeOverloaded": "code2vec_tpu.serve.batcher",
    "ServerClosed": "code2vec_tpu.serve.batcher",
    "ServingEngine": "code2vec_tpu.serve.engine",
    "SwapController": "code2vec_tpu.serve.swap",
    "SwapValidationError": "code2vec_tpu.serve.swap",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    target = _EXPORTS.get(name)
    if target is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}"
        )
    import importlib

    return getattr(importlib.import_module(target), name)
