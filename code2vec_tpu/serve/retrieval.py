"""Top-k nearest-method retrieval over the exported code-vector matrix.

``predict.nearest_from_rows`` is the offline NumPy lookup: one matvec per
query on the host. The serving endpoint instead keeps the matrix resident
on the device(s) — L2-normalized once at load, so cosine similarity is a
plain matmul — and answers each query with one compiled
``sims = q @ rows.T`` + ``lax.top_k`` call. On a mesh the matrix rows are
sharded over the ``model`` axis by ``parallel/shardings
.retrieval_shardings`` (the same tall-skinny rule as the embedding
tables): the matmul is fully shard-local and the top-k over the sharded
row axis is the only collective, inserted by GSPMD. Rows are padded to a
multiple of the axis size so the shard actually happens; pad rows carry a
``-inf`` similarity bias so they can never surface.

Parity contract (tests/test_serve.py): identical ranking to a NumPy
normalize→matmul→argsort reference on both the single-device and meshed
paths.
"""

from __future__ import annotations

import logging

import numpy as np

logger = logging.getLogger(__name__)

__all__ = ["RetrievalIndex"]


class RetrievalIndex:
    """Device-resident cosine top-k over ``[n_methods, E]`` vectors."""

    def __init__(self, labels: list[str], rows: np.ndarray, mesh=None) -> None:
        import jax
        import jax.numpy as jnp

        if rows.ndim != 2 or len(labels) != rows.shape[0]:
            raise ValueError(
                f"rows must be [len(labels), E]; got {rows.shape} for "
                f"{len(labels)} labels"
            )
        self.labels = list(labels)
        self.n = len(labels)
        self.dim = int(rows.shape[1])
        self._mesh = mesh

        norms = np.linalg.norm(rows.astype(np.float32), axis=1, keepdims=True)
        unit = rows.astype(np.float32) / np.maximum(norms, 1e-12)

        # pad the row count so the model axis shards it evenly (the
        # _spec_for_param divisibility rule would otherwise silently
        # replicate); pad rows get -inf similarity, never surfacing
        pad_to = 1
        if mesh is not None:
            from code2vec_tpu.parallel.mesh import AXIS_MODEL

            pad_to = max(int(mesh.shape[AXIS_MODEL]), 1)
        n_padded = -(-self.n // pad_to) * pad_to
        if n_padded != self.n:
            unit = np.concatenate(
                [unit, np.zeros((n_padded - self.n, self.dim), np.float32)]
            )
        bias = np.zeros(n_padded, np.float32)
        bias[self.n :] = -np.inf

        if mesh is not None:
            from code2vec_tpu.parallel.shardings import retrieval_shardings

            sh = retrieval_shardings(mesh)
            self._rows = jax.device_put(unit, sh["rows"])
            # the bias aligns with the rows' sharded dim
            from jax.sharding import NamedSharding, PartitionSpec

            self._bias = jax.device_put(
                bias, NamedSharding(mesh, PartitionSpec(sh["rows"].spec[0]))
            )
            self._query_sharding = sh["query"]
        else:
            self._rows = jnp.asarray(unit)
            self._bias = jnp.asarray(bias)
            self._query_sharding = None
        self._fns: dict[int, object] = {}  # k -> jitted query fn

    @classmethod
    def from_code_vec(cls, path: str, mesh=None) -> "RetrievalIndex":
        """Load an exported ``code.vec`` (word2vec text format)."""
        from code2vec_tpu.formats.vectors_io import read_code_vectors

        labels, rows = read_code_vectors(path)
        logger.info(
            "retrieval index: %d vectors of dim %d from %s",
            len(labels), rows.shape[1] if rows.ndim == 2 else -1, path,
        )
        return cls(labels, rows, mesh=mesh)

    # ---- query ----------------------------------------------------------
    def _bucketed_k(self, k: int) -> int:
        """Round ``k`` up to a power of two (capped at n): the jitted
        query fn is compiled per BUCKET, not per client-supplied k, so a
        client sweeping top_k 1..1000 costs at most log2(n) compiles over
        the index's whole lifetime instead of one compile per distinct k
        on the request path — results are sliced back to the exact k."""
        bucket = 1
        while bucket < k:
            bucket *= 2
        return min(bucket, self.n)

    def _cache_size(self) -> int:
        """Compiled query-fn count — lets the obs RecompileDetector track
        the index like the engine's executable table."""
        return len(self._fns)

    def _fn(self, k: int):
        fn = self._fns.get(k)
        if fn is None:
            import jax

            rows, bias = self._rows, self._bias

            def query(q):  # q: [Q, E] unit-normalized
                sims = q @ rows.T + bias[None, :]
                return jax.lax.top_k(sims, k)

            if self._mesh is not None:
                fn = jax.jit(
                    query,
                    in_shardings=self._query_sharding,
                    out_shardings=self._query_sharding,
                )
            else:
                fn = jax.jit(query)
            # jit caches per (k bucket, Q): serving queries are Q=1 per
            # request, so compiles are bounded by log2(n) buckets
            self._fns[k] = fn
        return fn

    def top_k_batch(
        self, vectors: np.ndarray, k: int = 5
    ) -> list[list[tuple[str, float]]]:
        """Cosine top-k per query row of ``vectors [Q, E]``."""
        k = min(int(k), self.n)
        if k < 1:
            return [[] for _ in range(len(vectors))]
        q = np.asarray(vectors, np.float32).reshape(-1, self.dim)
        qn = np.linalg.norm(q, axis=1, keepdims=True)
        q = q / np.maximum(qn, 1e-12)
        values, indices = self._fn(self._bucketed_k(k))(q)
        values = np.asarray(values)[:, :k]
        indices = np.asarray(indices)[:, :k]
        return [
            [
                (self.labels[int(i)], float(v))
                for i, v in zip(indices[row], values[row])
            ]
            for row in range(q.shape[0])
        ]

    def top_k(self, vector: np.ndarray, k: int = 5) -> list[tuple[str, float]]:
        """Single-query convenience wrapper."""
        return self.top_k_batch(np.asarray(vector)[None, :], k)[0]
