"""Top-k nearest-method retrieval: exact matmul and ANN (IVF-PQ) backends.

``predict.nearest_from_rows`` is the offline NumPy lookup: one matvec per
query on the host. The serving endpoint instead offers two device-resident
backends behind one interface (``labels``/``n``/``dim``/``top_k``/
``top_k_batch``/``describe``/``_cache_size``):

- :class:`RetrievalIndex` (``exact``, the default) keeps the matrix
  resident on the device(s) — L2-normalized once at load, so cosine
  similarity is a plain matmul — and answers each query with one compiled
  ``sims = q @ rows.T`` + ``lax.top_k`` call. On a mesh the matrix rows
  are sharded over the ``model`` axis by ``parallel/shardings
  .retrieval_shardings`` (the same tall-skinny rule as the embedding
  tables): the matmul is fully shard-local and the top-k over the sharded
  row axis is the only collective, inserted by GSPMD. Rows are padded to
  a multiple of the axis size so the shard actually happens; pad rows
  carry a ``-inf`` similarity bias so they can never surface.

- :class:`AnnRetrievalIndex` (``ann``) answers from an IVF-PQ index built
  by ``tools/ann_build.py`` (``code2vec_tpu/ann/``): probe ``n_probe`` of
  ``n_list`` cells, LUT-score their quantized codes, exact-f32 re-rank a
  ``shortlist`` against the container's (mmap) unit rows — per-query cost
  proportional to the probed fraction, not the corpus. The response
  schema is identical to the exact backend's; the client's ``k`` only
  enters the host-side re-rank, so the compiled table is keyed by query
  bucket alone.

Both backends bucket compiled entry points by power-of-two query-batch
size AND (exact only) power-of-two k, so a client alternating single and
batched queries (or sweeping top_k) cannot grow the executable table
unboundedly — the ``_cache_size`` probes keep that assertable.

Parity contract (tests/test_serve.py): identical ranking to a NumPy
normalize→matmul→argsort reference on both the single-device and meshed
paths.
"""

from __future__ import annotations

import logging

import numpy as np

from code2vec_tpu.obs import handles

logger = logging.getLogger(__name__)

__all__ = ["RetrievalIndex", "AnnRetrievalIndex", "load_retrieval_index"]

# the one power-of-two executable-table keying rule (and the PR-9 k-bucket
# fix), shared with the ANN searcher so the backends cannot drift
from code2vec_tpu.ann.index import pow2_bucket as _pow2_bucket  # noqa: E402


class RetrievalIndex:
    """Device-resident cosine top-k over ``[n_methods, E]`` vectors."""

    def __init__(self, labels: list[str], rows: np.ndarray, mesh=None) -> None:
        import jax
        import jax.numpy as jnp

        if rows.ndim != 2 or len(labels) != rows.shape[0]:
            raise ValueError(
                f"rows must be [len(labels), E]; got {rows.shape} for "
                f"{len(labels)} labels"
            )
        self.labels = list(labels)
        self.n = len(labels)
        self.dim = int(rows.shape[1])
        self._mesh = mesh

        norms = np.linalg.norm(rows.astype(np.float32), axis=1, keepdims=True)
        unit = rows.astype(np.float32) / np.maximum(norms, 1e-12)

        # pad the row count so the model axis shards it evenly (the
        # _spec_for_param divisibility rule would otherwise silently
        # replicate); pad rows get -inf similarity, never surfacing
        pad_to = 1
        if mesh is not None:
            from code2vec_tpu.parallel.mesh import AXIS_MODEL

            pad_to = max(int(mesh.shape[AXIS_MODEL]), 1)
        n_padded = -(-self.n // pad_to) * pad_to
        if n_padded != self.n:
            unit = np.concatenate(
                [unit, np.zeros((n_padded - self.n, self.dim), np.float32)]
            )
        bias = np.zeros(n_padded, np.float32)
        bias[self.n :] = -np.inf

        if mesh is not None:
            from code2vec_tpu.parallel.shardings import retrieval_shardings

            sh = retrieval_shardings(mesh)
            self._rows = jax.device_put(unit, sh["rows"])
            # the bias aligns with the rows' sharded dim
            from jax.sharding import NamedSharding, PartitionSpec

            self._bias = jax.device_put(
                bias, NamedSharding(mesh, PartitionSpec(sh["rows"].spec[0]))
            )
            self._query_sharding = sh["query"]
        else:
            self._rows = jnp.asarray(unit)
            self._bias = jnp.asarray(bias)
            self._query_sharding = None
        self._fns: dict[int, object] = {}  # k -> jitted query fn

    @classmethod
    def from_code_vec(cls, path: str, mesh=None) -> "RetrievalIndex":
        """Load an exported ``code.vec`` (word2vec text format)."""
        from code2vec_tpu.formats.vectors_io import read_code_vectors

        labels, rows = read_code_vectors(path)
        logger.info(
            "retrieval index: %d vectors of dim %d from %s",
            len(labels), rows.shape[1] if rows.ndim == 2 else -1, path,
        )
        return cls(labels, rows, mesh=mesh)

    # ---- query ----------------------------------------------------------
    def _cache_size(self) -> int:
        """Compiled query-fn count — lets the obs RecompileDetector track
        the index like the engine's executable table."""
        return len(self._fns)

    def describe(self) -> dict:
        """The health op's retrieval block (serve/protocol.py)."""
        return {
            "backend": "exact",
            "size": self.n,
            "dim": self.dim,
            "query_executables": self._cache_size(),
        }

    def _fn(self, k: int, qb: int):
        """The jitted query fn for one (k bucket, query-batch bucket)
        pair. Both axes round up to powers of two — k capped at n, the
        batch uncapped — so a client alternating single and batched
        neighbor queries AND sweeping top_k costs at most
        log2(n) * log2(max Q) compiles over the index's lifetime, never
        one per distinct request shape (the `_cache_size` regression test
        pins this)."""
        fn = self._fns.get((k, qb))
        if fn is None:
            import jax

            rows, bias = self._rows, self._bias

            def query(q):  # q: [qb, E] unit-normalized
                sims = q @ rows.T + bias[None, :]
                return jax.lax.top_k(sims, k)

            if self._mesh is not None:
                fn = jax.jit(
                    query,
                    in_shardings=self._query_sharding,
                    out_shardings=self._query_sharding,
                )
            else:
                fn = jax.jit(query)
            self._fns[(k, qb)] = fn
        return fn

    def top_k_batch(
        self, vectors: np.ndarray, k: int = 5
    ) -> list[list[tuple[str, float]]]:
        """Cosine top-k per query row of ``vectors [Q, E]``."""
        k = min(int(k), self.n)
        if k < 1:
            return [[] for _ in range(len(vectors))]
        q = np.asarray(vectors, np.float32).reshape(-1, self.dim)
        qn = np.linalg.norm(q, axis=1, keepdims=True)
        q = q / np.maximum(qn, 1e-12)
        n_q = q.shape[0]
        qb = _pow2_bucket(max(n_q, 1), 1 << 30)
        if n_q < qb:  # pad to the batch bucket; padded rows sliced away
            q = np.concatenate([q, np.zeros((qb - n_q, self.dim), np.float32)])
        values, indices = self._fn(_pow2_bucket(k, self.n), qb)(q)
        values = np.asarray(values)[:n_q, :k]
        indices = np.asarray(indices)[:n_q, :k]
        return [
            [
                (self.labels[int(i)], float(v))
                for i, v in zip(indices[row], values[row])
            ]
            for row in range(n_q)
        ]

    def top_k(self, vector: np.ndarray, k: int = 5) -> list[tuple[str, float]]:
        """Single-query convenience wrapper."""
        return self.top_k_batch(np.asarray(vector)[None, :], k)[0]


class AnnRetrievalIndex:
    """The ``ann`` backend: IVF-PQ shortlist + exact f32 re-rank.

    Drop-in for :class:`RetrievalIndex` behind the ``neighbors`` op — the
    response schema (ranked ``(label, cosine)`` pairs) is unchanged; only
    the candidate set is approximate, and every returned similarity is the
    EXACT cosine (re-ranked against the container's unit rows, which stay
    an mmap view until the shortlist touches them)."""

    def __init__(
        self,
        labels: list[str],
        unit_rows: np.ndarray,
        index,
        *,
        n_probe: int = 8,
        shortlist: int = 128,
        mesh=None,
        schedule=None,
        source: str | None = None,
    ) -> None:
        from code2vec_tpu.ann.index import AnnSearcher

        if unit_rows.ndim != 2 or len(labels) != unit_rows.shape[0]:
            raise ValueError(
                f"rows must be [len(labels), E]; got {unit_rows.shape} "
                f"for {len(labels)} labels"
            )
        self.labels = list(labels)
        self.n = len(labels)
        self.dim = int(unit_rows.shape[1])
        self._rows = unit_rows  # unit-normalized; may be an mmap view
        self._source = source
        self.searcher = AnnSearcher(
            index, n_probe=n_probe, shortlist=shortlist, mesh=mesh,
            schedule=schedule,
        )

    @classmethod
    def from_container(
        cls,
        path: str,
        *,
        n_probe: int | None = None,
        shortlist: int | None = None,
        mesh=None,
    ) -> "AnnRetrievalIndex":
        """Load a ``tools/ann_build.py`` container; ``n_probe``/
        ``shortlist`` default to the values baked into its header."""
        from code2vec_tpu.ann.index import load_index

        index, rows, labels = load_index(path)
        defaults = index.meta.get("defaults", {})
        resolved_probe = int(
            n_probe if n_probe is not None else defaults.get("n_probe", 8)
        )
        resolved_short = int(
            shortlist
            if shortlist is not None
            else defaults.get("shortlist", 128)
        )
        logger.info(
            "ann retrieval index: %d vectors of dim %d from %s "
            "(n_list=%d m=%d n_probe=%d shortlist=%d)",
            index.meta["n"], index.meta["dim"], path, index.meta["n_list"],
            index.meta["m"], resolved_probe, resolved_short,
        )
        return handles.track(
            cls(
                labels, rows, index, n_probe=resolved_probe,
                shortlist=resolved_short, mesh=mesh, source=path,
            ),
            "mmap_ann",
            name=path,
        )

    def close(self) -> None:
        """Retire this index from the handle ledger (idempotent). The
        container's mmap pages are released when the last row view dies
        with the owning generation; nothing to flush."""
        handles.untrack(self)

    def _cache_size(self) -> int:
        return self.searcher._cache_size()

    def describe(self) -> dict:
        out = {
            "backend": "ann",
            "size": self.n,
            "dim": self.dim,
            **self.searcher.describe(),
        }
        if self._source:
            out["index_path"] = self._source
        return out

    def top_k_batch(
        self, vectors: np.ndarray, k: int = 5
    ) -> list[list[tuple[str, float]]]:
        """ANN cosine top-k per query row: shortlist on device, exact
        re-rank on the host (O(shortlist * E) — the client's ``k`` never
        reaches the compiled path).

        ``k`` beyond the shortlist is rejected loudly (the exact backend
        would return ``k`` entries; silently truncating to the candidate
        pool would break the identical-schema contract) — raise the
        server's ``--ann_shortlist`` instead."""
        k = min(int(k), self.n)
        if k < 1:
            return [[] for _ in range(len(vectors))]
        if k > self.searcher.shortlist:
            raise ValueError(
                f"top_k={k} exceeds the ANN shortlist "
                f"({self.searcher.shortlist}) — the re-rank pool cannot "
                "fill the response; raise --ann_shortlist (or lower "
                "top_k)"
            )
        q = np.asarray(vectors, np.float32).reshape(-1, self.dim)
        qn = q / np.maximum(np.linalg.norm(q, axis=1, keepdims=True), 1e-12)
        _, id_rows = self.searcher.search(qn)
        out: list[list[tuple[str, float]]] = []
        for row in range(qn.shape[0]):
            ids = id_rows[row]
            ids = ids[ids >= 0]
            sims = self._rows[ids].astype(np.float32) @ qn[row]
            order = np.argsort(-sims, kind="stable")[:k]
            out.append(
                [(self.labels[int(ids[i])], float(sims[i])) for i in order]
            )
        return out

    def top_k(self, vector: np.ndarray, k: int = 5) -> list[tuple[str, float]]:
        return self.top_k_batch(np.asarray(vector)[None, :], k)[0]

    def probed_fraction(self, vectors: np.ndarray) -> float:
        return self.searcher.probed_fraction(vectors)


def load_retrieval_index(
    backend: str,
    *,
    code_vec_path: str | None = None,
    ann_index_path: str | None = None,
    n_probe: int | None = None,
    shortlist: int | None = None,
    mesh=None,
):
    """Backend dispatch for the serve CLI (``--retrieval_backend``)."""
    if backend == "exact":
        if not code_vec_path:
            raise ValueError(
                "retrieval_backend 'exact' needs --code_vec_path"
            )
        return RetrievalIndex.from_code_vec(code_vec_path, mesh=mesh)
    if backend == "ann":
        if not ann_index_path:
            raise ValueError(
                "retrieval_backend 'ann' needs --ann_index_path (build one "
                "with tools/ann_build.py)"
            )
        return AnnRetrievalIndex.from_container(
            ann_index_path, n_probe=n_probe, shortlist=shortlist, mesh=mesh
        )
    raise ValueError(
        f"retrieval_backend must be 'exact' or 'ann', got {backend!r}"
    )
