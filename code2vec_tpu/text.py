"""Method-name normalization and subtokenization — the kernel of truth.

These rules define label identity for training and the subtoken metrics, so
they must match the reference exactly (reference: model/dataset.py:55-56,86-92).
Golden-tested in tests/test_text.py.
"""

from __future__ import annotations

import re
from functools import lru_cache

# Characters stripped from method/variable names before subtokenization
# (reference: model/dataset.py:55). "get_value_2" -> "getvalue" after
# normalize+lower.
_REDUNDANT_SYMBOL_CHARS = re.compile(r"[_0-9]+")

# camelCase splitter (reference: model/dataset.py:56). Used with re.split so
# the capture groups become the emitted tokens; None/'' entries are dropped.
# "toString" -> ["to", "String"]; "HTMLParser" -> (degenerate but pinned
# behavior, see tests).
_METHOD_SUBTOKEN_SEPARATOR = re.compile(r"([a-z]+)([A-Z][a-z]+)|([A-Z][a-z]+)")


def normalize_method_name(name: str) -> str:
    """Strip underscores and digits (reference: model/dataset.py:86-88)."""
    return _REDUNDANT_SYMBOL_CHARS.sub("", name)


def subtokenize(normalized_name: str) -> list[str]:
    """Split a normalized camelCase name into lowercase subtokens.

    Mirrors Vocab.get_method_subtokens (reference: model/dataset.py:90-92):
    re.split with capturing groups, dropping None and empty strings, then
    lowercasing each piece.
    """
    return [
        piece.lower()
        for piece in _METHOD_SUBTOKEN_SEPARATOR.split(normalized_name)
        if piece is not None and piece != ""
    ]


@lru_cache(maxsize=1 << 20)
def normalize_and_subtokenize(name: str) -> tuple[str, tuple[str, ...]]:
    """(normalized_lower_name, subtokens) for a raw method/variable name.

    This is the composition applied to every label in the corpus
    (reference: model/dataset_reader.py:97-100), cached because corpora
    repeat names heavily.
    """
    normalized = normalize_method_name(name)
    return normalized.lower(), tuple(subtokenize(normalized))
