"""Single-source inference: predict method names for new Java/Python code
from a trained checkpoint.

The reference has no inference surface at all — its closest facility is
``print_sample`` (main.py:362-390), which replays attention on a *training*
example. This module closes the loop for a real user: point it at a trained
``--model_path`` and a source file, and it extracts path-contexts natively,
maps them into the training vocabulary (the ``@question`` index shift of
dataset_reader.py:29-41 included), applies the same answer-leak framing the
trainer uses (``@method_0 -> @question``, dataset_builder.py:122-144), runs
the jitted forward, and returns the top-k label names with probabilities
and the per-context attention.

Inference needs three things the checkpoint alone doesn't carry — model
dims, the label vocabulary (insertion-ordered at corpus-load time), and the
task flags. ``save_inference_meta`` persists them next to the checkpoint
(``model_meta.json`` + ``label_vocab.txt``) at train start, so prediction
requires only the model dir and the extraction vocab files.
"""

from __future__ import annotations

import json
import logging
import os
from dataclasses import dataclass

import numpy as np

from code2vec_tpu import PAD_INDEX, QUESTION_TOKEN_INDEX, QUESTION_TOKEN_NAME

logger = logging.getLogger(__name__)

MODEL_META = "model_meta.json"
LABEL_VOCAB = "label_vocab.txt"


def save_inference_meta(
    out_dir: str, config, model_config, data, bucket_ladder=None
) -> None:
    """Persist what prediction needs beyond the checkpoint (called by the
    train loop on process 0): model dims/flags and the label vocab.

    ``bucket_ladder``: the training run's resolved bag-width ladder (or a
    corpus-derived one for fixed-L runs). Recording it lets the serving
    layer (code2vec_tpu.serve) build its AOT executable ladder WITHOUT the
    corpus on the serving host; absent (older checkpoints), the server
    falls back to a width histogram of the live request stream."""
    meta = {
        "rng_impl": config.rng_impl,
        "adam_mu_dtype": config.adam_mu_dtype,
        "table_update": config.table_update,
        "terminal_count": model_config.terminal_count,
        "path_count": model_config.path_count,
        "label_count": model_config.label_count,
        "terminal_embed_size": model_config.terminal_embed_size,
        "path_embed_size": model_config.path_embed_size,
        "encode_size": model_config.encode_size,
        "angular_margin_loss": model_config.angular_margin_loss,
        "angular_margin": model_config.angular_margin,
        "inverse_temp": model_config.inverse_temp,
        "vocab_pad_multiple": model_config.vocab_pad_multiple,
        "max_path_length": config.max_path_length,
        "infer_method_name": config.infer_method_name,
        "infer_variable_name": config.infer_variable_name,
        # training is always f32 (train/loop.py rejects otherwise), so this
        # records the DEFAULT serving storage; the Predictor can override
        # per deployment (--table_dtype int8 for the bandwidth-lean tier)
        "table_dtype": getattr(config, "table_dtype", "f32"),
        "bucket_ladder": (
            [int(w) for w in bucket_ladder] if bucket_ladder else None
        ),
    }
    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, MODEL_META), "w", encoding="utf-8") as f:
        json.dump(meta, f, indent=1)
    from code2vec_tpu.formats.vocab_io import write_vocab

    write_vocab(
        os.path.join(out_dir, LABEL_VOCAB),
        sorted(data.label_vocab.itos.items()),
    )


@dataclass
class Prediction:
    name: str
    prob: float


def softmax_top_k(
    logits: np.ndarray, n_labels: int, top_k: int
) -> list[tuple[int, float]]:
    """Top-k ``(label index, probability)`` from one logits row — float64
    softmax over the REAL label rows (the head may be vocab-padded for
    even model-axis sharding; the dummy rows are meaningless). THE one
    implementation shared by offline prediction and the serving protocol,
    so the two surfaces cannot drift numerically."""
    logits = np.asarray(logits, np.float64)[:n_labels]
    z = np.exp(logits - logits.max())
    probs = z / z.sum()
    order = np.argsort(-probs)[:top_k]
    return [(int(i), float(probs[i])) for i in order]


@dataclass
class MethodPrediction:
    method_name: str  # the actual name found in the source
    predictions: list[Prediction]  # top-k, most probable first
    n_contexts: int  # contexts fed to the model (after OOV drop)
    n_oov: int  # contexts dropped: path or terminal unseen in training
    attention: list[tuple[str, str, str, float]]  # (start, path, end, weight)
    code_vector: np.ndarray | None = None  # [encode_size] embedding
    target_variable: str | None = None  # set for variable-name predictions


def nearest_from_rows(
    labels: list[str],
    rows: np.ndarray,
    vector: np.ndarray,
    top_k: int = 5,
    row_norms: np.ndarray | None = None,
) -> list[tuple[str, float]]:
    """Cosine-nearest rows of a preloaded code.vec matrix to ``vector``.
    Pass precomputed ``row_norms`` when querying many vectors so each
    query is a single matvec."""
    if row_norms is None:
        row_norms = np.linalg.norm(rows, axis=1)
    norms = row_norms * max(np.linalg.norm(vector), 1e-12)
    sims = rows @ vector / np.maximum(norms, 1e-12)
    order = np.argsort(-sims)[:top_k]
    return [(labels[int(i)], float(sims[i])) for i in order]


def nearest_neighbors(
    code_vec_path: str, vector: np.ndarray, top_k: int = 5
) -> list[tuple[str, float]]:
    """Cosine-nearest rows of an exported code.vec to ``vector`` —
    'which training methods does this new method embed next to'. The
    reference only ships vectors to the TensorBoard projector for manual
    inspection (visualize_code_vec.py); this is the programmatic lookup.
    Querying many vectors? ``read_code_vectors`` once + ``nearest_from_rows``."""
    from code2vec_tpu.formats.vectors_io import read_code_vectors

    labels, rows = read_code_vectors(code_vec_path)
    return nearest_from_rows(labels, rows, vector, top_k)


class Predictor:
    """Loads checkpoint + metadata once; predicts per source string/file."""

    def __init__(
        self,
        model_path: str,
        terminal_idx_path: str,
        path_idx_path: str,
        table_dtype: str | None = None,
    ) -> None:
        """``table_dtype``: embedding-table storage for the serving forward
        (``f32``/``bf16``/``int8`` — ops/quant.py). ``None`` follows the
        checkpoint's ``model_meta.json`` (itself ``f32`` unless edited for
        a deployment). Quantization happens ONCE here at load; the jitted
        forward then gathers through the pre-quantized tables."""
        import jax

        from code2vec_tpu.checkpoint import restore_checkpoint
        from code2vec_tpu.formats.vocab_io import read_vocab
        from code2vec_tpu.models.code2vec import Code2VecConfig
        from code2vec_tpu.train.config import TrainConfig
        from code2vec_tpu.train.step import create_train_state

        meta_path = os.path.join(model_path, MODEL_META)
        if not os.path.exists(meta_path):
            raise FileNotFoundError(
                f"{meta_path} not found — the model dir must come from a "
                "train run of this framework (which persists inference "
                "metadata next to the checkpoint)"
            )
        with open(meta_path, encoding="utf-8") as f:
            meta = json.load(f)
        self.meta = meta
        # same loading rules as training: @question injected into the
        # terminal vocab at index 1, raw indices shifted up
        self.terminal_vocab = read_vocab(
            terminal_idx_path, extra_tokens=[QUESTION_TOKEN_NAME]
        )
        self.path_vocab = read_vocab(path_idx_path)
        self.label_vocab = read_vocab(os.path.join(model_path, LABEL_VOCAB))

        self.bag = int(meta["max_path_length"])
        # the TRAINING bag width, before any longbag raise below — the
        # serving engine keys its base/longbag split off this
        self.base_bag = self.bag
        # bag-width ladder for single-forward padding: each prediction is
        # padded to the nearest ladder width (shared rule with the serving
        # micro-batcher — data/pipeline.nearest_bucket_width), so the jitted
        # forward compiles AT MOST len(ladder) variants and repeat
        # predictions of differently-sized methods reuse them — instead of
        # paying full-bag FLOPs/gathers for every 5-context method. Older
        # checkpoints without a recorded ladder get the geometric default.
        from code2vec_tpu.data.pipeline import derive_bucket_ladder

        recorded = meta.get("bucket_ladder")
        # ladder_recorded distinguishes "the checkpoint told us" from the
        # geometric guess below: the serving engine (serve/engine.py) must
        # NOT inherit a guess — an unrecorded ladder routes it to the
        # request-stream histogram fallback instead
        self.ladder_recorded = bool(recorded)
        self.ladder: tuple[int, ...] = (
            tuple(int(w) for w in recorded)
            if recorded
            else derive_bucket_ladder(np.zeros(0, np.int64), self.bag)
        )
        if self.ladder_recorded and self.ladder[-1] > self.bag:
            # longbag rungs (a --max_contexts 0 run recorded widths above
            # its base bag): single forwards pad oversized bags to a rung
            # instead of subsampling them — no truncation offline either
            self.bag = int(self.ladder[-1])
        # extraction hyperparameters: the corpus records them in params.txt
        # next to the vocab files (reference format, typo'd 'nomalize_' keys
        # included) — new sources must be extracted identically or their
        # path strings silently diverge from the training vocab
        self.extract_params = self._load_extract_params(
            os.path.join(os.path.dirname(os.path.abspath(path_idx_path)),
                         "params.txt")
        )
        self.table_dtype = table_dtype or meta.get("table_dtype", "f32")
        model_config = Code2VecConfig(
            terminal_count=meta["terminal_count"],
            path_count=meta["path_count"],
            label_count=meta["label_count"],
            terminal_embed_size=meta["terminal_embed_size"],
            path_embed_size=meta["path_embed_size"],
            encode_size=meta["encode_size"],
            dropout_prob=0.0,
            angular_margin_loss=meta["angular_margin_loss"],
            angular_margin=meta["angular_margin"],
            inverse_temp=meta["inverse_temp"],
            vocab_pad_multiple=meta.get("vocab_pad_multiple", 1) or 1,
            table_dtype=self.table_dtype,
        )
        config = TrainConfig(
            batch_size=1, max_path_length=self.bag,
            infer_method_name=True, infer_variable_name=False,
            # the checkpoint's dropout key carries its PRNG impl and its
            # opt_state carries the mu dtype and table-update mode; restore
            # validates all three, so reconstruct with what the model was
            # trained with
            rng_impl=meta.get("rng_impl", "threefry2x32"),
            adam_mu_dtype=meta.get("adam_mu_dtype", "float32"),
            table_update=meta.get("table_update", "dense"),
        )
        example = {
            "starts": np.zeros((1, self.bag), np.int32),
            "paths": np.zeros((1, self.bag), np.int32),
            "ends": np.zeros((1, self.bag), np.int32),
            "labels": np.zeros(1, np.int32),
            "example_mask": np.ones(1, np.float32),
        }
        state = create_train_state(
            config, model_config, jax.random.PRNGKey(0), example
        )
        restored = restore_checkpoint(
            model_path, state, prefer_best=True,
            vocab_pad_multiple=model_config.vocab_pad_multiple,
        )
        if restored is None:
            raise FileNotFoundError(f"no checkpoint found under {model_path}")
        self.state = restored[0]

        # quantize the restored f32 master tables ONCE for the serving
        # forward — the per-call path then gathers int8/bf16 rows + scales
        # (and never reads the f32 master again)
        self._quant_tables = None
        if self.table_dtype != "f32":
            from code2vec_tpu.ops.quant import quantize_table

            params = self.state.params
            self._quant_tables = (
                quantize_table(
                    params["terminal_embedding"]["embedding"], self.table_dtype
                ),
                quantize_table(
                    params["path_embedding"]["embedding"], self.table_dtype
                ),
            )
            logger.info("serving with %s-quantized tables", self.table_dtype)

        # the training eval step deliberately omits full logits (they would
        # be [B, labels] of device->host traffic per batch); inference
        # wants them, so jit a dedicated forward
        quant_tables = self._quant_tables

        def forward(state, batch):
            logits, code_vector, attention = state.apply_fn(
                {"params": state.params},
                batch["starts"], batch["paths"], batch["ends"],
                labels=None, deterministic=True,
                quant_tables=quant_tables,
            )
            return logits, code_vector, attention

        self._forward = jax.jit(forward)

    # ---- extraction-param matching --------------------------------------
    @staticmethod
    def _load_extract_params(params_path: str) -> dict:
        """Extraction kwargs matching the training corpus's params.txt
        (length/width caps + literal normalization). Falls back to the
        reference defaults with a warning when the file is absent."""
        defaults = dict(
            max_length=8, max_width=3, normalize_string=True,
            normalize_char=True, normalize_int=False, normalize_double=True,
        )
        if not os.path.exists(params_path):
            logger.warning(
                "%s not found — extracting with the default caps; if the "
                "corpus used custom extraction params, predictions degrade",
                params_path,
            )
            return defaults
        from code2vec_tpu.formats.params_io import read_params

        p = read_params(params_path)

        def flag(key: str, default: bool) -> bool:
            # the reference writes the typo'd 'nomalize_' keys (kept for
            # byte parity); tolerate the correct spelling from hand-written
            # params files too
            raw = p.get("nomalize_" + key, p.get("normalize_" + key))
            if raw is None:
                return default
            return raw.strip() == "true"

        return dict(
            max_length=int(p.get("max_length", 8)),
            max_width=int(p.get("max_width", 3)),
            normalize_string=flag("string_literal", True),
            normalize_char=flag("char_literal", True),
            normalize_int=flag("int_literal", False),
            normalize_double=flag("double_literal", True),
        )

    # ---- vocab mapping ---------------------------------------------------
    def _map_contexts(
        self,
        contexts: list[tuple[str, str, str]],
        question_token: str = "@method_0",
    ) -> tuple[list[tuple[int, int, int]], int]:
        """(start, path, end) NAME triples -> training vocab ids. Names are
        the join key across extractor runs. Contexts whose path or either
        terminal never occurred in training are dropped (counted as OOV).
        ``question_token`` maps to ``@question`` — the trainer's answer-leak
        substitution (the method's own alias for the method task, the
        target variable's alias for the variable task). Terminals are
        lowercased like the vocab writers'."""
        t_stoi = self.terminal_vocab.stoi
        p_stoi = self.path_vocab.stoi

        def term_id(name: str) -> int | None:
            if name == question_token:
                return QUESTION_TOKEN_INDEX
            return t_stoi.get(name.lower())

        mapped, oov = [], 0
        for s, p, e in contexts:
            ts, te = term_id(s), term_id(e)
            tp = p_stoi.get(p)
            if ts is None or te is None or tp is None:
                oov += 1
                continue
            mapped.append((ts, tp, te))
        return mapped, oov

    # ---- extraction (shared by both tasks) -------------------------------
    def _extract(
        self, source: str, method_name: str, language: str
    ) -> list[tuple[str, list[tuple[str, str, str]], list[tuple[str, str]]]]:
        """Extract to (label, NAME triples, (original, alias) pairs) per
        method. Both extractors are normalized: the Java one returns
        run-local int ids + vocab dicts, the Python one string triples."""
        methods = []
        if language == "java":
            from code2vec_tpu.extractor import extract_source

            result = extract_source(source, method_name, **self.extract_params)
            for m in result.methods:
                methods.append((
                    m.label,
                    [(result.terminal_vocab[s], result.path_vocab[p],
                      result.terminal_vocab[e]) for s, p, e in m.path_contexts],
                    list(m.aliases),
                ))
        elif language == "python":
            from code2vec_tpu.pyextract import PyExtractConfig, extract_python_source

            ep = self.extract_params
            py_config = PyExtractConfig(
                normalize_string_literal=ep["normalize_string"],
                normalize_char_literal=ep["normalize_char"],
                normalize_int_literal=ep["normalize_int"],
                normalize_double_literal=ep["normalize_double"],
                max_length=ep["max_length"],
                max_width=ep["max_width"],
            )
            for m in extract_python_source(source, method_name, py_config):
                methods.append((m.label, list(m.contexts), list(m.variables)))
        else:
            raise ValueError(f"unknown language: {language!r}")
        return methods

    # ---- prediction ------------------------------------------------------
    def predict_source(
        self,
        source: str,
        method_name: str = "*",
        language: str = "java",
        top_k: int = 5,
        rng: np.random.Generator | None = None,
    ) -> list[MethodPrediction]:
        """Method-name predictions for every matching method in ``source``."""
        if not self.meta.get("infer_method_name", True):
            raise ValueError(
                "this checkpoint was trained for the variable-name task "
                "only; use predict_variables (CLI: --task variable)"
            )
        out = []
        for label, contexts, _ in self._extract(source, method_name, language):
            mapped, oov = self._map_contexts(contexts)
            if not mapped:
                logger.warning(
                    "%s: every context is OOV against the training vocab — "
                    "prediction will be the label prior",
                    label,
                )
            out.append(self._predict_contexts(label, mapped, oov, top_k, rng))
        return out

    def predict_variables(
        self,
        source: str,
        method_name: str = "*",
        language: str = "java",
        top_k: int = 5,
        rng: np.random.Generator | None = None,
    ) -> list[MethodPrediction]:
        """Variable-name predictions: one per ``@var_*`` alias of each
        matching method, with the trainer's framing (keep only the target
        variable's contexts, its alias becomes ``@question`` —
        model/dataset_builder.py:152-204)."""
        if not self.meta.get("infer_variable_name", False):
            raise ValueError(
                "this checkpoint was not trained for the variable-name "
                "task; use predict_source (CLI: --task method)"
            )
        out = []
        for label, contexts, aliases in self._extract(
            source, method_name, language
        ):
            # extractor encounter order is deterministic — keep it
            for original, alias in aliases:
                if not alias.startswith("@var_"):
                    continue  # @method_/@label_ aliases are not variables
                mine = [
                    (s, p, e) for s, p, e in contexts
                    if s == alias or e == alias
                ]
                mapped, oov = self._map_contexts(mine, question_token=alias)
                if not mapped:
                    logger.warning(
                        "%s.%s: every context is OOV against the training "
                        "vocab — prediction will be the label prior",
                        label, original,
                    )
                m = self._predict_contexts(
                    f"{label}.{original}", mapped, oov, top_k, rng
                )
                m.target_variable = original
                out.append(m)
        return out

    def embed_file(
        self,
        source: str,
        language: str = "java",
        method_name: str = "*",
        rng: np.random.Generator | None = None,
    ) -> tuple[np.ndarray, list[str]]:
        """One vector for a whole SOURCE FILE: embed every matching method,
        then attention-pool the method vectors with the checkpoint's
        trained method-level attention param (the hierarchical two-level
        head — models/hierarchical.py). Returns ``(file_vector [H] f32,
        method_names)``; raises ValueError when no method embeds (nothing
        extracted, or everything OOV)."""
        from code2vec_tpu.models.hierarchical import pool_vectors

        names: list[str] = []
        vectors: list[np.ndarray] = []
        for label, contexts, _ in self._extract(source, method_name, language):
            mapped, _oov = self._map_contexts(contexts)
            if not mapped:
                continue
            m = self._predict_contexts(label, mapped, 0, top_k=1, rng=rng)
            names.append(label)
            vectors.append(m.code_vector)
        if not vectors:
            raise ValueError(
                "no method in the source produced an embedding (nothing "
                "extracted, or every context is OOV against the training "
                "vocab)"
            )
        attn = np.asarray(self.state.params["attention"], np.float32)
        return pool_vectors(np.stack(vectors), attn), names

    def _predict_contexts(
        self, label: str, contexts, n_oov: int, top_k: int, rng
    ) -> MethodPrediction:
        # over-long bags: random subsample, matching the trainer's per-epoch
        # truncation (dataset_builder.py:134-135) but seeded for inference
        if len(contexts) > self.bag:
            r = rng if rng is not None else np.random.default_rng(0)
            keep = r.choice(len(contexts), self.bag, replace=False)
            contexts = [contexts[i] for i in sorted(keep)]
        from code2vec_tpu.data.pipeline import nearest_bucket_width

        arr = np.asarray(contexts, np.int32).reshape(-1, 3)
        n = arr.shape[0]
        # pad to the nearest ladder width, not the full bag: PAD lanes carry
        # exactly-zero attention weight, so the outputs are identical at any
        # width >= n (the PR-4 bucketing invariant) while the forward pays
        # for the small shape — and the jit cache stays at <= len(ladder)
        width = nearest_bucket_width(max(n, 1), self.ladder)
        starts = np.full((1, width), PAD_INDEX, np.int32)
        paths = np.full((1, width), PAD_INDEX, np.int32)
        ends = np.full((1, width), PAD_INDEX, np.int32)
        starts[0, :n], paths[0, :n], ends[0, :n] = arr[:, 0], arr[:, 1], arr[:, 2]
        batch = {"starts": starts, "paths": paths, "ends": ends}
        logits, code_vector, attn = self._forward(self.state, batch)
        preds = [
            Prediction(self.label_vocab.itos[i], prob)
            for i, prob in softmax_top_k(
                np.asarray(logits)[0], len(self.label_vocab), top_k
            )
        ]
        attn = np.asarray(attn)[0]
        t_itos, p_itos = self.terminal_vocab.itos, self.path_vocab.itos
        attention = [
            (t_itos[int(s)], p_itos[int(p)], t_itos[int(e)], float(a))
            for s, p, e, a in zip(
                starts[0, :n], paths[0, :n], ends[0, :n], attn[:n]
            )
        ]
        attention.sort(key=lambda row: -row[3])
        return MethodPrediction(
            method_name=label,
            predictions=preds,
            n_contexts=n,
            n_oov=n_oov,
            attention=attention,
            code_vector=np.asarray(code_vector)[0],
        )


def main(argv: list[str] | None = None) -> None:
    import argparse

    parser = argparse.ArgumentParser(
        description="Predict method names for a source file from a trained "
        "checkpoint."
    )
    parser.add_argument("source_file", help=".java or .py file")
    parser.add_argument("--model_path", required=True)
    parser.add_argument("--terminal_idx_path", required=True)
    parser.add_argument("--path_idx_path", required=True)
    parser.add_argument("--method_name", default="*", help="* = all methods")
    parser.add_argument("--no_cuda", action="store_true", default=False,
                        help="accepted for train-CLI symmetry; CPU is "
                        "already the default here")
    parser.add_argument(
        "--accelerator", action="store_true", default=False,
        help="run on the default device backend instead of CPU. Off by "
        "default: a single-example forward gains nothing from the TPU, "
        "and the first compile through a cold (or wedged) device tunnel "
        "costs 20-40s (or hangs) — latency a one-off inference CLI "
        "should not pay",
    )
    parser.add_argument(
        "--task", default="auto", choices=("auto", "method", "variable"),
        help="what to predict; auto follows the checkpoint's training task "
        "(method wins for dual-task checkpoints)",
    )
    parser.add_argument("--top_k", type=int, default=5)
    parser.add_argument(
        "--table_dtype", default=None, choices=("f32", "bf16", "int8"),
        help="embedding-table storage for the serving forward (int8 = "
        "per-row scale, dequant on load; 4x less gather bandwidth). "
        "Default: the checkpoint's model_meta.json (f32 unless edited)",
    )
    parser.add_argument(
        "--show_attention", type=int, default=0, metavar="N",
        help="also print the N highest-attention path-contexts per method",
    )
    parser.add_argument(
        "--neighbors", type=int, default=0, metavar="N",
        help="also print the N cosine-nearest methods from --code_vec_path",
    )
    parser.add_argument(
        "--code_vec_path", default=None,
        help="exported code.vec for --neighbors (default: "
        "<model_path>/code.vec if present)",
    )
    args = parser.parse_args(argv)

    from code2vec_tpu.cli import pin_platform

    # CPU unless --accelerator: inference is one tiny forward, and the
    # ambient JAX_PLATFORMS can point at a device tunnel that is cold or
    # wedged. An explicit --no_cuda still wins over --accelerator — the
    # flag's CPU guarantee must hold in every combination.
    pin_platform(args.no_cuda or not args.accelerator)

    # resolve/validate the neighbors source BEFORE the expensive model
    # load: file present, dims matching the checkpoint, loaded once with
    # row norms precomputed so each per-method query is one matvec
    neighbor_index = None
    if args.neighbors:
        code_vec_path = args.code_vec_path
        if code_vec_path is None:
            default = os.path.join(args.model_path, "code.vec")
            if not os.path.exists(default):
                parser.error("--neighbors needs --code_vec_path (no "
                             f"{default} found)")
            code_vec_path = default
        from code2vec_tpu.formats.vectors_io import read_code_vectors

        nn_labels, nn_rows = read_code_vectors(code_vec_path)
        meta_file = os.path.join(args.model_path, MODEL_META)
        if os.path.exists(meta_file):
            with open(meta_file, encoding="utf-8") as f:
                encode_size = json.load(f).get("encode_size")
            if encode_size and nn_rows.ndim == 2 and nn_rows.shape[1] != encode_size:
                parser.error(
                    f"{code_vec_path} holds {nn_rows.shape[1]}-dim vectors "
                    f"but the checkpoint's encode_size is {encode_size} — "
                    "it was exported from a different model"
                )
        neighbor_index = (nn_labels, nn_rows, np.linalg.norm(nn_rows, axis=1))

    predictor = Predictor(
        args.model_path, args.terminal_idx_path, args.path_idx_path,
        table_dtype=args.table_dtype,
    )
    with open(args.source_file, encoding="utf-8") as f:
        source = f.read()
    language = "python" if args.source_file.endswith(".py") else "java"
    task = args.task
    if task == "auto":
        task = (
            "method"
            if predictor.meta.get("infer_method_name", True)
            else "variable"
        )
    predict = (
        predictor.predict_source if task == "method"
        else predictor.predict_variables
    )
    results = predict(
        source, args.method_name, language=language, top_k=args.top_k
    )
    if not results:
        print("no matching methods found")
        return
    for m in results:
        print(
            f"{m.method_name}  ({m.n_contexts} contexts"
            + (f", {m.n_oov} OOV dropped" if m.n_oov else "")
            + ")"
        )
        for p in m.predictions:
            print(f"  {p.prob:6.3f}  {p.name}")
        for s, pth, e, a in m.attention[: args.show_attention]:
            print(f"    [{a:.3f}] {s} {pth} {e}")
        if neighbor_index is not None:
            nn_labels, nn_rows, nn_norms = neighbor_index
            for name, sim in nearest_from_rows(
                nn_labels, nn_rows, m.code_vector, args.neighbors,
                row_norms=nn_norms,
            ):
                print(f"    ~{sim:.3f}  {name}")


if __name__ == "__main__":
    main()
