"""Vector export and sample printing (reference: main.py:226-230,362-423).

``code.vec`` is rewritten on every new best F1: header line, then train rows
followed by test rows. The optional test-result TSV records per-example
predictions. ``print_sample`` logs one correctly-predicted example with its
per-context attention, skipping PAD rows.
"""

from __future__ import annotations

import logging

import numpy as np

from code2vec_tpu import PAD_INDEX
from code2vec_tpu.data.pipeline import EpochArrays, iter_batches
from code2vec_tpu.data.reader import CorpusData
from code2vec_tpu.formats.vectors_io import (
    append_code_vectors,
    write_code_vectors_header,
    write_test_results,
)

logger = logging.getLogger(__name__)


def _forward_all(
    eval_step, state, epoch: EpochArrays, batch_size: int, to_device=lambda b: b
):
    """Run the jitted eval step over every example; returns host arrays
    (labels, preds, max_logit, code_vectors) with padding rows removed."""
    from code2vec_tpu.parallel.distributed import allgather_to_host

    labels, preds, logits, vectors, ids = [], [], [], [], []
    for batch in iter_batches(epoch, batch_size, rng=None, pad_final=True):
        out = eval_step(state, to_device(batch))
        valid = batch["example_mask"].astype(bool)
        labels.append(batch["labels"][valid])
        ids.append(batch["ids"][valid])
        preds.append(allgather_to_host(out["preds"])[valid])
        logits.append(allgather_to_host(out["max_logit"])[valid])
        vectors.append(allgather_to_host(out["code_vector"])[valid])
    return (
        np.concatenate(labels),
        np.concatenate(ids),
        np.concatenate(preds),
        np.concatenate(logits),
        np.concatenate(vectors),
    )


def write_code_vectors(
    data: CorpusData,
    state,
    eval_step,
    train_epoch: EpochArrays,
    test_epoch: EpochArrays,
    batch_size: int,
    vectors_path: str,
    encode_size: int,
    test_result_path: str | None = None,
    to_device=lambda b: b,
) -> tuple[np.ndarray, np.ndarray]:
    """Rewrite code.vec (train rows then test rows, reference
    main.py:226-230) and optionally the test-result TSV (main.py:418-420).

    Header counts the actual rows written — with the variable task enabled
    an epoch holds one extra example per @var alias, so this can exceed
    ``data.n_items`` (the reference writes n_items and under-counts;
    external word2vec-format readers need the true count).

    Multi-host: every process runs the forward passes (they participate in
    the collectives) but only process 0 touches the files.

    Returns the test split's ``(labels, preds)`` so callers that need a
    metric afterwards (export_from_checkpoint) don't repeat the forward.
    """
    import jax

    write_files = jax.process_index() == 0
    if write_files:
        write_code_vectors_header(
            vectors_path, len(train_epoch) + len(test_epoch), encode_size
        )
    itos = data.label_vocab.itos

    test_labels = test_preds = np.zeros(0, np.int32)
    for split_epoch, is_test in ((train_epoch, False), (test_epoch, True)):
        if len(split_epoch) == 0:
            # a tiny corpus can leave the 20% test split empty; the header
            # already counts zero rows for it, and a requested TSV is still
            # created (with zero rows) so callers find the file they asked for
            if is_test and test_result_path is not None and write_files:
                open(test_result_path, "w", encoding="utf-8").close()
            continue
        labels, ids, preds, max_logit, vectors = _forward_all(
            eval_step, state, split_epoch, batch_size, to_device
        )
        if is_test:
            test_labels, test_preds = labels, preds
        if not write_files:
            continue
        label_names = [itos[int(label)] for label in labels]
        append_code_vectors(vectors_path, label_names, vectors)
        if is_test and test_result_path is not None:
            pred_names = [itos[int(p)] for p in preds]
            with open(test_result_path, "w", encoding="utf-8") as f:
                write_test_results(f, ids.tolist(), label_names, pred_names,
                                   max_logit.tolist())
    return test_labels, test_preds


def print_sample(
    data: CorpusData,
    state,
    eval_step,
    test_epoch: EpochArrays,
    batch_size: int,
    to_device=lambda b: b,
) -> None:
    """Log one correctly-predicted test example with per-context attention
    weights, skipping PAD rows (reference: main.py:362-390)."""
    terminal_itos = data.terminal_vocab.itos
    path_itos = data.path_vocab.itos
    label_itos = data.label_vocab.itos
    from code2vec_tpu.parallel.distributed import allgather_to_host

    for batch in iter_batches(test_epoch, batch_size, rng=None, pad_final=True):
        out = eval_step(state, to_device(batch))
        preds = allgather_to_host(out["preds"])
        attn = allgather_to_host(out["attention"])
        valid = batch["example_mask"].astype(bool)
        hits = np.nonzero((preds == batch["labels"]) & valid)[0]
        if not len(hits):
            continue
        i = int(hits[0])
        for s, p, e, a in zip(
            batch["starts"][i], batch["paths"][i], batch["ends"][i], attn[i]
        ):
            if s != PAD_INDEX:
                logger.info(
                    "%s %s %s [%s]",
                    terminal_itos[int(s)],
                    path_itos[int(p)],
                    terminal_itos[int(e)],
                    a,
                )
        logger.info("expected label: %s", label_itos[int(batch["labels"][i])])
        logger.info("actual label:   %s", label_itos[int(preds[i])])
        return


def export_file_vectors(
    method_vectors: np.ndarray,  # [N, H] f32 (e.g. read from code.vec)
    group_ids,  # length-N file/class key per method
    vectors_path: str,
    attn_param: np.ndarray | None = None,
    group_names=None,  # optional key -> written label (default: str(key))
) -> tuple[list, np.ndarray]:
    """Hierarchical file/class export: attention-pool method vectors per
    group (``models/hierarchical.py``) and write the pooled rows in the
    ``code.vec`` word2vec format — one row per FILE, label = group name.

    The output is format-identical to ``code.vec``, so the whole existing
    retrieval stack consumes it untouched: ``serve/retrieval.py``'s exact
    index (``--code_vec_path file.vec``), the IVF-PQ builder
    (``tools/ann_build.py``), and the ``neighbors`` op — whole-file code
    search through the same serving machinery as method search.

    ``attn_param``: the checkpoint's method-level attention param (the
    trained salience direction — see models/hierarchical.py for why it
    transfers); None = mean pooling. Returns ``(group_keys, [G, H])``.
    """
    from code2vec_tpu.models.hierarchical import pool_vectors_by_group

    keys, pooled = pool_vectors_by_group(
        method_vectors, group_ids, attn_param
    )
    names = [
        str(group_names[k]) if group_names is not None else str(k)
        for k in keys
    ]
    write_code_vectors_header(vectors_path, len(names), pooled.shape[-1])
    append_code_vectors(vectors_path, names, pooled)
    logger.info(
        "exported %d file vectors (from %d method vectors) to %s",
        len(names), len(method_vectors), vectors_path,
    )
    return keys, pooled


def export_from_checkpoint(
    config,
    data: CorpusData,
    out_dir: str,
    vectors_path: str,
    test_result_path: str | None = None,
) -> float:
    """Standalone export pass: restore the checkpoint in ``out_dir`` and
    rewrite code.vec (+ optional test TSV) without training — the
    ``--export_only`` mode. Needed after host-sharded pod runs (the loop
    skips in-training export there) or to re-export any finished run.
    Returns the test F1 of the restored model.
    """
    import jax

    from code2vec_tpu.checkpoint import restore_checkpoint
    from code2vec_tpu.data.pipeline import build_epoch, split_items
    from code2vec_tpu.metrics import evaluate
    from code2vec_tpu.train.loop import (
        build_mesh,
        class_weights_from,
        dummy_batch,
        model_config_from,
    )
    from code2vec_tpu.train.step import create_train_state, make_eval_step

    if data.shard is not None:
        raise ValueError(
            "export needs the full corpus on this host; load it unsharded"
        )

    # pin the kernel-schedule cache before the first trace, exactly like
    # train() does — --pallas_impl auto on an export pass must consult the
    # SAME --autotune_cache the operator tuned into, not the default path
    if getattr(config, "autotune_cache", ""):
        from code2vec_tpu.ops.autotune import get_cache

        get_cache(config.autotune_cache)

    np_rng = np.random.default_rng(config.random_seed)
    train_idx, test_idx = split_items(data.n_items, np_rng)
    model_config = model_config_from(config, data)
    if model_config.table_dtype != "f32":
        # quantized export: the checkpoint's f32 master tables are restored
        # as-is; the forward gathers through the quantized storage derived
        # from them (ops/quant.py), so the written vectors ARE the vectors
        # a quantized serving deployment would produce
        logger.info(
            "exporting with %s-quantized embedding tables", model_config.table_dtype
        )
    class_weights = class_weights_from(config, data)
    state = create_train_state(
        config, model_config, jax.random.PRNGKey(config.random_seed),
        dummy_batch(config),
    )

    # same mesh layout as train() so model_axis-sharded tables restore
    # sharded instead of OOMing one device
    mesh = build_mesh(config)
    if mesh is not None:
        from code2vec_tpu.parallel.shardings import shard_batch, shard_state
        from code2vec_tpu.parallel.step import make_parallel_eval_step

        state = shard_state(mesh, state)

    # the best-F1 slot, NOT the newest save: with --checkpoint_cycle a
    # fresher periodic "last" snapshot may exist, but the export contract
    # is the model the in-training export would have written. mesh-aware:
    # the export pass may run on a different topology than training — the
    # checkpointed PartitionSpecs re-bind to this mesh
    restored = restore_checkpoint(
        out_dir, state, vocab_pad_multiple=model_config.vocab_pad_multiple,
        prefer_best=True, mesh=mesh,
    )
    if restored is None:
        raise FileNotFoundError(f"no checkpoint found under {out_dir}")
    state, meta = restored
    logger.info(
        "restored checkpoint (epoch %d, best_f1=%s)", meta.epoch, meta.best_f1
    )

    # quantize ONCE from the restored masters (mirrors predict.Predictor)
    # — the per-batch eval forward then gathers int8/bf16 rows and never
    # re-derives the quantized storage inside the traced call. The mesh
    # path keeps in-graph derivation: the quantized tables would need
    # their own shardings, and the post-hoc pod export is not the
    # bandwidth-sensitive consumer.
    quant_tables = None
    if model_config.table_dtype != "f32" and mesh is None:
        from code2vec_tpu.ops.quant import quantize_table

        quant_tables = (
            quantize_table(
                state.params["terminal_embedding"]["embedding"],
                model_config.table_dtype,
            ),
            quantize_table(
                state.params["path_embedding"]["embedding"],
                model_config.table_dtype,
            ),
        )

    if mesh is not None:
        eval_step = make_parallel_eval_step(
            model_config, class_weights, mesh, state
        )
        to_device = lambda b: shard_batch(mesh, b)  # noqa: E731
    else:
        eval_step = make_eval_step(model_config, class_weights, quant_tables)
        to_device = lambda b: b  # noqa: E731

    train_epoch = build_epoch(
        data, train_idx, config.max_path_length, np_rng,
        config.shuffle_variable_indexes,
    )
    test_epoch = build_epoch(
        data, test_idx, config.max_path_length, np_rng,
        config.shuffle_variable_indexes,
    )
    labels, preds = write_code_vectors(
        data, state, eval_step, train_epoch, test_epoch, config.batch_size,
        vectors_path, config.encode_size, test_result_path, to_device,
    )
    _, _, _, f1 = evaluate(config.eval_method, labels, preds, data.label_vocab)
    logger.info("exported %s (test f1=%s)", vectors_path, f1)
    return f1
