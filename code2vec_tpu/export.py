"""Vector export and sample printing (reference: main.py:226-230,362-423).

``code.vec`` is rewritten on every new best F1: header line, then train rows
followed by test rows. The optional test-result TSV records per-example
predictions. ``print_sample`` logs one correctly-predicted example with its
per-context attention, skipping PAD rows.
"""

from __future__ import annotations

import logging

import numpy as np

from code2vec_tpu import PAD_INDEX
from code2vec_tpu.data.pipeline import EpochArrays, iter_batches
from code2vec_tpu.data.reader import CorpusData
from code2vec_tpu.formats.vectors_io import (
    append_code_vectors,
    write_code_vectors_header,
    write_test_results,
)

logger = logging.getLogger(__name__)


def _forward_all(
    eval_step, state, epoch: EpochArrays, batch_size: int, to_device=lambda b: b
):
    """Run the jitted eval step over every example; returns host arrays
    (labels, preds, max_logit, code_vectors) with padding rows removed."""
    from code2vec_tpu.parallel.distributed import allgather_to_host

    labels, preds, logits, vectors, ids = [], [], [], [], []
    for batch in iter_batches(epoch, batch_size, rng=None, pad_final=True):
        out = eval_step(state, to_device(batch))
        valid = batch["example_mask"].astype(bool)
        labels.append(batch["labels"][valid])
        ids.append(batch["ids"][valid])
        preds.append(allgather_to_host(out["preds"])[valid])
        logits.append(allgather_to_host(out["max_logit"])[valid])
        vectors.append(allgather_to_host(out["code_vector"])[valid])
    return (
        np.concatenate(labels),
        np.concatenate(ids),
        np.concatenate(preds),
        np.concatenate(logits),
        np.concatenate(vectors),
    )


def write_code_vectors(
    data: CorpusData,
    state,
    eval_step,
    train_epoch: EpochArrays,
    test_epoch: EpochArrays,
    batch_size: int,
    vectors_path: str,
    encode_size: int,
    test_result_path: str | None = None,
    to_device=lambda b: b,
) -> None:
    """Rewrite code.vec (train rows then test rows, reference
    main.py:226-230) and optionally the test-result TSV (main.py:418-420).

    Header counts the actual rows written — with the variable task enabled
    an epoch holds one extra example per @var alias, so this can exceed
    ``data.n_items`` (the reference writes n_items and under-counts;
    external word2vec-format readers need the true count).

    Multi-host: every process runs the forward passes (they participate in
    the collectives) but only process 0 touches the files.
    """
    import jax

    write_files = jax.process_index() == 0
    if write_files:
        write_code_vectors_header(
            vectors_path, len(train_epoch) + len(test_epoch), encode_size
        )
    itos = data.label_vocab.itos

    for split_epoch, is_test in ((train_epoch, False), (test_epoch, True)):
        labels, ids, preds, max_logit, vectors = _forward_all(
            eval_step, state, split_epoch, batch_size, to_device
        )
        if not write_files:
            continue
        label_names = [itos[int(label)] for label in labels]
        append_code_vectors(vectors_path, label_names, vectors)
        if is_test and test_result_path is not None:
            pred_names = [itos[int(p)] for p in preds]
            with open(test_result_path, "w", encoding="utf-8") as f:
                write_test_results(f, ids.tolist(), label_names, pred_names,
                                   max_logit.tolist())


def print_sample(
    data: CorpusData,
    state,
    eval_step,
    test_epoch: EpochArrays,
    batch_size: int,
    to_device=lambda b: b,
) -> None:
    """Log one correctly-predicted test example with per-context attention
    weights, skipping PAD rows (reference: main.py:362-390)."""
    terminal_itos = data.terminal_vocab.itos
    path_itos = data.path_vocab.itos
    label_itos = data.label_vocab.itos
    from code2vec_tpu.parallel.distributed import allgather_to_host

    for batch in iter_batches(test_epoch, batch_size, rng=None, pad_final=True):
        out = eval_step(state, to_device(batch))
        preds = allgather_to_host(out["preds"])
        attn = allgather_to_host(out["attention"])
        valid = batch["example_mask"].astype(bool)
        hits = np.nonzero((preds == batch["labels"]) & valid)[0]
        if not len(hits):
            continue
        i = int(hits[0])
        for s, p, e, a in zip(
            batch["starts"][i], batch["paths"][i], batch["ends"][i], attn[i]
        ):
            if s != PAD_INDEX:
                logger.info(
                    "%s %s %s [%s]",
                    terminal_itos[int(s)],
                    path_itos[int(p)],
                    terminal_itos[int(e)],
                    a,
                )
        logger.info("expected label: %s", label_itos[int(batch["labels"][i])])
        logger.info("actual label:   %s", label_itos[int(preds[i])])
        return
