"""Parallel host ingest: multi-worker zero-copy batch building.

code2vec training is input-bound at accelerator speeds — the step is tiny
matmuls over ``[B, L]`` integer batches while batch construction (subsample
sort + CSR gather + pad) runs as single-threaded numpy on the coordinator.
:class:`ParallelFeed` wraps any :class:`~code2vec_tpu.data.pipeline.BatchSource`
behind the same protocol and executes its batch **plan** on ``--feed_workers
N`` forked worker processes:

- **RNG stays on the coordinator.** The wrapped source's
  ``plan_batches(rng, shuffle)`` draws every random value its
  ``batches()`` would — epoch plans, bucket interleaves, shuffles, the
  per-item subsample uniforms — in the identical order and sizes; workers
  only run the pure ``execute_plan`` build. Feed order, loss history, and
  mid-epoch resume cursors are **bitwise identical** to ``--feed_workers
  0`` (tests/test_feed.py pins the matrix).
- **Zero-copy transport.** Workers write finished batches into
  preallocated ``multiprocessing.shared_memory`` arena slots; the
  coordinator hands them to the consumer as numpy views — no pickling of
  batch tensors. Corpus arrays are fork-inherited: mmap-CSR views stay
  one shared OS mapping (zero per-worker context RSS), in-RAM arrays are
  shared copy-on-write pages.
- **In-order delivery.** Results are resequenced through a reorder
  buffer, so the consumer sees the exact sync stream order.
- **Arena recycling.** A delivered slot is reused only after the consumer
  moves past it. In ``views`` delivery a slot is recycled at the NEXT
  pull — and the pull/transfer loop is sequential, so a view is never
  overwritten before ``to_device`` returned (the prefetch producer
  additionally fences the async H2D; see ``fence_h2d``). On backends
  whose ``device_put`` zero-copy ALIASES page-aligned host buffers (jax's
  CPU client does), recycling a slot would corrupt the live device batch,
  so the pool probes once and falls back to ``copy`` delivery: one
  memcpy per batch, still a fraction of the build it displaced.
- **Failure propagation.** A worker exception ships its full traceback
  text back and re-raises on the coordinator as :class:`FeedWorkerError`
  (with an ``error`` event); a killed worker is detected by liveness
  polling and fails the stream instead of hanging it.

The small per-row fields (``ids``/``labels``/``example_mask``) are always
delivered as owned copies: eval reads them after later batches were pulled
(and their slots recycled); the big ``[B, L]`` context tensors are only
read by ``to_device`` before the next pull.
"""

from __future__ import annotations

import collections
import multiprocessing
import os
import queue as queue_mod
import time
import traceback
import warnings
import weakref
from dataclasses import dataclass

import numpy as np

from code2vec_tpu.obs import handles
from code2vec_tpu.obs.sync import guard_fork_safety

from code2vec_tpu.data.pipeline import (
    BatchSource,
    execute_plan,
    plan_real_slots,
)

__all__ = ["FeedPool", "FeedWorkerError", "ParallelFeed"]

# trace-span sampling for delivered batches — mirrors the prefetch
# producer's policy (a 16k-step epoch must not flood the tracer)
_SPAN_WARMUP = 8
_SPAN_STRIDE = 64
_POLL_S = 0.2  # result-wait poll cadence (worker-liveness check interval)


class FeedWorkerError(RuntimeError):
    """A feed worker failed (exception or death). ``remote_traceback``
    carries the child's formatted traceback when one crossed the process
    boundary (a SIGKILLed worker has none)."""

    def __init__(self, message: str, remote_traceback: str | None = None):
        if remote_traceback:
            message = (
                f"{message}\n--- feed worker traceback ---\n"
                f"{remote_traceback.rstrip()}"
            )
        super().__init__(message)
        self.remote_traceback = remote_traceback


@dataclass
class _CorpusArrays:
    """The slim, fork-shared corpus view workers build from. Extracted
    BEFORE fork so workers never touch the full CorpusData (vocabs,
    alias/label string lists) — touching Python objects dirties their
    copy-on-write pages via refcounting; numpy DATA pages (and mmap
    views) stay shared."""

    starts: np.ndarray
    paths: np.ndarray
    ends: np.ndarray
    row_splits: np.ndarray
    row_base: np.ndarray | None
    ids: np.ndarray
    labels: np.ndarray
    method_token_index: int | None

    @classmethod
    def from_data(cls, data) -> "_CorpusArrays":
        return cls(
            starts=data.starts,
            paths=data.paths,
            ends=data.ends,
            row_splits=data.row_splits,
            row_base=data.row_base,
            ids=data.ids,
            labels=data.labels,
            method_token_index=data.method_token_index,
        )


class _ArenaLayout:
    """Byte offsets of one arena slot: the per-row fields at the head,
    then three compact ``[B, width]`` int32 planes sized for the ladder's
    top width. A batch at a narrower width writes (and is viewed) as a
    C-contiguous ``[B, width]`` block at each plane's base."""

    def __init__(self, batch_size: int, max_width: int):
        self.batch_size = int(batch_size)
        self.max_width = int(max_width)
        plane = self.batch_size * self.max_width * 4
        self.off_ids = 0
        self.off_labels = self.off_ids + self.batch_size * 8
        self.off_mask = self.off_labels + self.batch_size * 4
        base = self.off_mask + self.batch_size * 4
        # 64-byte-align the context planes (harmless; keeps views friendly
        # to vectorized gathers either side of the boundary)
        base = -(-base // 64) * 64
        self.off_starts = base
        self.off_paths = base + plane
        self.off_ends = base + 2 * plane
        self.slot_bytes = base + 3 * plane

    def views(self, buf, width: int):
        """The slot's numpy views at ``width`` (no copies)."""
        b = self.batch_size
        return {
            "ids": np.ndarray((b,), np.int64, buffer=buf, offset=self.off_ids),
            "labels": np.ndarray(
                (b,), np.int32, buffer=buf, offset=self.off_labels
            ),
            "example_mask": np.ndarray(
                (b,), np.float32, buffer=buf, offset=self.off_mask
            ),
            "starts": np.ndarray(
                (b, width), np.int32, buffer=buf, offset=self.off_starts
            ),
            "paths": np.ndarray(
                (b, width), np.int32, buffer=buf, offset=self.off_paths
            ),
            "ends": np.ndarray(
                (b, width), np.int32, buffer=buf, offset=self.off_ends
            ),
        }


def _feed_worker_main(worker_id, arrays, shms, layout, task_q, result_q):
    """Worker loop: pull ``(gen, seq, slot, plan)`` tasks, run the pure
    build, write the batch into the slot's arena, post the result. Runs
    numpy only — never jax (forking an initialized backend is safe as
    long as the child stays out of it)."""
    # the fork inherited the parent's process-wide tracer (and its lock,
    # possibly mid-acquire on another thread at fork time): install the
    # no-op tracer FIRST so no span in the build path can touch it
    from code2vec_tpu.obs.trace import NullTracer, set_tracer

    set_tracer(NullTracer())
    bufs = [shm.buf for shm in shms]
    while True:
        task = task_q.get()
        if task is None:
            return
        gen, seq, slot, plan = task
        try:
            t0 = time.perf_counter()
            batch = execute_plan(arrays, plan)
            views = layout.views(bufs[slot], int(plan.width))
            for key, view in views.items():
                view[...] = batch[key]
            result_q.put(
                (
                    "ok", gen, seq, slot, int(plan.width), int(plan.valid),
                    worker_id, t0, time.perf_counter(),
                )
            )
        except BaseException as exc:  # noqa: BLE001 - shipped to the coordinator
            result_q.put(
                (
                    "error", gen, seq, slot,
                    f"{type(exc).__name__}: {exc}", traceback.format_exc(),
                )
            )


def _device_put_aliases_shared_memory(shm) -> bool:
    """One-time probe: does this backend's ``device_put`` zero-copy ALIAS
    a page-aligned host buffer? jax's CPU client does (mutating the numpy
    source after ``device_put`` changes the device array), so arena slots
    must not be recycled under live device batches there — the pool
    switches to copy-on-delivery. TPU/GPU transfers are real copies."""
    import jax

    probe = np.ndarray((64,), np.int32, buffer=shm.buf)
    probe[:] = np.arange(64, dtype=np.int32)
    device = jax.device_put(probe)
    jax.block_until_ready(device)
    probe[0] = -12345
    aliased = int(np.asarray(device)[0]) == -12345
    probe[0] = 0
    return aliased


class FeedPool:
    """``n_workers`` forked builder processes + a shared-memory batch
    arena, shared by every :class:`ParallelFeed` wrapper of a run (the
    train and test splits reuse one pool). One stream is active at a
    time — exactly the train loop's epoch structure."""

    def __init__(
        self,
        data,
        n_workers: int,
        batch_size: int,
        max_width: int,
        slots: int = 0,
        deliver: str = "auto",
        events=None,
        health=None,
        tracer=None,
    ):
        if n_workers < 1:
            raise ValueError(f"feed_workers must be >= 1, got {n_workers}")
        if deliver not in ("auto", "views", "copy"):
            raise ValueError(f"unknown deliver mode: {deliver!r}")
        if os.name != "posix":
            raise ValueError(
                "--feed_workers requires fork-capable multiprocessing "
                "(POSIX); use --feed_workers 0 here"
            )
        from multiprocessing import shared_memory

        self.n_workers = int(n_workers)
        # enough slots that every worker can build while a full reorder
        # window and the delivered batch stay pinned
        self.slots = int(slots) if slots else 2 * self.n_workers + 2
        self._layout = _ArenaLayout(batch_size, max_width)
        self._events = events
        self._health = health
        self._tracer = tracer
        # runtime twin of the static CX005 rule: a forked child inherits
        # any lock a live non-daemon thread holds, permanently frozen —
        # warn (error event + log) before requesting the fork context so
        # a coordinator that already started serving/training threads
        # hears about it instead of deadlocking a worker later
        guard_fork_safety("FeedPool", events=self._events)
        self._ctx = multiprocessing.get_context("fork")
        self._shms = [
            shared_memory.SharedMemory(
                create=True, size=self._layout.slot_bytes
            )
            for _ in range(self.slots)
        ]
        self._deliver = deliver
        self._task_q = self._ctx.Queue()
        self._result_q = self._ctx.Queue()
        arrays = _CorpusArrays.from_data(data)
        self._procs = [
            self._ctx.Process(
                target=_feed_worker_main,
                args=(
                    wid, arrays, self._shms, self._layout,
                    self._task_q, self._result_q,
                ),
                name=f"c2v-feed-worker-{wid}",
                daemon=True,
            )
            for wid in range(self.n_workers)
        ]
        with warnings.catch_warnings():
            # jax warns on ANY fork of its (multithreaded) process; the
            # hazard is a child calling into runtime state whose locks
            # were mid-acquire at fork time. These workers run numpy only
            # — they never touch jax — the standard dataloader-worker
            # pattern, so the blanket warning is noise here.
            warnings.filterwarnings(
                "ignore", message=r"os\.fork\(\) was called",
                category=RuntimeWarning,
            )
            for p in self._procs:
                p.start()
        self._free: collections.deque[int] = collections.deque(
            range(self.slots)
        )
        self._gen = 0
        self._active: _FeedStream | None = None
        self._closed = False
        # last-resort cleanup on GC/interpreter exit: a crash between
        # pool creation and the owner's finally must not leak worker
        # processes or named shared-memory segments
        self._finalizer = weakref.finalize(
            self, _release_pool_resources, self._procs, self._shms
        )
        handles.track(self, "feed_pool", name=f"workers={self.slots}")

    # ---- delivery mode -------------------------------------------------
    def deliver_mode(self) -> str:
        """Resolve ``auto`` on first use (the probe touches jax, which the
        jax-free RSS tests avoid by pinning ``views``)."""
        if self._deliver == "auto":
            self._deliver = (
                "copy"
                if _device_put_aliases_shared_memory(self._shms[0])
                else "views"
            )
        return self._deliver

    # ---- streams -------------------------------------------------------
    def run(self, plans, feed: "ParallelFeed | None" = None) -> "_FeedStream":
        if self._closed:
            raise RuntimeError("feed pool is closed")
        if self._active is not None and not self._active.finished:
            # the train loop runs one epoch stream at a time; a second
            # concurrent stream would interleave slot ownership
            raise RuntimeError(
                "a feed stream is already active on this pool; close or "
                "exhaust it before starting another"
            )
        self._gen += 1
        self._active = _FeedStream(self, plans, self._gen, feed)
        return self._active

    def check_workers(self) -> None:
        for wid, p in enumerate(self._procs):
            if not p.is_alive():
                message = (
                    f"feed worker {wid} died (exit code {p.exitcode}) "
                    "without reporting an error — killed or crashed hard; "
                    "restart the run (the pool cannot continue safely)"
                )
                if self._events is not None:
                    try:
                        self._events.emit(
                            "error", error=message, feed_worker=wid
                        )
                    except Exception:
                        pass
                raise FeedWorkerError(message)

    def worker_failed(self, wid_or_msg: str, tb_text: str) -> FeedWorkerError:
        message = f"feed worker build failed: {wid_or_msg}"
        if self._events is not None:
            try:
                self._events.emit(
                    "error", error=message, feed_worker_traceback=tb_text
                )
            except Exception:
                pass
        return FeedWorkerError(message, remote_traceback=tb_text)

    def close(self) -> None:
        """Stop workers and release the arena. Idempotent; safe after
        worker death (escalates to terminate)."""
        if self._closed:
            return
        self._closed = True
        self._gen += 1  # orphan any in-flight results
        for p in self._procs:
            if p.is_alive():
                try:
                    self._task_q.put(None)
                except Exception:
                    break
        deadline = time.monotonic() + 5.0
        for p in self._procs:
            p.join(timeout=max(deadline - time.monotonic(), 0.1))
        for p in self._procs:
            if p.is_alive():
                p.terminate()
                p.join(timeout=5.0)
        for q in (self._task_q, self._result_q):
            try:
                q.cancel_join_thread()
                q.close()
            except Exception:
                pass
        for shm in self._shms:
            try:
                shm.close()
                shm.unlink()
            except Exception:
                pass
        self._finalizer.detach()
        handles.untrack(self)


def _release_pool_resources(procs, shms) -> None:
    """The :func:`weakref.finalize` fallback behind :meth:`FeedPool.close`
    — hard teardown only (no queue draining): terminate stragglers and
    unlink the arena segments."""
    for p in procs:
        try:
            if p.is_alive():
                p.terminate()
        except Exception:
            pass
    for shm in shms:
        try:
            shm.close()
            shm.unlink()
        except Exception:
            pass


class _FeedStream:
    """One epoch's ordered batch stream off the pool.

    Iterates batch dicts exactly like the wrapped source's ``batches()``
    stream. Exposes the attributes the host pipeline probes:

    - ``last_wait_ms`` — how long the previous pull blocked on the pool
      (the ``feed_wait_ms`` profiler column; ~0 when workers keep up);
    - ``fence_h2d`` — True in views delivery: the consumer must fence the
      async H2D before pulling again (the next pull recycles the slot).
    """

    def __init__(self, pool: FeedPool, plans, gen: int, feed):
        self._pool = pool
        self._plans = iter(plans)
        self._gen = gen
        self._feed = feed
        self._mode = pool.deliver_mode()
        self._next_seq = 0
        self._submit_seq = 0
        self._plans_done = False
        self._inflight: dict[int, int] = {}  # seq -> slot
        self._ready: dict[int, tuple] = {}
        self._delivered_slot: int | None = None
        self._real = 0
        self._slots_total = 0
        self.finished = False
        self.last_wait_ms = 0.0

    @property
    def fence_h2d(self) -> bool:
        return self._mode == "views"

    def __iter__(self) -> "_FeedStream":
        return self

    # ---- submission ----------------------------------------------------
    def _submit_more(self) -> None:
        pool = self._pool
        while not self._plans_done and pool._free:
            try:
                plan = next(self._plans)
            except StopIteration:
                self._plans_done = True
                self._close_plans()
                break
            slot = pool._free.popleft()
            if self._feed is not None:
                real, slots = plan_real_slots(plan, self._feed._row_splits)
                self._real += real
                self._slots_total += slots
            self._inflight[self._submit_seq] = slot
            pool._task_q.put((self._gen, self._submit_seq, slot, plan))
            self._submit_seq += 1

    def _close_plans(self) -> None:
        close = getattr(self._plans, "close", None)
        if close is not None:
            close()

    # ---- delivery ------------------------------------------------------
    def _recycle_delivered(self) -> None:
        if self._delivered_slot is not None:
            self._pool._free.append(self._delivered_slot)
            self._delivered_slot = None

    def _handle(self, msg) -> None:
        kind, gen = msg[0], msg[1]
        if gen != self._gen:
            # a previous (closed) stream's straggler: reclaim its slot
            self._pool._free.append(msg[3])
            return
        if kind == "error":
            _, _, seq, slot, summary, tb_text = msg
            self._pool._free.append(slot)
            self._inflight.pop(seq, None)
            self._fail()
            raise self._pool.worker_failed(summary, tb_text)
        _, _, seq, slot, width, valid, wid, t0, t1 = msg
        self._ready[seq] = (slot, width, valid, wid, t0, t1)

    def __next__(self) -> dict[str, np.ndarray]:
        if self.finished:
            raise StopIteration
        self._recycle_delivered()
        self._submit_more()
        if self._plans_done and self._next_seq >= self._submit_seq:
            self._finish()
            raise StopIteration
        pool = self._pool
        health = pool._health
        # eager liveness check (one waitpid poll per worker): a dead
        # worker fails the stream NOW, not only when its lost in-flight
        # batch would have stalled the reorder window
        try:
            pool.check_workers()
        except BaseException:
            self._fail()
            raise
        waited = self._next_seq not in self._ready
        t0 = time.perf_counter()
        while self._next_seq not in self._ready:
            try:
                msg = pool._result_q.get(timeout=_POLL_S)
            except queue_mod.Empty:
                try:
                    pool.check_workers()
                except BaseException:
                    self._fail()
                    raise
                continue
            self._handle(msg)
            self._submit_more()
        self.last_wait_ms = (
            (time.perf_counter() - t0) * 1e3 if waited else 0.0
        )
        if health is not None:
            health.gauge("feed.queue_depth").set(len(self._ready))
            if waited:
                health.counter("feed.starved_steps").inc()
        slot, width, valid, wid, bt0, bt1 = self._ready.pop(self._next_seq)
        seq = self._next_seq
        self._next_seq += 1
        self._inflight.pop(seq, None)
        self._emit_span(seq, wid, width, bt0, bt1)
        views = pool._layout.views(pool._shms[slot].buf, width)
        if self._mode == "copy":
            batch = {key: np.array(view) for key, view in views.items()}
            pool._free.append(slot)
        else:
            # zero-copy big planes (valid until the NEXT pull); the small
            # per-row fields are owned copies — eval reads them after
            # later pulls recycled this slot
            batch = dict(
                views,
                ids=np.array(views["ids"]),
                labels=np.array(views["labels"]),
                example_mask=np.array(views["example_mask"]),
            )
            self._delivered_slot = slot
        return batch

    def _emit_span(self, seq, wid, width, t0, t1) -> None:
        tracer = self._pool._tracer
        if tracer is None or not getattr(tracer, "enabled", False):
            return
        if seq >= _SPAN_WARMUP and seq % _SPAN_STRIDE:
            return
        # perf_counter is CLOCK_MONOTONIC (system-wide on Linux), so the
        # child's stamps land directly on this process's span clock
        tracer.span_complete(
            "feed_build", category="data", start_s=t0, end_s=t1,
            track=f"feed-worker-{wid}", seq=seq, width=width,
        )

    # ---- teardown ------------------------------------------------------
    def _publish_pad(self) -> None:
        if self._feed is not None and self._slots_total:
            self._feed._last_pad = (self._real, self._slots_total)

    def _finish(self) -> None:
        self.finished = True
        self._recycle_delivered()
        self._publish_pad()

    def _fail(self) -> None:
        """Abandon the stream after an error: in-flight slots are orphaned
        to the stale-gen reclaim path (the pool bumps the gen at the next
        stream), ready ones are freed now."""
        self.finished = True
        self._recycle_delivered()
        for slot, *_ in self._ready.values():
            self._pool._free.append(slot)
        self._ready.clear()
        self._inflight.clear()
        self._publish_pad()

    def close(self) -> None:
        """Early shutdown (epoch aborted / preemption drain / skip): free
        what this stream holds; results still being built are reclaimed
        by the next stream's stale-gen handling."""
        if self.finished:
            return
        self._close_plans()
        self._plans_done = True
        self._fail()


class ParallelFeed(BatchSource):
    """A :class:`BatchSource` executing the wrapped source's plans on a
    :class:`FeedPool`. ``ladder`` mirrors the wrapped source; ``last_epoch``
    stays None (no epoch tensor ever exists on the coordinator), so
    export/print_sample fall back to an on-demand build like the other
    out-of-core sources."""

    def __init__(self, source: BatchSource, pool: FeedPool):
        self._source = source
        self._pool = pool
        self.ladder = source.ladder
        self.last_epoch = None
        self._row_splits = source.data.row_splits
        self._last_pad: tuple[int, int] | None = None
        # fail at wrap time, not at the first epoch: sources without a
        # plan split (or with the variable task) raise here
        probe = source.plan_batches(np.random.default_rng(0))
        close = getattr(probe, "close", None)
        if close is not None:
            close()

    def batches(self, rng, shuffle: bool = True):
        return self._pool.run(
            self._source.plan_batches(rng, shuffle), feed=self
        )

    def scheduled_batches(self, rng, schedule, shuffle: bool = True):
        raise NotImplementedError(
            "--feed_workers does not compose with host-sharded scheduled "
            "feeding; drop --feed_workers (or feed this host unsharded)"
        )

    def pad_stats(self) -> tuple[int, int] | None:
        return self._last_pad
