"""Host-side data layer: vocab, corpus reader, TPU-shaped input pipeline."""

from code2vec_tpu.data.vocab import Vocab
