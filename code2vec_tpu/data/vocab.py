"""Vocabulary: name<->index maps with per-index frequency and subtokens.

Semantics mirror the reference Vocab (model/dataset.py:52-92) including its
quirks, which downstream code depends on:

- ``add`` ignores names already present (first index wins).
- ``freq`` counts *appends per index*, and because duplicate names are
  ignored, every label's frequency ends up exactly 1 in the reference —
  making the 1/freq class weights de-facto uniform (SURVEY.md §2.2). We keep
  the same default but additionally track true occurrence counts in
  ``occurrences`` so real frequency weighting is available as an opt-in.
"""

from __future__ import annotations

from code2vec_tpu.text import normalize_and_subtokenize


class Vocab:
    __slots__ = ("stoi", "itos", "itosubtokens", "freq", "occurrences")

    def __init__(self) -> None:
        self.stoi: dict[str, int] = {}
        self.itos: dict[int, str] = {}
        self.itosubtokens: dict[int, tuple[str, ...]] = {}
        self.freq: dict[int, int] = {}
        self.occurrences: dict[int, int] = {}

    def __len__(self) -> int:
        return len(self.stoi)

    def add(
        self,
        name: str,
        index: int | None = None,
        subtokens: tuple[str, ...] | None = None,
    ) -> int:
        """Insert ``name`` if unseen; return its index either way.

        Mirrors Vocab.append (reference: model/dataset.py:64-74): explicit
        ``index`` wins, otherwise the next dense slot; freq increments only
        on first sight of the name. ``occurrences`` increments on every call.
        """
        existing = self.stoi.get(name)
        if existing is not None:
            self.occurrences[existing] = self.occurrences.get(existing, 0) + 1
            return existing
        if index is None:
            index = len(self.stoi)
        self.stoi[name] = index
        self.itos[index] = name
        if subtokens is not None:
            self.itosubtokens[index] = tuple(subtokens)
        self.freq[index] = self.freq.get(index, 0) + 1
        self.occurrences[index] = self.occurrences.get(index, 0) + 1
        return index

    def add_label(self, raw_name: str) -> int:
        """Normalize+subtokenize a raw label and insert it (the label-vocab
        path of the reference corpus loader, model/dataset_reader.py:94-102)."""
        normalized_lower, subtokens = normalize_and_subtokenize(raw_name)
        return self.add(normalized_lower, subtokens=subtokens)

    def freq_list(self) -> list[int]:
        """Dense frequency list indexed 0..len-1 (reference:
        model/dataset.py:76-81). Raises KeyError on index gaps, like the
        reference would."""
        return [self.freq[i] for i in range(len(self.stoi))]

    def occurrence_list(self) -> list[int]:
        """True occurrence counts (framework extension for real class
        weighting; the reference's freq is de-facto uniform, SURVEY §2.2)."""
        return [self.occurrences.get(i, 0) for i in range(len(self.stoi))]

    def to_state(self) -> list:
        """JSON-serializable snapshot (used by the corpus cache)."""
        return [
            [
                name,
                index,
                list(self.itosubtokens[index])
                if index in self.itosubtokens
                else None,
                self.freq.get(index, 0),
                self.occurrences.get(index, 0),
            ]
            for name, index in self.stoi.items()
        ]

    @classmethod
    def from_state(cls, state: list) -> "Vocab":
        vocab = cls()
        for name, index, subtokens, freq, occurrences in state:
            vocab.stoi[name] = index
            vocab.itos[index] = name
            if subtokens is not None:
                vocab.itosubtokens[index] = tuple(subtokens)
            vocab.freq[index] = freq
            vocab.occurrences[index] = occurrences
        return vocab
