"""Synthetic corpus generation.

The reference repo's large corpus blobs are stripped from the mount
(/root/reference/.MISSING_LARGE_BLOBS), so this module provides:

- ``generate_corpus_files``: small/medium text corpora in the exact L1
  format (SURVEY.md §2.4) with a *learnable* label<->context signal, used by
  integration tests and CLI smoke runs;
- ``generate_corpus_data``: array-level corpora at arbitrary scale (e.g.
  top11: 605,945 methods / 360,631 terminals / 342,845 paths —
  top11_dataset/params.txt) without writing gigabytes of text, used by
  bench.py.

Learnability: each label owns a "signature" pool of path-contexts; a
method's bag is mostly drawn from its label's pool plus uniform noise, so
attention over contexts genuinely predicts the label and F1 climbs within a
few epochs.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

import numpy as np

from code2vec_tpu.formats.corpus_io import CorpusRecord, write_corpus
from code2vec_tpu.formats.params_io import write_params
from code2vec_tpu.formats.vocab_io import write_vocab_from_names

_SUBTOKENS = [
    "get", "set", "is", "add", "remove", "find", "create", "build", "parse",
    "read", "write", "copy", "clear", "init", "close", "open", "load", "save",
    "value", "count", "name", "index", "list", "node", "item", "path", "file",
    "text", "data", "key", "map", "size", "hash", "code", "type", "state",
]


@dataclass
class SynthSpec:
    n_methods: int = 2000
    n_terminals: int = 1500  # excluding PAD; includes @method_0 and @var_*
    n_paths: int = 1200  # excluding PAD
    n_labels: int = 60
    n_vars: int = 12  # @var_0..@var_{n-1} terminal tokens
    mean_contexts: float = 60.0  # per-method bag size (lognormal-ish)
    # lognormal sigma of the per-method bag-size distribution: 0.0 is a
    # (clipped) constant-length corpus, larger values grow the heavy tail —
    # the length-skew knob the bucketed-batching A/B and tests dial
    length_sigma: float = 0.6
    max_contexts: int = 400
    signal: float = 0.8  # fraction of a bag drawn from the label's signature
    signature_size: int = 40
    vars_per_method: int = 3
    seed: int = 0


SPECS = {
    "tiny": SynthSpec(n_methods=200, n_terminals=300, n_paths=250, n_labels=12,
                      mean_contexts=30.0, signature_size=20),
    "small": SynthSpec(),
    # the head-to-head operating point (VERDICT r4 weak-#3): sized so both
    # implementations land MID-RANGE subtoken F1 — at "small" both sides
    # saturate >=0.95 where a multi-point quality regression could hide;
    # here the weaker signal (0.45 vs 0.8) and 10x label space keep the
    # task genuinely discriminating
    "parity10k": SynthSpec(
        n_methods=10_000, n_terminals=4_000, n_paths=3_000, n_labels=600,
        mean_contexts=60.0, signal=0.45, signature_size=30,
    ),
    "top11": SynthSpec(
        n_methods=605_945,
        n_terminals=360_631,
        n_paths=342_845,
        n_labels=8_000,
        mean_contexts=120.0,
        max_contexts=1000,
        signature_size=60,
    ),
}


def _label_names(n_labels: int, rng: np.random.Generator) -> list[str]:
    """Plausible camelCase method names so subtoken metrics are meaningful."""
    names: list[str] = []
    seen: set[str] = set()
    while len(names) < n_labels:
        k = int(rng.integers(1, 4))
        parts = [str(_SUBTOKENS[int(rng.integers(len(_SUBTOKENS)))]) for _ in range(k)]
        name = parts[0] + "".join(p.capitalize() for p in parts[1:])
        if name not in seen:
            seen.add(name)
            names.append(name)
    return names


def _terminal_names(spec: SynthSpec) -> list[str]:
    """Terminal vocab: @method_0, the @var_* family, then plain identifiers."""
    names = ["@method_0"] + [f"@var_{i}" for i in range(spec.n_vars)]
    names += [f"ident{i}" for i in range(spec.n_terminals - len(names))]
    return names


def _path_names(spec: SynthSpec) -> list[str]:
    """Path token strings in the extractor's up/hinge/down style
    (create_path_contexts.ipynb cell9 emits e.g.
    ``SimpleName^MethodCallExpr_NameExpr``)."""
    kinds = ["SimpleName", "NameExpr", "BlockStmt", "MethodCallExpr",
             "ReturnStmt", "BinaryExpr:PLUS", "IfStmt", "AssignExpr:ASSIGN"]
    names = []
    for i in range(spec.n_paths):
        a = kinds[i % len(kinds)]
        b = kinds[(i // len(kinds)) % len(kinds)]
        names.append(f"{a}^{b}_{i}")
    return names


@dataclass
class RawCorpus:
    """Array-level corpus with *raw on-disk* indices (no @question shift):
    feed to text writers or shift (+1) to build CorpusData directly."""

    starts: np.ndarray
    paths: np.ndarray
    ends: np.ndarray
    row_splits: np.ndarray
    label_ids: np.ndarray  # per-method index into label_names
    label_names: list[str]
    terminal_names: list[str]
    path_names: list[str]
    spec: SynthSpec


def corpus_data_from_raw(raw: RawCorpus):
    """Assemble a :class:`~code2vec_tpu.data.reader.CorpusData` directly from
    a :class:`RawCorpus`, skipping the text round-trip: apply the ``@question``
    index shift (+1) and register the special terminals so
    ``method_token_index`` resolves and the answer-leak substitution is
    exercised (synth sprinkles ``@method_0`` at raw index 1)."""
    from code2vec_tpu.data.reader import CorpusData
    from code2vec_tpu.data.vocab import Vocab
    from code2vec_tpu.text import normalize_and_subtokenize

    n_methods = len(raw.row_splits) - 1
    label_vocab = Vocab()
    for name in raw.label_names:
        label_vocab.add_label(name)
    normalized = [
        normalize_and_subtokenize(raw.label_names[i])[0]
        for i in raw.label_ids
    ]
    terminal_vocab = Vocab()
    terminal_vocab.add("<PAD/>", 0)
    terminal_vocab.add("@question", 1)
    # raw terminal idx i+1 -> shifted idx i+2; terminal_names[0] is
    # "@method_0", so method_token_index resolves to 2
    for i, name in enumerate(raw.terminal_names):
        terminal_vocab.add(name, i + 2)
    path_vocab = Vocab()
    path_vocab.add("<PAD/>", 0)
    for i, name in enumerate(raw.path_names):
        path_vocab.add(name, i + 1)
    return CorpusData(
        starts=raw.starts + 1,
        paths=raw.paths,
        ends=raw.ends + 1,
        row_splits=raw.row_splits,
        ids=np.arange(n_methods, dtype=np.int64),
        labels=raw.label_ids.astype(np.int32),
        normalized_labels=normalized,
        sources=[None] * n_methods,
        aliases=[{} for _ in range(n_methods)],
        terminal_vocab=terminal_vocab,
        path_vocab=path_vocab,
        label_vocab=label_vocab,
    )


def generate_corpus_data(spec: SynthSpec) -> RawCorpus:
    rng = np.random.default_rng(spec.seed)
    label_names = _label_names(spec.n_labels, rng)
    terminal_names = _terminal_names(spec)
    path_names = _path_names(spec)

    # signature pools: per label, a fixed set of (start, path, end) triples
    sig_starts = rng.integers(1, spec.n_terminals + 1,
                              (spec.n_labels, spec.signature_size), dtype=np.int64)
    sig_paths = rng.integers(1, spec.n_paths + 1,
                             (spec.n_labels, spec.signature_size), dtype=np.int64)
    sig_ends = rng.integers(1, spec.n_terminals + 1,
                            (spec.n_labels, spec.signature_size), dtype=np.int64)

    label_ids = rng.integers(0, spec.n_labels, spec.n_methods, dtype=np.int64)
    counts = np.clip(
        rng.lognormal(
            np.log(spec.mean_contexts), spec.length_sigma, spec.n_methods
        ).astype(np.int64),
        3,
        spec.max_contexts,
    )
    total = int(counts.sum())
    row_splits = np.zeros(spec.n_methods + 1, np.int64)
    np.cumsum(counts, out=row_splits[1:])

    seg_label = np.repeat(label_ids, counts)
    from_sig = rng.random(total) < spec.signal
    sig_slot = rng.integers(0, spec.signature_size, total)

    starts = np.where(from_sig, sig_starts[seg_label, sig_slot],
                      rng.integers(1, spec.n_terminals + 1, total))
    paths = np.where(from_sig, sig_paths[seg_label, sig_slot],
                     rng.integers(1, spec.n_paths + 1, total))
    ends = np.where(from_sig, sig_ends[seg_label, sig_slot],
                    rng.integers(1, spec.n_terminals + 1, total))

    # sprinkle @method_0 (raw idx 1) into some bags so the @question
    # substitution path is exercised
    is_method_tok = rng.random(total) < 0.02
    starts = np.where(is_method_tok, 1, starts)

    return RawCorpus(
        starts=starts.astype(np.int32),
        paths=paths.astype(np.int32),
        ends=ends.astype(np.int32),
        row_splits=row_splits,
        label_ids=label_ids,
        label_names=label_names,
        terminal_names=terminal_names,
        path_names=path_names,
        spec=spec,
    )


def generate_corpus_files(out_dir: str | os.PathLike, spec: SynthSpec) -> dict[str, str]:
    """Write the five L1 artifacts for a synthetic corpus; returns paths."""
    os.makedirs(out_dir, exist_ok=True)
    raw = generate_corpus_data(spec)
    rng = np.random.default_rng(spec.seed + 1)

    records = []
    n_vars = spec.n_vars
    for i in range(spec.n_methods):
        lo, hi = raw.row_splits[i], raw.row_splits[i + 1]
        contexts = list(
            zip(
                raw.starts[lo:hi].tolist(),
                raw.paths[lo:hi].tolist(),
                raw.ends[lo:hi].tolist(),
            )
        )
        k = int(rng.integers(0, spec.vars_per_method + 1))
        aliases = [
            (f"local{j}Var", f"@var_{j}") for j in range(min(k, n_vars))
        ]
        # make variable contexts exist: retarget a few starts to the aliases
        for j in range(len(aliases)):
            if contexts:
                slot = int(rng.integers(len(contexts)))
                s, p, e = contexts[slot]
                contexts[slot] = (2 + j, p, e)  # raw idx of @var_j is 2+j
        records.append(
            CorpusRecord(
                id=i + 1,
                label=raw.label_names[raw.label_ids[i]],
                source=f"synthetic/Class{i % 97}.java",
                path_contexts=contexts,
                aliases=aliases,
            )
        )

    paths = {
        "corpus": os.path.join(out_dir, "corpus.txt"),
        "path_idx": os.path.join(out_dir, "path_idxs.txt"),
        "terminal_idx": os.path.join(out_dir, "terminal_idxs.txt"),
        "params": os.path.join(out_dir, "params.txt"),
    }
    write_corpus(paths["corpus"], records)
    write_vocab_from_names(paths["terminal_idx"], raw.terminal_names)
    write_vocab_from_names(paths["path_idx"], raw.path_names)
    write_params(
        paths["params"],
        {
            "max_length": 8,
            "max_width": 3,
            "terminal_vocab_count": len(raw.terminal_names),
            "path_vocab_count": len(raw.path_names),
            "method_count": spec.n_methods,
        },
    )
    return paths
