"""TPU-shaped input pipeline: split, per-epoch resampling, batching.

The reference rebuilds all tensors in a Python loop per method per epoch
(model/dataset_builder.py:112-210) — its host-side hot loop (SURVEY.md §3.1).
Here the same semantics run as O(total log total) vectorized numpy over the
CSR arrays:

- seeded train/test split (fixing the reference's unseeded global-random
  split, model/dataset_builder.py:19-26 / SURVEY.md §2.6);
- per-epoch *random subsample* of up to ``max_contexts`` path-contexts per
  method — the reference's load-bearing data augmentation
  (model/dataset_builder.py:134-135);
- ``@method_0 -> @question`` substitution so the answer isn't leaked
  (model/dataset_builder.py:122-144);
- the variable-name task expansion with optional index permutation
  (model/dataset_builder.py:152-204);
- static-shape ``[B, L]`` batches (PAD=0) with an example mask so the last
  partial batch never changes compiled shapes.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator

import numpy as np

from code2vec_tpu import PAD_INDEX, QUESTION_TOKEN_INDEX
from code2vec_tpu.data.reader import CorpusData
from code2vec_tpu.obs.trace import get_tracer


@dataclass
class EpochArrays:
    """One epoch's worth of examples, padded to static shape [N, L]."""

    ids: np.ndarray  # int64 [N]
    starts: np.ndarray  # int32 [N, L]
    paths: np.ndarray  # int32 [N, L]
    ends: np.ndarray  # int32 [N, L]
    labels: np.ndarray  # int32 [N]

    def __len__(self) -> int:
        return len(self.labels)


def split_items(
    n_items: int, rng: np.random.Generator, split_ratio: float = 0.2
) -> tuple[np.ndarray, np.ndarray]:
    """Seeded shuffle-then-slice split: first ``ratio`` fraction is test,
    rest is train (same slicing as model/dataset_builder.py:23-26, but
    reproducible — the reference leaves Python's global RNG unseeded)."""
    perm = rng.permutation(n_items)
    test_count = int(n_items * split_ratio)
    return perm[test_count:], perm[:test_count]


def flat_context_indices(
    row_splits: np.ndarray, item_idx: np.ndarray
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Vectorized CSR row gather: for the selected items, the flat indices
    of all their contexts plus each context's (segment, position-in-segment).

    Returns ``(flat, seg, within)``, each of length ``counts.sum()``. Shared
    by the host epoch builder and device staging (train/device_epoch.py).
    """
    counts = (row_splits[item_idx + 1] - row_splits[item_idx]).astype(np.int64)
    total = int(counts.sum())
    if total == 0:
        empty = np.zeros(0, np.int64)
        return empty, empty, empty
    seg = np.repeat(np.arange(len(item_idx), dtype=np.int64), counts)
    seg_starts = np.concatenate(([0], np.cumsum(counts)[:-1]))
    within = np.arange(total, dtype=np.int64) - np.repeat(seg_starts, counts)
    flat = np.repeat(row_splits[item_idx], counts) + within
    return flat, seg, within


def _segment_subsample(
    row_splits: np.ndarray,
    item_idx: np.ndarray,
    max_contexts: int,
    rng: np.random.Generator,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Pick up to ``max_contexts`` random contexts per selected item.

    Returns ``(flat_idx, out_row, out_col)``: indices into the flat CSR
    arrays plus the destination (row, col) in the padded [N, L] output.

    Vectorized equivalent of "shuffle each method's context list, keep the
    first L" (model/dataset_builder.py:134-135): draw one uniform per
    context, stably sort by (segment, uniform), keep the first L positions
    of each segment.
    """
    flat, seg, within = flat_context_indices(row_splits, item_idx)
    total = len(flat)
    if total == 0:
        return flat, seg, within

    order = np.lexsort((rng.random(total), seg))
    # after the stable per-segment sort the segment layout is unchanged,
    # so position-in-segment is the same ``within`` sequence
    keep = within < max_contexts
    kept_order = order[keep]
    return flat[kept_order], seg[keep], within[keep]


def build_method_epoch(
    data: CorpusData,
    item_idx: np.ndarray,
    max_contexts: int,
    rng: np.random.Generator,
) -> EpochArrays:
    """Method-name task epoch: fresh context subsample per method, with the
    method's own ``@method_0`` token replaced by ``@question``
    (model/dataset_builder.py:122-150)."""
    n = len(item_idx)
    with get_tracer().span("build_method_epoch", category="data", items=n):
        return _build_method_epoch(data, item_idx, max_contexts, rng)


def _build_method_epoch(
    data: CorpusData,
    item_idx: np.ndarray,
    max_contexts: int,
    rng: np.random.Generator,
) -> EpochArrays:
    n = len(item_idx)
    flat, row, col = _segment_subsample(data.row_splits, item_idx, max_contexts, rng)

    starts = np.full((n, max_contexts), PAD_INDEX, np.int32)
    paths = np.full((n, max_contexts), PAD_INDEX, np.int32)
    ends = np.full((n, max_contexts), PAD_INDEX, np.int32)
    starts[row, col] = data.starts[flat]
    paths[row, col] = data.paths[flat]
    ends[row, col] = data.ends[flat]

    method_idx = data.method_token_index
    if method_idx is not None:
        np.putmask(starts, starts == method_idx, QUESTION_TOKEN_INDEX)
        np.putmask(ends, ends == method_idx, QUESTION_TOKEN_INDEX)

    return EpochArrays(
        ids=data.ids[item_idx],
        starts=starts,
        paths=paths,
        ends=ends,
        labels=data.labels[item_idx],
    )


def variable_items(data: CorpusData, item_idx: np.ndarray):
    """The variable-task expansion core, shared by the host epoch builder
    and device staging (train/device_epoch.py): per item, the ``@var_*``
    aliases and the contexts touching ANY of them
    (model/dataset_builder.py:152-177). Yields
    ``(item, alias_names, alias_idx, starts, paths, ends)``; the caller
    applies its own shuffling/selection/renaming so rng draw order stays
    exactly the reference's."""
    terminal_stoi = data.terminal_vocab.stoi
    for i in item_idx:
        alias_map = data.aliases[i]
        alias_names = [a for a in alias_map if a.startswith("@var_")]
        if not alias_names:
            continue
        alias_idx = np.asarray(
            [terminal_stoi[a] for a in alias_names], dtype=np.int32
        )
        lo, hi = data.row_splits[i], data.row_splits[i + 1]
        s, p, e = data.starts[lo:hi], data.paths[lo:hi], data.ends[lo:hi]
        touches = np.isin(s, alias_idx) | np.isin(e, alias_idx)
        yield i, alias_names, alias_idx, s[touches], p[touches], e[touches]


def build_variable_epoch(
    data: CorpusData,
    item_idx: np.ndarray,
    max_contexts: int,
    rng: np.random.Generator,
    shuffle_variable_indexes: bool = False,
) -> EpochArrays:
    """Variable-name task epoch (context2name-style extension).

    One example per ``@var_*`` alias of each method: keep only contexts
    touching *any* variable of interest, shuffle them once per method, then
    per target variable keep its contexts, rename the target to
    ``@question`` and optionally remap the other variable ids through a
    shuffled permutation of the whole ``@var_*`` id set so the model can't
    memorize id order (model/dataset_builder.py:152-204).

    Examples-per-method varies, so this stays a per-method loop with
    vectorized inner ops; corpora are method-bounded so this is not the
    per-context hot path.
    """
    with get_tracer().span(
        "build_variable_epoch", category="data", items=len(item_idx)
    ):
        return _build_variable_epoch(
            data, item_idx, max_contexts, rng, shuffle_variable_indexes
        )


def _build_variable_epoch(
    data: CorpusData,
    item_idx: np.ndarray,
    max_contexts: int,
    rng: np.random.Generator,
    shuffle_variable_indexes: bool = False,
) -> EpochArrays:
    variable_indexes = data.variable_indexes
    perm_map = None
    if not shuffle_variable_indexes and len(variable_indexes):
        # identity remap outside shuffle mode (reference builds the same
        # dict once, model/dataset_builder.py:155-156)
        perm_map = _index_remap(variable_indexes, variable_indexes)

    ids: list[int] = []
    labels: list[int] = []
    rows_s: list[np.ndarray] = []
    rows_p: list[np.ndarray] = []
    rows_e: list[np.ndarray] = []

    label_stoi = data.label_vocab.stoi

    for i, alias_names, alias_idx, s, p, e in variable_items(data, item_idx):
        alias_map = data.aliases[i]
        if shuffle_variable_indexes:
            shuffled = variable_indexes.copy()
            rng.shuffle(shuffled)
            perm_map = _index_remap(variable_indexes, shuffled)

        order = rng.permutation(len(s))
        s, p, e = s[order], p[order], e[order]

        for alias_name, var_idx in zip(alias_names, alias_idx):
            mine = (s == var_idx) | (e == var_idx)
            ms, mp, me = s[mine][:max_contexts], p[mine][:max_contexts], e[mine][:max_contexts]
            ms = _rename_target(ms, var_idx, perm_map)
            me = _rename_target(me, var_idx, perm_map)
            ids.append(int(data.ids[i]))
            labels.append(label_stoi[alias_map[alias_name]])
            rows_s.append(ms)
            rows_p.append(mp)
            rows_e.append(me)

    n = len(ids)
    starts = np.full((n, max_contexts), PAD_INDEX, np.int32)
    paths = np.full((n, max_contexts), PAD_INDEX, np.int32)
    ends = np.full((n, max_contexts), PAD_INDEX, np.int32)
    for r, (ms, mp, me) in enumerate(zip(rows_s, rows_p, rows_e)):
        starts[r, : len(ms)] = ms
        paths[r, : len(mp)] = mp
        ends[r, : len(me)] = me

    return EpochArrays(
        ids=np.asarray(ids, np.int64),
        starts=starts,
        paths=paths,
        ends=ends,
        labels=np.asarray(labels, np.int32),
    )


def _index_remap(src: np.ndarray, dst: np.ndarray) -> np.ndarray:
    """Dense lookup table mapping terminal id -> remapped id (identity
    everywhere except the ``@var_*`` ids)."""
    table = np.arange(int(src.max()) + 1, dtype=np.int32)
    table[src] = dst
    return table


def _rename_target(
    values: np.ndarray, target_idx: int, perm_map: np.ndarray | None
) -> np.ndarray:
    """Target variable -> @question; other variables through the remap
    (model/dataset_builder.py:181-195)."""
    is_target = values == target_idx
    if perm_map is not None:
        # the table only covers ids up to max(@var id); larger ids are plain
        # identifiers and must pass through untouched
        in_table = values < len(perm_map)
        remapped = perm_map[np.where(in_table, values, 0)].astype(np.int32)
        values = np.where(in_table, remapped, values)
    return np.where(is_target, np.int32(QUESTION_TOKEN_INDEX), values)


def build_epoch(
    data: CorpusData,
    item_idx: np.ndarray,
    max_contexts: int,
    rng: np.random.Generator,
    shuffle_variable_indexes: bool = False,
) -> EpochArrays:
    """Full epoch for whichever tasks the corpus was loaded with, method
    examples first then variable examples (matching the reference's
    concatenation order, model/dataset_builder.py:122-204)."""
    parts: list[EpochArrays] = []
    if data.infer_method:
        parts.append(build_method_epoch(data, item_idx, max_contexts, rng))
    if data.infer_variable:
        parts.append(
            build_variable_epoch(
                data, item_idx, max_contexts, rng, shuffle_variable_indexes
            )
        )
    if len(parts) == 1:
        return parts[0]
    return EpochArrays(
        ids=np.concatenate([p.ids for p in parts]),
        starts=np.concatenate([p.starts for p in parts]),
        paths=np.concatenate([p.paths for p in parts]),
        ends=np.concatenate([p.ends for p in parts]),
        labels=np.concatenate([p.labels for p in parts]),
    )


def iter_batches(
    epoch: EpochArrays,
    batch_size: int,
    rng: np.random.Generator | None = None,
    pad_final: bool = True,
) -> Iterator[dict[str, np.ndarray]]:
    """Yield static-shape batches.

    Every batch has exactly ``batch_size`` rows; the final partial batch is
    padded with repeated row 0 and masked via ``example_mask`` so jitted
    steps never see a new shape (XLA recompiles per shape — SURVEY.md §7
    "static shapes" hard part). With ``pad_final=False`` the remainder is
    dropped (training-style).
    """
    n = len(epoch)
    order = rng.permutation(n) if rng is not None else None
    stop = n if pad_final else (n - n % batch_size)
    for lo in range(0, stop, batch_size):
        hi = min(lo + batch_size, n)
        valid = hi - lo
        if order is None and valid == batch_size:
            # sequential full batches (the eval path): contiguous slices are
            # numpy VIEWS — skips the per-batch gather copy, which dominates
            # eval's host-build time. Consumers never mutate batches.
            def take(a, lo=lo, hi=hi):
                return a[lo:hi]
        else:
            idx = order[lo:hi] if order is not None else np.arange(lo, hi)
            if valid < batch_size:
                idx = np.concatenate(
                    [idx, np.zeros(batch_size - valid, idx.dtype)]
                )

            def take(a, idx=idx):
                return a[idx]
        mask = np.zeros(batch_size, np.float32)
        mask[:valid] = 1.0
        yield {
            "ids": take(epoch.ids),
            "starts": take(epoch.starts),
            "paths": take(epoch.paths),
            "ends": take(epoch.ends),
            "labels": take(epoch.labels),
            "example_mask": mask,
        }


# ---------------------------------------------------------------------------
# Length-aware bucketed batching
#
# Bag lengths are heavy-tailed (data/synth.py models them as lognormal), so
# padding every example to one fixed ``max_contexts`` makes PAD slots the
# majority of the embedding gathers, attention FLOPs, and HBM traffic per
# step on a skewed corpus. The bucketizer partitions examples by REAL
# context count into a small static ladder of bag widths (geometric,
# capped at ``max_contexts``) and emits ``[B, L_b]`` batches per bucket:
# jit caches per shape, so a run compiles exactly ``len(ladder)`` step
# variants and then reuses them forever. Because PAD positions carry zero
# attention weight (ops.attention masks them to -inf), an example's
# forward pass is identical at any bag width >= its real count — the
# per-example loss multiset over an epoch is invariant to bucketing
# (tests/test_bucketing.py enforces this).
# ---------------------------------------------------------------------------


def derive_bucket_ladder(
    counts: np.ndarray,
    max_contexts: int,
    max_buckets: int = 4,
    min_fraction: float = 0.05,
    min_width: int = 8,
) -> tuple[int, ...]:
    """A geometric ladder of bag widths capped at ``max_contexts``, pruned
    by the corpus length histogram.

    Candidate widths halve down from ``max_contexts`` (e.g. 200 -> {25, 50,
    100, 200}); a narrow width is kept only if at least ``min_fraction`` of
    the examples would land in its bucket — sparse buckets just add a
    compile without saving meaningful padding. The top width is always
    ``max_contexts`` so long bags are never truncated relative to the
    fixed-width path.
    """
    if max_buckets < 1:
        raise ValueError(f"max_buckets must be >= 1, got {max_buckets}")
    widths: list[int] = []
    w = int(max_contexts)
    while len(widths) < max_buckets and w >= min_width:
        widths.append(w)
        nxt = -(-w // 2)
        if nxt == w:
            break
        w = nxt
    if not widths:
        # a bag narrower than min_width: the contract ("the top width is
        # always max_contexts") still holds — one rung, never an empty
        # ladder (which would crash every nearest_bucket_width consumer)
        widths = [int(max_contexts)]
    widths = sorted(set(widths))
    counts = np.minimum(np.asarray(counts), max_contexts)
    if len(counts) and len(widths) > 1:
        kept: list[int] = []
        prev = 0
        for width in widths[:-1]:
            frac = ((counts > prev) & (counts <= width)).mean()
            if frac >= min_fraction:
                kept.append(width)
                prev = width
        kept.append(widths[-1])
        widths = kept
    return tuple(widths)


def parse_bucket_ladder(spec: str, max_contexts: int) -> tuple[int, ...] | None:
    """Parse a ``--bucket_ladder`` comma list (e.g. ``"25,50,100,200"``);
    None for an empty spec (= derive from the corpus). The top width must
    equal ``max_contexts``: a ladder topping below it would silently
    truncate long bags relative to the fixed-width path."""
    if spec is None or not spec.strip():
        return None
    try:
        widths = sorted({int(tok) for tok in spec.split(",") if tok.strip()})
    except ValueError as exc:
        raise ValueError(f"malformed bucket ladder {spec!r}: {exc}") from None
    if not widths or widths[0] < 1:
        raise ValueError(f"bucket ladder widths must be >= 1, got {spec!r}")
    if widths[-1] != max_contexts:
        raise ValueError(
            f"bucket ladder must end at max_contexts ({max_contexts}) so "
            f"long bags are not truncated; got top width {widths[-1]}"
        )
    return tuple(widths)


def nearest_bucket_width(count: int, ladder: tuple[int, ...]) -> int:
    """The smallest ladder width holding ``count`` real contexts (the top
    width for anything longer). THE padding rule shared by every consumer
    of a ladder — the bucketed trainer, ``predict.Predictor``'s single
    forwards, and the serving micro-batcher — so all of them land on the
    same static shapes and reuse the same compiled executables."""
    if not ladder:
        raise ValueError("bucket ladder must not be empty")
    for width in ladder:
        if count <= width:
            return int(width)
    return int(ladder[-1])


def assign_buckets(counts: np.ndarray, ladder: tuple[int, ...]) -> np.ndarray:
    """Bucket index per example: the smallest ladder width holding its
    (capped) real context count."""
    arr = np.asarray(ladder)
    return np.searchsorted(arr, np.minimum(counts, arr[-1]), side="left")


def epoch_context_counts(epoch: EpochArrays) -> np.ndarray:
    """Real (non-PAD) contexts per example. Epoch rows fill contiguously
    from position 0 and PAD paths are index 0, so this is exact."""
    return (epoch.paths != PAD_INDEX).sum(axis=1)


def pad_stats(
    counts: np.ndarray,
    ladder: tuple[int, ...],
    batch_size: int,
    pad_final: bool = True,
) -> tuple[int, int]:
    """(real context slots, padded slots) for one epoch of bucketed batches
    — the ``pad_efficiency`` accounting. A single-width ladder gives the
    fixed-``L`` numbers."""
    counts = np.minimum(np.asarray(counts), ladder[-1])
    bucket_of = assign_buckets(counts, ladder)
    real = int(counts.sum())
    slots = 0
    for b, width in enumerate(ladder):
        n_b = int((bucket_of == b).sum())
        n_batches = -(-n_b // batch_size) if pad_final else n_b // batch_size
        slots += n_batches * batch_size * width
    return real, slots


def iter_bucketed_batches(
    epoch: EpochArrays,
    ladder: tuple[int, ...],
    batch_size: int,
    rng: np.random.Generator | None = None,
    pad_final: bool = True,
) -> Iterator[dict[str, np.ndarray]]:
    """Yield static-shape ``[B, L_b]`` batches, one width per bucket.

    Same contract as :func:`iter_batches` — every batch has exactly
    ``batch_size`` rows, the final partial batch OF EACH BUCKET is padded
    with a repeated row and masked via ``example_mask`` — except the bag
    width varies over the (static) ladder. Examples keep their full
    subsampled context rows (bucket width >= real count by construction),
    so the forward math per example matches the fixed-width path exactly.

    ``rng`` drives both the within-bucket shuffle and the deterministic
    bucket interleave (a seeded permutation of the batch schedule);
    ``rng=None`` (eval) emits buckets sequentially in ladder order.
    """
    bucket_of = assign_buckets(epoch_context_counts(epoch), ladder)
    plans: list[tuple[int, np.ndarray]] = []
    for b, width in enumerate(ladder):
        members = np.flatnonzero(bucket_of == b)
        if rng is not None:
            members = members[rng.permutation(len(members))]
        stop = (
            len(members)
            if pad_final
            else len(members) - len(members) % batch_size
        )
        for lo in range(0, stop, batch_size):
            plans.append((width, members[lo : lo + batch_size]))
    if rng is not None:
        plans = [plans[i] for i in rng.permutation(len(plans))]
    for width, idx in plans:
        valid = len(idx)
        if valid < batch_size:
            idx = np.concatenate(
                [idx, np.full(batch_size - valid, idx[0], idx.dtype)]
            )
        mask = np.zeros(batch_size, np.float32)
        mask[:valid] = 1.0
        yield {
            "ids": epoch.ids[idx],
            "starts": epoch.starts[idx, :width],
            "paths": epoch.paths[idx, :width],
            "ends": epoch.ends[idx, :width],
            "labels": epoch.labels[idx],
            "example_mask": mask,
        }


def iter_streaming_batches(
    epoch_builder,
    item_idx: np.ndarray,
    batch_size: int,
    rng: np.random.Generator,
    chunk_items: int = 65536,
    pad_final: bool = True,
    shuffle: bool = True,
) -> Iterator[dict[str, np.ndarray]]:
    """Stream an epoch as static-shape batches without materializing [N, L].

    ``build_epoch`` allocates 3 x [N, L] int32 — ~38 GB host RAM at
    java-large scale (16M methods x bag 200, BASELINE.json config 3). This
    generator shuffles the *item order* globally, then materializes only
    ``chunk_items`` rows at a time (3 x chunk x L int32, ~157 MB at the
    default chunk and bag 200), carrying sub-batch remainders across chunk
    boundaries so emitted batches are identical in shape/semantics to
    ``iter_batches`` over a full epoch.

    ``epoch_builder(chunk_idx)`` -> :class:`EpochArrays` for those items —
    pass a closure over :func:`build_epoch` (the per-method context
    subsample is independent per item, so chunked construction draws the
    same distribution as a whole-epoch build). Variable-task expansion may
    return more examples than items; the carry buffer absorbs that.
    """
    order = rng.permutation(len(item_idx)) if shuffle else np.arange(len(item_idx))
    carry: EpochArrays | None = None

    def emit(epoch: EpochArrays, final: bool):
        # batch assembly delegates to iter_batches so the layout/padding
        # semantics exist in exactly one place
        n_full = len(epoch) // batch_size * batch_size
        yield from iter_batches(
            _slice_epoch(epoch, 0, n_full), batch_size, rng=None,
            pad_final=False,
        )
        rest = _slice_epoch(epoch, n_full, len(epoch))
        if final and len(rest) and pad_final:
            yield from iter_batches(rest, batch_size, rng=None, pad_final=True)
            rest = None
        return rest

    for lo in range(0, len(order), chunk_items):
        chunk_idx = item_idx[order[lo : lo + chunk_items]]
        with get_tracer().span(
            "stream_chunk", category="data", items=len(chunk_idx)
        ):
            chunk = epoch_builder(chunk_idx)
        if carry is not None and len(carry):
            chunk = _concat_epochs([carry, chunk])
        final = lo + chunk_items >= len(order)
        # ``yield from`` hands back emit()'s return value: the sub-batch
        # remainder carried into the next chunk (None once padded/emitted)
        carry = yield from emit(chunk, final)


def _slice_epoch(epoch: EpochArrays, lo: int, hi: int) -> EpochArrays:
    return EpochArrays(
        ids=epoch.ids[lo:hi],
        starts=epoch.starts[lo:hi],
        paths=epoch.paths[lo:hi],
        ends=epoch.ends[lo:hi],
        labels=epoch.labels[lo:hi],
    )


def _concat_epochs(parts: list[EpochArrays]) -> EpochArrays:
    return EpochArrays(
        ids=np.concatenate([p.ids for p in parts]),
        starts=np.concatenate([p.starts for p in parts]),
        paths=np.concatenate([p.paths for p in parts]),
        ends=np.concatenate([p.ends for p in parts]),
        labels=np.concatenate([p.labels for p in parts]),
    )


def skip_batches(
    batches: Iterator[dict[str, np.ndarray]],
    n: int,
    expect_widths: dict[int, int] | None = None,
) -> Iterator[dict[str, np.ndarray]]:
    """Consume the first ``n`` batches of an epoch stream — the mid-epoch
    resume replay (train/loop.py).

    Every epoch iterator here is a pure function of the epoch arrays and
    the RNG state it was created under, so re-creating it from the
    checkpointed cursor and discarding the first ``n`` batches puts the
    stream *bitwise* where the interrupted run left it — including the
    bucketed path, whose whole batch plan (bucket membership, interleave)
    is drawn up front from the same RNG. Skipping costs host batch builds
    only; no device work is dispatched for skipped batches.

    ``expect_widths``: the cursor's recorded per-bucket positions; a
    mismatch means the run's ladder/batching config changed since the save
    and the cursor cannot be honored, so fail with guidance instead of
    silently training on the wrong examples.
    """
    it = iter(batches)
    seen: dict[int, int] = {}
    for i in range(n):
        try:
            batch = next(it)
        except StopIteration:
            raise ValueError(
                f"mid-epoch cursor points past the epoch: batch {i} of "
                f"{n} does not exist — the corpus or batching config "
                "changed since the checkpoint was saved; restart without "
                "--resume (or restore the original config)"
            ) from None
        width = int(batch["paths"].shape[1])
        seen[width] = seen.get(width, 0) + 1
    if expect_widths is not None and seen != {
        int(w): c for w, c in expect_widths.items()
    }:
        raise ValueError(
            f"mid-epoch cursor bucket positions {expect_widths} do not "
            f"match the replayed stream {seen}; the bucket ladder or batch "
            "size changed since the checkpoint was saved — resume with the "
            "original settings or restart without --resume"
        )
    return it


def empty_batch(batch_size: int, max_contexts: int) -> dict[str, np.ndarray]:
    """A fully-masked all-PAD batch (the no-op collective step)."""
    bag = (batch_size, max_contexts)
    return {
        "ids": np.zeros(batch_size, np.int64),
        "starts": np.full(bag, PAD_INDEX, np.int32),
        "paths": np.full(bag, PAD_INDEX, np.int32),
        "ends": np.full(bag, PAD_INDEX, np.int32),
        "labels": np.zeros(batch_size, np.int32),
        "example_mask": np.zeros(batch_size, np.float32),
    }


def pad_batch_stream(
    batches: Iterator[dict[str, np.ndarray]],
    n_steps: int,
    template: dict[str, np.ndarray],
) -> Iterator[dict[str, np.ndarray]]:
    """Yield exactly ``n_steps`` batches, extending with fully-masked
    ``template`` batches (:func:`empty_batch`). Multi-host feeding: every
    host must dispatch the same number of collective steps even when its
    local shard runs out of rows first — including the degenerate case of a
    host with zero local rows, which yields only templates."""
    count = 0
    for batch in batches:
        count += 1
        yield batch
    while count < n_steps:
        count += 1
        yield template


def oov_rate(
    data: CorpusData,
    train_idx: np.ndarray,
    test_idx: np.ndarray,
    exact: bool = False,
) -> float:
    """Fraction of test label (sub)tokens absent from the train label token
    set (reference: model/dataset_builder.py:72-110). ``exact=True`` uses
    whole labels (the ``eval_method == 'exact'`` branch)."""

    def tokens_of(i: int, out: list[str]) -> None:
        if data.infer_method:
            out.extend(_label_tokens(data, data.normalized_labels[i], exact))
        if data.infer_variable:
            for alias, normalized in data.aliases[i].items():
                if alias.startswith("@var_"):
                    out.extend(_label_tokens(data, normalized, exact))

    train_vocab: set[str] = set()
    buf: list[str] = []
    for i in train_idx:
        tokens_of(int(i), buf)
    train_vocab.update(buf)

    match = count = 0
    for i in test_idx:
        buf = []
        tokens_of(int(i), buf)
        match += sum(1 for t in buf if t in train_vocab)
        count += len(buf)
    return 1.0 - match / count if count else 0.0


def _label_tokens(data: CorpusData, normalized_label: str, exact: bool) -> list[str]:
    if exact:
        return [normalized_label]
    index = data.label_vocab.stoi[normalized_label]
    return list(data.label_vocab.itosubtokens.get(index, ()))
