"""TPU-shaped input pipeline: split, per-epoch resampling, batching.

The reference rebuilds all tensors in a Python loop per method per epoch
(model/dataset_builder.py:112-210) — its host-side hot loop (SURVEY.md §3.1).
Here the same semantics run as O(total log total) vectorized numpy over the
CSR arrays:

- seeded train/test split (fixing the reference's unseeded global-random
  split, model/dataset_builder.py:19-26 / SURVEY.md §2.6);
- per-epoch *random subsample* of up to ``max_contexts`` path-contexts per
  method — the reference's load-bearing data augmentation
  (model/dataset_builder.py:134-135);
- ``@method_0 -> @question`` substitution so the answer isn't leaked
  (model/dataset_builder.py:122-144);
- the variable-name task expansion with optional index permutation
  (model/dataset_builder.py:152-204);
- static-shape ``[B, L]`` batches (PAD=0) with an example mask so the last
  partial batch never changes compiled shapes.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator

import numpy as np

from code2vec_tpu import PAD_INDEX, QUESTION_TOKEN_INDEX
from code2vec_tpu.data.reader import CorpusData
from code2vec_tpu.obs.trace import get_tracer


@dataclass
class EpochArrays:
    """One epoch's worth of examples, padded to static shape [N, L]."""

    ids: np.ndarray  # int64 [N]
    starts: np.ndarray  # int32 [N, L]
    paths: np.ndarray  # int32 [N, L]
    ends: np.ndarray  # int32 [N, L]
    labels: np.ndarray  # int32 [N]

    def __len__(self) -> int:
        return len(self.labels)


def split_items(
    n_items: int, rng: np.random.Generator, split_ratio: float = 0.2
) -> tuple[np.ndarray, np.ndarray]:
    """Seeded shuffle-then-slice split: first ``ratio`` fraction is test,
    rest is train (same slicing as model/dataset_builder.py:23-26, but
    reproducible — the reference leaves Python's global RNG unseeded)."""
    perm = rng.permutation(n_items)
    test_count = int(n_items * split_ratio)
    return perm[test_count:], perm[:test_count]


def flat_context_indices(
    row_splits: np.ndarray,
    item_idx: np.ndarray,
    row_base: np.ndarray | None = None,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Vectorized CSR row gather: for the selected items, the flat indices
    of all their contexts plus each context's (segment, position-in-segment).

    Returns ``(flat, seg, within)``, each of length ``counts.sum()``. Shared
    by the host epoch builder and device staging (train/device_epoch.py).

    ``row_base`` (sharded mmap corpora — data/reader.py:CorpusData.row_base)
    overrides each item's base offset into the flat arrays when they are a
    superset of the local rows; default is the contiguous ``row_splits``
    layout.
    """
    counts = (row_splits[item_idx + 1] - row_splits[item_idx]).astype(np.int64)
    total = int(counts.sum())
    if total == 0:
        empty = np.zeros(0, np.int64)
        return empty, empty, empty
    seg = np.repeat(np.arange(len(item_idx), dtype=np.int64), counts)
    seg_starts = np.concatenate(([0], np.cumsum(counts)[:-1]))
    within = np.arange(total, dtype=np.int64) - np.repeat(seg_starts, counts)
    base = row_splits[item_idx] if row_base is None else row_base[item_idx]
    flat = np.repeat(base, counts) + within
    return flat, seg, within


def _segment_subsample(
    row_splits: np.ndarray,
    item_idx: np.ndarray,
    max_contexts: int,
    rng: np.random.Generator,
    row_base: np.ndarray | None = None,
    context_order: str = "shuffled",
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Pick up to ``max_contexts`` random contexts per selected item.

    Returns ``(flat_idx, out_row, out_col)``: indices into the flat CSR
    arrays plus the destination (row, col) in the padded [N, L] output.

    Vectorized equivalent of "shuffle each method's context list, keep the
    first L" (model/dataset_builder.py:134-135): draw one uniform per
    context, stably sort by (segment, uniform), keep the first L positions
    of each segment.

    ``context_order="corpus"`` re-sorts the KEPT contexts back to corpus
    order (the rng draws — and hence the kept SUBSET and the stream's
    consumption of the generator — are identical to the default "shuffled"
    mode; only the within-row placement changes). The attention pool is
    order-invariant mathematically but not bitwise, so canonical order is
    what makes per-example losses exactly comparable ACROSS feed paths that
    build rows at different stream positions (the {fixed-L, bucketed,
    streaming, mmap} parity matrix in tests/test_ooc.py).
    """
    flat, seg, within = flat_context_indices(row_splits, item_idx, row_base)
    total = len(flat)
    if total == 0:
        return flat, seg, within

    order = np.lexsort((rng.random(total), seg))
    # after the stable per-segment sort the segment layout is unchanged,
    # so position-in-segment is the same ``within`` sequence
    keep = within < max_contexts
    kept_order = order[keep]
    kept_flat, kept_seg = flat[kept_order], seg[keep]
    if context_order == "corpus":
        kept_flat = kept_flat[np.lexsort((kept_flat, kept_seg))]
    elif context_order != "shuffled":
        raise ValueError(f"unknown context_order: {context_order!r}")
    return kept_flat, kept_seg, within[keep]


def build_method_epoch(
    data: CorpusData,
    item_idx: np.ndarray,
    max_contexts: int,
    rng: np.random.Generator,
    context_order: str = "shuffled",
) -> EpochArrays:
    """Method-name task epoch: fresh context subsample per method, with the
    method's own ``@method_0`` token replaced by ``@question``
    (model/dataset_builder.py:122-150)."""
    n = len(item_idx)
    with get_tracer().span("build_method_epoch", category="data", items=n):
        return _build_method_epoch(data, item_idx, max_contexts, rng, context_order)


def _build_method_epoch(
    data: CorpusData,
    item_idx: np.ndarray,
    max_contexts: int,
    rng: np.random.Generator,
    context_order: str = "shuffled",
) -> EpochArrays:
    n = len(item_idx)
    flat, row, col = _segment_subsample(
        data.row_splits, item_idx, max_contexts, rng,
        row_base=data.row_base, context_order=context_order,
    )

    starts = np.full((n, max_contexts), PAD_INDEX, np.int32)
    paths = np.full((n, max_contexts), PAD_INDEX, np.int32)
    ends = np.full((n, max_contexts), PAD_INDEX, np.int32)
    starts[row, col] = data.starts[flat]
    paths[row, col] = data.paths[flat]
    ends[row, col] = data.ends[flat]

    method_idx = data.method_token_index
    if method_idx is not None:
        np.putmask(starts, starts == method_idx, QUESTION_TOKEN_INDEX)
        np.putmask(ends, ends == method_idx, QUESTION_TOKEN_INDEX)

    return EpochArrays(
        ids=data.ids[item_idx],
        starts=starts,
        paths=paths,
        ends=ends,
        labels=data.labels[item_idx],
    )


def variable_items(data: CorpusData, item_idx: np.ndarray):
    """The variable-task expansion core, shared by the host epoch builder
    and device staging (train/device_epoch.py): per item, the ``@var_*``
    aliases and the contexts touching ANY of them
    (model/dataset_builder.py:152-177). Yields
    ``(item, alias_names, alias_idx, starts, paths, ends)``; the caller
    applies its own shuffling/selection/renaming so rng draw order stays
    exactly the reference's."""
    terminal_stoi = data.terminal_vocab.stoi
    for i in item_idx:
        alias_map = data.aliases[i]
        alias_names = [a for a in alias_map if a.startswith("@var_")]
        if not alias_names:
            continue
        alias_idx = np.asarray(
            [terminal_stoi[a] for a in alias_names], dtype=np.int32
        )
        lo = int(
            data.row_splits[i] if data.row_base is None else data.row_base[i]
        )
        hi = lo + int(data.row_splits[i + 1] - data.row_splits[i])
        s, p, e = data.starts[lo:hi], data.paths[lo:hi], data.ends[lo:hi]
        touches = np.isin(s, alias_idx) | np.isin(e, alias_idx)
        yield i, alias_names, alias_idx, s[touches], p[touches], e[touches]


def build_variable_epoch(
    data: CorpusData,
    item_idx: np.ndarray,
    max_contexts: int,
    rng: np.random.Generator,
    shuffle_variable_indexes: bool = False,
    context_order: str = "shuffled",
) -> EpochArrays:
    """Variable-name task epoch (context2name-style extension).

    One example per ``@var_*`` alias of each method: keep only contexts
    touching *any* variable of interest, shuffle them once per method, then
    per target variable keep its contexts, rename the target to
    ``@question`` and optionally remap the other variable ids through a
    shuffled permutation of the whole ``@var_*`` id set so the model can't
    memorize id order (model/dataset_builder.py:152-204).

    Examples-per-method varies, so this stays a per-method loop with
    vectorized inner ops; corpora are method-bounded so this is not the
    per-context hot path.
    """
    with get_tracer().span(
        "build_variable_epoch", category="data", items=len(item_idx)
    ):
        return _build_variable_epoch(
            data, item_idx, max_contexts, rng, shuffle_variable_indexes,
            context_order,
        )


def _build_variable_epoch(
    data: CorpusData,
    item_idx: np.ndarray,
    max_contexts: int,
    rng: np.random.Generator,
    shuffle_variable_indexes: bool = False,
    context_order: str = "shuffled",
) -> EpochArrays:
    # RNG-consumption compatibility: every draw below (the per-item
    # perm_map shuffle, the per-item context permutation) happens in
    # exactly the calls, order, and sizes the historical per-alias loop
    # made — the vectorization only replaces the ALIAS-dimension Python
    # loop (per-alias boolean scans + per-row copy-in) with the same
    # repeat/cumsum/scatter formulation the method task uses — so epochs
    # (and hence loss histories and resume cursors) are bitwise unchanged.
    variable_indexes = data.variable_indexes
    perm_map = None
    if not shuffle_variable_indexes and len(variable_indexes):
        # identity remap outside shuffle mode (reference builds the same
        # dict once, model/dataset_builder.py:155-156)
        perm_map = _index_remap(variable_indexes, variable_indexes)

    ids: list[int] = []
    labels: list[int] = []
    # kept (row, col, value) triples across ALL items/aliases; three
    # scatters at the end instead of a Python loop per output row
    out_rows: list[np.ndarray] = []
    out_cols: list[np.ndarray] = []
    out_s: list[np.ndarray] = []
    out_p: list[np.ndarray] = []
    out_e: list[np.ndarray] = []

    label_stoi = data.label_vocab.stoi

    for i, alias_names, alias_idx, s, p, e in variable_items(data, item_idx):
        alias_map = data.aliases[i]
        if shuffle_variable_indexes:
            shuffled = variable_indexes.copy()
            rng.shuffle(shuffled)
            perm_map = _index_remap(variable_indexes, shuffled)

        # the permutation is drawn in BOTH order modes so the rng stream's
        # consumption (and every later draw) is identical; canonical mode
        # just declines to apply it (see _segment_subsample)
        order = rng.permutation(len(s))
        if context_order == "shuffled":
            s, p, e = s[order], p[order], e[order]

        base = len(ids)
        for alias_name in alias_names:
            ids.append(int(data.ids[i]))
            labels.append(label_stoi[alias_map[alias_name]])

        # one [A, C] membership pass over the whole alias set: nonzero()
        # is row-major, so pair order is (alias, context-stream order) —
        # identical to the old per-alias `(s == v) | (e == v)` scans
        member = (s[None, :] == alias_idx[:, None]) | (
            e[None, :] == alias_idx[:, None]
        )
        a_ids, c_ids = np.nonzero(member)
        total = len(a_ids)
        if not total:
            continue
        counts = member.sum(axis=1).astype(np.int64)
        seg_starts = np.concatenate(([0], np.cumsum(counts)[:-1]))
        within = np.arange(total, dtype=np.int64) - np.repeat(
            seg_starts, counts
        )
        keep = within < max_contexts  # first-L per alias, as `[:max_contexts]` did
        a_kept, c_kept = a_ids[keep], c_ids[keep]
        targets = alias_idx[a_kept]  # per-element rename target
        out_rows.append(base + a_kept)
        out_cols.append(within[keep])
        out_s.append(_rename_target(s[c_kept], targets, perm_map))
        out_p.append(p[c_kept])
        out_e.append(_rename_target(e[c_kept], targets, perm_map))

    n = len(ids)
    starts = np.full((n, max_contexts), PAD_INDEX, np.int32)
    paths = np.full((n, max_contexts), PAD_INDEX, np.int32)
    ends = np.full((n, max_contexts), PAD_INDEX, np.int32)
    if out_rows:
        rows = np.concatenate(out_rows)
        cols = np.concatenate(out_cols)
        starts[rows, cols] = np.concatenate(out_s)
        paths[rows, cols] = np.concatenate(out_p)
        ends[rows, cols] = np.concatenate(out_e)

    return EpochArrays(
        ids=np.asarray(ids, np.int64),
        starts=starts,
        paths=paths,
        ends=ends,
        labels=np.asarray(labels, np.int32),
    )


def _index_remap(src: np.ndarray, dst: np.ndarray) -> np.ndarray:
    """Dense lookup table mapping terminal id -> remapped id (identity
    everywhere except the ``@var_*`` ids)."""
    table = np.arange(int(src.max()) + 1, dtype=np.int32)
    table[src] = dst
    return table


def _rename_target(
    values: np.ndarray, target_idx: int, perm_map: np.ndarray | None
) -> np.ndarray:
    """Target variable -> @question; other variables through the remap
    (model/dataset_builder.py:181-195)."""
    is_target = values == target_idx
    if perm_map is not None:
        # the table only covers ids up to max(@var id); larger ids are plain
        # identifiers and must pass through untouched
        in_table = values < len(perm_map)
        remapped = perm_map[np.where(in_table, values, 0)].astype(np.int32)
        values = np.where(in_table, remapped, values)
    return np.where(is_target, np.int32(QUESTION_TOKEN_INDEX), values)


def build_epoch(
    data: CorpusData,
    item_idx: np.ndarray,
    max_contexts: int,
    rng: np.random.Generator,
    shuffle_variable_indexes: bool = False,
    context_order: str = "shuffled",
) -> EpochArrays:
    """Full epoch for whichever tasks the corpus was loaded with, method
    examples first then variable examples (matching the reference's
    concatenation order, model/dataset_builder.py:122-204)."""
    parts: list[EpochArrays] = []
    if data.infer_method:
        parts.append(
            build_method_epoch(data, item_idx, max_contexts, rng, context_order)
        )
    if data.infer_variable:
        parts.append(
            build_variable_epoch(
                data, item_idx, max_contexts, rng, shuffle_variable_indexes,
                context_order,
            )
        )
    if len(parts) == 1:
        return parts[0]
    return EpochArrays(
        ids=np.concatenate([p.ids for p in parts]),
        starts=np.concatenate([p.starts for p in parts]),
        paths=np.concatenate([p.paths for p in parts]),
        ends=np.concatenate([p.ends for p in parts]),
        labels=np.concatenate([p.labels for p in parts]),
    )


def iter_batches(
    epoch: EpochArrays,
    batch_size: int,
    rng: np.random.Generator | None = None,
    pad_final: bool = True,
) -> Iterator[dict[str, np.ndarray]]:
    """Yield static-shape batches.

    Every batch has exactly ``batch_size`` rows; the final partial batch is
    padded with repeated row 0 and masked via ``example_mask`` so jitted
    steps never see a new shape (XLA recompiles per shape — SURVEY.md §7
    "static shapes" hard part). With ``pad_final=False`` the remainder is
    dropped (training-style).
    """
    n = len(epoch)
    order = rng.permutation(n) if rng is not None else None
    stop = n if pad_final else (n - n % batch_size)
    for lo in range(0, stop, batch_size):
        hi = min(lo + batch_size, n)
        valid = hi - lo
        if order is None and valid == batch_size:
            # sequential full batches (the eval path): contiguous slices are
            # numpy VIEWS — skips the per-batch gather copy, which dominates
            # eval's host-build time. Consumers never mutate batches.
            def take(a, lo=lo, hi=hi):
                return a[lo:hi]
        else:
            idx = order[lo:hi] if order is not None else np.arange(lo, hi)
            if valid < batch_size:
                idx = np.concatenate(
                    [idx, np.zeros(batch_size - valid, idx.dtype)]
                )

            def take(a, idx=idx):
                return a[idx]
        mask = np.zeros(batch_size, np.float32)
        mask[:valid] = 1.0
        yield {
            "ids": take(epoch.ids),
            "starts": take(epoch.starts),
            "paths": take(epoch.paths),
            "ends": take(epoch.ends),
            "labels": take(epoch.labels),
            "example_mask": mask,
        }


# ---------------------------------------------------------------------------
# Length-aware bucketed batching
#
# Bag lengths are heavy-tailed (data/synth.py models them as lognormal), so
# padding every example to one fixed ``max_contexts`` makes PAD slots the
# majority of the embedding gathers, attention FLOPs, and HBM traffic per
# step on a skewed corpus. The bucketizer partitions examples by REAL
# context count into a small static ladder of bag widths (geometric,
# capped at ``max_contexts``) and emits ``[B, L_b]`` batches per bucket:
# jit caches per shape, so a run compiles exactly ``len(ladder)`` step
# variants and then reuses them forever. Because PAD positions carry zero
# attention weight (ops.attention masks them to -inf), an example's
# forward pass is identical at any bag width >= its real count — the
# per-example loss multiset over an epoch is invariant to bucketing
# (tests/test_bucketing.py enforces this).
# ---------------------------------------------------------------------------


def derive_bucket_ladder_hist(
    lengths: np.ndarray,
    weights: np.ndarray,
    max_contexts: int,
    max_buckets: int = 4,
    min_fraction: float = 0.05,
    min_width: int = 8,
) -> tuple[int, ...]:
    """:func:`derive_bucket_ladder` from a context-count HISTOGRAM —
    ``weights[i]`` examples have ``lengths[i]`` real contexts.

    THE shared ladder-derivation entry point for every consumer that has a
    histogram rather than per-example counts: the CSR container's
    ``row_splits``-histogram footer (formats/corpus_io.py — the ladder
    without a context scan), ``tools/corpus_stats.py``, and the serving
    layer's live request-width warmup fallback (serve/engine.py).
    Equivalent to expanding the histogram and calling
    :func:`derive_bucket_ladder`, at O(distinct lengths) instead of
    O(examples).
    """
    if max_buckets < 1:
        raise ValueError(f"max_buckets must be >= 1, got {max_buckets}")
    widths: list[int] = []
    w = int(max_contexts)
    while len(widths) < max_buckets and w >= min_width:
        widths.append(w)
        nxt = -(-w // 2)
        if nxt == w:
            break
        w = nxt
    if not widths:
        # a bag narrower than min_width: the contract ("the top width is
        # always max_contexts") still holds — one rung, never an empty
        # ladder (which would crash every nearest_bucket_width consumer)
        widths = [int(max_contexts)]
    widths = sorted(set(widths))
    lengths = np.minimum(np.asarray(lengths), max_contexts)
    weights = np.asarray(weights, np.int64)
    total = int(weights.sum())
    if total and len(widths) > 1:
        kept: list[int] = []
        prev = 0
        for width in widths[:-1]:
            frac = (
                weights[(lengths > prev) & (lengths <= width)].sum() / total
            )
            if frac >= min_fraction:
                kept.append(width)
                prev = width
        kept.append(widths[-1])
        widths = kept
    return tuple(widths)


def derive_bucket_ladder(
    counts: np.ndarray,
    max_contexts: int,
    max_buckets: int = 4,
    min_fraction: float = 0.05,
    min_width: int = 8,
) -> tuple[int, ...]:
    """A geometric ladder of bag widths capped at ``max_contexts``, pruned
    by the corpus length histogram.

    Candidate widths halve down from ``max_contexts`` (e.g. 200 -> {25, 50,
    100, 200}); a narrow width is kept only if at least ``min_fraction`` of
    the examples would land in its bucket — sparse buckets just add a
    compile without saving meaningful padding. The top width is always
    ``max_contexts`` so long bags are never truncated relative to the
    fixed-width path. Per-example-counts front end of
    :func:`derive_bucket_ladder_hist`.
    """
    lengths, weights = np.unique(np.asarray(counts), return_counts=True)
    return derive_bucket_ladder_hist(
        lengths, weights, max_contexts,
        max_buckets=max_buckets,
        min_fraction=min_fraction,
        min_width=min_width,
    )


def parse_bucket_ladder(spec: str, max_contexts: int) -> tuple[int, ...] | None:
    """Parse a ``--bucket_ladder`` comma list (e.g. ``"25,50,100,200"``);
    None for an empty spec (= derive from the corpus). The top width must
    equal ``max_contexts``: a ladder topping below it would silently
    truncate long bags relative to the fixed-width path."""
    if spec is None or not spec.strip():
        return None
    try:
        widths = sorted({int(tok) for tok in spec.split(",") if tok.strip()})
    except ValueError as exc:
        raise ValueError(f"malformed bucket ladder {spec!r}: {exc}") from None
    if not widths or widths[0] < 1:
        raise ValueError(f"bucket ladder widths must be >= 1, got {spec!r}")
    if widths[-1] != max_contexts:
        raise ValueError(
            f"bucket ladder must end at max_contexts ({max_contexts}) so "
            f"long bags are not truncated; got top width {widths[-1]}"
        )
    return tuple(widths)


def derive_longbag_ladder(
    lengths: np.ndarray,
    weights: np.ndarray,
    base_top: int,
    chunk_l: int = 128,
    max_rungs: int = 4,
) -> tuple[int, ...]:
    """Longbag rungs ABOVE a base ladder's top width — the ``--max_contexts
    0`` arm (no truncation anywhere).

    Widths double geometrically from ``base_top``, each rounded up to a
    multiple of ``chunk_l`` (the fused kernel's chunked softmax streams the
    bag in ``chunk_l`` tiles, so rung widths that are chunk multiples waste
    no lane padding inside the kernel), until the longest observed bag is
    covered; if ``max_rungs`` doublings fall short, the last rung jumps
    straight to the (chunk-rounded) maximum. Rungs holding no examples are
    pruned, except the top one — the ladder must cover the tail, that is
    the whole point. Returns ``()`` when nothing exceeds ``base_top``.

    ``lengths``/``weights``: the corpus context-count histogram (the CSR
    footer, ``np.unique`` of ``np.diff(row_splits)``, or a request-stream
    histogram — the same inputs as :func:`derive_bucket_ladder_hist`).
    """
    if chunk_l < 1:
        raise ValueError(f"chunk_l must be >= 1, got {chunk_l}")
    lengths = np.asarray(lengths, np.int64)
    weights = np.asarray(weights, np.int64)
    over = lengths > base_top
    if not over.any():
        return ()
    max_len = int(lengths[over].max())

    def round_chunk(w: int) -> int:
        return -(-int(w) // chunk_l) * chunk_l

    rungs: list[int] = []
    w = int(base_top)
    while w < max_len and len(rungs) < max_rungs:
        w = round_chunk(w * 2)  # ceil-to-chunk of 2w: > w, so always advances
        rungs.append(w)
    if rungs and rungs[-1] < max_len:
        rungs[-1] = round_chunk(max_len)
    kept: list[int] = []
    prev = int(base_top)
    for width in rungs:
        occupied = int(weights[(lengths > prev) & (lengths <= width)].sum())
        if occupied or width == rungs[-1]:
            kept.append(width)
            prev = width
    return tuple(kept)


def truncated_fraction(
    lengths: np.ndarray, weights: np.ndarray, cap: int
) -> float:
    """Fraction of REAL contexts a per-example cap of ``cap`` drops — the
    ``truncated_context_fraction`` accounting (obs gauge, epoch metrics,
    ``tools/corpus_stats.py``, ``bench.py --longbag-ab``). Today that loss
    is invisible: ``max_contexts`` subsampling silently discards the tail
    of every long bag. 0.0 means no truncation (the longbag arm's
    acceptance bar)."""
    lengths = np.asarray(lengths, np.int64)
    weights = np.asarray(weights, np.int64)
    total = int((lengths * weights).sum())
    if total == 0:
        return 0.0
    dropped = int((np.maximum(lengths - int(cap), 0) * weights).sum())
    return dropped / total


def truncated_fraction_of_counts(counts: np.ndarray, cap: int) -> float:
    """Per-example-counts front end of :func:`truncated_fraction`."""
    lengths, weights = np.unique(np.asarray(counts), return_counts=True)
    return truncated_fraction(lengths, weights, cap)


def nearest_bucket_width(count: int, ladder: tuple[int, ...]) -> int:
    """The smallest ladder width holding ``count`` real contexts (the top
    width for anything longer). THE padding rule shared by every consumer
    of a ladder — the bucketed trainer, ``predict.Predictor``'s single
    forwards, and the serving micro-batcher — so all of them land on the
    same static shapes and reuse the same compiled executables."""
    if not ladder:
        raise ValueError("bucket ladder must not be empty")
    for width in ladder:
        if count <= width:
            return int(width)
    return int(ladder[-1])


def assign_buckets(counts: np.ndarray, ladder: tuple[int, ...]) -> np.ndarray:
    """Bucket index per example: the smallest ladder width holding its
    (capped) real context count."""
    arr = np.asarray(ladder)
    return np.searchsorted(arr, np.minimum(counts, arr[-1]), side="left")


def epoch_context_counts(epoch: EpochArrays) -> np.ndarray:
    """Real (non-PAD) contexts per example. Epoch rows fill contiguously
    from position 0 and PAD paths are index 0, so this is exact."""
    return (epoch.paths != PAD_INDEX).sum(axis=1)


def pad_stats(
    counts: np.ndarray,
    ladder: tuple[int, ...],
    batch_size: int,
    pad_final: bool = True,
) -> tuple[int, int]:
    """(real context slots, padded slots) for one epoch of bucketed batches
    — the ``pad_efficiency`` accounting. A single-width ladder gives the
    fixed-``L`` numbers."""
    counts = np.minimum(np.asarray(counts), ladder[-1])
    bucket_of = assign_buckets(counts, ladder)
    real = int(counts.sum())
    slots = 0
    for b, width in enumerate(ladder):
        n_b = int((bucket_of == b).sum())
        n_batches = -(-n_b // batch_size) if pad_final else n_b // batch_size
        slots += n_batches * batch_size * width
    return real, slots


def iter_bucketed_batches(
    epoch: EpochArrays,
    ladder: tuple[int, ...],
    batch_size: int,
    rng: np.random.Generator | None = None,
    pad_final: bool = True,
) -> Iterator[dict[str, np.ndarray]]:
    """Yield static-shape ``[B, L_b]`` batches, one width per bucket.

    Same contract as :func:`iter_batches` — every batch has exactly
    ``batch_size`` rows, the final partial batch OF EACH BUCKET is padded
    with a repeated row and masked via ``example_mask`` — except the bag
    width varies over the (static) ladder. Examples keep their full
    subsampled context rows (bucket width >= real count by construction),
    so the forward math per example matches the fixed-width path exactly.

    ``rng`` drives both the within-bucket shuffle and the deterministic
    bucket interleave (a seeded permutation of the batch schedule);
    ``rng=None`` (eval) emits buckets sequentially in ladder order.
    """
    bucket_of = assign_buckets(epoch_context_counts(epoch), ladder)
    plans: list[tuple[int, np.ndarray]] = []
    for b, width in enumerate(ladder):
        members = np.flatnonzero(bucket_of == b)
        if rng is not None:
            members = members[rng.permutation(len(members))]
        stop = (
            len(members)
            if pad_final
            else len(members) - len(members) % batch_size
        )
        for lo in range(0, stop, batch_size):
            plans.append((width, members[lo : lo + batch_size]))
    if rng is not None:
        plans = [plans[i] for i in rng.permutation(len(plans))]
    for width, idx in plans:
        yield _bucket_batch(epoch, idx, width, batch_size)


def _bucket_batch(
    epoch: EpochArrays, idx: np.ndarray, width: int, batch_size: int
) -> dict[str, np.ndarray]:
    """Materialize one ``[B, width]`` batch from epoch rows ``idx`` — THE
    bucketed batch layout (row-0-repeat padding + example mask), shared by
    every bucketed iterator so the semantics exist in one place."""
    valid = len(idx)
    if valid < batch_size:
        idx = np.concatenate(
            [idx, np.full(batch_size - valid, idx[0], idx.dtype)]
        )
    mask = np.zeros(batch_size, np.float32)
    mask[:valid] = 1.0
    return {
        "ids": epoch.ids[idx],
        "starts": epoch.starts[idx, :width],
        "paths": epoch.paths[idx, :width],
        "ends": epoch.ends[idx, :width],
        "labels": epoch.labels[idx],
        "example_mask": mask,
    }


def bucket_batch_counts(
    counts: np.ndarray, ladder: tuple[int, ...], batch_size: int
) -> np.ndarray:
    """Per-ladder-width batch counts (ceil division) for examples with
    ``counts`` real contexts — the static epoch geometry behind the
    host-sharded bucketed width SCHEDULE (train/loop.py): every feed group
    derives its local counts, the global max per width is agreed once, and
    short groups pad with masked batches so collective shapes stay in
    lockstep."""
    arr = np.asarray(ladder)
    if not len(counts):
        return np.zeros(len(arr), np.int64)
    members = np.bincount(
        assign_buckets(np.asarray(counts), ladder), minlength=len(arr)
    )
    return -(-members // batch_size)


def iter_scheduled_bucketed_batches(
    epoch: EpochArrays,
    ladder: tuple[int, ...],
    batch_size: int,
    schedule: np.ndarray,
    rng: np.random.Generator | None = None,
) -> Iterator[dict[str, np.ndarray]]:
    """Bucketed batches following an externally-agreed width ``schedule``
    (one width per step) instead of a locally-drawn interleave — the
    host-sharded composition: every feed group walks the SAME schedule, so
    all hosts dispatch identical collective shapes in lockstep even though
    their local bucket membership differs. When this group's rows for a
    width run out before the schedule does, the remaining steps of that
    width emit fully-masked empty batches (the multi-host no-op step,
    :func:`empty_batch`).

    ``rng`` shuffles members within each bucket (None = sequential); the
    schedule itself must already carry whatever interleave the caller
    wants, drawn from an rng every host shares.
    """
    bucket_of = assign_buckets(epoch_context_counts(epoch), ladder)
    queues: dict[int, np.ndarray] = {}
    heads: dict[int, int] = {}
    for b, width in enumerate(ladder):
        members = np.flatnonzero(bucket_of == b)
        if rng is not None:
            members = members[rng.permutation(len(members))]
        queues[int(width)] = members
        heads[int(width)] = 0
    for width in schedule:
        width = int(width)
        members, head = queues[width], heads[width]
        idx = members[head : head + batch_size]
        heads[width] = head + len(idx)
        if len(idx) == 0:
            yield empty_batch(batch_size, width)
        else:
            yield _bucket_batch(epoch, idx, width, batch_size)


def iter_streaming_batches(
    epoch_builder,
    item_idx: np.ndarray,
    batch_size: int,
    rng: np.random.Generator,
    chunk_items: int = 65536,
    pad_final: bool = True,
    shuffle: bool = True,
    ladder: tuple[int, ...] | None = None,
) -> Iterator[dict[str, np.ndarray]]:
    """Stream an epoch as static-shape batches without materializing [N, L].

    ``build_epoch`` allocates 3 x [N, L] int32 — ~38 GB host RAM at
    java-large scale (16M methods x bag 200, BASELINE.json config 3). This
    generator shuffles the *item order* globally, then materializes only
    ``chunk_items`` rows at a time (3 x chunk x L int32, ~157 MB at the
    default chunk and bag 200), carrying sub-batch remainders across chunk
    boundaries so emitted batches are identical in shape/semantics to
    ``iter_batches`` over a full epoch.

    ``epoch_builder(chunk_idx)`` -> :class:`EpochArrays` for those items —
    pass a closure over :func:`build_epoch` (the per-method context
    subsample is independent per item, so chunked construction draws the
    same distribution as a whole-epoch build). Variable-task expansion may
    return more examples than items; the carry buffer absorbs that.

    ``ladder``: emit length-aware BUCKETED ``[B, L_b]`` batches instead of
    fixed-shape ones — the streaming x bucketed composition. Rows are
    assigned to buckets as each chunk is built; each bucket carries its
    sub-batch remainder across chunk boundaries, and each chunk's ready
    batches go out in a seeded interleave (``rng``). Same static-shape
    contract as :func:`iter_bucketed_batches`: only ladder widths appear,
    every batch has exactly ``batch_size`` rows, partial batches are
    row-0-padded and masked.
    """
    if ladder is not None:
        yield from _iter_streaming_bucketed_batches(
            epoch_builder, item_idx, ladder, batch_size, rng,
            chunk_items=chunk_items, pad_final=pad_final, shuffle=shuffle,
        )
        return
    order = rng.permutation(len(item_idx)) if shuffle else np.arange(len(item_idx))
    carry: EpochArrays | None = None

    def emit(epoch: EpochArrays, final: bool):
        # batch assembly delegates to iter_batches so the layout/padding
        # semantics exist in exactly one place
        n_full = len(epoch) // batch_size * batch_size
        yield from iter_batches(
            _slice_epoch(epoch, 0, n_full), batch_size, rng=None,
            pad_final=False,
        )
        rest = _slice_epoch(epoch, n_full, len(epoch))
        if final and len(rest) and pad_final:
            yield from iter_batches(rest, batch_size, rng=None, pad_final=True)
            rest = None
        return rest

    for lo in range(0, len(order), chunk_items):
        chunk_idx = item_idx[order[lo : lo + chunk_items]]
        with get_tracer().span(
            "stream_chunk", category="data", items=len(chunk_idx)
        ):
            chunk = epoch_builder(chunk_idx)
        if carry is not None and len(carry):
            chunk = _concat_epochs([carry, chunk])
        final = lo + chunk_items >= len(order)
        # ``yield from`` hands back emit()'s return value: the sub-batch
        # remainder carried into the next chunk (None once padded/emitted)
        carry = yield from emit(chunk, final)


def _iter_streaming_bucketed_batches(
    epoch_builder,
    item_idx: np.ndarray,
    ladder: tuple[int, ...],
    batch_size: int,
    rng: np.random.Generator,
    chunk_items: int = 65536,
    pad_final: bool = True,
    shuffle: bool = True,
) -> Iterator[dict[str, np.ndarray]]:
    """The bucketed body of :func:`iter_streaming_batches` (``ladder=``).

    Per chunk: build, assign rows to ladder buckets, join each bucket's
    rows onto its carry, emit the full batches (interleaved by ``rng``),
    and keep each bucket's ``< batch_size`` remainder as the next carry —
    so peak materialization stays chunk-bounded while every emitted shape
    is a ladder width. The final chunk flushes all remainders as padded,
    masked partial batches (``pad_final``).
    """
    order = (
        rng.permutation(len(item_idx)) if shuffle else np.arange(len(item_idx))
    )
    carry: list[EpochArrays | None] = [None] * len(ladder)
    for lo in range(0, len(order), chunk_items):
        chunk_idx = item_idx[order[lo : lo + chunk_items]]
        with get_tracer().span(
            "stream_chunk", category="data", items=len(chunk_idx)
        ):
            chunk = epoch_builder(chunk_idx)
        final = lo + chunk_items >= len(order)
        bucket_of = assign_buckets(epoch_context_counts(chunk), ladder)
        plans: list[tuple[int, EpochArrays]] = []
        for b, width in enumerate(ladder):
            part = _gather_epoch_rows(chunk, np.flatnonzero(bucket_of == b))
            if carry[b] is not None and len(carry[b]):
                part = _concat_epochs([carry[b], part])
            n_full = len(part) // batch_size * batch_size
            for s in range(0, n_full, batch_size):
                plans.append((width, _slice_epoch(part, s, s + batch_size)))
            rest = _slice_epoch(part, n_full, len(part))
            if final and len(rest) and pad_final:
                plans.append((width, rest))
                rest = None
            carry[b] = rest if rest is not None and len(rest) else None
        if shuffle:
            plans = [plans[i] for i in rng.permutation(len(plans))]
        for width, part in plans:
            yield _bucket_batch(
                part, np.arange(len(part)), width, batch_size
            )


def _gather_epoch_rows(epoch: EpochArrays, idx: np.ndarray) -> EpochArrays:
    return EpochArrays(
        ids=epoch.ids[idx],
        starts=epoch.starts[idx],
        paths=epoch.paths[idx],
        ends=epoch.ends[idx],
        labels=epoch.labels[idx],
    )


def _slice_epoch(epoch: EpochArrays, lo: int, hi: int) -> EpochArrays:
    return EpochArrays(
        ids=epoch.ids[lo:hi],
        starts=epoch.starts[lo:hi],
        paths=epoch.paths[lo:hi],
        ends=epoch.ends[lo:hi],
        labels=epoch.labels[lo:hi],
    )


def _concat_epochs(parts: list[EpochArrays]) -> EpochArrays:
    return EpochArrays(
        ids=np.concatenate([p.ids for p in parts]),
        starts=np.concatenate([p.starts for p in parts]),
        paths=np.concatenate([p.paths for p in parts]),
        ends=np.concatenate([p.ends for p in parts]),
        labels=np.concatenate([p.labels for p in parts]),
    )


def skip_batches(
    batches: Iterator[dict[str, np.ndarray]],
    n: int,
    expect_widths: dict[int, int] | None = None,
) -> Iterator[dict[str, np.ndarray]]:
    """Consume the first ``n`` batches of an epoch stream — the mid-epoch
    resume replay (train/loop.py).

    Every epoch iterator here is a pure function of the epoch arrays and
    the RNG state it was created under, so re-creating it from the
    checkpointed cursor and discarding the first ``n`` batches puts the
    stream *bitwise* where the interrupted run left it — including the
    bucketed path, whose whole batch plan (bucket membership, interleave)
    is drawn up front from the same RNG. Skipping costs host batch builds
    only; no device work is dispatched for skipped batches.

    ``expect_widths``: the cursor's recorded per-bucket positions; a
    mismatch means the run's ladder/batching config changed since the save
    and the cursor cannot be honored, so fail with guidance instead of
    silently training on the wrong examples.
    """
    it = iter(batches)
    seen: dict[int, int] = {}
    for i in range(n):
        try:
            batch = next(it)
        except StopIteration:
            raise ValueError(
                f"mid-epoch cursor points past the epoch: batch {i} of "
                f"{n} does not exist — the corpus or batching config "
                "changed since the checkpoint was saved; restart without "
                "--resume (or restore the original config)"
            ) from None
        width = int(batch["paths"].shape[1])
        seen[width] = seen.get(width, 0) + 1
    if expect_widths is not None and seen != {
        int(w): c for w, c in expect_widths.items()
    }:
        raise ValueError(
            f"mid-epoch cursor bucket positions {expect_widths} do not "
            f"match the replayed stream {seen}; the bucket ladder or batch "
            "size changed since the checkpoint was saved — resume with the "
            "original settings or restart without --resume"
        )
    return it


def empty_batch(batch_size: int, max_contexts: int) -> dict[str, np.ndarray]:
    """A fully-masked all-PAD batch (the no-op collective step)."""
    bag = (batch_size, max_contexts)
    return {
        "ids": np.zeros(batch_size, np.int64),
        "starts": np.full(bag, PAD_INDEX, np.int32),
        "paths": np.full(bag, PAD_INDEX, np.int32),
        "ends": np.full(bag, PAD_INDEX, np.int32),
        "labels": np.zeros(batch_size, np.int32),
        "example_mask": np.zeros(batch_size, np.float32),
    }


def pad_batch_stream(
    batches: Iterator[dict[str, np.ndarray]],
    n_steps: int,
    template: dict[str, np.ndarray],
) -> Iterator[dict[str, np.ndarray]]:
    """Yield exactly ``n_steps`` batches, extending with fully-masked
    ``template`` batches (:func:`empty_batch`). Multi-host feeding: every
    host must dispatch the same number of collective steps even when its
    local shard runs out of rows first — including the degenerate case of a
    host with zero local rows, which yields only templates."""
    count = 0
    for batch in batches:
        count += 1
        yield batch
    while count < n_steps:
        count += 1
        yield template


# ---------------------------------------------------------------------------
# Batch sources: every host epoch variant behind ONE protocol
#
# The train loop used to pick among hand-wired epoch branches (fixed-L,
# bucketed, streaming, host-sharded, prefetched), and the best ones were
# mutually exclusive. A BatchSource owns one split's epoch construction and
# exposes the same four things for every variant, so the loop — and the
# prefetcher, the sharded feed padding, and mid-epoch resume — compose with
# all of them:
#
# - ``ladder``: the static shape ladder the source emits (a single-width
#   ladder is the fixed-L case) — the run's whole compile budget;
# - ``batches(rng, shuffle)``: one epoch's stream, a PURE FUNCTION of the
#   rng state at the call — which is exactly what makes ``skip_batches``
#   mid-epoch resume replay work on every variant;
# - ``scheduled_batches(rng, schedule)``: the same stream following an
#   externally-agreed width schedule (host-sharded lockstep);
# - ``pad_stats()``: (real context slots, padded slots) for the last built
#   epoch — the ``pad_efficiency`` honesty metric, now reported by every
#   variant including streaming.
# ---------------------------------------------------------------------------


class BatchSource:
    """Protocol base for host epoch feeds (see module section comment).

    ``last_epoch`` holds the most recently built :class:`EpochArrays` for
    sources that materialize one (the in-RAM source) — exports and
    print_sample reuse it instead of re-drawing; out-of-core sources leave
    it None and callers fall back to an on-demand build.
    """

    ladder: tuple[int, ...] = ()
    last_epoch: EpochArrays | None = None

    def batches(
        self, rng: np.random.Generator, shuffle: bool = True
    ) -> Iterator[dict[str, np.ndarray]]:
        raise NotImplementedError

    def scheduled_batches(
        self,
        rng: np.random.Generator,
        schedule: np.ndarray,
        shuffle: bool = True,
    ) -> Iterator[dict[str, np.ndarray]]:
        raise NotImplementedError(
            f"{type(self).__name__} cannot follow an external width "
            "schedule (host-sharded bucketed feeding); use the in-RAM or "
            "mmap-CSR source (convert the corpus with "
            "tools/corpus_convert.py and pass --corpus_format csr)"
        )

    def plan_batches(
        self, rng: np.random.Generator, shuffle: bool = True
    ) -> "Iterator[BatchPlan]":
        """The plan half of the plan/build split (parallel host ingest):
        draw every RNG value ``batches(rng, shuffle)`` would — identical
        order, identical sizes — and yield :class:`BatchPlan`s whose
        :func:`execute_plan` rebuilds are bitwise the sync stream's
        batches. Method task only: the variable expansion interleaves
        per-item draws with data-dependent row counts and stays on the
        coordinator."""
        raise NotImplementedError(
            f"{type(self).__name__} has no batch-plan split; "
            "--feed_workers supports the in-RAM, streaming, and mmap-CSR "
            "method-task sources"
        )

    def pad_stats(self) -> tuple[int, int] | None:
        """(real, slots) of the last streamed epoch; None before any."""
        return None

    def _accounted(self, stream):
        """Tally (real context slots, total padded slots) while a stream is
        consumed — the streaming/mmap variants' ``pad_stats`` backing.
        Masked rows (partial-batch padding, lockstep empties) count as
        slots but never as real contexts, matching :func:`pad_stats`."""
        real = slots = 0
        try:
            for batch in stream:
                valid = batch["example_mask"].astype(bool)
                real += int((batch["paths"][valid] != PAD_INDEX).sum())
                slots += int(batch["paths"].size)
                yield batch
        finally:
            self._last_pad = (real, slots)


class EpochSource(BatchSource):
    """The in-RAM variant: one materialized :class:`EpochArrays` per epoch,
    batched fixed-L or bucketed. The build happens at the stream's first
    pull (not at :meth:`batches` time) so the host RNG draw order is
    identical to the historical loop — resumes of old checkpoints replay
    bitwise."""

    def __init__(
        self,
        data: CorpusData,
        item_idx: np.ndarray,
        batch_size: int,
        max_contexts: int,
        ladder: tuple[int, ...] | None = None,
        shuffle_variable_indexes: bool = False,
        context_order: str = "shuffled",
    ):
        self.data = data
        self.item_idx = np.asarray(item_idx)
        self.batch_size = int(batch_size)
        self.max_contexts = int(max_contexts)
        self.ladder = tuple(ladder) if ladder else (int(max_contexts),)
        self._bucketed = ladder is not None
        self._svi = shuffle_variable_indexes
        self._context_order = context_order
        self.last_epoch: EpochArrays | None = None
        # (n_rows, real, slots): per-row counts are min(raw count, bag)
        # regardless of which contexts the per-epoch subsample picked, so
        # the O(N*L) scan need not repeat every epoch
        self._pad_cache: tuple[int, int, int] | None = None
        # set by a scheduled (host-sharded lockstep) stream: its masked
        # empty batches are dispatched work the exact epoch geometry does
        # not see, so pad accounting must come from the stream tally —
        # keeping pad_efficiency's meaning identical across backings
        # (MmapCorpusSource always tallies)
        self._last_pad: tuple[int, int] | None = None

    def _build(self, rng: np.random.Generator) -> EpochArrays:
        epoch = build_epoch(
            self.data, self.item_idx, self.max_contexts, rng, self._svi,
            self._context_order,
        )
        self.last_epoch = epoch
        return epoch

    def batches(self, rng, shuffle: bool = True):
        self._last_pad = None  # exact geometry applies to a plain epoch

        def gen():
            epoch = self._build(rng)
            if self._bucketed:
                yield from iter_bucketed_batches(
                    epoch, self.ladder, self.batch_size,
                    rng=rng if shuffle else None, pad_final=True,
                )
            else:
                yield from iter_batches(
                    epoch, self.batch_size,
                    rng=rng if shuffle else None, pad_final=True,
                )

        return gen()

    def scheduled_batches(self, rng, schedule, shuffle: bool = True):
        def gen():
            epoch = self._build(rng)
            yield from iter_scheduled_bucketed_batches(
                epoch, self.ladder, self.batch_size, schedule,
                rng=rng if shuffle else None,
            )

        return self._accounted(gen())

    def plan_batches(self, rng, shuffle: bool = True):
        if self.data.infer_variable:
            raise ValueError(
                "the in-RAM source plans the method task only (the "
                "variable expansion draws per-item rng on the "
                "coordinator); run variable-task corpora with "
                "--feed_workers 0"
            )

        def gen():
            # mirrors batches(): the whole-epoch subsample draw happens at
            # the stream's FIRST PULL (build laziness), then the batch-
            # order draws — identical rng consumption to the sync path
            entries, counts_full = _uniform_entries(
                rng, self.data.row_splits, self.item_idx
            )
            built_counts = np.minimum(counts_full, self.max_contexts)
            B = self.batch_size
            if self._bucketed:
                # iter_bucketed_batches' draws: per-bucket member
                # permutations in ladder order, then the plan interleave;
                # partial batches repeat the batch's own first row
                bucket_of = assign_buckets(built_counts, self.ladder)
                plans: list[tuple[int, np.ndarray]] = []
                for b, width in enumerate(self.ladder):
                    members = np.flatnonzero(bucket_of == b)
                    if shuffle:
                        members = members[rng.permutation(len(members))]
                    for lo in range(0, len(members), B):
                        plans.append((int(width), members[lo : lo + B]))
                if shuffle:
                    plans = [plans[i] for i in rng.permutation(len(plans))]
                for width, rows in plans:
                    valid = len(rows)
                    if valid < B:
                        rows = np.concatenate(
                            [rows, np.full(B - valid, rows[0], rows.dtype)]
                        )
                    yield _plan_of(
                        width, [entries[r] for r in rows], valid,
                        self._context_order,
                    )
            else:
                # iter_batches' draws: one row permutation when shuffling;
                # the final partial batch repeats EPOCH row 0
                n = len(self.item_idx)
                order = rng.permutation(n) if shuffle else None
                for lo in range(0, n, B):
                    hi = min(lo + B, n)
                    valid = hi - lo
                    rows = (
                        order[lo:hi] if order is not None
                        else np.arange(lo, hi)
                    )
                    if valid < B:
                        rows = np.concatenate(
                            [rows, np.zeros(B - valid, rows.dtype)]
                        )
                    yield _plan_of(
                        self.max_contexts, [entries[r] for r in rows],
                        valid, self._context_order,
                    )

        return gen()

    def pad_stats(self) -> tuple[int, int] | None:
        if self._last_pad is not None:
            # a scheduled stream ran: report the DISPATCHED slots (incl.
            # lockstep empties), same accounting the mmap source uses
            return self._last_pad
        if self.last_epoch is None:
            return None
        n_rows = len(self.last_epoch.ids)
        if self._pad_cache is None or self._pad_cache[0] != n_rows:
            real, slots = pad_stats(
                epoch_context_counts(self.last_epoch),
                self.ladder,
                self.batch_size,
            )
            self._pad_cache = (n_rows, real, slots)
        _, real, slots = self._pad_cache
        return real, slots


class StreamingSource(BatchSource):
    """The bounded-RSS variant: chunked epoch builds
    (:func:`iter_streaming_batches`), fixed-L or — new — bucketed via the
    per-bucket carry. Works over any CorpusData backing, including the
    mmap-CSR container (chunk gathers page only the touched rows), and is
    the out-of-core path for the VARIABLE task, whose per-item expansion
    defeats the gather source's static batch plans."""

    def __init__(
        self,
        data: CorpusData,
        item_idx: np.ndarray,
        batch_size: int,
        max_contexts: int,
        chunk_items: int,
        ladder: tuple[int, ...] | None = None,
        shuffle_variable_indexes: bool = False,
        context_order: str = "shuffled",
    ):
        self.data = data
        self.item_idx = np.asarray(item_idx)
        self.batch_size = int(batch_size)
        self.max_contexts = int(max_contexts)
        self.chunk_items = int(chunk_items)
        self.ladder = tuple(ladder) if ladder else (int(max_contexts),)
        self._bucket_ladder = tuple(ladder) if ladder else None
        self._svi = shuffle_variable_indexes
        self._context_order = context_order
        self._last_pad: tuple[int, int] | None = None

    def batches(self, rng, shuffle: bool = True):
        def chunk_builder(idx):
            return build_epoch(
                self.data, idx, self.max_contexts, rng, self._svi,
                self._context_order,
            )

        return self._accounted(
            iter_streaming_batches(
                chunk_builder, self.item_idx, self.batch_size, rng,
                chunk_items=self.chunk_items, shuffle=shuffle,
                ladder=self._bucket_ladder,
            )
        )

    def plan_batches(self, rng, shuffle: bool = True):
        if self.data.infer_variable:
            raise ValueError(
                "streaming plans the method task only (the variable "
                "expansion draws per-item rng on the coordinator); run "
                "variable-task corpora with --feed_workers 0"
            )

        def gen():
            # mirrors iter_streaming_batches: global item-order draw, then
            # one chunk-sized subsample draw per chunk, carrying sub-batch
            # remainders (per bucket when laddered) across chunk
            # boundaries as (item, uniform-segment) row entries
            order = (
                rng.permutation(len(self.item_idx)) if shuffle
                else np.arange(len(self.item_idx))
            )
            B = self.batch_size
            ladder = self._bucket_ladder
            pending: list = []  # fixed-L carry
            carry: list[list] = [[] for _ in (ladder or ())]
            for lo in range(0, len(order), self.chunk_items):
                chunk_idx = self.item_idx[order[lo : lo + self.chunk_items]]
                entries, counts_full = _uniform_entries(
                    rng, self.data.row_splits, chunk_idx
                )
                final = lo + self.chunk_items >= len(order)
                if ladder is None:
                    pending.extend(entries)
                    n_full = len(pending) // B * B
                    for s in range(0, n_full, B):
                        yield _plan_of(
                            self.max_contexts, pending[s : s + B], B,
                            self._context_order,
                        )
                    pending = pending[n_full:]
                    if final and pending:
                        rows = pending + [pending[0]] * (B - len(pending))
                        yield _plan_of(
                            self.max_contexts, rows, len(pending),
                            self._context_order,
                        )
                        pending = []
                    continue
                # bucketed: per-bucket carry + per-chunk seeded interleave
                built = np.minimum(counts_full, self.max_contexts)
                bucket_of = assign_buckets(built, ladder)
                plans: list[tuple[int, list, int]] = []
                for b, width in enumerate(ladder):
                    part = carry[b] + [
                        entries[j] for j in np.flatnonzero(bucket_of == b)
                    ]
                    n_full = len(part) // B * B
                    for s in range(0, n_full, B):
                        plans.append((int(width), part[s : s + B], B))
                    rest = part[n_full:]
                    if final and rest:
                        plans.append(
                            (
                                int(width),
                                rest + [rest[0]] * (B - len(rest)),
                                len(rest),
                            )
                        )
                        rest = []
                    carry[b] = rest
                if shuffle:
                    plans = [plans[i] for i in rng.permutation(len(plans))]
                for width, rows, valid in plans:
                    yield _plan_of(width, rows, valid, self._context_order)

        return gen()

    def pad_stats(self) -> tuple[int, int] | None:
        return self._last_pad


class MmapCorpusSource(BatchSource):
    """The never-materialize variant: batches gathered straight from the
    (mmap-backed) CSR arrays, per bucket — no ``[N, L]`` epoch tensor
    exists at ANY point, so host RSS stays bounded by one batch regardless
    of corpus size (the out-of-core acceptance bar; see the rlimit test in
    tests/test_ooc.py).

    The epoch geometry (bucket membership) is corpus-static for the method
    task — ``min(row count, top width)`` per item — so the batch plan comes
    from ``row_splits`` alone; each planned ``[B, L_b]`` batch then runs
    the standard per-method context subsample over just its ``B`` items
    (:func:`build_method_epoch` at the bucket's width). Method task only:
    the variable expansion is data-dependent per item — route those through
    :class:`StreamingSource`, which composes with mmap backing too.
    """

    def __init__(
        self,
        data: CorpusData,
        item_idx: np.ndarray,
        batch_size: int,
        max_contexts: int,
        ladder: tuple[int, ...] | None = None,
        context_order: str = "shuffled",
    ):
        if data.infer_variable:
            raise ValueError(
                "MmapCorpusSource supports the method task only (the "
                "variable expansion is data-dependent per item); use "
                "stream_chunk_items for variable-task out-of-core feeding"
            )
        self.data = data
        self.item_idx = np.asarray(item_idx)
        self.batch_size = int(batch_size)
        self.max_contexts = int(max_contexts)
        self.ladder = tuple(ladder) if ladder else (int(max_contexts),)
        self._context_order = context_order
        counts = (
            data.row_splits[self.item_idx + 1]
            - data.row_splits[self.item_idx]
        )
        self._counts = np.minimum(counts, self.ladder[-1])
        self._last_pad: tuple[int, int] | None = None

    def _plan(
        self, rng: np.random.Generator | None
    ) -> list[tuple[int, np.ndarray]]:
        """The epoch's (width, items) batch plan — same shuffle/interleave
        draws as :func:`iter_bucketed_batches` (a single-width ladder
        degenerates to the fixed-L plan)."""
        bucket_of = assign_buckets(self._counts, self.ladder)
        plans: list[tuple[int, np.ndarray]] = []
        for b, width in enumerate(self.ladder):
            members = self.item_idx[bucket_of == b]
            if rng is not None:
                members = members[rng.permutation(len(members))]
            for lo in range(0, len(members), self.batch_size):
                plans.append((width, members[lo : lo + self.batch_size]))
        if rng is not None:
            plans = [plans[i] for i in rng.permutation(len(plans))]
        return plans

    def _batch_plan(
        self, items: np.ndarray, width: int, rng: np.random.Generator
    ) -> "BatchPlan":
        """(items, width) → plan: THE per-batch subsample draw + padding
        rule of this source. The sync stream is defined as executing these
        plans inline, so the ``--feed_workers`` bitwise contract is
        structural here — there is no second draw schedule to drift."""
        entries, _ = _uniform_entries(rng, self.data.row_splits, items)
        valid = len(items)
        if valid < self.batch_size:
            # the _bucket_batch rule: pad by repeating the batch's row 0
            entries = entries + [entries[0]] * (self.batch_size - valid)
        return _plan_of(width, entries, valid, self._context_order)

    def batches(self, rng, shuffle: bool = True):
        def gen():
            for width, items in self._plan(rng if shuffle else None):
                yield execute_plan(
                    self.data, self._batch_plan(items, width, rng)
                )

        return self._accounted(gen())

    def plan_batches(self, rng, shuffle: bool = True):
        def gen():
            # the (width, items) plan draws up front, then each batch's
            # subsample uniforms lazily at yield time — exactly when the
            # sync stream draws them
            for width, items in self._plan(rng if shuffle else None):
                yield self._batch_plan(items, width, rng)

        return gen()

    def scheduled_batches(self, rng, schedule, shuffle: bool = True):
        """Follow an external width schedule (host-sharded lockstep): the
        gather plan is random-access, so ANY schedule order costs no
        buffering — the composition text streaming cannot offer."""

        def gen():
            bucket_of = assign_buckets(self._counts, self.ladder)
            queues: dict[int, np.ndarray] = {}
            heads: dict[int, int] = {}
            for b, width in enumerate(self.ladder):
                members = self.item_idx[bucket_of == b]
                if shuffle:
                    members = members[rng.permutation(len(members))]
                queues[int(width)] = members
                heads[int(width)] = 0
            for width in schedule:
                width = int(width)
                members, head = queues[width], heads[width]
                items = members[head : head + self.batch_size]
                heads[width] = head + len(items)
                if len(items) == 0:
                    yield empty_batch(self.batch_size, width)
                else:
                    yield execute_plan(
                        self.data, self._batch_plan(items, width, rng)
                    )

        return self._accounted(gen())

    def pad_stats(self) -> tuple[int, int] | None:
        return self._last_pad


def make_batch_source(
    data: CorpusData,
    item_idx: np.ndarray,
    batch_size: int,
    max_contexts: int,
    ladder: tuple[int, ...] | None = None,
    stream_chunk_items: int = 0,
    shuffle_variable_indexes: bool = False,
    context_order: str = "shuffled",
) -> BatchSource:
    """Pick the feed variant for one split — THE policy point:

    - ``stream_chunk_items > 0``: chunked streaming (any backing, any task);
    - mmap-backed corpus (CSR container), method task: the never-materialize
      per-bucket gather source;
    - otherwise: the in-RAM epoch source.

    ``ladder=None`` means fixed-L; every source treats it as the
    single-width ladder, so bucketing composes with all of them.
    """
    if stream_chunk_items:
        return StreamingSource(
            data, item_idx, batch_size, max_contexts, stream_chunk_items,
            ladder=ladder,
            shuffle_variable_indexes=shuffle_variable_indexes,
            context_order=context_order,
        )
    if data.mmap_backed and not data.infer_variable:
        return MmapCorpusSource(
            data, item_idx, batch_size, max_contexts, ladder=ladder,
            context_order=context_order,
        )
    return EpochSource(
        data, item_idx, batch_size, max_contexts, ladder=ladder,
        shuffle_variable_indexes=shuffle_variable_indexes,
        context_order=context_order,
    )


# ---------------------------------------------------------------------------
# Plan/build split: parallel host ingest (data/parallel_feed.py)
#
# Every batch a method-task source emits is a pure function of (item set,
# bag width, the per-item subsample uniforms) — all the gathers, sorts,
# padding and the @question substitution contain no randomness of their
# own. So each source can split its epoch stream into:
#
# - ``plan_batches(rng, shuffle)``: COORDINATOR side — draws every RNG
#   value its ``batches(rng, shuffle)`` would (epoch plans, bucket
#   interleaves, shuffles, the subsample uniforms), in the identical
#   order and sizes, and yields :class:`BatchPlan`s;
# - ``execute_plan(data, plan)``: PURE — rebuilds the planned batch from
#   the corpus arrays, safe to run in a worker process.
#
# ``execute_plan(plan_k)`` is bitwise-equal to the k-th batch of
# ``batches()`` under the same rng, and consuming a whole plan stream
# leaves the generator in the identical state — which is what makes
# ``--feed_workers N`` runs (feed order, loss history, mid-epoch resume
# cursors) bitwise-identical to ``--feed_workers 0``.
# ---------------------------------------------------------------------------


@dataclass
class BatchPlan:
    """One executable batch: every RNG draw already made.

    ``items`` has one entry per OUTPUT ROW (already padded to the full
    batch size by repeating a real row — the row-0-repeat padding rule of
    :func:`iter_batches` / :func:`_bucket_batch`); ``uniforms`` holds each
    row's subsample draws back to back (``len == sum of the rows' FULL
    context counts``, the exact ``rng.random(total)`` the sync build
    consumes). Rebuilding a duplicated pad row from the duplicated draws
    reproduces the repeated row bitwise.
    """

    width: int
    valid: int  # rows with example_mask 1.0
    items: np.ndarray  # int64 [batch_size]
    uniforms: np.ndarray  # float64 [sum counts(items)]
    context_order: str = "shuffled"


class _PlannedDraws:
    """``np.random.Generator`` stand-in replaying coordinator-drawn
    uniforms inside :func:`execute_plan` — the builder code path is the
    SAME :func:`build_method_epoch` the sync stream runs, so there is no
    second implementation of the subsample to keep in sync."""

    def __init__(self, uniforms: np.ndarray):
        self._uniforms = uniforms
        self._pos = 0

    def random(self, n: int) -> np.ndarray:
        out = self._uniforms[self._pos : self._pos + n]
        if len(out) != n:
            raise ValueError(
                f"batch plan carries {len(self._uniforms)} uniforms but the "
                f"build asked for {self._pos + n}: the plan and the corpus "
                "disagree (corpus changed since planning?)"
            )
        self._pos += n
        return out


def execute_plan(data, plan: BatchPlan) -> dict[str, np.ndarray]:
    """Build the planned batch — PURE (all randomness lives in
    ``plan.uniforms``), corpus arrays in, batch dict out. This is the
    function ``--feed_workers`` worker processes run; ``data`` may be any
    object with the CSR array attributes (a :class:`CorpusData` or the
    feed's slim fork-shared view)."""
    sub = build_method_epoch(
        data, plan.items, plan.width, _PlannedDraws(plan.uniforms),
        plan.context_order,
    )
    mask = np.zeros(len(plan.items), np.float32)
    mask[: plan.valid] = 1.0
    return {
        "ids": sub.ids,
        "starts": sub.starts,
        "paths": sub.paths,
        "ends": sub.ends,
        "labels": sub.labels,
        "example_mask": mask,
    }


def plan_real_slots(plan: BatchPlan, row_splits) -> tuple[int, int]:
    """(real context slots, padded slots) this plan's batch will carry —
    the :meth:`BatchSource.pad_stats` accounting computed from geometry
    alone (the feed never scans the built arrays)."""
    items = plan.items[: plan.valid]
    counts = (row_splits[items + 1] - row_splits[items]).astype(np.int64)
    real = int(np.minimum(counts, plan.width).sum())
    return real, len(plan.items) * int(plan.width)


def _plan_of(
    width: int,
    entries: list[tuple[int, np.ndarray]],
    valid: int,
    context_order: str,
) -> BatchPlan:
    """Assemble a plan from per-row ``(item, uniform-segment)`` entries
    (already padded to the batch size by the caller's padding rule)."""
    items = np.asarray([e[0] for e in entries], np.int64)
    segs = [e[1] for e in entries]
    uniforms = (
        np.concatenate(segs) if segs else np.zeros(0, np.float64)
    )
    return BatchPlan(
        width=int(width), valid=int(valid), items=items, uniforms=uniforms,
        context_order=context_order,
    )


def _uniform_entries(
    rng, row_splits: np.ndarray, items: np.ndarray
) -> tuple[list[tuple[int, np.ndarray]], np.ndarray]:
    """Draw the subsample uniforms for ``items`` exactly as one
    ``build_method_epoch(items, ...)`` call would — ONE ``rng.random(total
    full contexts)`` draw, nothing when total is 0 (mirroring the early
    return in :func:`flat_context_indices`) — and slice them into per-item
    segments. Returns ``(entries, full_counts)``."""
    items = np.asarray(items)
    counts = (row_splits[items + 1] - row_splits[items]).astype(np.int64)
    seg = np.zeros(len(items) + 1, np.int64)
    np.cumsum(counts, out=seg[1:])
    total = int(seg[-1])
    u = rng.random(total) if total else np.zeros(0, np.float64)
    entries = [
        (int(items[j]), u[seg[j] : seg[j + 1]]) for j in range(len(items))
    ]
    return entries, counts


def oov_rate(
    data: CorpusData,
    train_idx: np.ndarray,
    test_idx: np.ndarray,
    exact: bool = False,
) -> float:
    """Fraction of test label (sub)tokens absent from the train label token
    set (reference: model/dataset_builder.py:72-110). ``exact=True`` uses
    whole labels (the ``eval_method == 'exact'`` branch)."""

    def tokens_of(i: int, out: list[str]) -> None:
        if data.infer_method:
            out.extend(_label_tokens(data, data.normalized_labels[i], exact))
        if data.infer_variable:
            for alias, normalized in data.aliases[i].items():
                if alias.startswith("@var_"):
                    out.extend(_label_tokens(data, normalized, exact))

    train_vocab: set[str] = set()
    buf: list[str] = []
    for i in train_idx:
        tokens_of(int(i), buf)
    train_vocab.update(buf)

    match = count = 0
    for i in test_idx:
        buf = []
        tokens_of(int(i), buf)
        match += sum(1 for t in buf if t in train_vocab)
        count += len(buf)
    return 1.0 - match / count if count else 0.0


def _label_tokens(data: CorpusData, normalized_label: str, exact: bool) -> list[str]:
    if exact:
        return [normalized_label]
    index = data.label_vocab.stoi[normalized_label]
    return list(data.label_vocab.itosubtokens.get(index, ()))
