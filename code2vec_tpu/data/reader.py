"""Corpus → flat CSR numpy arrays (the TPU-shaped in-memory representation).

Replaces the reference's list-of-CodeData representation
(model/dataset_reader.py:44-128) with structure-of-arrays storage: one flat
int32 array per field plus row_splits, so per-epoch resampling and padding
are vectorized numpy instead of a Python loop per method per epoch
(the reference's hot host loop, SURVEY.md §3.1).

Terminal indices are stored *shifted* (+1 for the injected ``@question``
token), exactly as the reference applies at parse time
(model/dataset_reader.py:113-115).
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass, field

import numpy as np

from code2vec_tpu import QUESTION_TOKEN_INDEX, QUESTION_TOKEN_NAME
from code2vec_tpu.data.vocab import Vocab
from code2vec_tpu.formats.corpus_io import iter_corpus_records
from code2vec_tpu.formats.vocab_io import read_vocab
from code2vec_tpu.text import normalize_and_subtokenize

logger = logging.getLogger(__name__)


@dataclass
class CorpusData:
    """Entire corpus in structure-of-arrays form.

    ``starts/paths/ends`` are flat over all path-contexts of all methods;
    method ``i`` owns slice ``row_splits[i]:row_splits[i+1]``.
    """

    # CSR context arrays (terminal ids already @question-shifted)
    starts: np.ndarray  # int32 [total_contexts]
    paths: np.ndarray  # int32 [total_contexts]
    ends: np.ndarray  # int32 [total_contexts]
    row_splits: np.ndarray  # int64 [n_items + 1]

    # per-item fields
    ids: np.ndarray  # int64 [n_items] — corpus record ids
    labels: np.ndarray  # int32 [n_items] — label vocab index (-1 if no method task)
    normalized_labels: list[str]
    sources: list[str | None]
    aliases: list[dict[str, str]]  # alias name -> normalized original name

    # vocabs
    terminal_vocab: Vocab = field(repr=False)
    path_vocab: Vocab = field(repr=False)
    label_vocab: Vocab = field(repr=False)

    # task config this corpus was loaded with
    infer_method: bool = True
    infer_variable: bool = False

    # terminal ids whose name starts with "@var_"
    # (reference: model/dataset_reader.py:54-56)
    variable_indexes: np.ndarray = field(default_factory=lambda: np.zeros(0, np.int32))

    @property
    def n_items(self) -> int:
        return len(self.row_splits) - 1

    @property
    def n_contexts(self) -> int:
        return int(self.row_splits[-1])

    def context_counts(self) -> np.ndarray:
        return np.diff(self.row_splits)

    @property
    def method_token_index(self) -> int | None:
        """Shifted index of ``@method_0`` if present (needed for the
        answer-leak substitution, reference: model/dataset_builder.py:124)."""
        return self.terminal_vocab.stoi.get("@method_0")


def load_corpus(
    corpus_path: str | os.PathLike,
    path_idx_path: str | os.PathLike,
    terminal_idx_path: str | os.PathLike,
    infer_method: bool = True,
    infer_variable: bool = False,
) -> CorpusData:
    """Load vocabs + corpus into a CorpusData.

    Mirrors DatasetReader (reference: model/dataset_reader.py:44-128):
    terminal vocab read with ``@question`` injected at 1, raw corpus
    terminal indices shifted +1, label vocab built record-by-record from
    method labels (if ``infer_method``) and ``@var_*`` original names
    (if ``infer_variable``) — same insertion order, hence identical indices.
    """
    path_vocab = read_vocab(path_idx_path)
    logger.info("path vocab size: %d", len(path_vocab))
    terminal_vocab = read_vocab(terminal_idx_path, extra_tokens=[QUESTION_TOKEN_NAME])
    logger.info("terminal vocab size: %d", len(terminal_vocab))

    variable_indexes = np.asarray(
        sorted(
            idx for name, idx in terminal_vocab.stoi.items() if name.startswith("@var_")
        ),
        dtype=np.int32,
    )
    logger.info("variable index size: %d", len(variable_indexes))

    label_vocab = Vocab()
    starts_parts: list[np.ndarray] = []
    paths_parts: list[np.ndarray] = []
    ends_parts: list[np.ndarray] = []
    counts: list[int] = []
    ids: list[int] = []
    labels: list[int] = []
    normalized_labels: list[str] = []
    sources: list[str | None] = []
    aliases: list[dict[str, str]] = []

    for record in iter_corpus_records(corpus_path):
        ids.append(record.id if record.id is not None else len(ids))
        sources.append(record.source)

        normalized_lower, _ = normalize_and_subtokenize(record.label or "")
        normalized_labels.append(normalized_lower)
        if infer_method:
            labels.append(label_vocab.add_label(record.label or ""))
        else:
            labels.append(-1)

        contexts = np.asarray(record.path_contexts, dtype=np.int32).reshape(-1, 3)
        starts_parts.append(contexts[:, 0] + QUESTION_TOKEN_INDEX)
        paths_parts.append(contexts[:, 1])
        ends_parts.append(contexts[:, 2] + QUESTION_TOKEN_INDEX)
        counts.append(len(contexts))

        alias_map: dict[str, str] = {}
        for original, alias in record.aliases:
            normalized_var, _ = normalize_and_subtokenize(original)
            alias_map[alias] = normalized_var.lower()
            if infer_variable and alias.startswith("@var_"):
                label_vocab.add_label(original)
        aliases.append(alias_map)

    row_splits = np.zeros(len(counts) + 1, dtype=np.int64)
    np.cumsum(counts, out=row_splits[1:])

    data = CorpusData(
        starts=np.concatenate(starts_parts) if starts_parts else np.zeros(0, np.int32),
        paths=np.concatenate(paths_parts) if paths_parts else np.zeros(0, np.int32),
        ends=np.concatenate(ends_parts) if ends_parts else np.zeros(0, np.int32),
        row_splits=row_splits,
        ids=np.asarray(ids, dtype=np.int64),
        labels=np.asarray(labels, dtype=np.int32),
        normalized_labels=normalized_labels,
        sources=sources,
        aliases=aliases,
        terminal_vocab=terminal_vocab,
        path_vocab=path_vocab,
        label_vocab=label_vocab,
        infer_method=infer_method,
        infer_variable=infer_variable,
        variable_indexes=variable_indexes,
    )
    logger.info("label vocab size: %d", len(label_vocab))
    logger.info("corpus: %d items, %d contexts", data.n_items, data.n_contexts)
    return data
