"""Corpus → flat CSR numpy arrays (the TPU-shaped in-memory representation).

Replaces the reference's list-of-CodeData representation
(model/dataset_reader.py:44-128) with structure-of-arrays storage: one flat
int32 array per field plus row_splits, so per-epoch resampling and padding
are vectorized numpy instead of a Python loop per method per epoch
(the reference's hot host loop, SURVEY.md §3.1).

Terminal indices are stored *shifted* (+1 for the injected ``@question``
token), exactly as the reference applies at parse time
(model/dataset_reader.py:113-115).
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass, field

import numpy as np

from code2vec_tpu import QUESTION_TOKEN_INDEX, QUESTION_TOKEN_NAME
from code2vec_tpu.data.vocab import Vocab
from code2vec_tpu.formats.corpus_io import iter_corpus_records
from code2vec_tpu.formats.vocab_io import read_vocab
from code2vec_tpu.text import normalize_and_subtokenize

logger = logging.getLogger(__name__)


@dataclass
class CorpusData:
    """Entire corpus in structure-of-arrays form.

    ``starts/paths/ends`` are flat over all path-contexts of all methods;
    method ``i`` owns slice ``row_splits[i]:row_splits[i+1]``.
    """

    # CSR context arrays (terminal ids already @question-shifted)
    starts: np.ndarray  # int32 [total_contexts]
    paths: np.ndarray  # int32 [total_contexts]
    ends: np.ndarray  # int32 [total_contexts]
    row_splits: np.ndarray  # int64 [n_items + 1]

    # per-item fields
    ids: np.ndarray  # int64 [n_items] — corpus record ids
    labels: np.ndarray  # int32 [n_items] — label vocab index (-1 if no method task)
    normalized_labels: list[str]
    sources: list[str | None]
    aliases: list[dict[str, str]]  # alias name -> normalized original name

    # vocabs
    terminal_vocab: Vocab = field(repr=False)
    path_vocab: Vocab = field(repr=False)
    label_vocab: Vocab = field(repr=False)

    # task config this corpus was loaded with
    infer_method: bool = True
    infer_variable: bool = False

    # terminal ids whose name starts with "@var_"
    # (reference: model/dataset_reader.py:54-56)
    variable_indexes: np.ndarray = field(default_factory=lambda: np.zeros(0, np.int32))

    # out-of-core backing (formats/corpus_io.py CSR container): True when
    # starts/paths/ends are mmap VIEWS of the on-disk container — gathers
    # touch only the rows they index and the OS pages the file lazily, so
    # holding this CorpusData costs ~zero host RSS at any corpus size. The
    # batch-source factory (data/pipeline.py) picks the never-materialize
    # feed for such corpora.
    mmap_backed: bool = False
    # per-item base offsets into the FLAT context arrays when they differ
    # from ``row_splits[:-1]`` — a sharded mmap corpus keeps the full
    # on-disk arrays (no gather copy) with LOCAL row_splits, so local item
    # i's contexts live at ``row_base[i] : row_base[i] + count_i`` of the
    # global arrays. None = contiguous (row_splits themselves).
    row_base: np.ndarray | None = None

    # host-shard bookkeeping (multi-host pods, SURVEY §7.4): when loaded
    # with load_corpus(..., shard=(index, count)), this CorpusData holds
    # only records assigned round-robin to this host (record i is local iff
    # i % count == index) and these fields map between the global and local
    # index spaces. Vocabs (including the label vocab, whose indices are
    # insertion-ordered) are always GLOBAL so every host agrees on them.
    shard: tuple[int, int] | None = None
    global_n_items: int = -1

    @property
    def n_items(self) -> int:
        return len(self.row_splits) - 1

    def local_rows_of_global(self, global_idx: np.ndarray) -> np.ndarray:
        """Filter a GLOBAL item-index array (e.g. a seeded split computed
        identically on every host) down to this shard, in the same relative
        order, returned as LOCAL row indices."""
        if self.shard is None:
            return np.asarray(global_idx)
        index, count = self.shard
        g = np.asarray(global_idx)
        mine = g[g % count == index]
        return mine // count

    def global_of_local(self, local_idx: np.ndarray) -> np.ndarray:
        if self.shard is None:
            return np.asarray(local_idx)
        index, count = self.shard
        return np.asarray(local_idx) * count + index

    @property
    def n_contexts(self) -> int:
        return int(self.row_splits[-1])

    def context_counts(self) -> np.ndarray:
        return np.diff(self.row_splits)

    @property
    def method_token_index(self) -> int | None:
        """Shifted index of ``@method_0`` if present (needed for the
        answer-leak substitution, reference: model/dataset_builder.py:124)."""
        return self.terminal_vocab.stoi.get("@method_0")


def _cache_fingerprint(
    corpus_path, path_idx_path, terminal_idx_path, infer_method, infer_variable,
    shard=None,
) -> dict:
    def stat(p):
        s = os.stat(p)
        return [int(s.st_size), int(s.st_mtime_ns)]

    return {
        "version": 1,
        "corpus": stat(corpus_path),
        "path_idx": stat(path_idx_path),
        "terminal_idx": stat(terminal_idx_path),
        "infer_method": infer_method,
        "infer_variable": infer_variable,
        "shard": list(shard) if shard is not None else None,
    }


_CACHE_ARRAY_KEYS = (
    "starts", "paths", "ends", "row_splits", "ids", "labels",
    "variable_indexes",
)


def _cache_digest(fingerprint) -> str:
    import hashlib
    import json

    return hashlib.sha1(
        json.dumps(fingerprint, sort_keys=True).encode()
    ).hexdigest()[:16]


def _cache_file_paths(corpus_path, fingerprint) -> tuple[str, str]:
    """(npz, json) sidecar paths, digest-keyed so runs with different task
    flags (or corpus versions) use disjoint files and can never pair a
    json from one configuration with arrays from another."""
    digest = _cache_digest(fingerprint)
    return (
        f"{corpus_path}.cache-{digest}.npz",
        f"{corpus_path}.cache-{digest}.json",
    )


def _try_load_cache(corpus_path, fingerprint) -> dict | None:
    import json
    import zipfile

    npz_path, meta_path = _cache_file_paths(corpus_path, fingerprint)
    if not (os.path.exists(npz_path) and os.path.exists(meta_path)):
        return None
    try:
        with open(meta_path, encoding="utf-8") as f:
            meta = json.load(f)
        if meta.get("fingerprint") != fingerprint:
            return None
        # materialize all arrays inside the guard: a truncated/corrupt npz
        # surfaces here (BadZipFile/CRC/missing key) and degrades to a
        # re-parse instead of crashing startup
        with np.load(npz_path) as npz:
            arrays = {k: np.array(npz[k]) for k in _CACHE_ARRAY_KEYS}
        return {"meta": meta, "arrays": arrays}
    except (OSError, ValueError, KeyError, zipfile.BadZipFile) as e:
        logger.warning("ignoring unreadable corpus cache: %s", e)
        return None


def _write_cache(corpus_path, fingerprint, data: "CorpusData") -> None:
    import json

    npz_path, meta_path = _cache_file_paths(corpus_path, fingerprint)
    tmp_suffix = f".tmp{os.getpid()}"  # unique per process: concurrent
    # writers of the same digest produce identical content, so whichever
    # os.replace lands last is equivalent; different digests never collide
    try:
        np.savez(
            npz_path + tmp_suffix + ".npz",
            **{k: getattr(data, k) for k in _CACHE_ARRAY_KEYS},
        )
        os.replace(npz_path + tmp_suffix + ".npz", npz_path)
        with open(meta_path + tmp_suffix, "w", encoding="utf-8") as f:
            json.dump(
                {
                    "fingerprint": fingerprint,
                    "label_vocab": data.label_vocab.to_state(),
                    "normalized_labels": data.normalized_labels,
                    "sources": data.sources,
                    "aliases": data.aliases,
                    "shard": list(data.shard) if data.shard else None,
                    "global_n_items": data.global_n_items,
                },
                f,
            )
        os.replace(meta_path + tmp_suffix, meta_path)
        logger.info("wrote corpus cache: %s", npz_path)
        # NOTE: sidecars of older corpus versions are left behind (one pair
        # per task-flag combination per corpus version); delete
        # <corpus>.cache-* to reclaim the space
    except OSError as e:
        logger.warning("could not write corpus cache (continuing): %s", e)


def _build_label_state(headers, var_lists, infer_method, infer_variable):
    """Per-record label/alias processing — ONE implementation for every
    loader (python parser, native parser, CSR container), so label-vocab
    insertion order (and hence label indices) cannot drift between them
    (reference: model/dataset_reader.py:94-125). ALWAYS over every record,
    even when sharded: the vocab must be global."""
    label_vocab = Vocab()
    labels: list[int] = []
    normalized_labels: list[str] = []
    sources: list[str | None] = []
    aliases: list[dict[str, str]] = []
    for (label, source), var_pairs in zip(headers, var_lists):
        sources.append(source)
        normalized_lower, _ = normalize_and_subtokenize(label)
        normalized_labels.append(normalized_lower)
        labels.append(label_vocab.add_label(label) if infer_method else -1)
        alias_map: dict[str, str] = {}
        for original, alias in var_pairs:
            normalized_var, _ = normalize_and_subtokenize(original)
            alias_map[alias] = normalized_var.lower()
            if infer_variable and alias.startswith("@var_"):
                label_vocab.add_label(original)
        aliases.append(alias_map)
    return label_vocab, labels, normalized_labels, sources, aliases


def _variable_indexes_of(terminal_vocab: Vocab) -> np.ndarray:
    return np.asarray(
        sorted(
            idx
            for name, idx in terminal_vocab.stoi.items()
            if name.startswith("@var_")
        ),
        dtype=np.int32,
    )


def load_corpus_csr(
    corpus_path: str | os.PathLike,
    path_idx_path: str | os.PathLike,
    terminal_idx_path: str | os.PathLike,
    infer_method: bool = True,
    infer_variable: bool = False,
    shard: tuple[int, int] | None = None,
) -> CorpusData:
    """Load a CSR container (formats/corpus_io.py) as an mmap-backed
    CorpusData — the out-of-core corpus path.

    The context arrays stay mmap VIEWS of the on-disk sections (the
    container stores terminal ids already ``@question``-shifted, so the
    views feed training zero-copy); only O(n_items) bookkeeping and the
    label/alias string pass materialize. ``shard=(index, count)`` keeps the
    FULL on-disk arrays (no gather copy — they cost no RSS) and maps this
    host's round-robin items onto them via LOCAL ``row_splits`` plus
    ``row_base`` global flat offsets, so host-sharded pod feeding composes
    with mmap at zero per-host context RSS.
    """
    from code2vec_tpu.formats.corpus_io import FLAG_ID, open_corpus_csr

    corpus = open_corpus_csr(corpus_path)
    path_vocab = read_vocab(path_idx_path)
    logger.info("path vocab size: %d", len(path_vocab))
    terminal_vocab = read_vocab(terminal_idx_path, extra_tokens=[QUESTION_TOKEN_NAME])
    logger.info("terminal vocab size: %d", len(terminal_vocab))

    if corpus.terminal_shift == QUESTION_TOKEN_INDEX:
        starts, paths, ends = corpus.starts, corpus.paths, corpus.ends
        mmap_backed = True
    else:
        # container written without the standard shift: materialize once
        # (loses the zero-RSS property; re-convert with the default shift)
        logger.warning(
            "CSR container stores terminal_shift=%d (expected %d); "
            "materializing shifted copies — re-run tools/corpus_convert.py "
            "for zero-copy mmap feeding",
            corpus.terminal_shift, QUESTION_TOKEN_INDEX,
        )
        delta = np.int32(QUESTION_TOKEN_INDEX - corpus.terminal_shift)
        starts = corpus.starts + delta
        ends = corpus.ends + delta
        paths = np.array(corpus.paths)
        mmap_backed = False

    n = corpus.n_items
    # the label/alias pass mirrors the text loaders record-for-record (the
    # blobs are small next to the context sections)
    headers = [(corpus.label(i) or "", corpus.source(i)) for i in range(n)]
    var_lists = [corpus.aliases(i) for i in range(n)]
    label_vocab, labels, normalized_labels, sources, aliases = (
        _build_label_state(headers, var_lists, infer_method, infer_variable)
    )

    ids_arr = corpus.ids.astype(np.int64)
    missing_id = (corpus.flags & FLAG_ID) == 0  # records without a #id line
    if missing_id.any():
        ids_arr = ids_arr.copy()
        ids_arr[missing_id] = np.nonzero(missing_id)[0]

    global_splits = corpus.row_splits
    row_base = None
    if shard is not None:
        index, count = shard
        local = np.arange(index, n, count)
        local_counts = np.diff(global_splits)[local]
        row_splits = np.zeros(len(local) + 1, np.int64)
        np.cumsum(local_counts, out=row_splits[1:])
        row_base = global_splits[local].astype(np.int64)
        ids_arr = ids_arr[local]
        labels = labels[index::count]
        normalized_labels = normalized_labels[index::count]
        sources = sources[index::count]
        aliases = aliases[index::count]
    else:
        row_splits = global_splits.astype(np.int64)

    data = CorpusData(
        starts=starts,
        paths=paths,
        ends=ends,
        row_splits=row_splits,
        ids=ids_arr,
        labels=np.asarray(labels, dtype=np.int32),
        normalized_labels=normalized_labels,
        sources=sources,
        aliases=aliases,
        terminal_vocab=terminal_vocab,
        path_vocab=path_vocab,
        label_vocab=label_vocab,
        infer_method=infer_method,
        infer_variable=infer_variable,
        variable_indexes=_variable_indexes_of(terminal_vocab),
        shard=shard,
        global_n_items=n,
        mmap_backed=mmap_backed,
        row_base=row_base,
    )
    logger.info("label vocab size: %d", len(label_vocab))
    logger.info(
        "corpus (csr mmap): %d items, %d contexts", data.n_items, data.n_contexts
    )
    # the reader handle is done: the context views handed into CorpusData
    # hold their own reference to the mapping (CsrCorpus.close contract)
    corpus.close()
    return data


def load_corpus(
    corpus_path: str | os.PathLike,
    path_idx_path: str | os.PathLike,
    terminal_idx_path: str | os.PathLike,
    infer_method: bool = True,
    infer_variable: bool = False,
    cache: bool = True,
    native: bool = True,
    shard: tuple[int, int] | None = None,
) -> CorpusData:
    """Load vocabs + corpus into a CorpusData.

    ``shard=(index, count)`` loads only this host's round-robin share of the
    records (record i is local iff ``i % count == index``) — the multi-host
    pod feeding path (SURVEY §7.4): context arrays, the dominant memory
    cost, are held 1/count per host. Labels/aliases of ALL records are still
    scanned so the label vocab (insertion-ordered) is identical on every
    host. The Python parser skips non-local context rows while reading
    (bounded peak RSS); the native parser parses fully, then slices (peak
    RSS is one full CSR copy — use the Python parser or pre-split corpora
    when even the parse doesn't fit).

    Mirrors DatasetReader (reference: model/dataset_reader.py:44-128):
    terminal vocab read with ``@question`` injected at 1, raw corpus
    terminal indices shifted +1, label vocab built record-by-record from
    method labels (if ``infer_method``) and ``@var_*`` original names
    (if ``infer_variable``) — same insertion order, hence identical indices.

    With ``cache`` (default), the parsed arrays are stored in sidecar files
    next to the corpus (``<corpus>.cache-<digest>.npz`` / ``.json``) keyed on
    the size+mtime of all three inputs and the task flags, cutting repeat
    startup from minutes to seconds at top11 scale (605k methods). Cache
    write failures degrade to a warning. The reference re-parses the full
    corpus in Python on every run (model/dataset_reader.py:72-128).

    A CSR container (formats/corpus_io.py, ``tools/corpus_convert.py``) is
    detected by magic and routed to :func:`load_corpus_csr` — mmap-backed
    arrays, no parse, no sidecar cache needed.
    """
    from code2vec_tpu.formats.corpus_io import is_csr_corpus

    if is_csr_corpus(corpus_path):
        return load_corpus_csr(
            corpus_path,
            path_idx_path,
            terminal_idx_path,
            infer_method=infer_method,
            infer_variable=infer_variable,
            shard=shard,
        )
    fingerprint = None
    if cache:
        fingerprint = _cache_fingerprint(
            corpus_path, path_idx_path, terminal_idx_path, infer_method,
            infer_variable, shard,
        )
        cached = _try_load_cache(corpus_path, fingerprint)
    else:
        cached = None

    path_vocab = read_vocab(path_idx_path)
    logger.info("path vocab size: %d", len(path_vocab))
    terminal_vocab = read_vocab(terminal_idx_path, extra_tokens=[QUESTION_TOKEN_NAME])
    logger.info("terminal vocab size: %d", len(terminal_vocab))

    if cached is not None:
        arrays, meta = cached["arrays"], cached["meta"]
        data = CorpusData(
            starts=arrays["starts"],
            paths=arrays["paths"],
            ends=arrays["ends"],
            row_splits=arrays["row_splits"],
            ids=arrays["ids"],
            labels=arrays["labels"],
            normalized_labels=meta["normalized_labels"],
            sources=meta["sources"],
            aliases=meta["aliases"],
            terminal_vocab=terminal_vocab,
            path_vocab=path_vocab,
            label_vocab=Vocab.from_state(meta["label_vocab"]),
            infer_method=infer_method,
            infer_variable=infer_variable,
            variable_indexes=arrays["variable_indexes"],
            shard=tuple(meta["shard"]) if meta.get("shard") else None,
            global_n_items=meta.get("global_n_items", -1),
        )
        logger.info("label vocab size: %d", len(data.label_vocab))
        logger.info(
            "corpus (cached): %d items, %d contexts", data.n_items, data.n_contexts
        )
        return data

    variable_indexes = _variable_indexes_of(terminal_vocab)
    logger.info("variable index size: %d", len(variable_indexes))

    native_arrays = None
    if native:
        try:
            from code2vec_tpu.extractor import parse_corpus_native

            native_arrays = parse_corpus_native(corpus_path)
        except Exception as e:  # missing toolchain, parse error, ...
            logger.warning(
                "native corpus parser unavailable (%s); using Python parser", e
            )

    def is_local(i: int) -> bool:
        return shard is None or i % shard[1] == shard[0]

    if native_arrays is not None:
        raw_starts, raw_paths, raw_ends, row_splits, ids_arr, headers, var_lists = (
            native_arrays
        )
        starts = raw_starts + QUESTION_TOKEN_INDEX
        ends = raw_ends + QUESTION_TOKEN_INDEX
        paths = raw_paths
        missing_id = ids_arr < 0  # records without a #id line: positional
        if missing_id.any():
            ids_arr = ids_arr.copy()
            ids_arr[missing_id] = np.nonzero(missing_id)[0]
        if shard is not None:
            # keep only this host's rows (vectorized CSR row gather); the
            # full parse was materialized by the C++ side — see docstring
            local = np.arange(shard[0], len(row_splits) - 1, shard[1])
            counts = np.diff(row_splits)[local]
            new_splits = np.zeros(len(local) + 1, np.int64)
            np.cumsum(counts, out=new_splits[1:])
            flat = np.repeat(
                row_splits[local] - new_splits[:-1], counts
            ) + np.arange(int(counts.sum()))
            starts, paths, ends = starts[flat], paths[flat], ends[flat]
            row_splits = new_splits
            ids_arr = ids_arr[local]
        parser_tag = "native parse"
    else:
        starts_parts: list[np.ndarray] = []
        paths_parts: list[np.ndarray] = []
        ends_parts: list[np.ndarray] = []
        counts: list[int] = []
        id_list: list[int] = []
        headers = []
        var_lists = []
        for record in iter_corpus_records(corpus_path):
            record_index = len(headers)
            id_list.append(record.id if record.id is not None else record_index)
            headers.append((record.label or "", record.source))
            var_lists.append(record.aliases)
            if not is_local(record_index):
                continue  # context arrays stay 1/count per host
            contexts = np.asarray(record.path_contexts, dtype=np.int32).reshape(-1, 3)
            starts_parts.append(contexts[:, 0] + QUESTION_TOKEN_INDEX)
            paths_parts.append(contexts[:, 1])
            ends_parts.append(contexts[:, 2] + QUESTION_TOKEN_INDEX)
            counts.append(len(contexts))
        row_splits = np.zeros(len(counts) + 1, dtype=np.int64)
        np.cumsum(counts, out=row_splits[1:])
        starts = (
            np.concatenate(starts_parts) if starts_parts else np.zeros(0, np.int32)
        )
        paths = np.concatenate(paths_parts) if paths_parts else np.zeros(0, np.int32)
        ends = np.concatenate(ends_parts) if ends_parts else np.zeros(0, np.int32)
        ids_arr = np.asarray(id_list, dtype=np.int64)
        if shard is not None:
            ids_arr = ids_arr[shard[0] :: shard[1]]
        parser_tag = "python parse"

    label_vocab, labels, normalized_labels, sources, aliases = (
        _build_label_state(headers, var_lists, infer_method, infer_variable)
    )

    global_n_items = len(headers)
    if shard is not None:
        index, count = shard
        labels = labels[index::count]
        normalized_labels = normalized_labels[index::count]
        sources = sources[index::count]
        aliases = aliases[index::count]

    data = CorpusData(
        starts=starts,
        paths=paths,
        ends=ends,
        row_splits=row_splits,
        ids=ids_arr,
        labels=np.asarray(labels, dtype=np.int32),
        normalized_labels=normalized_labels,
        sources=sources,
        aliases=aliases,
        terminal_vocab=terminal_vocab,
        path_vocab=path_vocab,
        label_vocab=label_vocab,
        infer_method=infer_method,
        infer_variable=infer_variable,
        variable_indexes=variable_indexes,
        shard=shard,
        global_n_items=global_n_items,
    )
    logger.info("label vocab size: %d", len(label_vocab))
    logger.info(
        "corpus (%s): %d items, %d contexts",
        parser_tag, data.n_items, data.n_contexts,
    )
    if cache and fingerprint is not None:
        _write_cache(corpus_path, fingerprint, data)
    return data
