"""``python -m code2vec_tpu`` — the training/HPO entry point."""

from code2vec_tpu.cli import main

main()
