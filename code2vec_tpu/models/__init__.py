"""Model family: the code2vec attention model and its head variants."""

from code2vec_tpu.models.code2vec import Code2Vec, Code2VecConfig
from code2vec_tpu.models.hierarchical import (
    HierarchicalAttentionPool,
    pool_vectors_by_group,
)
