"""The code2vec model as a Flax module.

Architecture parity with the reference Code2Vec nn.Module
(model/model.py:15-105), built TPU-first:

  terminal/path embedding gathers
    -> concat [start; path; end]
    -> Dense(no bias) -> LayerNorm -> tanh -> dropout      (context encoder)
    -> masked global-attention pooling                      (ops.attention)
    -> output head: plain Dense (bias zero-init) or ArcFace-style
       additive-angular-margin cosine head (model/model.py:33-42,71-83)

Differences from the reference, by design:
- compute dtype is configurable (bf16 on TPU keeps the MXU fed; params and
  softmax statistics stay f32);
- the margin head's dead ``th``/``mm`` constants (model/model.py:38-39,
  computed but never used in forward — SURVEY.md §2.2) are not replicated;
- embedding tables may be sharded over a mesh axis (see
  code2vec_tpu.parallel.shardings) — vocabs reach 360k+ rows (SURVEY.md §5.7).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, replace

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.nn.initializers import normal, zeros

from code2vec_tpu.ops.attention import attention_pool, streaming_attention_pool
from code2vec_tpu.ops.embed import embedding_lookup


@dataclass(frozen=True)
class Code2VecConfig:
    terminal_count: int
    path_count: int
    label_count: int
    terminal_embed_size: int = 100
    path_embed_size: int = 100
    encode_size: int = 300
    dropout_prob: float = 0.25
    angular_margin_loss: bool = False
    angular_margin: float = 0.5
    inverse_temp: float = 30.0
    dtype: jnp.dtype = jnp.float32  # compute dtype (bf16 for TPU throughput)
    use_pallas: bool = False  # Pallas kernels on the aggregation hot path
    pallas_block_b: int = 8  # batch-tile size of the Pallas kernels
    # which kernel serves the forward when use_pallas is set:
    # "pool_only"    fuse score->softmax->pool only (ops.pallas_attention);
    # "gather_split" XLA gathers rows, kernel fuses encode->attend->pool;
    # "fused"        in-kernel DMA gather too — gathered rows and encoded
    #                contexts never touch HBM (ops.fused_encode_pool);
    # "auto"         consult the autotuned schedule cache per traced
    #                (batch, width) shape (ops.autotune) — the tuner may
    #                also pick plain "xla". Param tree is IDENTICAL across
    #                impls, so checkpoints interchange freely.
    pallas_impl: str = "pool_only"
    # which lowering family serves the kernels (ops/backend.py): "auto"
    # resolves per C2V_KERNEL_BACKEND env then the actual device; "tpu" /
    # "gpu" pin the Pallas formulations (interpreted off-device); "cpu"
    # pins the compiled XLA strategy (never interprets); "interpret" pins
    # the TPU formulation under the Pallas interpreter (parity-test mode)
    pallas_backend: str = "auto"
    pallas_dma_depth: int = 2  # fused-impl gather double-buffer slots
    pallas_chunk_l: int = 128  # fused-impl bag-chunk lane tile
    # bag-softmax numerics of the fused kernel (ops/fused_encode_pool.py):
    # "materialize" (VMEM-resident encoded bag — the original kernel),
    # "online" / "two_pass" (flash-style chunked softmax, bounded VMEM at
    # any bag length), or "auto": materialize at widths <= longbag_width
    # (or everywhere when longbag_width is 0), online above it — unless a
    # cached autotune schedule says otherwise
    pallas_softmax: str = "auto"
    # widths STRICTLY ABOVE this are "longbag" shapes (0 = none): their
    # traces force the fused kernel with a chunked softmax, because every
    # other Pallas impl materializes O(L*E) VMEM and would not fit. Set by
    # the train loop to max_path_length when --max_contexts 0 extends the
    # ladder past the top rung; plain-XLA forwards (use_pallas=False) need
    # no forcing — XLA is HBM-bound at any width.
    longbag_width: int = 0
    # embedding-table storage for the gathers: "f32" (master weights) |
    # "bf16" | "int8" (per-row scale, dequant on load — ops.quant).
    # Serving/eval only: the train loop rejects quantized tables, and the
    # f32 master params remain in the tree (quantized storage is derived
    # in-graph unless the caller passes pre-quantized ``quant_tables``).
    table_dtype: str = "f32"
    # "xla" = jax.nn.softmax chain; "streaming" = the explicit exp/sum
    # decomposition (ops.attention.streaming_attention_pool) — same math,
    # different lowering; use_pallas overrides both
    attn_impl: str = "xla"
    # "concat" = [start;path;end] concat then one [3E,H] matmul (the
    # reference formulation, model/model.py:24,56-61); "split" = the same
    # kernel applied as three sliced matmuls summed — algebraically
    # identical, but skips materializing the [B, L, 3E] concat (and its
    # gradient) if XLA wasn't already fusing it. Param tree is identical
    # either way (input_dense/kernel [3E, H]), so checkpoints interchange.
    encoder_impl: str = "concat"
    embed_grad: str = "dense"  # embedding backward formulation (ops.embed)
    # round table/head vocab dims up to this multiple so they shard evenly
    # over the model mesh axis (parallel.shardings.pad_to_multiple); padded
    # embedding rows are never gathered and padded label columns are sliced
    # off before loss/argmax, so the math is identical to the unpadded model
    vocab_pad_multiple: int = 1

    def with_updates(self, **kw) -> "Code2VecConfig":
        return replace(self, **kw)

    def padded(self, count: int) -> int:
        from code2vec_tpu.parallel.shardings import pad_to_multiple

        return pad_to_multiple(count, max(self.vocab_pad_multiple, 1))


class _EmbedTable(nn.Module):
    """Bare embedding-table param with nn.Embed's param layout
    (``{<name>: {"embedding": [vocab, dim] f32}}``); the lookup itself is
    done by :func:`code2vec_tpu.ops.embed.embedding_lookup`."""

    vocab: int
    dim: int

    @nn.compact
    def __call__(self) -> jnp.ndarray:
        return self.param(
            "embedding", normal(stddev=1.0), (self.vocab, self.dim), jnp.float32
        )


class _SplitEncoder(nn.Module):
    """``concat([a,b,c]) @ W`` computed as ``a@W1 + b@W2 + c@W3`` on slices
    of the SAME ``kernel`` param ``nn.Dense(name="input_dense")`` would
    create (same path, shape, dtype, and default init → identical values
    from the same RNG), so the two encoder lowerings share checkpoints."""

    features: int
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, e_start, e_path, e_end):
        ds, dp = e_start.shape[-1], e_path.shape[-1]
        de = e_end.shape[-1]
        kernel = self.param(
            "kernel",
            nn.linear.default_kernel_init,  # nn.Dense's init (lecun_normal)
            (ds + dp + de, self.features),
            jnp.float32,
        ).astype(self.dtype)
        return (
            e_start @ kernel[:ds]
            + e_path @ kernel[ds : ds + dp]
            + e_end @ kernel[ds + dp :]
        )


class _DenseKernelParam(nn.Module):
    """Bare ``input_dense/kernel`` param with ``nn.Dense``'s path, shape,
    dtype, and default init — same RNG fold → identical values — so the
    fused-kernel path (which consumes the raw kernel) shares checkpoints
    with both unfused encoder lowerings (the ``_SplitEncoder`` trick)."""

    features: int
    in_features: int

    @nn.compact
    def __call__(self) -> jnp.ndarray:
        return self.param(
            "kernel",
            nn.linear.default_kernel_init,
            (self.in_features, self.features),
            jnp.float32,
        )


class _LayerNormParams(nn.Module):
    """Bare ``input_layer_norm/{scale,bias}`` params matching
    ``nn.LayerNorm``'s names/inits, for the fused path (the kernel applies
    the normalization itself)."""

    features: int

    @nn.compact
    def __call__(self) -> tuple[jnp.ndarray, jnp.ndarray]:
        scale = self.param(
            "scale", nn.initializers.ones, (self.features,), jnp.float32
        )
        bias = self.param(
            "bias", nn.initializers.zeros, (self.features,), jnp.float32
        )
        return scale, bias


class Code2Vec(nn.Module):
    """Returns ``(logits, code_vector, attention)`` like the reference
    forward (model/model.py:88); the margin head uses ``labels`` to place
    the training margin and serves plain scaled-cosine logits without
    them (inference)."""

    config: Code2VecConfig

    def _resolve_kernel(self, batch: int, width: int):
        """(impl, schedule) for this trace — ``None`` impl means the plain
        XLA path. ``pallas_impl="auto"`` consults the persisted autotune
        schedule cache (ops.autotune) at trace time with the concrete
        ``(batch, width)``: a cached winner is used as-is (it may be plain
        "xla"), a miss falls back to the configured pool-only kernel with
        zero search on the hot path."""
        c = self.config
        if not c.use_pallas:
            return None, None
        import dataclasses as _dc

        from code2vec_tpu.ops.autotune import KernelSchedule, lookup_schedule

        if c.pallas_softmax not in ("auto", "materialize", "online", "two_pass"):
            raise ValueError(
                f"unknown pallas_softmax {c.pallas_softmax!r}: expected "
                "'auto', 'materialize', 'online', or 'two_pass'"
            )
        longbag = bool(c.longbag_width) and width > c.longbag_width
        configured_softmax = (
            c.pallas_softmax
            if c.pallas_softmax != "auto"
            else ("online" if longbag else "materialize")
        )
        configured = KernelSchedule(
            impl=c.pallas_impl if c.pallas_impl != "auto" else "pool_only",
            block_b=c.pallas_block_b,
            dma_depth=c.pallas_dma_depth,
            chunk_l=c.pallas_chunk_l,
            softmax=configured_softmax,
            backend=c.pallas_backend,
            source="config",
        )
        if c.pallas_impl == "auto":
            sched = lookup_schedule(
                batch, width, c.terminal_embed_size, c.path_embed_size,
                c.encode_size, c.table_dtype, default=configured,
            )
        elif c.pallas_impl in ("pool_only", "gather_split", "fused"):
            sched = configured
        else:
            raise ValueError(
                f"unknown pallas_impl {c.pallas_impl!r}: expected "
                "'pool_only', 'gather_split', 'fused', or 'auto'"
            )
        if longbag and (
            sched.impl != "fused" or sched.softmax == "materialize"
        ):
            # a longbag width must stream: force the fused kernel with a
            # chunked softmax (honoring an explicit two_pass preference /
            # a cached chunked schedule) — any other variant materializes
            # O(L*E) or O(L*H) VMEM and cannot fit an unbounded bag
            sched = _dc.replace(
                sched,
                impl="fused",
                softmax=(
                    sched.softmax
                    if sched.softmax != "materialize"
                    else ("online" if c.pallas_softmax in ("auto", "materialize")
                          else c.pallas_softmax)
                ),
            )
        return sched.impl, sched

    def _lookup(self, store, ids: jnp.ndarray) -> jnp.ndarray:
        """Quant-aware row gather: the f32 master table goes through
        ops.embed (selectable backward); quantized storage dequants on
        load (ops.quant — serving/eval, no backward)."""
        from code2vec_tpu.ops.quant import QuantTable, dequantize_rows

        c = self.config
        if isinstance(store, QuantTable):
            return dequantize_rows(store, ids, c.dtype)
        return embedding_lookup(
            store, ids, compute_dtype=c.dtype, grad_mode=c.embed_grad
        )

    @nn.compact
    def __call__(
        self,
        starts: jnp.ndarray,  # int32 [B, L]
        paths: jnp.ndarray,  # int32 [B, L]
        ends: jnp.ndarray,  # int32 [B, L]
        labels: jnp.ndarray | None = None,  # int32 [B], margin head only
        deterministic: bool = True,
        embed_offsets: tuple[jnp.ndarray, jnp.ndarray] | None = None,
        quant_tables: tuple | None = None,
    ):
        """``embed_offsets``: optional ``(off_se [B, 2L, E_t], off_p
        [B, L, E_p])`` zero tensors added to the gathered embeddings — the
        touched-rows optimizer differentiates w.r.t. these instead of the
        tables, so the dense ``[vocab, dim]`` table gradient is never
        materialized (train/table_opt.py). Zeros leave the forward math
        bit-identical.

        ``quant_tables``: optional pre-quantized ``(terminal, path)``
        ``ops.quant.QuantTable`` pair used for the gathers when
        ``config.table_dtype != "f32"`` — serving paths (predict.Predictor)
        quantize ONCE at load instead of deriving quantized storage from
        the f32 master params inside every traced forward."""
        c = self.config
        from code2vec_tpu.ops.quant import TABLE_DTYPES, quantize_table

        if c.table_dtype not in TABLE_DTYPES:
            raise ValueError(
                f"unknown table_dtype {c.table_dtype!r}: expected one of "
                f"{TABLE_DTYPES}"
            )

        # the param tree matches nn.Embed's ({name: {"embedding": table}}),
        # but the lookup goes through ops.embed so the backward formulation
        # is selectable (c.embed_grad); tables init per torch nn.Embedding
        # defaults (std-normal, model/model.py:21-22)
        terminal_table = _EmbedTable(
            c.padded(c.terminal_count), c.terminal_embed_size,
            name="terminal_embedding",
        )()
        path_table = _EmbedTable(
            c.padded(c.path_count), c.path_embed_size, name="path_embedding"
        )()

        # serving storage: quantized tables ride NEXT TO the f32 master
        # params (which stay the training/source of truth) — pre-quantized
        # when the caller did it once at load, derived in-graph otherwise
        if c.table_dtype == "f32":
            t_store, p_store = terminal_table, path_table
        elif quant_tables is not None:
            t_store, p_store = quant_tables
        else:
            t_store = quantize_table(terminal_table, c.table_dtype)
            p_store = quantize_table(path_table, c.table_dtype)

        b, l = starts.shape
        impl, sched = self._resolve_kernel(b, l)
        mask = (starts > 0).astype(jnp.float32)  # PAD = 0 (model/model.py:64)
        # xavier-normal over the reference's [E, 1] shape -> std sqrt(2/(E+1))
        # (model/model.py:31)
        attention_param = self.param(
            "attention",
            normal(stddev=math.sqrt(2.0 / (c.encode_size + 1))),
            (c.encode_size,),
            jnp.float32,
        )

        if impl in ("fused", "gather_split"):
            # the fully-fused path: raw encoder params (identical tree to
            # the unfused modules — checkpoints interchange) feed the
            # gather→encode→attend→pool kernel (ops.fused_encode_pool)
            from code2vec_tpu.ops.fused_encode_pool import (
                fused_encode_attend_pool,
            )

            in_features = 2 * c.terminal_embed_size + c.path_embed_size
            dense_kernel = _DenseKernelParam(
                c.encode_size, in_features, name="input_dense"
            )()
            ln_scale, ln_bias = _LayerNormParams(
                c.encode_size, name="input_layer_norm"
            )()
            drop_mask = None
            if 0.0 < c.dropout_prob < 1.0 and not deterministic:
                # pre-scaled keep mask applied by the kernel after tanh —
                # nn.Dropout semantics (same keep prob and scaling; the
                # stream differs from nn.Dropout's module-scoped RNG fold)
                keep = 1.0 - c.dropout_prob
                drop_mask = (
                    jax.random.bernoulli(
                        self.make_rng("dropout"), keep,
                        (b, l, c.encode_size),
                    ).astype(jnp.float32)
                    / keep
                )
            off_se = off_p = None
            if embed_offsets is not None:
                off_se, off_p = embed_offsets
            code_vector_f32, attention = fused_encode_attend_pool(
                t_store, p_store, starts, paths, ends, mask,
                dense_kernel, ln_scale, ln_bias, attention_param,
                drop_mask=drop_mask, off_se=off_se, off_p=off_p,
                impl=impl, block_b=sched.block_b,
                dma_depth=sched.dma_depth, chunk_l=sched.chunk_l,
                softmax_mode=sched.softmax,
                compute_dtype=c.dtype,
                backend=None if sched.backend == "auto" else sched.backend,
            )
        else:
            code_vector_f32, attention = self._unfused_forward(
                t_store, p_store, starts, paths, ends, mask,
                attention_param, deterministic, embed_offsets,
                impl, sched,
            )

        if c.angular_margin_loss:
            logits = self._angular_margin_head(code_vector_f32, labels)
        else:
            logits = nn.Dense(
                c.padded(c.label_count),
                use_bias=True,
                dtype=jnp.float32,
                param_dtype=jnp.float32,
                bias_init=zeros,  # explicit zero bias (model/model.py:42)
                name="output_dense",
            )(code_vector_f32)
            logits = logits[:, : c.label_count]  # drop sharding-pad columns

        return logits, code_vector_f32, attention

    def _unfused_forward(
        self, t_store, p_store, starts, paths, ends, mask,
        attention_param, deterministic, embed_offsets, impl, sched,
    ):
        """XLA gather + encode, with the pool stage dispatched across the
        lowerings (pool-only Pallas kernel / streaming softmax / plain
        XLA). ``impl`` is "pool_only", or None/"xla" for no kernel (the
        autotuner may pick "xla" even under use_pallas)."""
        c = self.config
        embed_se = self._lookup(
            t_store, jnp.concatenate([starts, ends], axis=1)
        )
        if embed_offsets is not None:
            embed_se = embed_se + embed_offsets[0]
        embed_starts, embed_ends = jnp.split(embed_se, 2, axis=1)
        embed_paths = self._lookup(p_store, paths)
        if embed_offsets is not None:
            embed_paths = embed_paths + embed_offsets[1]
        if c.encoder_impl == "split":
            contexts = _SplitEncoder(
                c.encode_size, dtype=c.dtype, name="input_dense"
            )(embed_starts, embed_paths, embed_ends)
        elif c.encoder_impl == "concat":
            contexts = jnp.concatenate(
                [embed_starts, embed_paths, embed_ends], axis=-1
            )
            contexts = nn.Dense(
                c.encode_size,
                use_bias=False,
                dtype=c.dtype,
                param_dtype=jnp.float32,
                name="input_dense",
            )(contexts)
        else:  # fail loudly, same contract as attn_impl
            raise ValueError(
                f"unknown encoder_impl {c.encoder_impl!r}: expected "
                "'concat' or 'split'"
            )
        contexts = nn.LayerNorm(
            dtype=jnp.float32, param_dtype=jnp.float32, name="input_layer_norm"
        )(contexts.astype(jnp.float32)).astype(c.dtype)
        contexts = jnp.tanh(contexts)

        if 0.0 < c.dropout_prob < 1.0:  # gate mirrors model/model.py:26-29
            contexts = nn.Dropout(rate=c.dropout_prob)(
                contexts, deterministic=deterministic
            )

        if impl == "pool_only":
            from code2vec_tpu.ops.pallas_attention import pallas_attention_pool

            code_vector, attention = pallas_attention_pool(
                contexts, mask, attention_param.astype(c.dtype),
                block_b=sched.block_b,
                backend=None if sched.backend == "auto" else sched.backend,
            )
        elif c.attn_impl == "streaming":
            code_vector, attention = streaming_attention_pool(
                contexts, mask, attention_param.astype(c.dtype)
            )
        elif c.attn_impl == "xla":
            code_vector, attention = attention_pool(
                contexts, mask, attention_param.astype(c.dtype)
            )
        else:  # fail loudly: a typo'd lowering name must not run (and get
            # measured as) the default one
            raise ValueError(
                f"unknown attn_impl {c.attn_impl!r}: expected 'xla' or 'streaming'"
            )
        return code_vector.astype(jnp.float32), attention

    def _angular_margin_head(
        self, code_vector: jnp.ndarray, labels: jnp.ndarray | None
    ) -> jnp.ndarray:
        """ArcFace-style head (model/model.py:71-80): cosine logits with an
        additive angular margin on the true class, falling back to the plain
        cosine where cos <= 0, scaled by the inverse temperature.

        With ``labels=None`` (inference — the reference never runs this
        head without labels) the margin is skipped and the scaled cosine
        logits are returned directly: the margin exists to shape the
        TRAINING decision boundary; at inference ArcFace-family models
        rank classes by plain cosine similarity."""
        c = self.config
        weight = self.param(
            "output_margin_weight",
            nn.initializers.xavier_uniform(),
            (c.padded(c.label_count), c.encode_size),
            jnp.float32,
        )
        normalized_cv = code_vector / (
            jnp.linalg.norm(code_vector, axis=-1, keepdims=True) + 1e-12
        )
        normalized_w = weight / (
            jnp.linalg.norm(weight, axis=-1, keepdims=True) + 1e-12
        )
        cosine = (normalized_cv @ normalized_w.T)[:, : c.label_count]
        if labels is None:
            return cosine * c.inverse_temp
        sine = jnp.sqrt(jnp.clip(1.0 - cosine**2, 0.0, 1.0))
        cos_m = math.cos(c.angular_margin)
        sin_m = math.sin(c.angular_margin)
        phi = cosine * cos_m - sine * sin_m
        phi = jnp.where(cosine > 0, phi, cosine)
        one_hot = jax.nn.one_hot(labels, c.label_count, dtype=cosine.dtype)
        logits = one_hot * phi + (1.0 - one_hot) * cosine
        return logits * c.inverse_temp
