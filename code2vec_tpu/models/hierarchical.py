"""Hierarchical two-level pooling: method vectors → file/class vectors.

The base model embeds one METHOD per forward (a bag of path-contexts →
attention pool → ``[H]`` code vector). Whole-file / whole-class code
search needs one vector per FILE, and the natural second level is the
same aggregation applied one tier up: the file's method vectors form a
bag, a learned salience direction scores them, masked softmax weights
them, and the weighted sum is the file vector — structurally identical
to ``ops.attention.attention_pool`` with methods in the bag axis.

Two entry points:

- :func:`pool_vectors_by_group` — host-side (numpy) pooling of exported
  method vectors grouped by an arbitrary key (source file, class, repo
  directory). This is what ``export.export_file_vectors`` and the serving
  ``embed_file`` op run: it needs no new trained parameters, because the
  checkpoint's method-level ``attention`` param is reused as the salience
  direction — method vectors live in the SAME ``H``-dim space as the
  encoded contexts that param was trained to score (a code vector is a
  convex combination of them), so the trained direction transfers one
  level up. ``attn_param=None`` falls back to masked mean pooling.

- :class:`HierarchicalAttentionPool` — the flax module form ([G, M, H]
  batched groups with a mask), carrying its OWN ``file_attention`` param
  for runs that fine-tune the file level (e.g. a contrastive file-search
  head, ROADMAP item 1). Init matches the method-level attention param's
  (xavier-normal over the reference's [H, 1] shape).

File vectors round-trip through the existing stack untouched: they are
``[H]`` f32 rows, so ``formats/vectors_io.py`` writes them (``file.vec``),
``serve/retrieval.py`` indexes them (exact or IVF-PQ), and the
``neighbors`` op returns them — whole-file code search with zero new
serving machinery.
"""

from __future__ import annotations

import math

import flax.linen as nn
import jax.numpy as jnp
import numpy as np
from jax.nn.initializers import normal

from code2vec_tpu.ops.attention import attention_pool

__all__ = [
    "HierarchicalAttentionPool",
    "pool_vectors",
    "pool_vectors_by_group",
]


def pool_vectors(
    vectors: np.ndarray,  # [M, H] f32 method vectors (one group)
    attn_param: np.ndarray | None,  # [H] salience direction; None = mean
) -> np.ndarray:
    """Attention-pool one group of method vectors into one ``[H]`` vector.

    Same arithmetic as ``ops.attention.attention_pool`` for a single row
    with an all-ones mask (scores → shifted softmax → weighted sum),
    computed in float64 host-side so group size cannot perturb the result
    at f32 resolution.
    """
    vectors = np.asarray(vectors, np.float64)
    if vectors.ndim != 2 or not len(vectors):
        raise ValueError(
            f"need a non-empty [M, H] vector matrix, got {vectors.shape}"
        )
    if attn_param is None:
        pooled = vectors.mean(axis=0)
    else:
        scores = vectors @ np.asarray(attn_param, np.float64)
        z = np.exp(scores - scores.max())
        weights = z / z.sum()
        pooled = weights @ vectors
    return pooled.astype(np.float32)


def pool_vectors_by_group(
    vectors: np.ndarray,  # [N, H] f32 method vectors
    group_ids,  # length-N group key per method (str/int, any hashable)
    attn_param: np.ndarray | None = None,
) -> tuple[list, np.ndarray]:
    """Group method vectors by key and pool each group —
    ``(group_keys, [G, H] f32)``, groups in first-appearance order (the
    corpus/export row order, so repeated exports are stable)."""
    vectors = np.asarray(vectors, np.float32)
    if len(vectors) != len(group_ids):
        raise ValueError(
            f"{len(vectors)} vectors but {len(group_ids)} group ids"
        )
    members: dict = {}
    for row, gid in enumerate(group_ids):
        members.setdefault(gid, []).append(row)
    keys = list(members)
    if not keys:
        dim = vectors.shape[-1] if vectors.ndim == 2 else 0
        return keys, np.zeros((0, dim), np.float32)
    pooled = np.stack(
        [pool_vectors(vectors[members[gid]], attn_param) for gid in keys]
    )
    return keys, pooled


class HierarchicalAttentionPool(nn.Module):
    """``(file_vector [G, H] f32, attention [G, M] f32)`` from batched
    method-vector groups; ``mask`` marks real methods (1) vs padding rows
    (0). Masking semantics are ``attention_pool``'s (an all-masked group
    degenerates to uniform over M), so padded groups pool exactly like
    padded bags do one level down."""

    encode_size: int

    @nn.compact
    def __call__(self, method_vectors: jnp.ndarray, mask: jnp.ndarray):
        attn = self.param(
            "file_attention",
            normal(stddev=math.sqrt(2.0 / (self.encode_size + 1))),
            (self.encode_size,),
            jnp.float32,
        )
        file_vector, attention = attention_pool(
            method_vectors.astype(jnp.float32),
            mask.astype(jnp.float32),
            attn,
        )
        return file_vector.astype(jnp.float32), attention
