"""Metric sinks — the reference's three observability backends
(reference: main.py:183-205): stdlib logging (``train.loop.logging_sink``),
Floyd-style JSON lines on stdout, and TensorBoard scalars.

Sinks are plain callables ``(epoch, metrics_dict) -> None`` so the train
loop stays backend-agnostic; compose any number of them via the ``sinks``
tuple of :func:`code2vec_tpu.train.loop.train`. The loop dispatches them as
consumers of the run event stream (``code2vec_tpu.obs.events``), so sink
output and the ``--events_dir`` JSONL log derive from the same metrics
dict. A sink may expose a ``close()`` attribute; the train loop calls it in
its ``finally`` block (the TensorBoard writer needs the final flush).

JSON hygiene: training can legitimately produce non-finite metrics (a
diverged ``train_loss`` is ``nan``/``inf``); ``json.dumps`` would print
bare ``NaN``/``Infinity`` — not JSON — so the line sinks serialize them as
``null`` with the original in a string ``"raw"`` field
(:func:`code2vec_tpu.obs.events.metric_record`).
"""

from __future__ import annotations

import json
import logging
import sys
from typing import Callable

from code2vec_tpu.obs.events import metric_record

logger = logging.getLogger(__name__)

MetricSink = Callable[[int, dict[str, float]], None]


def logging_sink(epoch: int, metrics: dict[str, float]) -> None:
    """Per-epoch JSON metric lines through stdlib logging — the default
    sink (reference emits the same shape, main.py:183-205)."""
    logger.info("epoch %d", epoch)
    for name, value in metrics.items():
        logger.info("%s", json.dumps(metric_record(name, value)))


def floyd_sink(epoch: int, metrics: dict[str, float]) -> None:
    """One ``{"metric": name, "value": value}`` JSON line per metric on
    stdout (reference ``--env floyd``, main.py:183-190)."""
    for name, value in metrics.items():
        sys.stdout.write(json.dumps(metric_record(name, value)) + "\n")
    sys.stdout.flush()


def tensorboard_sink(log_dir: str) -> MetricSink:
    """TensorBoard scalar sink (reference ``--env tensorboard``,
    main.py:152-155,199-205): each metric becomes a scalar series keyed by
    its name, stepped by epoch. The returned sink carries a ``close()``
    attribute closing the writer (final flush); the train loop calls it on
    exit.

    Requires ``tensorboardX`` (present in this image); raises ImportError
    with a clear message otherwise — the import is deferred exactly like the
    reference's lazy ``--env``-gated import (main.py:87-88).
    """
    try:
        from tensorboardX import SummaryWriter
    except ImportError as e:  # pragma: no cover - env without tensorboardX
        raise ImportError(
            "tensorboard_sink requires tensorboardX; install it or drop "
            "--env tensorboard"
        ) from e

    writer = SummaryWriter(log_dir)

    def sink(epoch: int, metrics: dict[str, float]) -> None:
        for name, value in metrics.items():
            writer.add_scalar(name, value, epoch)
        writer.flush()

    sink.close = writer.close
    return sink
