"""Kernel backend resolution: device kind → lowering strategy.

Every Pallas kernel in the repo used to carry its own copy of the same
heuristic — ``interpret = jax.default_backend() != "tpu"`` — which meant
off-TPU callers always paid the Pallas *interpreter* (correct, slow) and
no caller could ask for a genuinely compiled non-TPU lowering. This
module is the single replacement: one resolver maps the requested
backend (explicit argument > ``C2V_KERNEL_BACKEND`` env > device auto)
to a :class:`BackendStrategy`, and every kernel wrapper consumes that.

Strategies (``BackendStrategy.strategy``):

- ``"pallas_tpu"`` — the TPU kernel formulation (DMA gathers, VMEM
  scratch, semaphores). Compiled on TPU; anywhere else it runs under the
  Pallas interpreter (``interpret=True``) — the pre-existing test mode,
  kept bit-for-bit so parity suites still validate the TPU kernel bodies
  on CPU.
- ``"pallas_gpu"`` — the GPU (Triton-lowered) kernel formulation:
  XLA-side gathers feed portable kernel bodies (no TPU memory spaces,
  no DMA/semaphores) behind warp-friendly block specs. Compiled on GPU;
  elsewhere it runs under the interpreter so the GPU formulation is
  validated even on CPU-only CI.
- ``"cpu"`` — the compiled CPU strategy: plain XLA formulations with the
  kernels' exact masking/softmax semantics. NEVER enters the Pallas
  interpreter (``interpret`` is always False) — this is what serving and
  bench paths get on CPU by default.

Resolution precedence (``resolve``):

1. An explicit ``interpret`` bool with no explicit backend — the legacy
   per-call flag. ``True`` pins the TPU formulation under the
   interpreter; ``False`` compiles for the device we are actually on.
2. An explicit ``backend`` argument (``models.Code2VecConfig
   .pallas_backend``, autotune's per-variant backend axis).
3. ``C2V_KERNEL_BACKEND`` env — ``auto`` | ``tpu`` | ``gpu`` | ``cpu``
   | ``interpret``. The test suite pins ``interpret`` (tests/conftest.py)
   so existing suites exercise the kernel bodies unchanged; the CI
   kernel-portability job pins ``cpu`` to run the same suites compiled.
4. Device auto: tpu→pallas_tpu, gpu→pallas_gpu, cpu→cpu.
"""

from __future__ import annotations

import dataclasses
import os

import jax

ENV_VAR = "C2V_KERNEL_BACKEND"
BACKENDS = ("auto", "tpu", "gpu", "cpu", "interpret")
STRATEGIES = ("pallas_tpu", "pallas_gpu", "cpu")


@dataclasses.dataclass(frozen=True)
class BackendStrategy:
    """One resolved lowering decision (hashable — goes into jit statics
    and provenance records)."""

    backend: str  # device family the lowering targets: "tpu"|"gpu"|"cpu"
    strategy: str  # "pallas_tpu" | "pallas_gpu" | "cpu"
    interpret: bool  # Pallas interpreter? (always False for "cpu")

    @property
    def label(self) -> str:
        """Compact provenance form: ``cpu``, ``pallas_tpu``,
        ``pallas_tpu:interpret``, ``pallas_gpu`` …"""
        return self.strategy + (":interpret" if self.interpret else "")


def device_backend() -> str:
    """The platform jax actually runs on, folded to {tpu, gpu, cpu}."""
    b = jax.default_backend()
    return b if b in ("tpu", "gpu") else "cpu"


def _for_family(family: str, interpret: bool | None) -> BackendStrategy:
    dev = device_backend()
    if family == "tpu":
        itp = (dev != "tpu") if interpret is None else bool(interpret)
        return BackendStrategy("tpu", "pallas_tpu", itp)
    if family == "gpu":
        itp = (dev != "gpu") if interpret is None else bool(interpret)
        return BackendStrategy("gpu", "pallas_gpu", itp)
    # the compiled CPU strategy is plain XLA by construction — there is
    # no interpreter to fall into
    return BackendStrategy("cpu", "cpu", False)


def resolve(
    backend: str | None = None, interpret: bool | None = None
) -> BackendStrategy:
    """Resolve the lowering strategy for one kernel call site.

    ``backend`` is one of :data:`BACKENDS` (or None = consult the env /
    device). ``interpret`` is the legacy per-call flag: an explicit bool
    with no explicit backend wins over everything (True pins the TPU
    formulation under the interpreter — what parity tests pass); combined
    with an explicit tpu/gpu backend it overrides that family's
    compiled-vs-interpret default.
    """
    req = (backend or "").strip().lower() or None
    if req is None:
        if interpret is not None:
            if interpret:
                return BackendStrategy(device_backend(), "pallas_tpu", True)
            return _for_family(device_backend(), False)
        req = os.environ.get(ENV_VAR, "").strip().lower() or "auto"
    if req not in BACKENDS:
        raise ValueError(f"backend must be one of {BACKENDS}, got {req!r}")
    if req == "interpret":
        return BackendStrategy(device_backend(), "pallas_tpu", True)
    if req == "auto":
        req = device_backend()
    return _for_family(req, interpret)
