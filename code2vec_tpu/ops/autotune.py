"""Autocomp-style kernel-schedule autotuning with a persisted cache.

Per-shape schedule search — not a single hand-picked tiling — is where
accelerator kernels win ("Autocomp: A Powerful and Portable Code Optimizer
for Tensor Accelerators"; "LLM-Aided Compilation for Tensor Accelerators",
PAPERS.md), and it pays doubly here because the bucket ladder (PR 4) gives
a SMALL STATIC set of ``(bucket width, batch)`` shapes to tune for.

For each ``(device kind, batch, bag width, embed dims, table dtype)`` key
the tuner enumerates kernel variants — plain XLA, the pool-only Pallas
kernel, and the gather-split / fully-fused kernels of
``ops/fused_encode_pool.py`` across ``block_b`` batch tiling, lane chunk,
and DMA pipeline depth — times each on the real device, and persists the
winner to a JSON cache. The cache is CONSULTED AT TRACE TIME
(``lookup_schedule``, called from ``models/code2vec.py`` when
``pallas_impl="auto"``), so a second run with the same shape set performs
zero timing runs: every schedule loads from disk.

Accounting is observable: ``obs.runtime.global_health()`` counters
``autotune_cache_hit`` / ``autotune_cache_miss`` / ``autotune_timing_run``
/ ``autotune_schedule_stored`` let callers (tests, ``bench.py
--kernel-ab``) assert exactly how much search a run paid.

Schedules carry a ``backend`` axis (``ops/backend.py``): variant spaces,
miss-fallback defaults, and per-entry interpret provenance all follow
the resolved lowering strategy (TPU Pallas / GPU Triton / compiled CPU /
interpreter), so one cache file holds per-backend winners side by side —
``ShapeKey.device_kind`` already keys entries per device. Old entries
deserialize with ``backend="auto"`` (resolve-at-call-time), no version
bump.

``--dry`` writes default schedules without timing — the serialization
smoke CI runs on every PR::

    python -m code2vec_tpu.ops.autotune --autotune --dry --cache /tmp/c.json
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time

DEFAULT_CACHE_ENV = "C2V_AUTOTUNE_CACHE"
_CACHE_VERSION = 1

IMPLS = ("xla", "pool_only", "gather_split", "fused")


@dataclasses.dataclass(frozen=True)
class KernelSchedule:
    """One tuned kernel configuration (the search space point)."""

    impl: str = "pool_only"  # "xla" | "pool_only" | "gather_split" | "fused"
    block_b: int = 8  # batch-tile rows per kernel program
    dma_depth: int = 2  # gather double-buffer slots (fused impl only)
    chunk_l: int = 128  # bag-chunk lane tile the gather pipelines over
    # bag-softmax numerics of the fused impl (ops/fused_encode_pool.py):
    # "materialize" keeps the encoded bag in VMEM scratch; "online" /
    # "two_pass" stream it flash-style in bounded VMEM (the longbag modes).
    # Pre-PR-13 cache entries deserialize with the default — unchanged
    # behavior, no cache version bump.
    softmax: str = "materialize"
    # the backend axis (ops/backend.py): "auto" resolves at call time
    # (env/device — the pre-existing behavior, so old cache entries
    # deserialize unchanged); "tpu"/"gpu"/"cpu"/"interpret" pin the
    # lowering this variant was timed under. Same no-version-bump
    # tolerant-from_dict contract as the softmax field.
    backend: str = "auto"
    source: str = "default"  # "default" | "dry" | "autotune" | "cache"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "KernelSchedule":
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in fields})


@dataclasses.dataclass(frozen=True)
class ShapeKey:
    """What a schedule is keyed by: the device plus everything that changes
    the kernel's tiling economics. Vocab size is deliberately absent — the
    gather cost per row depends on row width, not table height."""

    device_kind: str
    batch: int
    width: int  # bag width L (one per bucket-ladder rung)
    terminal_embed: int
    path_embed: int
    encode: int
    table_dtype: str  # "f32" | "bf16" | "int8"

    def cache_key(self) -> str:
        return (
            f"{self.device_kind}|b={self.batch}|l={self.width}"
            f"|et={self.terminal_embed}|ep={self.path_embed}"
            f"|h={self.encode}|dt={self.table_dtype}"
        )


LUT_IMPLS = ("xla", "pallas")


@dataclasses.dataclass(frozen=True)
class LutSchedule:
    """One tuned configuration of the ANN LUT-scoring kernel
    (``ann/lut_kernel.py``) — the second variant axis this cache carries."""

    impl: str = "xla"  # "xla" | "pallas"
    chunk_c: int = 128  # cell rows DMA'd per chunk (pallas impl only)
    dma_depth: int = 2  # double-buffer slots (pallas impl only)
    backend: str = "auto"  # lowering axis, same contract as KernelSchedule
    source: str = "default"  # "default" | "dry" | "autotune" | "cache"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "LutSchedule":
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in fields})


@dataclasses.dataclass(frozen=True)
class LutShapeKey:
    """LUT-kernel schedule key: device plus the knobs that change the
    scoring economics — subspace count M (LUT height and per-row gather
    width), cell count and padded cell capacity (the DMA'd slab), and the
    shortlist (top-k width downstream of the kernel). The ``lut|`` prefix
    keeps these entries disjoint from the forward-kernel keys in the one
    shared cache file."""

    device_kind: str
    m: int
    n_list: int
    capacity: int
    shortlist: int

    def cache_key(self) -> str:
        return (
            f"lut|{self.device_kind}|m={self.m}|nl={self.n_list}"
            f"|cap={self.capacity}|sl={self.shortlist}"
        )


def device_kind() -> str:
    import jax

    return jax.devices()[0].device_kind


def default_cache_path() -> str:
    env = os.environ.get(DEFAULT_CACHE_ENV, "").strip()
    if env:
        return env
    return os.path.join(
        os.path.expanduser("~"), ".cache", "code2vec_tpu",
        "autotune_schedules.json",
    )


def _counters():
    from code2vec_tpu.obs.runtime import global_health

    h = global_health()
    return {
        "hit": h.counter("autotune_cache_hit"),
        "miss": h.counter("autotune_cache_miss"),
        "timing": h.counter("autotune_timing_run"),
        "stored": h.counter("autotune_schedule_stored"),
    }


def counters_snapshot() -> dict[str, int]:
    c = _counters()
    return {
        "autotune_cache_hit": c["hit"].value,
        "autotune_cache_miss": c["miss"].value,
        "autotune_timing_run": c["timing"].value,
        "autotune_schedule_stored": c["stored"].value,
    }


class ScheduleCache:
    """JSON-backed schedule store; loads tolerantly (a corrupt or
    version-skewed file is an empty cache, never a crash) and saves
    atomically (tmp + ``os.replace``)."""

    def __init__(self, path: str) -> None:
        self.path = path
        self.entries: dict[str, dict] = {}
        self._load()

    def _load(self) -> None:
        try:
            with open(self.path, encoding="utf-8") as f:
                payload = json.load(f)
        except (OSError, json.JSONDecodeError):
            return
        if (
            not isinstance(payload, dict)
            or payload.get("version") != _CACHE_VERSION
            or not isinstance(payload.get("entries"), dict)
        ):
            return
        self.entries = payload["entries"]

    def save(self) -> None:
        os.makedirs(os.path.dirname(os.path.abspath(self.path)), exist_ok=True)
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(
                {"version": _CACHE_VERSION, "entries": self.entries}, f,
                indent=1, sort_keys=True,
            )
        os.replace(tmp, self.path)

    def get(self, key: ShapeKey) -> KernelSchedule | None:
        entry = self.entries.get(key.cache_key())
        if not isinstance(entry, dict) or "schedule" not in entry:
            return None
        try:
            sched = KernelSchedule.from_dict(entry["schedule"])
        except TypeError:
            return None
        return dataclasses.replace(sched, source="cache")

    def get_lut(self, key: LutShapeKey) -> LutSchedule | None:
        entry = self.entries.get(key.cache_key())
        if not isinstance(entry, dict) or "schedule" not in entry:
            return None
        try:
            sched = LutSchedule.from_dict(entry["schedule"])
        except TypeError:
            return None
        return dataclasses.replace(sched, source="cache")

    def put(
        self, key: ShapeKey, schedule: KernelSchedule,
        timings_ms: dict | None = None, interpret: bool | None = None,
    ) -> None:
        self.entries[key.cache_key()] = {
            "schedule": schedule.to_dict(),
            "timings_ms": timings_ms,
            "interpret": interpret,
            "created": time.time(),
        }
        _counters()["stored"].inc()


_cache_singleton: ScheduleCache | None = None


def get_cache(path: str | None = None) -> ScheduleCache:
    """The process-wide cache. An explicit ``path`` pins (and reloads) the
    singleton; ``path=None`` returns whatever is pinned — so a run that
    pointed the cache somewhere (``--autotune_cache``) keeps it for every
    later trace-time ``lookup_schedule`` in the process."""
    global _cache_singleton
    if path is None:
        if _cache_singleton is None:
            _cache_singleton = ScheduleCache(default_cache_path())
        return _cache_singleton
    if _cache_singleton is None or _cache_singleton.path != path:
        _cache_singleton = ScheduleCache(path)
    return _cache_singleton


def reset_cache() -> None:
    """Drop the memoized cache (tests; a fresh env var takes effect)."""
    global _cache_singleton
    _cache_singleton = None


def _resolve_backend(backend: str | None = None):
    from code2vec_tpu.ops import backend as _backend

    return _backend.resolve(backend=backend)


def default_schedule() -> KernelSchedule:
    """The configured fallback on a cache miss, per resolved backend: the
    pool-only kernel where the Pallas lowerings run (TPU, and the
    interpret test mode — the pre-existing default), the compiled
    gather_split chain under the cpu/gpu strategies (off-TPU the
    interpreter is exactly what ``auto`` must avoid)."""
    bs = _resolve_backend()
    if bs.strategy == "cpu":
        return KernelSchedule(
            impl="gather_split", backend="cpu", source="default"
        )
    if bs.strategy == "pallas_gpu":
        return KernelSchedule(
            impl="gather_split", backend="gpu", source="default"
        )
    return KernelSchedule(impl="pool_only", source="default")


def lookup_schedule(
    batch: int,
    width: int,
    terminal_embed: int,
    path_embed: int,
    encode: int,
    table_dtype: str = "f32",
    *,
    default: KernelSchedule | None = None,
    cache: ScheduleCache | None = None,
) -> KernelSchedule:
    """Trace-time schedule lookup (``pallas_impl="auto"``). A cache hit
    returns the persisted winner; a miss falls back to ``default``
    (:func:`default_schedule` unless overridden) WITHOUT timing anything —
    search happens only in :func:`autotune`, never on the training hot
    path."""
    key = ShapeKey(
        device_kind=device_kind(), batch=int(batch), width=int(width),
        terminal_embed=int(terminal_embed), path_embed=int(path_embed),
        encode=int(encode), table_dtype=table_dtype,
    )
    cache = cache or get_cache()
    c = _counters()
    found = cache.get(key)
    if found is not None:
        c["hit"].inc()
        return found
    c["miss"].inc()
    return default or default_schedule()


def consult_schedules(
    keys: list[ShapeKey], cache: ScheduleCache | None = None
) -> list[dict]:
    """The serving-startup consultation (``--expect-cached``-style warmup):
    for every shape the server is about to compile an executable for, look
    up the persisted schedule WITHOUT any timing and return one provenance
    record per key — ``{"key", "schedule", "cached"}``. Hits/misses land on
    the shared ``autotune_*`` counters, so a deployment can assert 'the
    warm cache covered every serving shape' exactly like the CLI's
    ``--expect-cached`` does; the records go into the serve run manifest."""
    cache = cache or get_cache()
    c = _counters()
    out: list[dict] = []
    for key in keys:
        found = cache.get(key)
        if found is not None:
            c["hit"].inc()
            schedule = found
        else:
            c["miss"].inc()
            schedule = default_schedule()
        out.append(
            {
                "key": key.cache_key(),
                "schedule": schedule.to_dict(),
                "cached": found is not None,
            }
        )
    return out


def default_lut_schedule() -> LutSchedule:
    """The configured fallback on a cache miss: the Pallas kernels where
    they compile (TPU DMA kernel, GPU Triton kernel), the take-based XLA
    formulation everywhere else — including the interpret test mode,
    where ``xla`` was already the pre-existing CPU default."""
    bs = _resolve_backend()
    if not bs.interpret and bs.strategy == "pallas_tpu":
        return LutSchedule(impl="pallas", backend="tpu", source="default")
    if not bs.interpret and bs.strategy == "pallas_gpu":
        return LutSchedule(impl="pallas", backend="gpu", source="default")
    if bs.strategy == "cpu":
        return LutSchedule(impl="xla", backend="cpu", source="default")
    return LutSchedule(impl="xla", source="default")


def lookup_lut_schedule(
    m: int,
    n_list: int,
    capacity: int,
    shortlist: int,
    *,
    default: LutSchedule | None = None,
    cache: ScheduleCache | None = None,
) -> LutSchedule:
    """Trace-time LUT-kernel schedule lookup (``AnnSearcher``). Same
    contract as :func:`lookup_schedule`: a hit returns the persisted
    winner, a miss falls back WITHOUT timing anything; both land on the
    shared ``autotune_*`` counters."""
    key = LutShapeKey(
        device_kind=device_kind(), m=int(m), n_list=int(n_list),
        capacity=int(capacity), shortlist=int(shortlist),
    )
    cache = cache or get_cache()
    c = _counters()
    found = cache.get_lut(key)
    if found is not None:
        c["hit"].inc()
        return found
    c["miss"].inc()
    return default or default_lut_schedule()


def enumerate_lut_variants(
    capacity: int, backend: str | None = None
) -> list[LutSchedule]:
    """The LUT kernel's search space, per resolved backend. TPU (and the
    interpret test mode, which must exercise the same kernel bodies): the
    XLA gather formulation plus the Pallas DMA kernel across chunk size x
    pipeline depth — chunks that do not divide the padded cell capacity
    are pruned (the kernel would silently clamp them to one lane). CPU:
    the compiled take-based formulation only (no interpreter in a timing
    run). GPU: XLA plus the Triton-shaped kernel (no chunk/depth axis —
    it has no DMA pipeline)."""
    bs = _resolve_backend(backend)
    if bs.strategy == "cpu":
        return [LutSchedule(impl="xla", backend="cpu")]
    if bs.strategy == "pallas_gpu":
        return [
            LutSchedule(impl="xla", backend="gpu"),
            LutSchedule(impl="pallas", backend="gpu"),
        ]
    cap = max(int(capacity), 1)
    chunks = sorted({c for c in (128, 256, 512) if c <= cap and cap % c == 0})
    if not chunks:
        chunks = [cap]
    variants = [LutSchedule(impl="xla")]
    for cc in chunks:
        for depth in (1, 2):
            variants.append(
                LutSchedule(impl="pallas", chunk_c=cc, dma_depth=depth)
            )
    return variants


def _synth_lut_inputs(key: LutShapeKey, n_probe: int, q: int, seed: int = 0):
    import jax.numpy as jnp
    import numpy as np

    rng = np.random.default_rng(seed)
    lut = jnp.asarray(
        rng.normal(size=(q, key.m, 256)).astype(np.float32)
    )
    probed = jnp.asarray(
        rng.integers(0, key.n_list, (q, n_probe)).astype(np.int32)
    )
    codes = jnp.asarray(
        rng.integers(0, 256, (key.n_list, key.capacity, key.m)).astype(
            np.uint8
        )
    )
    scales = jnp.asarray(
        rng.random((key.n_list, key.capacity)).astype(np.float32)
    )
    bias = jnp.zeros((key.n_list, key.capacity), jnp.float32)
    return lut, probed, codes, scales, bias


def time_lut_variant(
    schedule: LutSchedule, inputs, iters: int = 3, repeats: int = 2
) -> float:
    """Best-of wall time (seconds per call) for one LUT variant; compile
    excluded via an untimed warmup call."""
    import jax

    from code2vec_tpu.ann.lut_kernel import lut_score_cells

    def fn():
        return lut_score_cells(
            *inputs, impl=schedule.impl, chunk_c=schedule.chunk_c,
            dma_depth=schedule.dma_depth,
            backend=None if schedule.backend == "auto" else schedule.backend,
        )

    jax.block_until_ready(fn())
    best = float("inf")
    for _ in range(max(repeats, 1)):
        t0 = time.perf_counter()
        for _ in range(max(iters, 1)):
            out = fn()
        jax.block_until_ready(out)
        best = min(best, (time.perf_counter() - t0) / max(iters, 1))
    return best


def _lut_variant_label(s: LutSchedule) -> str:
    label = "xla" if s.impl == "xla" else f"pallas/c{s.chunk_c}/d{s.dma_depth}"
    if s.backend != "auto":
        label += f"@{s.backend}"
    return label


def autotune_lut(
    keys: list[LutShapeKey],
    *,
    cache: ScheduleCache | None = None,
    dry: bool = False,
    iters: int = 3,
    repeats: int = 2,
    n_probe: int = 8,
    q_batch: int = 8,
    force: bool = False,
) -> dict[str, LutSchedule]:
    """Search (or dry-stamp) a LUT-kernel schedule per missing key and
    persist — the :func:`autotune` contract on the LUT variant axis."""
    cache = cache or get_cache()
    c = _counters()
    interpret = _resolve_backend().interpret
    out: dict[str, LutSchedule] = {}
    dirty = False
    for key in keys:
        cached = None if force else cache.get_lut(key)
        if cached is not None:
            c["hit"].inc()
            out[key.cache_key()] = cached
            continue
        c["miss"].inc()
        if dry:
            sched = dataclasses.replace(default_lut_schedule(), source="dry")
            cache.put(key, sched, timings_ms=None, interpret=interpret)
            out[key.cache_key()] = sched
            dirty = True
            continue
        inputs = _synth_lut_inputs(key, min(n_probe, key.n_list), q_batch)
        timings: dict[str, float] = {}
        best_sched, best_t = None, float("inf")
        for variant in enumerate_lut_variants(key.capacity):  # env-resolved
            c["timing"].inc()
            try:
                t = time_lut_variant(variant, inputs, iters=iters,
                                     repeats=repeats)
            except Exception as exc:  # noqa: BLE001 - same contract as the
                # forward tuner: a variant that fails to lower is skipped
                timings[_lut_variant_label(variant)] = float("nan")
                print(
                    f"autotune: lut variant {_lut_variant_label(variant)} "
                    f"failed on {key.cache_key()}: "
                    f"{type(exc).__name__}: {exc}",
                    file=sys.stderr,
                )
                continue
            timings[_lut_variant_label(variant)] = round(t * 1e3, 4)
            if t < best_t:
                best_sched, best_t = variant, t
        if best_sched is None:
            raise RuntimeError(
                f"every LUT variant failed for {key.cache_key()}"
            )
        sched = dataclasses.replace(best_sched, source="autotune")
        cache.put(key, sched, timings_ms=timings, interpret=interpret)
        out[key.cache_key()] = sched
        dirty = True
    if dirty:
        cache.save()
    return out


def enumerate_variants(
    batch: int, width: int, table_dtype: str, backend: str | None = None
) -> list[KernelSchedule]:
    """The search space for one shape, per resolved backend.

    TPU (and the interpret test mode): plain XLA, pool-only,
    gather-split, and fully-fused — the fused impl additionally across
    the chunked-softmax axis (``chunk_l`` × ``dma_depth`` ×
    two-pass-vs-online, PR 13) — across batch tiling / DMA pipeline
    depth / lane chunk. Tile sizes larger than the (padded) batch are
    pruned — they would all alias the same single-program grid. Variants
    that fail to lower on a shape (e.g. ``materialize`` blowing VMEM at a
    longbag width) are skipped by the tuner's try/except, so the space
    can stay uniform across widths.

    CPU: plain XLA vs the compiled gather_split chain across ``block_b``
    (the ``lax.map`` tile size — the only tiling economics left). GPU:
    XLA, pool-only, and gather_split across ``block_b`` (warp-friendly
    tile candidates; the DMA axes do not exist off-TPU)."""
    bp = max(batch, 1)
    blocks = [b for b in (8, 16, 32) if b <= max(bp, 8)]
    if not blocks:
        blocks = [8]
    bs = _resolve_backend(backend)
    if bs.strategy == "cpu":
        variants = [KernelSchedule(impl="xla", backend="cpu")]
        for b in blocks:
            variants.append(
                KernelSchedule(impl="gather_split", block_b=b, backend="cpu")
            )
        return variants
    if bs.strategy == "pallas_gpu":
        variants = [KernelSchedule(impl="xla", backend="gpu")]
        for b in blocks:
            variants.append(
                KernelSchedule(impl="pool_only", block_b=b, backend="gpu")
            )
            variants.append(
                KernelSchedule(impl="gather_split", block_b=b, backend="gpu")
            )
        return variants
    lane_pad = -(-max(width, 1) // 128) * 128
    chunks = sorted({c for c in (128, 256) if c <= lane_pad and lane_pad % c == 0})
    variants = [KernelSchedule(impl="xla")]
    for b in blocks:
        variants.append(KernelSchedule(impl="pool_only", block_b=b))
    for b in blocks:
        variants.append(KernelSchedule(impl="gather_split", block_b=b))
    for b in blocks[:2]:
        for depth in (1, 2):
            for cl in chunks:
                variants.append(
                    KernelSchedule(
                        impl="fused", block_b=b, dma_depth=depth, chunk_l=cl
                    )
                )
    # the chunked-softmax axis: one block size (the schedule dimension that
    # matters here is the streaming strategy, not batch tiling) × depth ×
    # chunk × {online, two_pass}
    for mode in ("online", "two_pass"):
        for depth in (1, 2):
            for cl in chunks:
                variants.append(
                    KernelSchedule(
                        impl="fused", block_b=blocks[0], dma_depth=depth,
                        chunk_l=cl, softmax=mode,
                    )
                )
    return variants


def _synth_inputs(key: ShapeKey, vocab: int, seed: int = 0):
    import jax.numpy as jnp
    import numpy as np

    from code2vec_tpu.ops.quant import maybe_quantize

    rng = np.random.default_rng(seed)
    tt = jnp.asarray(rng.normal(size=(vocab, key.terminal_embed)).astype(np.float32))
    pt = jnp.asarray(rng.normal(size=(vocab, key.path_embed)).astype(np.float32))
    t_table = maybe_quantize(tt, key.table_dtype)
    p_table = maybe_quantize(pt, key.table_dtype)
    b, l, h = key.batch, key.width, key.encode
    data = dict(
        starts=jnp.asarray(rng.integers(1, vocab, (b, l)).astype(np.int32)),
        paths=jnp.asarray(rng.integers(1, vocab, (b, l)).astype(np.int32)),
        ends=jnp.asarray(rng.integers(1, vocab, (b, l)).astype(np.int32)),
        mask=jnp.asarray((rng.random((b, l)) > 0.4).astype(np.float32)),
        dense_kernel=jnp.asarray(
            rng.normal(
                size=(2 * key.terminal_embed + key.path_embed, h)
            ).astype(np.float32)
            * 0.05
        ),
        ln_scale=jnp.ones(h, jnp.float32),
        ln_bias=jnp.zeros(h, jnp.float32),
        attn_param=jnp.asarray(rng.normal(size=h).astype(np.float32)),
    )
    return t_table, p_table, data


def _build_forward(schedule: KernelSchedule, t_table, p_table, data):
    """A jitted code-vector forward for one variant over fixed inputs."""
    import jax
    import jax.numpy as jnp

    from code2vec_tpu.ops.fused_encode_pool import (
        fused_encode_attend_pool,
        xla_reference_forward,
    )

    if schedule.impl == "xla":

        def fn():
            return xla_reference_forward(
                t_table, p_table, data["starts"], data["paths"], data["ends"],
                data["mask"], data["dense_kernel"], data["ln_scale"],
                data["ln_bias"], data["attn_param"],
            )[0]

    elif schedule.impl == "pool_only":
        from code2vec_tpu.ops.fused_encode_pool import xla_encode_contexts
        from code2vec_tpu.ops.pallas_attention import pallas_attention_pool
        from code2vec_tpu.ops.quant import QuantTable, dequantize_rows

        def lookup(table, ids):
            if isinstance(table, QuantTable):
                return dequantize_rows(table, ids)
            return table[ids]

        def fn():
            # the shared reference encode (ops/fused_encode_pool.py) — the
            # tuner must time exactly what the model runs, not a re-derived
            # copy that can drift
            enc = xla_encode_contexts(
                lookup(t_table, data["starts"]),
                lookup(p_table, data["paths"]),
                lookup(t_table, data["ends"]),
                data["dense_kernel"], data["ln_scale"], data["ln_bias"],
            )
            return pallas_attention_pool(
                enc, data["mask"], data["attn_param"],
                block_b=schedule.block_b,
                backend=(
                    None if schedule.backend == "auto" else schedule.backend
                ),
            )[0]

    elif schedule.impl in ("gather_split", "fused"):

        def fn():
            return fused_encode_attend_pool(
                t_table, p_table, data["starts"], data["paths"], data["ends"],
                data["mask"], data["dense_kernel"], data["ln_scale"],
                data["ln_bias"], data["attn_param"],
                impl=schedule.impl, block_b=schedule.block_b,
                dma_depth=schedule.dma_depth, chunk_l=schedule.chunk_l,
                softmax_mode=schedule.softmax,
                backend=(
                    None if schedule.backend == "auto" else schedule.backend
                ),
            )[0]

    else:
        raise ValueError(f"unknown impl {schedule.impl!r}")
    return jax.jit(fn)


def time_variant(
    schedule: KernelSchedule, t_table, p_table, data,
    iters: int = 3, repeats: int = 2,
) -> float:
    """Best-of wall time (seconds per forward) for one variant on the real
    device; compile excluded via an untimed warmup call."""
    import jax

    fn = _build_forward(schedule, t_table, p_table, data)
    jax.block_until_ready(fn())  # compile + warm, untimed
    best = float("inf")
    for _ in range(max(repeats, 1)):
        t0 = time.perf_counter()
        for _ in range(max(iters, 1)):
            out = fn()
        jax.block_until_ready(out)
        best = min(best, (time.perf_counter() - t0) / max(iters, 1))
    return best


def autotune(
    keys: list[ShapeKey],
    *,
    cache: ScheduleCache | None = None,
    dry: bool = False,
    iters: int = 3,
    repeats: int = 2,
    vocab: int | None = None,
    force: bool = False,
) -> dict[str, KernelSchedule]:
    """Search (or dry-stamp) a schedule for every key not already cached,
    persist the cache once, and return the full key→schedule mapping.

    ``dry=True`` writes the default schedule per missing key WITHOUT any
    timing — it exists so schedule-cache serialization is exercised
    cheaply (the CI smoke) and so a tuner can pre-create entries to edit
    by hand. Timed entries record per-variant ms for provenance.
    """
    cache = cache or get_cache()
    c = _counters()
    interpret = _resolve_backend().interpret
    vocab = vocab or int(os.environ.get("C2V_AUTOTUNE_VOCAB", 20_000))
    out: dict[str, KernelSchedule] = {}
    dirty = False
    for key in keys:
        cached = None if force else cache.get(key)
        if cached is not None:
            c["hit"].inc()
            out[key.cache_key()] = cached
            continue
        c["miss"].inc()
        if dry:
            sched = dataclasses.replace(default_schedule(), source="dry")
            cache.put(key, sched, timings_ms=None, interpret=interpret)
            out[key.cache_key()] = sched
            dirty = True
            continue
        t_table, p_table, data = _synth_inputs(key, vocab)
        timings: dict[str, float] = {}
        best_sched, best_t = None, float("inf")
        for variant in enumerate_variants(key.batch, key.width, key.table_dtype):
            c["timing"].inc()
            try:
                t = time_variant(
                    variant, t_table, p_table, data, iters=iters,
                    repeats=repeats,
                )
            except Exception as exc:  # noqa: BLE001 - a variant that fails
                # to lower on this backend is skipped, not fatal: the
                # tuner's whole job is to pick among what actually runs
                timings[_variant_label(variant)] = float("nan")
                print(
                    f"autotune: variant {_variant_label(variant)} failed on "
                    f"{key.cache_key()}: {type(exc).__name__}: {exc}",
                    file=sys.stderr,
                )
                continue
            timings[_variant_label(variant)] = round(t * 1e3, 4)
            if t < best_t:
                best_sched, best_t = variant, t
        if best_sched is None:
            raise RuntimeError(
                f"every kernel variant failed for {key.cache_key()}"
            )
        sched = dataclasses.replace(best_sched, source="autotune")
        cache.put(key, sched, timings_ms=timings, interpret=interpret)
        out[key.cache_key()] = sched
        dirty = True
    if dirty:
        cache.save()
    return out


def _variant_label(s: KernelSchedule) -> str:
    if s.impl == "xla":
        label = "xla"
    elif s.impl == "pool_only":
        label = f"pool_only/b{s.block_b}"
    elif s.impl == "gather_split":
        label = f"gather_split/b{s.block_b}"
    else:
        label = f"fused/b{s.block_b}/d{s.dma_depth}/c{s.chunk_l}"
        if s.softmax != "materialize":
            label += f"/{s.softmax}"
    if s.backend != "auto":
        label += f"@{s.backend}"
    return label


def keys_for(
    batch: int,
    widths: list[int],
    terminal_embed: int,
    path_embed: int,
    encode: int,
    table_dtypes: list[str],
    kind: str | None = None,
) -> list[ShapeKey]:
    kind = kind or device_kind()
    return [
        ShapeKey(
            device_kind=kind, batch=batch, width=w,
            terminal_embed=terminal_embed, path_embed=path_embed,
            encode=encode, table_dtype=dt,
        )
        for w in widths
        for dt in table_dtypes
    ]


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        description="kernel-schedule autotuner (see module docstring)"
    )
    parser.add_argument("--autotune", action="store_true",
                        help="accepted for CLI symmetry; this module IS the "
                             "autotuner")
    parser.add_argument("--dry", action="store_true",
                        help="write default schedules without timing "
                             "(serialization smoke)")
    parser.add_argument("--cache", type=str, default=None,
                        help=f"cache path (default ${DEFAULT_CACHE_ENV} or "
                             "~/.cache/code2vec_tpu/autotune_schedules.json)")
    parser.add_argument("--batch", type=int, default=8)
    parser.add_argument("--widths", type=str, default="16,32",
                        help="comma list of bag widths (the bucket ladder)")
    parser.add_argument("--terminal-embed", type=int, default=8)
    parser.add_argument("--path-embed", type=int, default=8)
    parser.add_argument("--encode", type=int, default=16)
    parser.add_argument("--table-dtypes", type=str, default="f32",
                        help="comma list from {f32,bf16,int8}")
    parser.add_argument("--iters", type=int, default=3)
    parser.add_argument("--vocab", type=int, default=None)
    parser.add_argument("--force", action="store_true",
                        help="re-tune even for cached shapes")
    parser.add_argument("--backend", type=str, default=None,
                        choices=("auto", "tpu", "gpu", "cpu", "interpret"),
                        help="pin the kernel lowering backend for this run "
                             "(sets C2V_KERNEL_BACKEND for the shared "
                             "resolver, ops/backend.py)")
    parser.add_argument("--expect-cached", action="store_true",
                        help="exit 2 if any shape missed the cache (the "
                             "round-trip assertion: a second identical run "
                             "must do zero search)")
    parser.add_argument("--lut", action="store_true",
                        help="tune the ANN LUT-scoring kernel "
                             "(ann/lut_kernel.py) instead of the forward "
                             "kernel; keys from the --lut-* knobs")
    parser.add_argument("--lut-m", type=int, default=8)
    parser.add_argument("--lut-n-list", type=int, default=64)
    parser.add_argument("--lut-capacity", type=int, default=256)
    parser.add_argument("--lut-shortlist", type=int, default=128)
    args = parser.parse_args(argv)

    if args.backend:
        from code2vec_tpu.ops.backend import ENV_VAR

        os.environ[ENV_VAR] = args.backend

    cache = ScheduleCache(args.cache or default_cache_path())
    before = counters_snapshot()
    if args.lut:
        lut_keys = [
            LutShapeKey(
                device_kind=device_kind(), m=args.lut_m,
                n_list=args.lut_n_list, capacity=args.lut_capacity,
                shortlist=args.lut_shortlist,
            )
        ]
        schedules = autotune_lut(
            lut_keys, cache=cache, dry=args.dry, iters=args.iters,
            force=args.force,
        )
    else:
        keys = keys_for(
            args.batch,
            [int(w) for w in args.widths.split(",") if w.strip()],
            args.terminal_embed, args.path_embed, args.encode,
            [d.strip() for d in args.table_dtypes.split(",") if d.strip()],
        )
        schedules = autotune(
            keys, cache=cache, dry=args.dry, iters=args.iters,
            vocab=args.vocab, force=args.force,
        )
    after = counters_snapshot()
    delta = {k: after[k] - before[k] for k in after}
    print(
        json.dumps(
            {
                "device_kind": device_kind(),
                "backend": _resolve_backend().label,
                "cache": cache.path,
                "dry": args.dry,
                "schedules": {k: s.to_dict() for k, s in schedules.items()},
                "counters": delta,
            }
        ),
        flush=True,
    )
    if args.expect_cached and delta["autotune_cache_miss"] > 0:
        print(
            f"autotune: --expect-cached but {delta['autotune_cache_miss']} "
            "shape(s) missed the cache",
            file=sys.stderr,
        )
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
