"""Compute ops: XLA reference implementations + Pallas TPU kernels."""

from code2vec_tpu.ops.attention import (
    attention_pool,
    masked_attention_weights,
    streaming_attention_pool,
)
