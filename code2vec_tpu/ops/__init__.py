"""Compute ops: XLA reference implementations + Pallas TPU kernels.

Heavy modules stay import-on-demand (``fused_encode_pool`` pulls pallas;
``autotune`` touches the device) — only the dependency-light XLA pool and
the quantized-table containers are re-exported eagerly.
"""

from code2vec_tpu.ops.attention import (
    attention_pool,
    masked_attention_weights,
    streaming_attention_pool,
)
from code2vec_tpu.ops.quant import (
    QuantTable,
    dequantize_rows,
    quantize_table,
)
