"""Fused masked-attention pooling as a Pallas TPU kernel.

One VMEM-resident pass per batch tile fuses the whole aggregation chain
(score matvec -> mask -> softmax -> weighted sum; reference semantics
model/model.py:63-69,90-105): the [TB, L, E] context tile is read from HBM
exactly once and only the [TB, E] code vector and [TB, L] weights go back —
the XLA path materializes the score/weight intermediates between fusions in
the large-bag regime.

Autodiff: forward runs the kernel; the backward pass is closed-form XLA
(softmax VJP) over the saved weights — exact, and itself fully fused by XLA.

The wrapper pads B to the batch-tile and L to the lane width (128); padded
bag columns are scored hard -inf inside the kernel (below even the finite
NINF of user-masked positions), so padding is invisible in the outputs —
including the degenerate all-masked row, which matches the XLA path's
uniform-over-real-L behavior exactly.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from code2vec_tpu.analysis.contracts import shape_contract
from code2vec_tpu.ops.attention import NINF, POOL_CONTRACT
from code2vec_tpu.ops.backend import BackendStrategy
from code2vec_tpu.ops.backend import resolve as resolve_backend

_BLOCK_B = 8
_LANE = 128


def _tile_pool(ctx, mask, attn, real_l: int):
    """The per-tile pool arithmetic — shared verbatim by the Pallas kernel
    and the compiled CPU strategy so their outputs are bitwise-equal.

    Lane-padding columns (l >= real_l) get a hard -inf — distinct from the
    finite NINF that *user*-masked positions get (parity with
    model/model.py:93) — so that a fully-masked row degenerates to uniform
    over the real bag length exactly like the XLA path, instead of leaking
    mass into the padding."""
    # VPU form throughout: Mosaic cannot lower batched dot_general, and
    # at these shapes (E <= a few hundred) the reductions are
    # bandwidth-bound anyway
    ctx32 = ctx.astype(jnp.float32)
    scores = jnp.sum(ctx32 * attn[0][None, None, :], axis=2)  # [TB, Lp]
    masked = scores * mask + (1.0 - mask) * NINF
    tb, lp = masked.shape
    col = jax.lax.broadcasted_iota(jnp.int32, (tb, lp), 1)
    masked = jnp.where(col < real_l, masked, -jnp.inf)
    masked = masked - jnp.max(masked, axis=-1, keepdims=True)
    e = jnp.exp(masked)
    weights = e / jnp.sum(e, axis=-1, keepdims=True)
    cv = jnp.sum(ctx32 * weights[:, :, None], axis=1)  # [TB, E]
    return cv, weights


def _make_kernel(real_l: int):
    """Kernel closure over the un-padded bag length (see ``_tile_pool``
    for the masking semantics)."""

    def _kernel(ctx_ref, mask_ref, attn_ref, cv_ref, w_ref):
        cv, weights = _tile_pool(
            ctx_ref[:], mask_ref[:].astype(jnp.float32), attn_ref[:], real_l
        )
        cv_ref[:] = cv.astype(cv_ref.dtype)
        w_ref[:] = weights

    return _kernel


def _pad_to(x: jnp.ndarray, axis: int, multiple: int) -> jnp.ndarray:
    size = x.shape[axis]
    target = -(-size // multiple) * multiple
    if target == size:
        return x
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, target - size)
    return jnp.pad(x, pad)


def _forward(contexts, mask, attn_param, *, block_b: int,
             strategy: BackendStrategy):
    b, bag, enc = contexts.shape
    ctx_p = _pad_to(_pad_to(contexts, 0, block_b), 1, _LANE)
    mask_p = _pad_to(_pad_to(mask.astype(jnp.float32), 0, block_b), 1, _LANE)
    bp, lp = ctx_p.shape[0], ctx_p.shape[1]
    attn = attn_param.reshape(1, enc).astype(jnp.float32)

    if strategy.strategy == "cpu":
        # compiled CPU strategy: sweep the identical tile arithmetic over
        # the same blocks in plain XLA — bitwise-equal to the interpreter
        # without entering it
        n_tiles = bp // block_b
        cv, weights = jax.lax.map(
            lambda t: _tile_pool(t[0], t[1], attn, bag),
            (
                ctx_p.reshape(n_tiles, block_b, lp, enc),
                mask_p.reshape(n_tiles, block_b, lp),
            ),
        )
        return (
            cv.reshape(bp, enc).astype(jnp.float32)[:b],
            weights.reshape(bp, lp)[:b, :bag],
        )

    ms = pltpu.VMEM if strategy.strategy != "pallas_gpu" else None
    grid = (bp // block_b,)
    cv, weights = pl.pallas_call(
        _make_kernel(bag),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_b, lp, enc), lambda i: (i, 0, 0), memory_space=ms),
            pl.BlockSpec((block_b, lp), lambda i: (i, 0), memory_space=ms),
            pl.BlockSpec((1, enc), lambda i: (0, 0), memory_space=ms),
        ],
        out_specs=[
            pl.BlockSpec((block_b, enc), lambda i: (i, 0), memory_space=ms),
            pl.BlockSpec((block_b, lp), lambda i: (i, 0), memory_space=ms),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bp, enc), jnp.float32),
            jax.ShapeDtypeStruct((bp, lp), jnp.float32),
        ],
        interpret=strategy.interpret,
    )(ctx_p, mask_p, attn)
    return cv[:b], weights[:b, :bag]


def compat_def_partition(p, *, partition, infer_sharding_from_operands,
                         sharding_rule=None) -> None:
    """``custom_partitioning.def_partition`` across jax versions.

    ``sharding_rule`` (the Shardy einsum-like spec) only exists on newer
    jax; 0.4.37's GSPMD partitioner needs only the infer/partition pair.
    Probed by signature, not try/except — a TypeError raised *inside* a
    user callback must not be misread as an unsupported kwarg."""
    import inspect

    kwargs = dict(
        partition=partition,
        infer_sharding_from_operands=infer_sharding_from_operands,
    )
    params = inspect.signature(type(p).def_partition).parameters
    if sharding_rule is not None and "sharding_rule" in params:
        kwargs["sharding_rule"] = sharding_rule
    p.def_partition(**kwargs)


_partitioned_forward_cache: dict = {}


def _get_partitioned_forward(block_b: int, strategy: BackendStrategy):
    """The pallas forward wrapped in ``custom_partitioning`` so GSPMD can
    shard it batch-wise over a mesh instead of replicating the Mosaic
    custom call behind a full all-gather. The rule: batch follows the
    operand sharding, bag/encode dims are forced replicated per shard (the
    kernel's softmax needs the whole bag) — GSPMD inserts the resharding
    if an upstream op sharded them."""
    key = (block_b, strategy)
    if key not in _partitioned_forward_cache:
        from jax.experimental.custom_partitioning import custom_partitioning
        from jax.sharding import NamedSharding, PartitionSpec as P

        def fwd(contexts, mask, attn_param):
            return _forward(
                contexts, mask, attn_param, block_b=block_b, strategy=strategy
            )

        def _batch_spec(arg_shapes):
            spec = arg_shapes[0].sharding.spec
            return spec[0] if len(spec) else None

        def infer_sharding(mesh, arg_shapes, result_shape):
            b = _batch_spec(arg_shapes)
            return (
                NamedSharding(mesh, P(b, None)),
                NamedSharding(mesh, P(b, None)),
            )

        def partition(mesh, arg_shapes, result_shape):
            b = _batch_spec(arg_shapes)
            arg_shardings = (
                NamedSharding(mesh, P(b, None, None)),
                NamedSharding(mesh, P(b, None)),
                NamedSharding(mesh, P()),
            )
            out_shardings = (
                NamedSharding(mesh, P(b, None)),
                NamedSharding(mesh, P(b, None)),
            )
            return mesh, fwd, out_shardings, arg_shardings

        p = custom_partitioning(fwd)
        compat_def_partition(
            p,
            partition=partition,
            infer_sharding_from_operands=infer_sharding,
            sharding_rule="b l e, b l, e -> b e, b l",
        )
        _partitioned_forward_cache[key] = p
    return _partitioned_forward_cache[key]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _pool(contexts, mask, attn_param, block_b, strategy):
    return _get_partitioned_forward(block_b, strategy)(
        contexts, mask, attn_param
    )


def _pool_fwd(contexts, mask, attn_param, block_b, strategy):
    cv, weights = _get_partitioned_forward(block_b, strategy)(
        contexts, mask, attn_param
    )
    return (cv, weights), (contexts, mask, attn_param, weights)


def _pool_bwd(block_b, strategy, residuals, grads):
    contexts, mask, attn_param, weights = residuals
    g_cv, g_w = grads
    ctx32 = contexts.astype(jnp.float32)
    mask32 = mask.astype(jnp.float32)
    g_cv = g_cv.astype(jnp.float32)

    # dL/dw_l: through the weighted sum, plus any direct grad on the weights
    dldw = jnp.einsum("be,ble->bl", g_cv, ctx32)
    if g_w is not None:
        dldw = dldw + g_w.astype(jnp.float32)
    # softmax VJP: ds = w * (dldw - sum_k w_k dldw_k); masked positions have
    # w == 0 exactly, so their ds vanishes
    ds = weights * (dldw - jnp.sum(weights * dldw, axis=-1, keepdims=True))
    ds = ds * mask32  # d(masked score)/d(raw score) = mask

    d_ctx = (
        weights[..., None] * g_cv[:, None, :]
        + ds[..., None] * attn_param.astype(jnp.float32)[None, None, :]
    )
    d_attn = jnp.einsum("bl,ble->e", ds, ctx32)
    d_mask = None  # mask is data, not a differentiable input
    return (
        d_ctx.astype(contexts.dtype),
        jnp.zeros_like(mask) if d_mask is None else d_mask,
        d_attn.astype(attn_param.dtype),
    )


_pool.defvjp(_pool_fwd, _pool_bwd)


@shape_contract(**POOL_CONTRACT)
def pallas_attention_pool(
    contexts: jnp.ndarray,  # [B, L, E]
    mask: jnp.ndarray,  # [B, L]
    attn_param: jnp.ndarray,  # [E]
    block_b: int = _BLOCK_B,
    interpret: bool | None = None,
    backend: str | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Drop-in replacement for ops.attention.attention_pool.

    ``backend``/``interpret`` route through the shared resolver
    (``ops/backend.py``): the resolved strategy picks the TPU kernel, the
    GPU (Triton) lowering, or the compiled CPU tile sweep — an explicit
    ``interpret=True`` keeps its legacy meaning (TPU formulation under
    the Pallas interpreter, the parity-test mode).
    """
    bs = resolve_backend(backend=backend, interpret=interpret)
    return _pool(contexts, mask, attn_param, block_b, bs)
