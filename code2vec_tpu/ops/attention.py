"""Masked global-attention pooling over a bag of context vectors.

The model's aggregation step (reference: model/model.py:63-69,90-105): one
learned vector ``a`` scores every context, PAD positions are masked to -inf,
softmax over the bag axis, weighted sum produces the code vector.

This is the XLA implementation; XLA already fuses the chain well. A fused
Pallas variant (for the large-bag regime, where keeping the [B, L, E]
context tensor out of HBM round-trips matters) lives in
code2vec_tpu.ops.pallas_attention.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from code2vec_tpu.analysis.contracts import shape_contract, spec

# Same sentinel the reference uses for masked scores (model/model.py:12).
NINF = -3.4e38

# trace-time input contract shared by the pool implementations (XLA,
# streaming, Pallas): symbols bind per call, so B/L/E must agree across
# the three arguments but are free across calls (bucketed widths each
# trace once). Checked once per trace — zero steady-state cost.
POOL_CONTRACT = {
    "contexts": spec("B,L,E", "float"),
    "mask": spec("B,L"),
    "attn_param": spec("E", "float"),
}


@shape_contract(scores=spec("B,L"), mask=spec("B,L"))
def masked_attention_weights(scores: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Softmax over the bag axis with PAD positions masked out.

    Replicates the reference's mask arithmetic ``s*m + (1-m)*NINF``
    (model/model.py:93) rather than a ``where`` so behavior is bit-compatible
    when every position is masked. Computed in f32 for softmax stability
    under bf16 activations.
    """
    scores = scores.astype(jnp.float32)
    mask = mask.astype(jnp.float32)
    masked = scores * mask + (1.0 - mask) * NINF
    return jax.nn.softmax(masked, axis=-1)


@shape_contract(**POOL_CONTRACT)
def attention_pool(
    contexts: jnp.ndarray,  # [B, L, E]
    mask: jnp.ndarray,  # [B, L] (1 = real, 0 = PAD)
    attn_param: jnp.ndarray,  # [E]
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Return (code_vector [B, E], attention [B, L])."""
    scores = jnp.einsum("ble,e->bl", contexts, attn_param)
    attention = masked_attention_weights(scores, mask)
    code_vector = jnp.einsum("bl,ble->be", attention.astype(contexts.dtype), contexts)
    return code_vector, attention


@shape_contract(**POOL_CONTRACT)
def streaming_attention_pool(
    contexts: jnp.ndarray,  # [B, l, E] (l = local shard of L when sharded)
    mask: jnp.ndarray,  # [B, l]
    attn_param: jnp.ndarray,  # [E]
    axis_name: str | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """The streaming-softmax decomposition of :func:`attention_pool`:

        m   = [pmax](max(local_scores))          one scalar per row
        e   = exp(local_scores - m)
        s   = [psum](sum(e))
        out = [psum](e @ local_contexts) / s

    With ``axis_name=None`` the collectives drop out and this is an exact
    single-device reformulation of the masked-softmax pool — same math as
    ``attention_pool`` (the ``1e-38`` clamp is inert: ``e`` always carries
    a 1.0 at the max position, so the sum is ≥ 1). It exists as a separate
    lowering because the explicit exp/sum chain can fuse differently from
    ``jax.nn.softmax`` (measured faster in isolation on TPU v5e —
    tools/bench_ctx.py pool rows; selectable end-to-end via
    ``Code2VecConfig.attn_impl="streaming"``).

    With ``axis_name`` set (under ``shard_map``, bag axis sharded) the
    pmax/psum collectives make it the ctx-parallel pool: ring attention's
    exact rank-1 degenerate case — one pmax + two psums over ICI touch
    each context shard exactly once (parallel/context.py).
    """
    scores = jnp.einsum("ble,e->bl", contexts, attn_param).astype(jnp.float32)
    mask = mask.astype(jnp.float32)
    masked = scores * mask + (1.0 - mask) * NINF
    local_max = jnp.max(masked, axis=-1)
    # stop_gradient INSIDE the collective: pmax has no AD rule, and none is
    # needed — the softmax max-shift is gradient-free (the -dm terms cancel
    # exactly in the normalization). Stopping the operand zeroes its tangent
    # symbolically, so AD never differentiates the collective, keeping
    # backward through the pool exact AND trainable.
    global_max = jax.lax.stop_gradient(local_max)
    if axis_name is not None:
        global_max = jax.lax.pmax(global_max, axis_name)
    e = jnp.exp(masked - global_max[:, None])
    local_sum = jnp.sum(e, axis=-1)
    global_sum = (
        jax.lax.psum(local_sum, axis_name) if axis_name is not None else local_sum
    )
    weights = e / jnp.maximum(global_sum[:, None], 1e-38)
    local_cv = jnp.einsum("bl,ble->be", weights.astype(contexts.dtype), contexts)
    code_vector = (
        jax.lax.psum(local_cv, axis_name) if axis_name is not None else local_cv
    )
    return code_vector, weights
