"""Masked global-attention pooling over a bag of context vectors.

The model's aggregation step (reference: model/model.py:63-69,90-105): one
learned vector ``a`` scores every context, PAD positions are masked to -inf,
softmax over the bag axis, weighted sum produces the code vector.

This is the XLA implementation; XLA already fuses the chain well. A fused
Pallas variant (for the large-bag regime, where keeping the [B, L, E]
context tensor out of HBM round-trips matters) lives in
code2vec_tpu.ops.pallas_attention.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# Same sentinel the reference uses for masked scores (model/model.py:12).
NINF = -3.4e38


def masked_attention_weights(scores: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Softmax over the bag axis with PAD positions masked out.

    Replicates the reference's mask arithmetic ``s*m + (1-m)*NINF``
    (model/model.py:93) rather than a ``where`` so behavior is bit-compatible
    when every position is masked. Computed in f32 for softmax stability
    under bf16 activations.
    """
    scores = scores.astype(jnp.float32)
    mask = mask.astype(jnp.float32)
    masked = scores * mask + (1.0 - mask) * NINF
    return jax.nn.softmax(masked, axis=-1)


def attention_pool(
    contexts: jnp.ndarray,  # [B, L, E]
    mask: jnp.ndarray,  # [B, L] (1 = real, 0 = PAD)
    attn_param: jnp.ndarray,  # [E]
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Return (code_vector [B, E], attention [B, L])."""
    scores = jnp.einsum("ble,e->bl", contexts, attn_param)
    attention = masked_attention_weights(scores, mask)
    code_vector = jnp.einsum("bl,ble->be", attention.astype(contexts.dtype), contexts)
    return code_vector, attention
