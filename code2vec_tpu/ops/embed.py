"""Embedding lookup with a selectable backward pass.

The forward is always a row gather (``table[ids]`` cast to the compute
dtype). The backward — the gradient w.r.t. a ``[vocab, dim]`` table from
``[..., dim]`` upstream grads — is where big-vocab models spend their time
on TPU (vocabs reach 360k+ rows here, SURVEY.md §5.7), and XLA's default
autodiff lowering (scatter-add with duplicate indices) is not always the
fastest formulation. Modes:

- ``dense``: plain autodiff (scatter-add), the default and the semantic
  twin of the reference's ``nn.Embedding`` backward (model/model.py:21-22);
- ``segment``: custom VJP computing the table grad as
  ``jax.ops.segment_sum`` over the flattened ids;
- ``segment_sorted``: same, but argsorts the ids first and tells XLA the
  indices are sorted — trades a bitonic sort of the id vector for a
  collision-free sequential accumulation pattern.

All modes accumulate the table gradient in float32 regardless of compute
dtype, matching the f32 param/optimizer precision recipe. Gradients are
mathematically identical across modes (same sums, different reduction
order — bitwise differences are float-associativity only).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from code2vec_tpu.analysis.contracts import shape_contract, spec

GRAD_MODES = ("dense", "segment", "segment_sorted")


def _segment_grad(ids: jnp.ndarray, g: jnp.ndarray, vocab: int, sort: bool):
    flat = ids.reshape(-1)
    gf = g.reshape(-1, g.shape[-1]).astype(jnp.float32)
    if sort:
        order = jnp.argsort(flat)
        return jax.ops.segment_sum(
            gf[order], flat[order], num_segments=vocab, indices_are_sorted=True
        )
    return jax.ops.segment_sum(gf, flat, num_segments=vocab)


@partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _lookup_segment(table, ids, compute_dtype, sort):
    return table[ids].astype(compute_dtype)


def _lookup_segment_fwd(table, ids, compute_dtype, sort):
    return table[ids].astype(compute_dtype), (ids, table.shape[0])


def _lookup_segment_bwd(compute_dtype, sort, res, g):
    ids, vocab = res
    return _segment_grad(ids, g, vocab, sort), None


_lookup_segment.defvjp(_lookup_segment_fwd, _lookup_segment_bwd)


# ids may be any rank ([B,L] contexts, [N] flat), but MUST be a strong
# integer array — a weak int (a Python literal, flax's fresh counters)
# entering the gather re-keys the jit cache per call site (JX001)
@shape_contract(table=spec("V,E", "float"), ids=spec(None, "int"))
def embedding_lookup(
    table: jnp.ndarray,  # f32 [vocab, dim]
    ids: jnp.ndarray,  # int [...]
    compute_dtype: jnp.dtype = jnp.float32,
    grad_mode: str = "dense",
) -> jnp.ndarray:  # [..., dim] in compute_dtype
    """Gather rows of ``table`` at ``ids``; backward per ``grad_mode``."""
    if grad_mode == "dense":
        return table[ids].astype(compute_dtype)
    if grad_mode == "segment":
        return _lookup_segment(table, ids, compute_dtype, False)
    if grad_mode == "segment_sorted":
        return _lookup_segment(table, ids, compute_dtype, True)
    raise ValueError(f"grad_mode must be one of {GRAD_MODES}, got {grad_mode!r}")
