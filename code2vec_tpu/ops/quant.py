"""Quantized embedding-table storage for serving/eval.

The forward's dominant HBM traffic at serving time is the row gathers out
of the two embedding tables (vocabs reach 360k+ rows, SURVEY.md §5.7);
int8 storage cuts that traffic (and the table's HBM footprint) 4x, bf16
2x. Production llama serving shards int8 tables the same way
(SNIPPETS.md [3]). Quantization is a SERVING/EVAL feature: training keeps
f32 master weights (the touched-rows optimizer already isolates table
updates, train/table_opt.py), and the train loop + the step contract
reject quantized tables outright — see ``train/loop.py`` and
``train/step.py:STEP_STATE_CONTRACT``.

Storage modes (``table_dtype``):

- ``f32``  — no quantization (identity; the training layout);
- ``bf16`` — values stored bfloat16, no scale;
- ``int8`` — values stored int8 with one f32 scale per ROW
  (``absmax/127`` symmetric), dequantized on load: ``row = q * scale``.
  Per-row (not per-table) scales matter here because embedding rows are
  independently distributed — a single table-wide scale would let one
  hot row crush the resolution of every other.

The gather-site dequant (:func:`dequantize_rows`) is the XLA formulation;
the fused Pallas kernel (``ops/fused_encode_pool.py``) DMAs the int8 rows
+ their scales into VMEM and applies the same dequant in-register.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

TABLE_DTYPES = ("f32", "bf16", "int8")


@jax.tree_util.register_pytree_node_class
@dataclass
class QuantTable:
    """A quantized ``[vocab, dim]`` embedding table.

    ``values``: int8 or bf16 ``[V, E]``; ``scale``: f32 ``[V, 1]`` per-row
    dequant scale for int8, ``None`` for bf16. A pytree, so it flows
    through jit/vmap unchanged (``table_dtype`` rides along statically).
    """

    values: jnp.ndarray
    scale: jnp.ndarray | None
    table_dtype: str  # "bf16" | "int8" (static — part of the treedef)

    def tree_flatten(self):
        return (self.values, self.scale), self.table_dtype

    @classmethod
    def tree_unflatten(cls, table_dtype, children):
        values, scale = children
        return cls(values=values, scale=scale, table_dtype=table_dtype)

    @property
    def shape(self) -> tuple:
        return self.values.shape

    def nbytes(self) -> int:
        n = self.values.size * self.values.dtype.itemsize
        if self.scale is not None:
            n += self.scale.size * self.scale.dtype.itemsize
        return n


def row_absmax(x: jnp.ndarray) -> jnp.ndarray:
    """Per-row absmax ``[V, 1]`` of a ``[V, E]`` matrix — THE per-row scale
    primitive. int8 table quantization divides it by 127 for the symmetric
    grid; the ANN index (``ann/pq.py``) uses it directly as the per-row
    residual scale so one magnitude convention covers both consumers. An
    all-zero row yields scale 0 (the callers' exact-zero round-trip
    contract hangs off that)."""
    return jnp.max(jnp.abs(x.astype(jnp.float32)), axis=1, keepdims=True)


def quantize_table(table: jnp.ndarray, table_dtype: str) -> QuantTable:
    """f32 ``[V, E]`` master table -> quantized storage.

    int8 is symmetric per-row absmax: ``scale = absmax/127``,
    ``q = round(x/scale)`` (an all-zero row keeps scale 0 and dequantizes
    to exact zeros — PAD row 0 stays bit-exact zero after round-trip when
    the table's PAD row is zero).
    """
    if table_dtype == "bf16":
        return QuantTable(
            values=table.astype(jnp.bfloat16), scale=None, table_dtype="bf16"
        )
    if table_dtype == "int8":
        scale = row_absmax(table) / 127.0
        # guard the divide only — a zero row quantizes to zeros either way,
        # and its STORED scale stays 0 so dequant returns exact zeros
        q = jnp.round(table.astype(jnp.float32) / jnp.where(scale > 0, scale, 1.0))
        values = jnp.clip(q, -127, 127).astype(jnp.int8)
        return QuantTable(values=values, scale=scale, table_dtype="int8")
    raise ValueError(
        f"table_dtype must be one of {TABLE_DTYPES[1:]} to quantize, "
        f"got {table_dtype!r}"
    )


def dequantize_rows(
    qt: QuantTable, ids: jnp.ndarray, compute_dtype=jnp.float32
) -> jnp.ndarray:
    """Gather rows at ``ids`` and dequantize to ``compute_dtype`` —
    the XLA serving lookup (the gather reads int8/bf16, the win)."""
    rows = qt.values[ids]
    if qt.scale is not None:
        rows = rows.astype(jnp.float32) * qt.scale[ids]
    return rows.astype(compute_dtype)


def dequantize_table(qt: QuantTable, dtype=jnp.float32) -> jnp.ndarray:
    """The full dequantized table (tests / error analysis)."""
    vals = qt.values
    if qt.scale is not None:
        vals = vals.astype(jnp.float32) * qt.scale
    return vals.astype(dtype)


def maybe_quantize(table: jnp.ndarray, table_dtype: str):
    """``table_dtype``-dispatch used by the model: "f32" passes the master
    table through untouched; anything else returns a :class:`QuantTable`."""
    if table_dtype == "f32":
        return table
    return quantize_table(table, table_dtype)
