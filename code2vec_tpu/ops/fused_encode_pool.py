"""Fully-fused gather→encode→attend→pool as a Pallas TPU kernel.

The code2vec hot path is a bag aggregation over enormous embedding tables
(vocabs reach 360k+ rows): two table gathers, concat, dense+layernorm+tanh
encode, then attention pooling. Lowered separately by XLA, the ``[B, L, 3E]``
gathered rows and the ``[B, L, E']`` encoded contexts round-trip HBM between
fusion boundaries; ``ops/pallas_attention.py`` fuses only the final
score→softmax→pool stage. This module fuses the WHOLE chain: per batch
tile, the needed start/path/end embedding rows are DMA'd from the HBM
tables into VMEM (double-buffered across bag chunks), then split-encode
(three sliced matmuls on the shared ``input_dense`` kernel — algebraically
the concat matmul, ``models/code2vec.py:_SplitEncoder``) → layernorm →
tanh → attention score → masked softmax → weighted pool run entirely in
VMEM. Only the ``[TB, E']`` code vector and ``[TB, L]`` weights go back to
HBM — the gathered rows and encoded contexts never touch it.

Two kernel variants (the autotuner's ``impl`` axis, ``ops/autotune.py``):

- ``fused``        in-kernel row DMA gather (tables stay in HBM/ANY space;
                   ``dma_depth`` buffers pipeline the gather of bag chunk
                   c+1 under the encode of chunk c);
- ``gather_split`` XLA performs the row gathers (its gather lowering is
                   hard to beat when rows are cache-resident), the kernel
                   fuses encode→attend→pool so the encoded contexts still
                   never hit HBM.

Quantized tables (``ops/quant.py``): int8 rows are DMA'd with their per-row
scales and dequantized in-register on load; bf16 rows are widened on load.
Serving/eval only — the backward exists only for f32 master tables.

Autodiff follows ``ops/pallas_attention.py``'s pattern: the forward runs
the kernel; the backward is closed-form XLA over the saved inputs — the
whole chain is rematerialized by XLA autodiff of the reference formulation
(flash-attention-style recompute), so gradients are exact to the unfused
path and the fused forward's HBM savings are kept.

Masking semantics are identical to ``pallas_attention_pool``: user-masked
positions score the finite ``NINF`` sentinel, lane-padding columns score a
hard ``-inf`` below it, so a fully-masked row degenerates to uniform over
the REAL bag length exactly like the XLA path.

Long bags — the chunked softmax (``softmax_mode``, PR 13): the default
``"materialize"`` numerics accumulate every encoded chunk into an
``[TB, L, H]`` VMEM scratch before one softmax+pool pass, so the bag
width is VMEM-bounded (the last static-shape ceiling the bucket ladder
papered over). The flash-attention-style modes stream the bag instead,
visiting each ``chunk_l`` tile once (``"online"``: carry a running max
``m``, rescaled denominator ``d``, and rescaled weighted sum — one
gather+encode pass with per-chunk rescaling) or twice (``"two_pass"``:
pass A computes the global max and masked scores, pass B re-gathers and
accumulates the weighted sum with no rescaling), so VMEM residency is
O(chunk_l·H) + O(L) score lanes regardless of bag length. Both modes
reuse the same DMA double-buffer machinery and reproduce the exact
masking semantics above (the running max starts at ``-inf`` and column 0
is always a real lane, so no NaN path exists). ``fused`` impl only —
the other impls materialize O(L·E) inputs by construction.

Multi-backend lowering (``ops/backend.py``): the TPU kernels above are
the ``pallas_tpu`` strategy. ``pallas_gpu`` lowers the portable
``gather_split`` kernel body through Pallas's Triton backend (no TPU
memory spaces or DMA — XLA gathers feed the same encode→attend→pool
tile). ``cpu`` is a compiled XLA strategy: ``_compiled_chain_forward``
sweeps ``_encode_f32``/``_pool_f32`` over the exact tiles the
interpret-mode grid would visit, so it is bitwise-equal to the
interpreter without ever entering it.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from code2vec_tpu.analysis.contracts import shape_contract, spec
from code2vec_tpu.ops.attention import NINF, attention_pool
from code2vec_tpu.ops.backend import resolve as resolve_backend
from code2vec_tpu.ops.quant import QuantTable

_LANE = 128
_LN_EPS = 1e-6  # flax nn.LayerNorm default

FUSED_IMPLS = ("fused", "gather_split")
# bag-softmax numerics of the fused kernel (see module docstring):
# "materialize" keeps the encoded bag in VMEM scratch; "online"/"two_pass"
# stream it flash-style in bounded VMEM (the longbag modes)
SOFTMAX_MODES = ("materialize", "online", "two_pass")


@dataclasses.dataclass(frozen=True)
class FusedStatic:
    """Hashable static configuration of one fused-op instantiation (the
    jit/custom_vjp nondiff payload). ``table_dtype``/``has_*`` determine
    the exact positional argument layout — see ``_ARG_NAMES``."""

    impl: str  # "fused" | "gather_split"
    block_b: int
    dma_depth: int
    chunk_l: int
    table_dtype: str  # "f32" | "bf16" | "int8"
    compute: str  # compute dtype name ("float32" | "bfloat16")
    has_drop: bool
    has_off: bool
    interpret: bool
    softmax: str = "materialize"  # "materialize" | "online" | "two_pass"
    # lowering strategy (ops/backend.py): "pallas_tpu" keeps the original
    # TPU kernel (compiled on TPU, interpreter elsewhere); "pallas_gpu"
    # lowers the portable gather_split kernel body via Triton with
    # GPU-friendly block specs; "cpu" runs the compiled XLA tile sweep
    # (_compiled_chain_forward) — never the Pallas interpreter
    strategy: str = "pallas_tpu"


# full primal layout of the custom_vjp op (entries may be None per static)
_ARG_NAMES = (
    "t_vals", "t_scale", "p_vals", "p_scale",
    "starts", "paths", "ends", "mask",
    "dense_kernel", "ln_scale", "ln_bias", "attn_param",
    "drop_mask", "off_se", "off_p",
)


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def _pad_dim(x: jnp.ndarray, axis: int, target: int) -> jnp.ndarray:
    if x.shape[axis] == target:
        return x
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, target - x.shape[axis])
    return jnp.pad(x, pad)


def _dequant(rows, scale_rows, table_dtype: str):
    """Widen gathered rows to f32; int8 applies the per-row scale."""
    if table_dtype == "int8":
        return rows.astype(jnp.float32) * scale_rows
    return rows.astype(jnp.float32)


def _encode_f32(s, p, e, kern_ref, lns_ref, lnb_ref):
    """Split-encode + layernorm + tanh on f32 row blocks.

    ``s/p/e``: [TB, C, E*] f32 gathered rows; returns [TB, C, H] f32.
    2D ``jnp.dot`` form so Mosaic lowers the contractions onto the MXU
    (batched dot_general does not lower; see ops/pallas_attention.py).
    """
    tb, c, et = s.shape
    ep = p.shape[-1]
    h = kern_ref.shape[-1]
    kern = kern_ref[:]
    x = jnp.dot(
        s.reshape(tb * c, et), kern[:et], preferred_element_type=jnp.float32
    )
    x = x + jnp.dot(
        p.reshape(tb * c, ep), kern[et : et + ep],
        preferred_element_type=jnp.float32,
    )
    x = x + jnp.dot(
        e.reshape(tb * c, et), kern[et + ep :],
        preferred_element_type=jnp.float32,
    )
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mu), axis=-1, keepdims=True)
    xn = (x - mu) * jax.lax.rsqrt(var + _LN_EPS)
    xn = xn * lns_ref[0][None, :] + lnb_ref[0][None, :]
    return jnp.tanh(xn).reshape(tb, c, h)


def _pool_f32(enc, mask, attn_ref, real_l: int):
    """Masked softmax + weighted pool over [TB, Lp, H] f32 encoded rows —
    the same arithmetic as ``pallas_attention.py``'s kernel (finite NINF
    for user-masked slots, hard -inf for lane padding)."""
    scores = jnp.sum(enc * attn_ref[0][None, None, :], axis=2)  # [TB, Lp]
    masked = scores * mask + (1.0 - mask) * NINF
    tb, lp = masked.shape
    col = jax.lax.broadcasted_iota(jnp.int32, (tb, lp), 1)
    masked = jnp.where(col < real_l, masked, -jnp.inf)
    masked = masked - jnp.max(masked, axis=-1, keepdims=True)
    ex = jnp.exp(masked)
    weights = ex / jnp.sum(ex, axis=-1, keepdims=True)
    cv = jnp.sum(enc * weights[:, :, None], axis=1)  # [TB, H]
    return cv, weights


def _make_split_kernel(real_l: int, has_drop: bool):
    """encode→attend→pool kernel over pre-gathered rows (gather_split)."""

    def _kernel(*refs):
        i = 0
        s_ref, p_ref, e_ref, mask_ref = refs[i : i + 4]; i += 4
        kern_ref, lns_ref, lnb_ref, attn_ref = refs[i : i + 4]; i += 4
        drop_ref = None
        if has_drop:
            drop_ref = refs[i]; i += 1
        cv_ref, w_ref = refs[i : i + 2]

        enc = _encode_f32(
            s_ref[:].astype(jnp.float32),
            p_ref[:].astype(jnp.float32),
            e_ref[:].astype(jnp.float32),
            kern_ref, lns_ref, lnb_ref,
        )
        if drop_ref is not None:
            enc = enc * drop_ref[:].astype(jnp.float32)
        cv, weights = _pool_f32(
            enc, mask_ref[:].astype(jnp.float32), attn_ref, real_l
        )
        cv_ref[:] = cv.astype(cv_ref.dtype)
        w_ref[:] = weights

    return _kernel


def _make_fused_kernel(
    real_l: int, lp: int, cl: int, depth: int, table_dtype: str,
    has_drop: bool, block_b: int, softmax: str = "materialize",
):
    """The full kernel: in-kernel DMA row gather (``depth``-buffered across
    bag chunks of ``cl``), then encode→attend→pool.

    ``softmax`` selects the bag-softmax numerics: ``"materialize"``
    accumulates encoded rows in an ``[TB, L, H]`` VMEM scratch and pools
    once at the end (bag bounded by VMEM); ``"online"`` and ``"two_pass"``
    stream the bag chunk by chunk with flash-style running statistics so
    the only O(L) VMEM residency is the 2D score/weight lanes."""

    quant = table_dtype == "int8"
    n_chunks = lp // cl
    chunked = softmax != "materialize"

    def _kernel(*refs):
        i = 0
        t_vals_ref = refs[i]; i += 1
        t_scale_ref = None
        if quant:
            t_scale_ref = refs[i]; i += 1
        p_vals_ref = refs[i]; i += 1
        p_scale_ref = None
        if quant:
            p_scale_ref = refs[i]; i += 1
        starts_ref, paths_ref, ends_ref, mask_ref = refs[i : i + 4]; i += 4
        kern_ref, lns_ref, lnb_ref, attn_ref = refs[i : i + 4]; i += 4
        drop_ref = None
        if has_drop:
            drop_ref = refs[i]; i += 1
        cv_ref, w_ref = refs[i : i + 2]; i += 2
        s_rows, p_rows, e_rows = refs[i : i + 3]; i += 3
        s_scl = p_scl = e_scl = None
        if quant:
            s_scl, p_scl, e_scl = refs[i : i + 3]; i += 3
        if chunked:
            acc_buf, m_buf, d_buf, sems = refs[i : i + 4]
            enc_buf = None
        else:
            enc_buf, sems = refs[i : i + 2]
            acc_buf = m_buf = d_buf = None

        def _copies(slot, c):
            """The chunk's row DMAs, as (src, dst) pairs rebuilt identically
            at issue and wait time (the double-buffer pattern)."""
            base = c * cl

            def row(j, op):
                bi = j // cl
                li = j - bi * cl
                sid = starts_ref[bi, base + li]
                pid = paths_ref[bi, base + li]
                eid = ends_ref[bi, base + li]
                pairs = [
                    (t_vals_ref.at[sid], s_rows.at[slot, bi, li]),
                    (p_vals_ref.at[pid], p_rows.at[slot, bi, li]),
                    (t_vals_ref.at[eid], e_rows.at[slot, bi, li]),
                ]
                if quant:
                    pairs += [
                        (t_scale_ref.at[sid], s_scl.at[slot, bi, li]),
                        (p_scale_ref.at[pid], p_scl.at[slot, bi, li]),
                        (t_scale_ref.at[eid], e_scl.at[slot, bi, li]),
                    ]
                for src, dst in pairs:
                    op(pltpu.make_async_copy(src, dst, sems.at[slot]))

            return row

        # the loops carry a strong-typed dummy (the bodies act by side
        # effect only); issue starts each copy, wait rebuilds the same
        # descriptors and waits them — all on the slot's semaphore, so
        # totals balance
        zero = jnp.int32(0)

        def issue_chunk(slot, c):
            row = _copies(slot, c)
            jax.lax.fori_loop(
                0, block_b * cl,
                lambda j, x: (row(j, lambda d: d.start()), x)[1], zero,
            )

        def wait_chunk(slot, c):
            row = _copies(slot, c)
            jax.lax.fori_loop(
                0, block_b * cl,
                lambda j, x: (row(j, lambda d: d.wait()), x)[1], zero,
            )

        def encode_chunk(slot, c):
            base = c * cl
            s = _dequant(
                s_rows[slot], s_scl[slot] if quant else None, table_dtype
            )
            p = _dequant(
                p_rows[slot], p_scl[slot] if quant else None, table_dtype
            )
            e = _dequant(
                e_rows[slot], e_scl[slot] if quant else None, table_dtype
            )
            enc = _encode_f32(s, p, e, kern_ref, lns_ref, lnb_ref)
            if drop_ref is not None:
                enc = enc * drop_ref[:, pl.ds(base, cl), :].astype(jnp.float32)
            return enc

        def chunk_scores(enc, base):
            """Masked attention scores of one chunk — the same arithmetic
            as ``_pool_f32`` (finite NINF user mask, hard -inf lane pad),
            applied tile-locally."""
            scores = jnp.sum(enc * attn_ref[0][None, None, :], axis=2)
            msk = mask_ref[:, pl.ds(base, cl)].astype(jnp.float32)
            masked = scores * msk + (1.0 - msk) * NINF
            col = jax.lax.broadcasted_iota(jnp.int32, masked.shape, 1) + base
            return jnp.where(col < real_l, masked, -jnp.inf)

        def run_pipeline(compute_chunk):
            """Drive the DMA double-buffer over every chunk, calling
            ``compute_chunk(slot, c)`` once per chunk — shared by the
            materialized pass and both chunked-softmax passes."""
            if depth <= 1:
                # no pipeline: strictly issue → wait → compute per chunk
                def serial_body(c, x):
                    issue_chunk(0, c)
                    wait_chunk(0, c)
                    compute_chunk(0, c)
                    return x

                jax.lax.fori_loop(0, n_chunks, serial_body, zero)
                return
            issue_chunk(0, 0)

            def pipe_body(c, x):
                slot = jax.lax.rem(c, depth)

                @pl.when(c + 1 < n_chunks)
                def _():
                    issue_chunk(jax.lax.rem(c + 1, depth), c + 1)

                wait_chunk(slot, c)
                compute_chunk(slot, c)
                return x

            jax.lax.fori_loop(0, n_chunks, pipe_body, zero)

        if softmax == "materialize":

            def compute_chunk(slot, c):
                enc_buf[:, pl.ds(c * cl, cl), :] = encode_chunk(slot, c)

            run_pipeline(compute_chunk)
            cv, weights = _pool_f32(
                enc_buf[:], mask_ref[:].astype(jnp.float32), attn_ref, real_l
            )
            cv_ref[:] = cv.astype(cv_ref.dtype)
            w_ref[:] = weights
            return

        # chunked softmax: each chunk is encoded, scored, and folded into
        # running statistics; its encoded rows are then DISCARDED. The
        # masked scores land in w_ref (the [TB, L] output block doubles as
        # scratch) so the final normalized weights come from one vectorized
        # pass. No-NaN invariant: column 0 is always a real lane (L >= 1),
        # so the running max is finite from chunk 0 on, and -inf lanes
        # always subtract a finite max (exp -> exact 0).
        m_buf[:] = jnp.full((block_b, 1), -jnp.inf, jnp.float32)
        d_buf[:] = jnp.zeros((block_b, 1), jnp.float32)
        acc_buf[:] = jnp.zeros(acc_buf.shape, jnp.float32)

        if softmax == "online":
            # one streamed pass: rescale d and the weighted sum whenever
            # the running max moves (the flash-attention recurrence)
            def compute_chunk(slot, c):
                base = c * cl
                enc = encode_chunk(slot, c)
                masked = chunk_scores(enc, base)
                w_ref[:, pl.ds(base, cl)] = masked
                m_prev = m_buf[:]
                m_new = jnp.maximum(
                    m_prev, jnp.max(masked, axis=-1, keepdims=True)
                )
                # chunk 0: exp(-inf - finite) = 0 and d/acc are zero, so
                # the first fold is exact; fully-padded chunks leave the
                # max unchanged (scale = exp(0) = 1)
                scale = jnp.exp(m_prev - m_new)
                e = jnp.exp(masked - m_new)
                d_buf[:] = d_buf[:] * scale + jnp.sum(
                    e, axis=-1, keepdims=True
                )
                acc_buf[:] = acc_buf[:] * scale + jnp.sum(
                    e[:, :, None] * enc, axis=1
                )
                m_buf[:] = m_new

            run_pipeline(compute_chunk)
        else:  # two_pass
            # pass A: global max + masked scores (scores persist in w_ref)
            def pass_a(slot, c):
                base = c * cl
                masked = chunk_scores(encode_chunk(slot, c), base)
                w_ref[:, pl.ds(base, cl)] = masked
                m_buf[:] = jnp.maximum(
                    m_buf[:], jnp.max(masked, axis=-1, keepdims=True)
                )

            run_pipeline(pass_a)
            d_buf[:] = jnp.sum(
                jnp.exp(w_ref[:] - m_buf[:]), axis=-1, keepdims=True
            )

            # pass B: re-gather + re-encode, accumulate the weighted sum
            # against the now-fixed max (no rescaling)
            def pass_b(slot, c):
                base = c * cl
                enc = encode_chunk(slot, c)
                e = jnp.exp(w_ref[:, pl.ds(base, cl)] - m_buf[:])
                acc_buf[:] = acc_buf[:] + jnp.sum(e[:, :, None] * enc, axis=1)

            run_pipeline(pass_b)

        d = d_buf[:]
        w_ref[:] = jnp.exp(w_ref[:] - m_buf[:]) / d
        cv_ref[:] = (acc_buf[:] / d).astype(cv_ref.dtype)

    return _kernel


def _compiled_chain_forward(static: FusedStatic, args: dict):
    """The compiled CPU strategy: the gather_split tile computation as
    plain XLA, swept (``lax.map``) over the identical ``[block_b, lp, ·]``
    tiles the interpret-mode kernel grid would visit — same padding, same
    per-tile arithmetic (``_encode_f32``/``_pool_f32``), so the outputs
    are bitwise-equal to the interpreter at compiled-XLA cost. No
    ``pallas_call`` anywhere on this path."""
    starts = args["starts"]
    b, l = starts.shape
    h = args["dense_kernel"].shape[-1]
    block_b = static.block_b
    bp = _round_up(max(b, 1), block_b)
    lp = _round_up(max(l, 1), _LANE)

    mask_p = _pad_dim(_pad_dim(args["mask"].astype(jnp.float32), 0, bp), 1, lp)
    kern = args["dense_kernel"].astype(jnp.float32)
    lns = args["ln_scale"].reshape(1, h).astype(jnp.float32)
    lnb = args["ln_bias"].reshape(1, h).astype(jnp.float32)
    attn = args["attn_param"].reshape(1, h).astype(jnp.float32)
    gs = _pad_dim(_pad_dim(args["g_start"], 0, bp), 1, lp)
    gp = _pad_dim(_pad_dim(args["g_path"], 0, bp), 1, lp)
    ge = _pad_dim(_pad_dim(args["g_end"], 0, bp), 1, lp)
    drop = args.get("drop_mask")
    if drop is not None:
        drop = _pad_dim(_pad_dim(drop.astype(jnp.float32), 0, bp), 1, lp)

    n_tiles = bp // block_b

    def tile(x):
        return x.reshape((n_tiles, block_b) + x.shape[1:])

    tiles = [tile(gs), tile(gp), tile(ge), tile(mask_p)]
    if drop is not None:
        tiles.append(tile(drop))

    def one_tile(t):
        enc = _encode_f32(
            t[0].astype(jnp.float32), t[1].astype(jnp.float32),
            t[2].astype(jnp.float32), kern, lns, lnb,
        )
        if drop is not None:
            enc = enc * t[4]
        return _pool_f32(enc, t[3], attn, l)

    cv, weights = jax.lax.map(one_tile, tuple(tiles))
    return cv.reshape(bp, h)[:b], weights.reshape(bp, lp)[:b, :l]


def _kernel_forward(static: FusedStatic, args: dict):
    """Pad, tile, and run the selected lowering. ``args`` holds the
    kernel-relevant arrays (tables/scales or pre-gathered rows, ids, mask,
    encoder params, optional drop mask). ``strategy="cpu"`` short-circuits
    to the compiled XLA tile sweep; the Pallas strategies differ only in
    memory-space annotations (TPU: VMEM/ANY; GPU: compiler-chosen)."""
    if static.strategy == "cpu":
        return _compiled_chain_forward(static, args)
    starts, paths, ends = args["starts"], args["paths"], args["ends"]
    mask = args["mask"]
    b, l = starts.shape
    h = args["dense_kernel"].shape[-1]
    block_b = static.block_b
    bp = _round_up(max(b, 1), block_b)
    lp = _round_up(max(l, 1), _LANE)
    cl = static.chunk_l
    if cl <= 0 or cl > lp or lp % cl:
        cl = _LANE

    mask_p = _pad_dim(_pad_dim(mask.astype(jnp.float32), 0, bp), 1, lp)
    grid = (bp // block_b,)
    # GPU (Triton) lowering rejects TPU memory spaces — let the compiler
    # place blocks there; the TPU strategy pins VMEM as before
    ms = pltpu.VMEM if static.strategy != "pallas_gpu" else None

    def tile2(x):  # [B, L] → blocked (block_b, lp)
        return pl.BlockSpec(
            (block_b, x.shape[-1]), lambda i: (i, 0), memory_space=ms
        )

    def vec_spec(x):  # params broadcast to every tile
        return pl.BlockSpec(
            x.shape, lambda i: (0,) * x.ndim, memory_space=ms
        )

    kern = args["dense_kernel"].astype(jnp.float32)
    lns = args["ln_scale"].reshape(1, h).astype(jnp.float32)
    lnb = args["ln_bias"].reshape(1, h).astype(jnp.float32)
    attn = args["attn_param"].reshape(1, h).astype(jnp.float32)
    drop = args.get("drop_mask")
    if drop is not None:
        drop = _pad_dim(_pad_dim(drop.astype(jnp.float32), 0, bp), 1, lp)

    out_shape = [
        jax.ShapeDtypeStruct((bp, h), jnp.float32),
        jax.ShapeDtypeStruct((bp, lp), jnp.float32),
    ]
    out_specs = [
        pl.BlockSpec((block_b, h), lambda i: (i, 0), memory_space=ms),
        pl.BlockSpec((block_b, lp), lambda i: (i, 0), memory_space=ms),
    ]

    if static.impl == "gather_split":
        gs, gp, ge = args["g_start"], args["g_path"], args["g_end"]
        gs = _pad_dim(_pad_dim(gs, 0, bp), 1, lp)
        gp = _pad_dim(_pad_dim(gp, 0, bp), 1, lp)
        ge = _pad_dim(_pad_dim(ge, 0, bp), 1, lp)
        inputs = [gs, gp, ge, mask_p, kern, lns, lnb, attn]
        in_specs = [
            pl.BlockSpec(
                (block_b, lp, gs.shape[-1]), lambda i: (i, 0, 0),
                memory_space=ms,
            ),
            pl.BlockSpec(
                (block_b, lp, gp.shape[-1]), lambda i: (i, 0, 0),
                memory_space=ms,
            ),
            pl.BlockSpec(
                (block_b, lp, ge.shape[-1]), lambda i: (i, 0, 0),
                memory_space=ms,
            ),
            tile2(mask_p), vec_spec(kern), vec_spec(lns), vec_spec(lnb),
            vec_spec(attn),
        ]
        if drop is not None:
            inputs.append(drop)
            in_specs.append(
                pl.BlockSpec(
                    (block_b, lp, h), lambda i: (i, 0, 0),
                    memory_space=ms,
                )
            )
        kernel = _make_split_kernel(l, drop is not None)
        scratch_shapes: list = []
    elif static.impl == "fused":
        if static.strategy == "pallas_gpu":
            raise ValueError(
                "impl='fused' (in-kernel DMA gather) is a TPU-only "
                "formulation; the gpu strategy lowers 'gather_split' "
                "(the public wrapper rewrites this automatically)"
            )
        t_vals, p_vals = args["t_vals"], args["p_vals"]
        quant = static.table_dtype == "int8"
        ids = [
            _pad_dim(_pad_dim(x.astype(jnp.int32), 0, bp), 1, lp)
            for x in (starts, paths, ends)
        ]
        inputs = [t_vals]
        in_specs: list = [pl.BlockSpec(memory_space=pltpu.ANY)]
        if quant:
            inputs.append(args["t_scale"])
            in_specs.append(pl.BlockSpec(memory_space=pltpu.ANY))
        inputs.append(p_vals)
        in_specs.append(pl.BlockSpec(memory_space=pltpu.ANY))
        if quant:
            inputs.append(args["p_scale"])
            in_specs.append(pl.BlockSpec(memory_space=pltpu.ANY))
        inputs += ids + [mask_p, kern, lns, lnb, attn]
        in_specs += [tile2(x) for x in ids] + [
            tile2(mask_p), vec_spec(kern), vec_spec(lns), vec_spec(lnb),
            vec_spec(attn),
        ]
        if drop is not None:
            inputs.append(drop)
            in_specs.append(
                pl.BlockSpec(
                    (block_b, lp, h), lambda i: (i, 0, 0),
                    memory_space=pltpu.VMEM,
                )
            )
        et, ep = t_vals.shape[-1], p_vals.shape[-1]
        depth = max(int(static.dma_depth), 1)
        store_dt = t_vals.dtype
        scratch_shapes = [
            pltpu.VMEM((depth, block_b, cl, et), store_dt),
            pltpu.VMEM((depth, block_b, cl, ep), store_dt),
            pltpu.VMEM((depth, block_b, cl, et), store_dt),
        ]
        if quant:
            scratch_shapes += [
                pltpu.VMEM((depth, block_b, cl, 1), jnp.float32),
                pltpu.VMEM((depth, block_b, cl, 1), jnp.float32),
                pltpu.VMEM((depth, block_b, cl, 1), jnp.float32),
            ]
        if static.softmax == "materialize":
            # the whole encoded bag stays resident — O(L*H) VMEM, the
            # bound the chunked modes exist to remove
            scratch_shapes += [pltpu.VMEM((block_b, lp, h), jnp.float32)]
        else:
            # flash-style running statistics: weighted-sum accumulator +
            # running max + denominator — O(H) per row however long the bag
            scratch_shapes += [
                pltpu.VMEM((block_b, h), jnp.float32),
                pltpu.VMEM((block_b, 1), jnp.float32),
                pltpu.VMEM((block_b, 1), jnp.float32),
            ]
        scratch_shapes += [pltpu.SemaphoreType.DMA((depth,))]
        kernel = _make_fused_kernel(
            l, lp, cl, depth, static.table_dtype, drop is not None, block_b,
            softmax=static.softmax,
        )
    else:
        raise ValueError(
            f"impl must be one of {FUSED_IMPLS}, got {static.impl!r}"
        )

    cv, weights = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratch_shapes,
        interpret=static.interpret,
    )(*inputs)
    return cv[:b], weights[:b, :l]


_partitioned_cache: dict = {}


def _get_partitioned_forward(static: FusedStatic, names: tuple[str, ...],
                             ranks: tuple[int, ...]):
    """The kernel forward wrapped in ``custom_partitioning`` so GSPMD
    shards it batch-wise over a mesh (same rationale and rule as
    ``pallas_attention.py``): batch-major args follow the operand's batch
    sharding, tables/params are replicated per shard (a model-sharded
    table is all-gathered — correct; the fused kernel needs whole rows)."""
    key = (static, names, ranks)
    if key not in _partitioned_cache:
        from jax.experimental.custom_partitioning import custom_partitioning
        from jax.sharding import NamedSharding, PartitionSpec as P

        from code2vec_tpu.ops.pallas_attention import compat_def_partition

        batch_major = {
            "starts", "paths", "ends", "mask", "drop_mask",
            "g_start", "g_path", "g_end",
        }
        is_batch = tuple(n in batch_major for n in names)
        first_batch = is_batch.index(True)

        def fwd(*arrays):
            return _kernel_forward(static, dict(zip(names, arrays)))

        def _bspec(arg_shapes):
            sharding = arg_shapes[first_batch].sharding
            spec = sharding.spec
            return spec[0] if len(spec) else None

        def infer_sharding(mesh, arg_shapes, result_shape):
            b = _bspec(arg_shapes)
            return (
                NamedSharding(mesh, P(b, None)),
                NamedSharding(mesh, P(b, None)),
            )

        def partition(mesh, arg_shapes, result_shape):
            b = _bspec(arg_shapes)
            arg_shardings = tuple(
                NamedSharding(mesh, P(b, *(None,) * (r - 1)))
                if bm else NamedSharding(mesh, P())
                for bm, r in zip(is_batch, ranks)
            )
            out_shardings = (
                NamedSharding(mesh, P(b, None)),
                NamedSharding(mesh, P(b, None)),
            )
            return mesh, fwd, out_shardings, arg_shardings

        p = custom_partitioning(fwd)
        compat_def_partition(
            p, partition=partition, infer_sharding_from_operands=infer_sharding
        )
        _partitioned_cache[key] = p
    return _partitioned_cache[key]


def _forward(static: FusedStatic, args: tuple):
    """Assemble kernel args (XLA-side gather for gather_split) and invoke
    the partitioned kernel forward."""
    named = dict(zip(_ARG_NAMES, args))
    cd = jnp.dtype(static.compute)
    kargs = {
        "starts": named["starts"], "paths": named["paths"],
        "ends": named["ends"], "mask": named["mask"],
        "dense_kernel": named["dense_kernel"], "ln_scale": named["ln_scale"],
        "ln_bias": named["ln_bias"], "attn_param": named["attn_param"],
    }
    if static.has_drop:
        kargs["drop_mask"] = named["drop_mask"]
    if static.impl == "gather_split":
        # XLA gathers (+ dequant); the kernel fuses the rest. Offsets (zero
        # by the table_opt contract) are added here so the forward matches
        # the reference formulation exactly even if that contract is bent.
        kargs["g_start"] = _gather_rows(
            named["t_vals"], named["t_scale"], named["starts"], static, cd
        )
        kargs["g_path"] = _gather_rows(
            named["p_vals"], named["p_scale"], named["paths"], static, cd
        )
        kargs["g_end"] = _gather_rows(
            named["t_vals"], named["t_scale"], named["ends"], static, cd
        )
        if static.has_off:
            o_s, o_e = jnp.split(named["off_se"], 2, axis=1)
            kargs["g_start"] = kargs["g_start"] + o_s
            kargs["g_path"] = kargs["g_path"] + named["off_p"]
            kargs["g_end"] = kargs["g_end"] + o_e
    else:
        kargs["t_vals"] = named["t_vals"]
        kargs["p_vals"] = named["p_vals"]
        if static.table_dtype == "int8":
            kargs["t_scale"] = named["t_scale"]
            kargs["p_scale"] = named["p_scale"]
        # the fused kernel gathers in-kernel and cannot add the offsets;
        # they are zeros by contract (train/table_opt.py) and enter only
        # the backward (where the reference differentiates w.r.t. them)

    names = tuple(kargs.keys())
    arrays = tuple(kargs.values())
    ranks = tuple(a.ndim for a in arrays)
    p = _get_partitioned_forward(static, names, ranks)
    return p(*arrays)


def _gather_rows(vals, scale, ids, static: FusedStatic, cd):
    if static.table_dtype == "f32":
        return vals[ids].astype(cd)
    rows = vals[ids]
    if static.table_dtype == "int8":
        rows = rows.astype(jnp.float32) * scale[ids]
    return rows.astype(cd)


def xla_encode_contexts(
    gs, gp, ge, dense_kernel, ln_scale, ln_bias, compute_dtype=jnp.float32
):
    """Split-encode + layernorm + tanh over pre-gathered rows — THE
    reference encode formulation. Single source of truth: the fused
    backward differentiates it and the autotuner's pool-only arm times it,
    so a change here changes every consumer in lockstep."""
    cd = jnp.dtype(compute_dtype)
    et, ep = gs.shape[-1], gp.shape[-1]
    kern = dense_kernel.astype(cd)
    x = gs @ kern[:et] + gp @ kern[et : et + ep] + ge @ kern[et + ep :]
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mu), axis=-1, keepdims=True)
    xn = (x32 - mu) * jax.lax.rsqrt(var + _LN_EPS)
    xn = xn * ln_scale.astype(jnp.float32) + ln_bias.astype(jnp.float32)
    return jnp.tanh(xn.astype(cd))


def _xla_reference(static: FusedStatic, args: tuple):
    """The unfused XLA formulation of the exact same math — the backward
    differentiates THIS (rematerialized: nothing but the primal inputs is
    saved), so fused gradients are exact to the unfused path."""
    named = dict(zip(_ARG_NAMES, args))
    cd = jnp.dtype(static.compute)
    gs = _gather_rows(named["t_vals"], named["t_scale"], named["starts"], static, cd)
    gp = _gather_rows(named["p_vals"], named["p_scale"], named["paths"], static, cd)
    ge = _gather_rows(named["t_vals"], named["t_scale"], named["ends"], static, cd)
    if static.has_off:
        o_s, o_e = jnp.split(named["off_se"], 2, axis=1)
        gs = gs + o_s
        gp = gp + named["off_p"]
        ge = ge + o_e
    enc = xla_encode_contexts(
        gs, gp, ge, named["dense_kernel"], named["ln_scale"],
        named["ln_bias"], cd,
    )
    if static.has_drop:
        enc = enc * named["drop_mask"].astype(cd)
    cv, weights = attention_pool(
        enc, named["mask"], named["attn_param"].astype(cd)
    )
    return cv.astype(jnp.float32), weights


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _op(static: FusedStatic, args: tuple):
    return _forward(static, args)


def _op_fwd(static: FusedStatic, args: tuple):
    return _forward(static, args), args


def _op_bwd(static: FusedStatic, residuals: tuple, grads):
    named = dict(zip(_ARG_NAMES, residuals))
    diff_names = ["dense_kernel", "ln_scale", "ln_bias", "attn_param"]
    if static.table_dtype == "f32":
        diff_names += ["t_vals", "p_vals"]
    if static.has_off:
        diff_names += ["off_se", "off_p"]

    def ref(diff: dict):
        merged = dict(named, **diff)
        return _xla_reference(
            static, tuple(merged[n] for n in _ARG_NAMES)
        )

    _, vjp = jax.vjp(ref, {n: named[n] for n in diff_names})
    (gd,) = vjp(grads)

    def cot(name):
        if name in gd:
            return gd[name]
        v = named[name]
        # float non-diff data (mask, drop, quant scales) gets explicit
        # zeros; integer ids / quantized values get None (no tangent space)
        if v is not None and jnp.issubdtype(v.dtype, jnp.floating):
            return jnp.zeros_like(v)
        return None

    return (tuple(cot(n) for n in _ARG_NAMES),)


_op.defvjp(_op_fwd, _op_bwd)


FUSED_CONTRACT = {
    "starts": spec("B,L", "int"),
    "paths": spec("B,L", "int"),
    "ends": spec("B,L", "int"),
    "mask": spec("B,L"),
    "dense_kernel": spec("D,H", "float"),
    "ln_scale": spec("H", "float"),
    "ln_bias": spec("H", "float"),
    "attn_param": spec("H", "float"),
}


@shape_contract(**{k: v for k, v in FUSED_CONTRACT.items()})
def _check_contract(starts, paths, ends, mask, dense_kernel, ln_scale,
                    ln_bias, attn_param):
    return None


def fused_encode_attend_pool(
    t_table,  # f32 [Vt, Et] master table OR ops.quant.QuantTable
    p_table,  # f32 [Vp, Ep] master table OR ops.quant.QuantTable
    starts: jnp.ndarray,  # int32 [B, L]
    paths: jnp.ndarray,  # int32 [B, L]
    ends: jnp.ndarray,  # int32 [B, L]
    mask: jnp.ndarray,  # [B, L] (1 = real, 0 = PAD)
    dense_kernel: jnp.ndarray,  # f32 [2*Et+Ep, H] (input_dense/kernel)
    ln_scale: jnp.ndarray,  # f32 [H]
    ln_bias: jnp.ndarray,  # f32 [H]
    attn_param: jnp.ndarray,  # f32 [H]
    drop_mask: jnp.ndarray | None = None,  # pre-scaled keep mask [B, L, H]
    off_se: jnp.ndarray | None = None,  # zero offsets [B, 2L, Et] (table_opt)
    off_p: jnp.ndarray | None = None,  # zero offsets [B, L, Ep]
    *,
    impl: str = "fused",
    block_b: int = 8,
    dma_depth: int = 2,
    chunk_l: int = _LANE,
    softmax_mode: str = "materialize",
    compute_dtype=jnp.float32,
    interpret: bool | None = None,
    backend: str | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Fused forward for the whole code2vec aggregation chain.

    Returns ``(code_vector [B, H] f32, attention [B, L] f32)`` matching
    the unfused model path (``models/code2vec.py``) within float tolerance
    and ``pallas_attention_pool``'s exact masking semantics.

    ``off_se``/``off_p`` are the touched-rows optimizer's zero offset
    tensors (``train/table_opt.py``): ZERO by that contract. The ``fused``
    kernel does not read them in the forward (adding zeros is a no-op);
    the backward differentiates w.r.t. them so the lazy optimizer's
    per-slot gradients come out exactly as on the unfused path.

    ``softmax_mode``: bag-softmax numerics (module docstring) —
    ``"materialize"`` (VMEM-resident encoded bag, the original kernel) or
    the flash-style chunked ``"online"``/``"two_pass"`` (bounded VMEM,
    arbitrary bag length; ``impl="fused"`` only — the other impls
    materialize O(L·E) inputs by construction).

    ``backend``/``interpret`` route through the shared resolver
    (``ops/backend.py``): explicit ``interpret`` keeps its legacy meaning
    (True pins the TPU formulation under the Pallas interpreter); with
    both None the ``C2V_KERNEL_BACKEND`` env or the device decides. Under
    the ``cpu`` and ``pallas_gpu`` strategies ``impl="fused"`` lowers as
    ``gather_split`` (the in-kernel DMA gather is TPU-only) and chunked
    softmax modes compute the materialized formulation — same semantics,
    host/GPU memory is not VMEM-bounded.
    """
    if impl not in FUSED_IMPLS:
        raise ValueError(f"impl must be one of {FUSED_IMPLS}, got {impl!r}")
    if softmax_mode not in SOFTMAX_MODES:
        raise ValueError(
            f"softmax_mode must be one of {SOFTMAX_MODES}, got "
            f"{softmax_mode!r}"
        )
    if softmax_mode != "materialize" and impl != "fused":
        raise ValueError(
            f"chunked softmax ({softmax_mode!r}) requires impl='fused': "
            f"{impl!r} materializes the full bag before the kernel runs, "
            "so streaming the softmax would not bound VMEM"
        )
    bs = resolve_backend(backend=backend, interpret=interpret)
    if bs.strategy != "pallas_tpu":
        if impl == "fused":
            impl = "gather_split"
        if softmax_mode != "materialize":
            softmax_mode = "materialize"
    t_vals, t_scale, table_dtype = _split_table(t_table)
    p_vals, p_scale, p_dtype = _split_table(p_table)
    if table_dtype != p_dtype:
        raise ValueError(
            f"terminal/path tables must share a storage dtype, got "
            f"{table_dtype!r} vs {p_dtype!r}"
        )
    if (off_se is None) != (off_p is None):
        raise ValueError("off_se and off_p must be provided together")
    _check_contract(starts, paths, ends, mask, dense_kernel, ln_scale,
                    ln_bias, attn_param)
    static = FusedStatic(
        impl=impl,
        block_b=max(int(block_b), 1),
        dma_depth=max(int(dma_depth), 1),
        chunk_l=int(chunk_l),
        table_dtype=table_dtype,
        compute=jnp.dtype(compute_dtype).name,
        has_drop=drop_mask is not None,
        has_off=off_se is not None,
        interpret=bs.interpret,
        softmax=softmax_mode,
        strategy=bs.strategy,
    )
    args = (
        t_vals, t_scale, p_vals, p_scale,
        starts, paths, ends, mask.astype(jnp.float32),
        dense_kernel, ln_scale, ln_bias, attn_param,
        drop_mask, off_se, off_p,
    )
    return _op(static, args)


def _split_table(table) -> tuple[jnp.ndarray, jnp.ndarray | None, str]:
    if isinstance(table, QuantTable):
        return table.values, table.scale, table.table_dtype
    return table, None, "f32"


def xla_reference_forward(
    t_table, p_table, starts, paths, ends, mask, dense_kernel, ln_scale,
    ln_bias, attn_param, drop_mask=None, off_se=None, off_p=None,
    *, compute_dtype=jnp.float32,
):
    """Public unfused formulation of the same op (parity tests and the
    autotuner's ``impl="xla"`` arm). Differentiable end to end."""
    t_vals, t_scale, table_dtype = _split_table(t_table)
    p_vals, p_scale, p_dtype = _split_table(p_table)
    static = FusedStatic(
        impl="xla", block_b=1, dma_depth=1, chunk_l=_LANE,
        table_dtype=table_dtype, compute=jnp.dtype(compute_dtype).name,
        has_drop=drop_mask is not None, has_off=off_se is not None,
        interpret=True,
    )
    args = (
        t_vals, t_scale, p_vals, p_scale,
        starts, paths, ends, mask.astype(jnp.float32),
        dense_kernel, ln_scale, ln_bias, attn_param,
        drop_mask, off_se, off_p,
    )
    return _xla_reference(static, args)
