"""Checkpoint save/restore via orbax.

The reference only ever writes ``torch.save(state_dict)`` on a new best F1
and has no load path at all (main.py:231; SURVEY.md §5.4). TPU pod runs get
preempted, so this framework treats resume as first-class: params, optimizer
state, RNG, epoch counter, and the early-stop bookkeeping all round-trip.
"""

from __future__ import annotations

import json
import os
from dataclasses import asdict, dataclass, field

import jax
import numpy as np
import orbax.checkpoint as ocp

CHECKPOINT_DIR = "code2vec_ckpt"
META_FILE = "train_meta.json"


@dataclass
class TrainMeta:
    """Host-side loop state saved alongside the device pytree."""

    epoch: int = 0
    best_f1: float | None = None
    last_loss: float | None = None
    last_accuracy: float | None = None
    bad_count: int = 0
    history: list[dict] = field(default_factory=list)


def _state_pytree(state) -> dict:
    dropout_rng = state.dropout_rng
    if jax.dtypes.issubdtype(dropout_rng.dtype, jax.dtypes.prng_key):
        dropout_rng = jax.random.key_data(dropout_rng)
    return {
        "params": state.params,
        "opt_state": state.opt_state,
        "dropout_rng": dropout_rng,
        "step": np.asarray(state.step),
    }


def _latest_step_dir(base: str) -> str | None:
    if not os.path.isdir(base):
        return None
    steps = sorted(
        (int(name.split("_")[1]), name)
        for name in os.listdir(base)
        if name.startswith("step_") and name.split("_")[1].isdigit()
    )
    return os.path.join(base, steps[-1][1]) if steps else None


def save_checkpoint(out_dir: str, state, meta: TrainMeta) -> str:
    """Save the train state pytree + loop metadata under ``out_dir``.

    Preemption-safe: each save goes to a fresh ``step_N`` directory and
    older checkpoints are pruned only after the new one is fully written, so
    a crash mid-save never leaves the run without a restorable checkpoint.
    """
    base = os.path.abspath(os.path.join(out_dir, CHECKPOINT_DIR))
    os.makedirs(base, exist_ok=True)
    previous = _latest_step_dir(base)
    path = os.path.join(base, f"step_{int(state.step)}")
    if os.path.exists(path):
        import shutil

        shutil.rmtree(path)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, _state_pytree(state))
    # orbax coordinates the multi-host array save; the sidecar metadata and
    # pruning are process-0-only
    if jax.process_index() == 0:
        meta_tmp = os.path.join(out_dir, META_FILE + ".tmp")
        with open(meta_tmp, "w") as f:
            json.dump(asdict(meta), f)
        os.replace(meta_tmp, os.path.join(out_dir, META_FILE))
        if previous is not None and previous != path:
            import shutil

            shutil.rmtree(previous, ignore_errors=True)
    return path


def restore_checkpoint(out_dir: str, state) -> tuple[object, TrainMeta] | None:
    """Restore into the shape of ``state``; returns None if no checkpoint."""
    base = os.path.abspath(os.path.join(out_dir, CHECKPOINT_DIR))
    meta_path = os.path.join(out_dir, META_FILE)
    path = _latest_step_dir(base)
    if path is None or not os.path.exists(meta_path):
        return None
    template = _state_pytree(state)
    abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, template)
    with ocp.StandardCheckpointer() as ckptr:
        restored = ckptr.restore(path, abstract)
    dropout_rng = restored["dropout_rng"]
    if jax.dtypes.issubdtype(state.dropout_rng.dtype, jax.dtypes.prng_key):
        dropout_rng = jax.random.wrap_key_data(dropout_rng)
    new_state = state.replace(
        params=restored["params"],
        opt_state=restored["opt_state"],
        dropout_rng=dropout_rng,
        step=int(restored["step"]),
    )
    with open(meta_path) as f:
        meta = TrainMeta(**json.load(f))
    return new_state, meta
