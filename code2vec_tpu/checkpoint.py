"""Checkpoint save/restore via orbax.

The reference only ever writes ``torch.save(state_dict)`` on a new best F1
and has no load path at all (main.py:231; SURVEY.md §5.4). TPU pod runs get
preempted, so this framework treats resume as first-class: params, optimizer
state, RNG, epoch counter, and the early-stop bookkeeping all round-trip.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
from dataclasses import asdict, dataclass, field

import jax
import jax.numpy as jnp
import numpy as np
import orbax.checkpoint as ocp

logger = logging.getLogger(__name__)

CHECKPOINT_DIR = "code2vec_ckpt"
META_FILE = "train_meta.json"


@dataclass
class TrainMeta:
    """Host-side loop state saved alongside the device pytree."""

    epoch: int = 0
    best_f1: float | None = None
    last_loss: float | None = None
    last_accuracy: float | None = None
    bad_count: int = 0
    history: list[dict] = field(default_factory=list)
    # PRNG impl of the saved dropout key — validated on restore so an
    # --rng_impl mismatch fails with guidance, not an orbax shape error
    rng_impl: str | None = None
    # vocab pad multiple the params were built with (table/head shapes
    # depend on it) — validated on restore so resuming under a different
    # model_axis fails with guidance, not an orbax shape error
    vocab_pad_multiple: int | None = None
    # Adam first-moment storage dtype (--adam_mu_dtype) — validated on
    # restore so resuming a bf16-mu checkpoint without the flag fails with
    # guidance, not an orbax dtype error
    adam_mu_dtype: str | None = None
    # embedding-table optimizer (--table_update): "lazy" stores a
    # structurally different opt_state (train/table_opt.py), so a mismatch
    # is caught here with guidance, not an orbax structure error
    table_update: str | None = None


def _adam_mu_dtype_name(state) -> str | None:
    """Dtype of the Adam first-moment buffers, read off the live opt_state
    (None when no ScaleByAdamState is present — e.g. a bare template).
    The lazy table optimizer nests a plain chain state for the non-table
    params inside MixedTableOptState, which this NamedTuple walk reaches."""
    import optax

    for leaf in jax.tree_util.tree_leaves(
        state.opt_state,
        is_leaf=lambda x: isinstance(x, optax.ScaleByAdamState),
    ):
        if isinstance(leaf, optax.ScaleByAdamState):
            mu_leaves = jax.tree_util.tree_leaves(leaf.mu)
            return str(mu_leaves[0].dtype) if mu_leaves else None
    return None


def _table_update_name(state) -> str:
    """"lazy" when the opt_state carries the touched-rows table optimizer
    (train/table_opt.py), else "dense"."""
    from code2vec_tpu.train.table_opt import MixedTableOptState

    return (
        "lazy"
        if isinstance(state.opt_state, MixedTableOptState)
        else "dense"
    )


def _rng_impl_name(dropout_rng) -> str:
    if jax.dtypes.issubdtype(dropout_rng.dtype, jax.dtypes.prng_key):
        return str(jax.random.key_impl(dropout_rng))
    return "threefry2x32"  # raw uint32 PRNGKey arrays are threefry


def _state_pytree(state) -> dict:
    dropout_rng = state.dropout_rng
    if jax.dtypes.issubdtype(dropout_rng.dtype, jax.dtypes.prng_key):
        dropout_rng = jax.random.key_data(dropout_rng)
    return {
        "params": state.params,
        "opt_state": state.opt_state,
        "dropout_rng": dropout_rng,
        "step": np.asarray(state.step),
    }


def _latest_step_dir(base: str, prefix: str = "step") -> str | None:
    if not os.path.isdir(base):
        return None
    steps = sorted(
        (int(name.rsplit("_", 1)[1]), name)
        for name in os.listdir(base)
        if name.startswith(prefix + "_") and name.rsplit("_", 1)[1].isdigit()
    )
    return os.path.join(base, steps[-1][1]) if steps else None


def save_checkpoint(out_dir: str, state, meta: TrainMeta, slot: str = "best") -> str:
    """Save the train state pytree + loop metadata under ``out_dir``.

    Two slots: ``best`` (``step_N`` dirs — the reference's best-F1 model
    contract, main.py:231) and ``last`` (``last_N`` dirs — periodic
    preemption-safety saves). Each slot prunes only its own older dirs, so
    a periodic save never deletes the best model.

    Preemption-safe: each save goes to a fresh directory and older ones are
    pruned only after the new one is fully written, so a crash mid-save
    never leaves the run without a restorable checkpoint.
    """
    assert slot in ("best", "last"), slot
    prefix = "step" if slot == "best" else "last"
    base = os.path.abspath(os.path.join(out_dir, CHECKPOINT_DIR))
    os.makedirs(base, exist_ok=True)
    previous = _latest_step_dir(base, prefix)
    meta.rng_impl = _rng_impl_name(state.dropout_rng)
    meta.adam_mu_dtype = _adam_mu_dtype_name(state) or meta.adam_mu_dtype
    meta.table_update = _table_update_name(state)
    path = os.path.join(base, f"{prefix}_{int(state.step)}")
    if os.path.exists(path):
        shutil.rmtree(path)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, _state_pytree(state))
    # orbax coordinates the multi-host array save; the sidecar metadata and
    # pruning are process-0-only
    if jax.process_index() == 0:
        meta_tmp = os.path.join(out_dir, META_FILE + ".tmp")
        with open(meta_tmp, "w") as f:
            json.dump(asdict(meta), f)
        os.replace(meta_tmp, os.path.join(out_dir, META_FILE))
        if previous is not None and previous != path:
            shutil.rmtree(previous, ignore_errors=True)
        if slot == "best":
            # a newer best supersedes any older periodic save: prune
            # `last_N` with N <= this step so dead checkpoints don't
            # accumulate (restore picks max-N, which is now this one)
            stale = _latest_step_dir(base, "last")
            if stale is not None and int(stale.rsplit("_", 1)[1]) <= int(
                state.step
            ):
                shutil.rmtree(stale, ignore_errors=True)
    return path


def clear_checkpoints(out_dir: str, slot: str = "last") -> None:
    """Remove a checkpoint slot under ``out_dir``.

    Fresh (non-resume) runs clear only the ``last`` (periodic) slot: it
    belongs to the interrupted run it was saved by, and left in place it
    could outrank the new run's ``best`` saves at a later ``--resume``. The
    ``best`` slot and metadata are preserved until the new run's first save
    overwrites them, so a crash before that never leaves the directory
    without a restorable checkpoint.

    Process-0-only under multi-host; other processes race benignly since
    they never read before the barrier implied by the first save.
    """
    if jax.process_index() != 0:
        return
    prefix = "step" if slot == "best" else "last"
    base = os.path.abspath(os.path.join(out_dir, CHECKPOINT_DIR))
    if not os.path.isdir(base):
        return
    for name in os.listdir(base):
        if name.startswith(prefix + "_"):
            logger.info("fresh run: clearing stale checkpoint %s", name)
            shutil.rmtree(os.path.join(base, name), ignore_errors=True)


def restore_checkpoint(
    out_dir: str,
    state,
    vocab_pad_multiple: int | None = None,
    prefer_best: bool = False,
) -> tuple[object, TrainMeta] | None:
    """Restore into the shape of ``state``; returns None if no checkpoint.

    Default (``--resume``): the newest save across both slots (the ``last``
    periodic save when it is fresher than the ``best`` one); ``step``
    counts optimizer steps monotonically, so the larger suffix is the
    later save. ``prefer_best`` (the export path): the best-F1 ``step``
    slot when present — a fresher periodic save is NOT the model the
    in-training export would have written. Note the meta sidecar is a
    single file owned by the newest save regardless of slot; with
    ``prefer_best`` only the restored arrays are slot-specific.
    """
    base = os.path.abspath(os.path.join(out_dir, CHECKPOINT_DIR))
    meta_path = os.path.join(out_dir, META_FILE)
    best_path = _latest_step_dir(base, "step")
    candidates = [
        p for p in (best_path, _latest_step_dir(base, "last")) if p is not None
    ]
    if not candidates or not os.path.exists(meta_path):
        return None
    if prefer_best and best_path is not None:
        path = best_path
    else:
        path = max(candidates, key=lambda p: int(p.rsplit("_", 1)[1]))
    with open(meta_path) as f:
        saved_meta = TrainMeta(**json.load(f))
    want_impl = _rng_impl_name(state.dropout_rng)
    # checkpoints from before rng_impl was recorded hold raw threefry keys
    saved_impl = saved_meta.rng_impl or "threefry2x32"
    if saved_impl != want_impl:
        raise ValueError(
            f"checkpoint in {base} was saved with --rng_impl "
            f"{saved_impl} but this run uses {want_impl}; pass "
            f"--rng_impl {saved_impl} to resume it"
        )
    want_update = _table_update_name(state)
    # metas from before the field are dense (the only behavior then)
    saved_update = saved_meta.table_update or "dense"
    if saved_update != want_update:
        raise ValueError(
            f"checkpoint in {base} was saved with --table_update "
            f"{saved_update} but this run uses {want_update}; pass "
            f"--table_update {saved_update} to resume it (the optimizer "
            "state structures differ)"
        )
    want_mu = _adam_mu_dtype_name(state)
    # metas from before the field hold f32 moments (the only behavior then);
    # a template without Adam state (want_mu None) skips the check
    saved_mu = saved_meta.adam_mu_dtype or "float32"
    if want_mu is not None and saved_mu != want_mu:
        raise ValueError(
            f"checkpoint in {base} stores Adam first moments as "
            f"{saved_mu} but this run uses {want_mu}; pass "
            f"--adam_mu_dtype {saved_mu} to resume it"
        )
    saved_pad = saved_meta.vocab_pad_multiple
    if (
        vocab_pad_multiple is not None
        and saved_pad is not None
        and saved_pad != vocab_pad_multiple
    ):
        raise ValueError(
            f"checkpoint in {base} was saved with vocab tables padded to a "
            f"multiple of {saved_pad} but this run pads to "
            f"{vocab_pad_multiple} (it follows model_axis unless pinned); "
            f"pass --vocab_pad_multiple {saved_pad} to resume it under a "
            "different mesh"
        )
    template = _state_pytree(state)
    abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, template)
    with ocp.StandardCheckpointer() as ckptr:
        restored = ckptr.restore(path, abstract)
    dropout_rng = restored["dropout_rng"]
    if jax.dtypes.issubdtype(state.dropout_rng.dtype, jax.dtypes.prng_key):
        # re-wrap with the template's impl: key-data shape differs between
        # threefry ([2] uint32) and rbg ([4] uint32) keys
        dropout_rng = jax.random.wrap_key_data(
            dropout_rng, impl=jax.random.key_impl(state.dropout_rng)
        )
    new_state = state.replace(
        params=restored["params"],
        opt_state=restored["opt_state"],
        dropout_rng=dropout_rng,
        # int32 array, not a weak Python int: a weak-typed step would trace
        # one extra jit-cache entry on the first post-resume step (see
        # create_train_state) and overflow the bucketed recompile budget
        step=jnp.asarray(int(restored["step"]), jnp.int32),
    )
    return new_state, saved_meta
