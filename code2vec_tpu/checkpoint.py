"""Checkpoint save/restore via orbax — the elastic-training substrate.

The reference only ever writes ``torch.save(state_dict)`` on a new best F1
and has no load path at all (main.py:231; SURVEY.md §5.4). TPU pod runs get
preempted and *resized*, so this framework treats resume as first-class:

- params, optimizer state, RNG, step counter, and the early-stop bookkeeping
  all round-trip; :class:`TrainMeta` additionally carries a **data cursor**
  (epoch + step-in-epoch + host RNG state) so ``--resume`` can restart
  *inside* an epoch (train/loop.py replays the epoch stream to the cursor);
- every save is **atomic**: arrays and sidecars are staged under a ``tmp.``
  prefix and published with one ``os.replace`` — a crash mid-save can never
  leave a partial dir that restore would select (restore additionally skips
  dirs missing orbax's commit marker, so even foreign partials are ignored);
- each slot dir carries its own ``train_meta.json`` sidecar, so a
  ``prefer_best`` restore gets the bookkeeping that matches the restored
  arrays (the old single top-level file — still written for compatibility —
  belonged to the newest save of *either* slot);
- a ``shardings.json`` sidecar records the PartitionSpec of every leaf plus
  the mesh shape; restore re-binds those specs to the *current* mesh
  (parallel/shardings.py), so a run killed on one topology resumes on
  another — the migration primitive;
- :class:`CheckpointWriter` gives the train loop **async** saves: the loop
  blocks only for the device-to-host snapshot, persistence runs on a
  background thread with at-most-one save in flight, and persist failures
  re-raise into the loop at the next save (or at shutdown).
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import threading
from dataclasses import asdict, dataclass, field

import jax
import jax.numpy as jnp
import numpy as np
import orbax.checkpoint as ocp

from code2vec_tpu import faultinject
from code2vec_tpu.obs import handles
from code2vec_tpu.obs.sync import make_lock

logger = logging.getLogger(__name__)

CHECKPOINT_DIR = "code2vec_ckpt"
META_FILE = "train_meta.json"
SHARDINGS_FILE = "shardings.json"
# staging prefix for in-progress saves; never matches a slot prefix, so
# `_latest_step_dir` cannot select one even before the completeness check
TMP_PREFIX = "tmp."
# completeness markers: our own (written with the sidecars, just before
# the atomic publish — so restore-ability never hinges on orbax
# internals), plus the files orbax itself writes only once a checkpoint
# is committed (local FS writes _CHECKPOINT_METADATA at finalize; GCS
# uses commit_success.txt) — which keep checkpoints from older saves of
# this framework restorable
_OWN_COMMIT_MARKER = "c2v_commit"
_COMMIT_MARKERS = (
    _OWN_COMMIT_MARKER, "_CHECKPOINT_METADATA", "commit_success.txt"
)


@dataclass
class TrainMeta:
    """Host-side loop state saved alongside the device pytree."""

    epoch: int = 0
    best_f1: float | None = None
    last_loss: float | None = None
    last_accuracy: float | None = None
    bad_count: int = 0
    history: list[dict] = field(default_factory=list)
    # PRNG impl of the saved dropout key — validated on restore so an
    # --rng_impl mismatch fails with guidance, not an orbax shape error
    rng_impl: str | None = None
    # vocab pad multiple the params were built with (table/head shapes
    # depend on it) — validated on restore so resuming under a different
    # model_axis fails with guidance, not an orbax shape error
    vocab_pad_multiple: int | None = None
    # Adam first-moment storage dtype (--adam_mu_dtype) — validated on
    # restore so resuming a bf16-mu checkpoint without the flag fails with
    # guidance, not an orbax dtype error
    adam_mu_dtype: str | None = None
    # embedding-table optimizer (--table_update): "lazy" stores a
    # structurally different opt_state (train/table_opt.py), so a mismatch
    # is caught here with guidance, not an orbax structure error
    table_update: str | None = None
    # mid-epoch data cursor (None = the save was an epoch boundary):
    # {"epoch", "step", "np_rng_state", "partial_train_loss",
    #  "bucket_positions"} — train/loop.py captures it at each mid-epoch
    # save and replays the host batch stream up to "step" on resume
    cursor: dict | None = None


def _adam_mu_dtype_name(state) -> str | None:
    """Dtype of the Adam first-moment buffers, read off the live opt_state
    (None when no ScaleByAdamState is present — e.g. a bare template).
    The lazy table optimizer nests a plain chain state for the non-table
    params inside MixedTableOptState, which this NamedTuple walk reaches."""
    import optax

    for leaf in jax.tree_util.tree_leaves(
        state.opt_state,
        is_leaf=lambda x: isinstance(x, optax.ScaleByAdamState),
    ):
        if isinstance(leaf, optax.ScaleByAdamState):
            mu_leaves = jax.tree_util.tree_leaves(leaf.mu)
            return str(mu_leaves[0].dtype) if mu_leaves else None
    return None


def _table_update_name(state) -> str:
    """"lazy" when the opt_state carries the touched-rows table optimizer
    (train/table_opt.py), else "dense"."""
    from code2vec_tpu.train.table_opt import MixedTableOptState

    return (
        "lazy"
        if isinstance(state.opt_state, MixedTableOptState)
        else "dense"
    )


def _rng_impl_name(dropout_rng) -> str:
    if jax.dtypes.issubdtype(dropout_rng.dtype, jax.dtypes.prng_key):
        return str(jax.random.key_impl(dropout_rng))
    return "threefry2x32"  # raw uint32 PRNGKey arrays are threefry


def _state_pytree(state) -> dict:
    dropout_rng = state.dropout_rng
    if jax.dtypes.issubdtype(dropout_rng.dtype, jax.dtypes.prng_key):
        dropout_rng = jax.random.key_data(dropout_rng)
    return {
        "params": state.params,
        "opt_state": state.opt_state,
        "dropout_rng": dropout_rng,
        "step": np.asarray(state.step),
    }


def _stamp_meta(meta: TrainMeta, state) -> None:
    """Record the state-derived compatibility fields on ``meta`` (shared by
    the sync save and the async snapshot)."""
    meta.rng_impl = _rng_impl_name(state.dropout_rng)
    meta.adam_mu_dtype = _adam_mu_dtype_name(state) or meta.adam_mu_dtype
    meta.table_update = _table_update_name(state)


def _is_complete_checkpoint(path: str) -> bool:
    """Whether ``path`` is a committed checkpoint dir: orbax's commit
    marker must be present. A dir truncated by a crash mid-save (or a
    leftover orbax-internal tmp dir) fails this and is skipped by restore
    instead of selected and died on."""
    if not os.path.isdir(path):
        return False
    name = os.path.basename(path)
    if name.startswith(TMP_PREFIX) or ".orbax-checkpoint-tmp" in name:
        return False
    return any(
        os.path.exists(os.path.join(path, marker))
        for marker in _COMMIT_MARKERS
    )


def _latest_step_dir(
    base: str, prefix: str = "step", complete_only: bool = True
) -> str | None:
    if not os.path.isdir(base):
        return None
    steps = sorted(
        (int(name.rsplit("_", 1)[1]), name)
        for name in os.listdir(base)
        if name.startswith(prefix + "_") and name.rsplit("_", 1)[1].isdigit()
    )
    for _, name in reversed(steps):
        path = os.path.join(base, name)
        if not complete_only or _is_complete_checkpoint(path):
            return path
        logger.warning(
            "skipping incomplete checkpoint %s (missing commit marker — "
            "interrupted save?)", path,
        )
    return None


def _slot_prefix(slot: str) -> str:
    """Dir-name prefix for a checkpoint slot (`step_N` / `last_N`)."""
    assert slot in ("best", "last"), slot
    return "step" if slot == "best" else "last"


def _slot_path(out_dir: str, slot: str, step: int) -> str:
    """The published dir for one save — the single source of the naming
    scheme (save, the async writer's return value, and pruning all
    derive from it)."""
    base = os.path.abspath(os.path.join(out_dir, CHECKPOINT_DIR))
    return os.path.join(base, f"{_slot_prefix(slot)}_{step}")


def sweep_staging_dirs(out_dir: str) -> None:
    """Remove orphaned ``tmp.`` staging dirs (full-size leftovers of saves
    killed mid-persist) and crash-truncated published slot dirs (missing
    the commit marker — e.g. left by a pre-atomic-save version). Restore
    merely *skips* both, so without this sweep every such incident would
    leak a checkpoint-sized dir that also warns on every later restore.
    `_save_tree` clears a stale staging dir only when a later save lands
    on the same step — which a signal-timed preemption save never
    revisits — so resumed runs sweep here (CheckpointWriter init; fresh
    runs additionally sweep via `clear_checkpoints`)."""
    if jax.process_index() != 0:
        return
    base = os.path.abspath(os.path.join(out_dir, CHECKPOINT_DIR))
    if not os.path.isdir(base):
        return
    for name in os.listdir(base):
        path = os.path.join(base, name)
        stem, sep, suffix = name.rpartition("_")
        truncated = (
            sep
            and stem in ("step", "last")
            and suffix.isdigit()
            and os.path.isdir(path)
            and not _is_complete_checkpoint(path)
        )
        if name.startswith(TMP_PREFIX) or truncated:
            logger.info(
                "sweeping %s checkpoint dir %s",
                "stale staging" if name.startswith(TMP_PREFIX)
                else "crash-truncated", name,
            )
            shutil.rmtree(path, ignore_errors=True)


# checkpoint dirs THIS process published or restored from. The same-step
# sidecar-only re-save below is valid only against these: within one
# process, arrays at one optimizer step are identical by construction
# (params/opt-state/rng change only through optimizer steps), but a
# complete dir left by a PREVIOUS run at a colliding step (a re-import
# into the same model_path, a fresh run re-reaching the same best step)
# holds different arrays and must be fully overwritten.
_SAME_RUN_PATHS: set[str] = set()


def _atomic_json(path: str, doc: dict) -> None:
    """Write ``doc`` to ``path`` atomically (tmp file + one os.replace)."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)


def _write_top_level_meta(out_dir: str, meta_dict: dict) -> None:
    """Atomic update of the legacy top-level ``train_meta.json`` — kept
    for compatibility (older tools and humans read it); the per-slot
    sidecar inside the checkpoint dir is authoritative."""
    _atomic_json(os.path.join(out_dir, META_FILE), meta_dict)


def _update_sidecars(
    out_dir: str, path: str, meta_dict: dict, spec_doc: dict,
    slot: str, step: int,
) -> str:
    """Refresh an already-published same-step checkpoint's sidecars (each
    an atomic file replace — a crash at any point leaves the dir complete
    with either the old or the new doc, both valid). Skips the orbax
    array write entirely; fires the same barriers/fault points as a full
    save so plans and multi-host pacing see one consistent sequence."""
    faultinject.fault_point("mid_save", slot=slot, step=step)
    if jax.process_index() == 0:
        logger.info("same-step re-save: refreshing sidecars of %s", path)
        _atomic_json(os.path.join(path, META_FILE), meta_dict)
        _atomic_json(os.path.join(path, SHARDINGS_FILE), spec_doc)
        _write_top_level_meta(out_dir, meta_dict)
    _sync_processes("c2v_ckpt_publish")
    faultinject.fault_point("post_save", slot=slot, step=step)
    return path


def _sync_processes(tag: str) -> None:
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(tag)


def _save_tree(
    out_dir: str,
    tree: dict,
    meta_dict: dict,
    spec_doc: dict,
    step: int,
    slot: str,
) -> str:
    """Write one checkpoint atomically: orbax save into a ``tmp.``-staged
    dir, sidecars (per-slot meta + shardings doc) into the same dir, one
    ``os.replace`` to publish, then pruning. ``tree`` may hold device
    arrays (sync save — orbax coordinates the multi-host write) or a host
    snapshot (the async persist thread, single-process only)."""
    prefix = _slot_prefix(slot)
    base = os.path.abspath(os.path.join(out_dir, CHECKPOINT_DIR))
    os.makedirs(base, exist_ok=True)
    previous = _latest_step_dir(base, prefix)
    path = _slot_path(out_dir, slot, step)
    same_run_resave = path in _SAME_RUN_PATHS and _is_complete_checkpoint(path)
    if jax.process_count() > 1:
        # the branch hinges on a filesystem check that cached-attribute
        # network filesystems can answer differently per host, and hosts
        # disagreeing here would enter different collective sequences
        # (deadlock in the barriers) — so process 0's view decides
        from jax.experimental import multihost_utils

        same_run_resave = bool(
            multihost_utils.broadcast_one_to_all(
                np.asarray(1 if same_run_resave else 0, np.int32)
            )
        )
    if same_run_resave:
        # same-step re-save of THIS run's own arrays (e.g. a preempted
        # resume re-persisting the state it just restored): only the
        # sidecars can differ, so update them atomically IN PLACE — an
        # rmtree+replace swap would open a window with NO published
        # checkpoint, and a SIGKILL there destroys the only restorable
        # save. Colliding dirs from OTHER runs (not in the set) take the
        # full staged save and are overwritten, arrays included.
        return _update_sidecars(out_dir, path, meta_dict, spec_doc, slot, step)
    tmp = os.path.join(base, f"{TMP_PREFIX}{prefix}_{step}")
    if jax.process_index() == 0 and os.path.exists(tmp):
        shutil.rmtree(tmp)  # stale staging dir from an interrupted save
    # all processes must observe the cleared staging dir before the
    # collective orbax save targets it
    _sync_processes("c2v_ckpt_stage")
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(tmp, tree)
    faultinject.fault_point("mid_save", slot=slot, step=step)
    # orbax coordinates the multi-host array save; sidecars, the atomic
    # publish, and pruning are process-0-only
    if jax.process_index() == 0:
        with open(os.path.join(tmp, META_FILE), "w") as f:
            json.dump(meta_dict, f)
        with open(os.path.join(tmp, SHARDINGS_FILE), "w") as f:
            json.dump(spec_doc, f)
        with open(os.path.join(tmp, _OWN_COMMIT_MARKER), "w"):
            pass  # our completeness marker (see _COMMIT_MARKERS)
        if os.path.exists(path):
            # an INCOMPLETE dir (crash-truncated — restore skips it
            # already, removing it destroys nothing restorable) or a
            # complete dir from ANOTHER run (a deliberate overwrite);
            # this run's own complete dirs took the sidecar path above
            shutil.rmtree(path)
        os.replace(tmp, path)
        _write_top_level_meta(out_dir, meta_dict)
        if previous is not None and previous != path:
            shutil.rmtree(previous, ignore_errors=True)
        if slot == "best":
            # a newer best supersedes any older periodic save: prune
            # `last_N` with N <= this step so dead checkpoints don't
            # accumulate (restore picks max-N, which is now this one)
            stale = _latest_step_dir(base, "last")
            if stale is not None and int(stale.rsplit("_", 1)[1]) <= step:
                shutil.rmtree(stale, ignore_errors=True)
    # other processes must not race ahead (e.g. into a restore or the next
    # save's staging) before the publish is visible
    _sync_processes("c2v_ckpt_publish")
    _SAME_RUN_PATHS.add(path)
    faultinject.fault_point("post_save", slot=slot, step=step)
    return path


def save_checkpoint(out_dir: str, state, meta: TrainMeta, slot: str = "best") -> str:
    """Save the train state pytree + loop metadata under ``out_dir``.

    Two slots: ``best`` (``step_N`` dirs — the reference's best-F1 model
    contract, main.py:231) and ``last`` (``last_N`` dirs — periodic
    preemption-safety saves). Each slot prunes only its own older dirs, so
    a periodic save never deletes the best model.

    Preemption-safe twice over: the arrays and sidecars are staged under a
    ``tmp.`` prefix and published with one atomic ``os.replace``, and older
    saves are pruned only after the publish — a crash at ANY point leaves
    either the previous complete checkpoint or both.
    """
    from code2vec_tpu.parallel.shardings import pytree_spec_doc

    faultinject.fault_point("pre_save", slot=slot)
    _stamp_meta(meta, state)
    tree = _state_pytree(state)
    return _save_tree(
        out_dir, tree, asdict(meta), pytree_spec_doc(tree),
        int(state.step), slot,
    )


def snapshot_state(state, meta: TrainMeta) -> tuple[dict, dict, dict, int]:
    """Device-to-host snapshot for an async save: the only phase the train
    loop blocks on. Returns ``(host_tree, meta_dict, spec_doc, step)`` —
    all host-side and immutable w.r.t. further training steps, so the
    persist thread races nothing. Requires every leaf to be process-
    addressable (single-process; multi-process saves stay synchronous)."""
    from code2vec_tpu.parallel.shardings import pytree_spec_doc

    _stamp_meta(meta, state)
    tree = _state_pytree(state)
    spec_doc = pytree_spec_doc(tree)
    # device_get blocks until in-flight steps producing `state` finish —
    # this IS the snapshot cost the loop pays; the disk write is not
    host_tree = jax.device_get(tree)
    return host_tree, asdict(meta), spec_doc, int(state.step)


class CheckpointWriter:
    """The train loop's save orchestrator: sync or async, one interface.

    Async mode (``--async_checkpoint``): :meth:`save` snapshots device
    state to host (``checkpoint_save.snapshot`` span), hands the snapshot
    to a background persist thread (``checkpoint_save.persist`` span,
    emitted on that thread's trace track), and returns — the next train
    step overlaps the disk write. **At most one save is in flight**: a new
    save first waits out the previous persist, so checkpoints can never
    interleave and the loop self-throttles if persistence is slower than
    the save cadence. A persist failure is stored and re-raised into the
    loop at the next :meth:`save`/:meth:`finish` — checkpoint corruption
    must fail the run, not a daemon thread.

    Multi-process runs force sync mode: the orbax array save is collective
    and a host snapshot would need every leaf process-addressable.

    Sync mode runs the same phases inline (the snapshot span then measures
    zero — sync saves hand device arrays straight to orbax).
    """

    def __init__(
        self,
        out_dir: str,
        async_save: bool = False,
        events=None,
        tracer=None,
    ):
        from code2vec_tpu.obs.trace import get_tracer

        self.out_dir = out_dir
        self.events = events
        self.tracer = tracer or get_tracer()
        if async_save and jax.process_count() > 1:
            logger.warning(
                "--async_checkpoint is single-process only (the orbax "
                "array save is collective on pods); using synchronous saves"
            )
            async_save = False
        self.async_save = async_save
        self._thread: threading.Thread | None = None
        self._failure: BaseException | None = None
        self._lock = make_lock("checkpoint.writer")
        sweep_staging_dirs(out_dir)
        handles.track(self, "checkpoint_writer", name=out_dir)

    # ---- failure propagation -------------------------------------------
    def check(self) -> None:
        """Re-raise a stored persist failure into the caller."""
        with self._lock:
            failure, self._failure = self._failure, None
        if failure is not None:
            raise failure

    def wait(self) -> None:
        """Block until no save is in flight (does NOT check for failure)."""
        thread = self._thread
        if thread is not None:
            thread.join()
            self._thread = None

    def finish(self) -> None:
        """Drain the in-flight save and surface any failure — the loop's
        normal-completion barrier."""
        self.wait()
        self.check()

    def close(self) -> None:
        """finally-block variant: drain, log (don't raise) failures, so an
        exception already unwinding is never masked."""
        self.wait()
        with self._lock:
            failure, self._failure = self._failure, None
        if failure is not None:
            logger.error("async checkpoint persist failed", exc_info=failure)
        handles.untrack(self)

    # ---- saving ---------------------------------------------------------
    def save(self, state, meta: TrainMeta, slot: str, **event_fields) -> str:
        """Save ``state``/``meta`` into ``slot``; returns the final path
        (for async saves: the path the in-flight persist will publish)."""
        # at-most-one in flight + propagate the previous save's failure
        self.wait()
        self.check()
        if not self.async_save:
            with self.tracer.span(
                "checkpoint_save.persist", category="checkpoint",
                slot=slot, mode="sync", **event_fields,
            ):
                path = save_checkpoint(self.out_dir, state, meta, slot=slot)
            self._emit(slot, path, int(state.step), False, event_fields)
            return path

        faultinject.fault_point("pre_save", slot=slot)
        with self.tracer.span(
            "checkpoint_save.snapshot", category="checkpoint",
            slot=slot, **event_fields,
        ):
            host_tree, meta_dict, spec_doc, step = snapshot_state(state, meta)
        path = _slot_path(self.out_dir, slot, step)
        self._thread = threading.Thread(
            target=self._persist,
            args=(host_tree, meta_dict, spec_doc, step, slot, event_fields),
            name="c2v-ckpt-persist",
            daemon=True,
        )
        self._thread.start()
        return path

    def _persist(
        self, host_tree, meta_dict, spec_doc, step, slot, event_fields
    ) -> None:
        try:
            with self.tracer.span(
                "checkpoint_save.persist", category="checkpoint",
                slot=slot, mode="async", **event_fields,
            ):
                path = _save_tree(
                    self.out_dir, host_tree, meta_dict, spec_doc, step, slot
                )
            self._emit(slot, path, step, True, event_fields)
        except BaseException as exc:  # noqa: BLE001 - re-raised in the loop
            with self._lock:
                self._failure = exc

    def _emit(self, slot, path, step, was_async, event_fields) -> None:
        if self.events is not None:
            self.events.emit(
                "checkpoint_saved",
                slot=slot,
                path=path,
                step=step,
                **{"async": was_async},
                **event_fields,
            )


def clear_checkpoints(out_dir: str, slot: str = "last") -> None:
    """Remove a checkpoint slot under ``out_dir``.

    Fresh (non-resume) runs clear only the ``last`` (periodic) slot: it
    belongs to the interrupted run it was saved by, and left in place it
    could outrank the new run's ``best`` saves at a later ``--resume``. The
    ``best`` slot and metadata are preserved until the new run's first save
    overwrites them, so a crash before that never leaves the directory
    without a restorable checkpoint. Staging (``tmp.``) leftovers from
    crashed saves are always swept.

    Process-0-only under multi-host; other processes race benignly since
    they never read before the barrier implied by the first save.
    """
    base = os.path.abspath(os.path.join(out_dir, CHECKPOINT_DIR))
    # a fresh run severs the same-run relationship with everything under
    # this model_path: surviving dirs (the preserved best slot) belong to
    # the PREVIOUS run and must never take the sidecar-only re-save path
    _SAME_RUN_PATHS.difference_update(
        {p for p in _SAME_RUN_PATHS if p.startswith(base + os.sep) or p == base}
    )
    if jax.process_index() != 0:
        return
    prefix = _slot_prefix(slot)
    if not os.path.isdir(base):
        return
    for name in os.listdir(base):
        if name.startswith(prefix + "_") or name.startswith(TMP_PREFIX):
            logger.info("fresh run: clearing stale checkpoint %s", name)
            shutil.rmtree(os.path.join(base, name), ignore_errors=True)


def _slot_meta(path: str, out_dir: str) -> TrainMeta:
    """The meta matching the checkpoint at ``path``: its per-slot sidecar
    when present (always, for saves from this version on), else the legacy
    single top-level file — which belonged to the newest save of either
    slot, the documented quirk the sidecar exists to fix."""
    sidecar = os.path.join(path, META_FILE)
    meta_path = sidecar if os.path.exists(sidecar) else os.path.join(
        out_dir, META_FILE
    )
    with open(meta_path) as f:
        return TrainMeta(**json.load(f))


@dataclass
class RestoredCheckpoint:
    """Restore result: unpacks like the historical ``(state, meta)`` tuple
    but also carries provenance for the ``checkpoint_restored`` event."""

    state: object
    meta: TrainMeta
    slot: str
    path: str
    resharded: bool
    saved_mesh_shape: dict | None

    def __iter__(self):
        return iter((self.state, self.meta))

    def __getitem__(self, index):
        return (self.state, self.meta)[index]


def restore_checkpoint(
    out_dir: str,
    state,
    vocab_pad_multiple: int | None = None,
    prefer_best: bool = False,
    mesh=None,
) -> RestoredCheckpoint | None:
    """Restore into the shape of ``state``; returns None if no checkpoint.

    Default (``--resume``): the newest *complete* save across both slots
    (the ``last`` periodic save when it is fresher than the ``best`` one);
    ``step`` counts optimizer steps monotonically, so the larger suffix is
    the later save. ``prefer_best`` (the export path): the best-F1 ``step``
    slot when present — a fresher periodic save is NOT the model the
    in-training export would have written. Metadata comes from the chosen
    dir's own sidecar, so the bookkeeping always matches the restored
    arrays.

    ``mesh``: the run's current mesh (or None). When the checkpoint carries
    a ``shardings.json`` sidecar, its PartitionSpecs are validated against
    this mesh (analysis.sharding_check.validate_runtime_spec) and re-bound
    to it (parallel.shardings.rebind_abstract_shardings) — orbax then loads
    every shard directly onto its new home device. ``resharded`` reports
    whether the save-time mesh shape differs from the current one.
    """
    base = os.path.abspath(os.path.join(out_dir, CHECKPOINT_DIR))
    best_path = _latest_step_dir(base, "step")
    candidates = [
        p for p in (best_path, _latest_step_dir(base, "last")) if p is not None
    ]
    if not candidates:
        return None
    if prefer_best and best_path is not None:
        path = best_path
    else:
        path = max(candidates, key=lambda p: int(p.rsplit("_", 1)[1]))
    if not os.path.exists(os.path.join(path, META_FILE)) and not os.path.exists(
        os.path.join(out_dir, META_FILE)
    ):
        return None
    saved_meta = _slot_meta(path, out_dir)
    want_impl = _rng_impl_name(state.dropout_rng)
    # checkpoints from before rng_impl was recorded hold raw threefry keys
    saved_impl = saved_meta.rng_impl or "threefry2x32"
    if saved_impl != want_impl:
        raise ValueError(
            f"checkpoint in {base} was saved with --rng_impl "
            f"{saved_impl} but this run uses {want_impl}; pass "
            f"--rng_impl {saved_impl} to resume it"
        )
    want_update = _table_update_name(state)
    # metas from before the field are dense (the only behavior then)
    saved_update = saved_meta.table_update or "dense"
    if saved_update != want_update:
        raise ValueError(
            f"checkpoint in {base} was saved with --table_update "
            f"{saved_update} but this run uses {want_update}; pass "
            f"--table_update {saved_update} to resume it (the optimizer "
            "state structures differ)"
        )
    want_mu = _adam_mu_dtype_name(state)
    # metas from before the field hold f32 moments (the only behavior then);
    # a template without Adam state (want_mu None) skips the check
    saved_mu = saved_meta.adam_mu_dtype or "float32"
    if want_mu is not None and saved_mu != want_mu:
        raise ValueError(
            f"checkpoint in {base} stores Adam first moments as "
            f"{saved_mu} but this run uses {want_mu}; pass "
            f"--adam_mu_dtype {saved_mu} to resume it"
        )
    saved_pad = saved_meta.vocab_pad_multiple
    if (
        vocab_pad_multiple is not None
        and saved_pad is not None
        and saved_pad != vocab_pad_multiple
    ):
        raise ValueError(
            f"checkpoint in {base} was saved with vocab tables padded to a "
            f"multiple of {saved_pad} but this run pads to "
            f"{vocab_pad_multiple} (it follows model_axis unless pinned); "
            f"pass --vocab_pad_multiple {saved_pad} to resume it under a "
            "different mesh"
        )
    template = _state_pytree(state)
    abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, template)
    saved_mesh_shape: dict | None = None
    resharded = False
    spec_path = os.path.join(path, SHARDINGS_FILE)
    if os.path.exists(spec_path):
        with open(spec_path) as f:
            spec_doc = json.load(f)
        saved_mesh_shape = spec_doc.get("mesh_shape")
        if mesh is not None:
            from code2vec_tpu.analysis.sharding_check import (
                validate_runtime_spec,
            )
            from code2vec_tpu.parallel.shardings import (
                rebind_abstract_shardings,
            )

            problems: list[str] = []
            for key, entries in (spec_doc.get("specs") or {}).items():
                if entries:
                    problems.extend(
                        validate_runtime_spec(
                            entries, mesh.axis_names, context=key
                        )
                    )
            if problems:
                raise ValueError(
                    f"checkpoint in {path} carries PartitionSpecs that do "
                    "not fit the restore mesh:\n  "
                    + "\n  ".join(problems)
                )
            abstract = rebind_abstract_shardings(mesh, abstract, spec_doc)
            resharded = saved_mesh_shape != dict(mesh.shape)
        else:
            resharded = saved_mesh_shape is not None
    with ocp.StandardCheckpointer() as ckptr:
        restored = ckptr.restore(path, abstract)
    if mesh is None:
        # drop orbax's COMMITTED placement on the single-device path: jit
        # keys on committed-ness, so a committed restored state would
        # re-specialize every step fn on the first post-resume step (one
        # full XLA compile per resume, and shape-churn noise against the
        # bucketed recompile budget). The host round-trip is a one-time
        # restore cost, far cheaper than the compile it avoids. Mesh runs
        # need no fix: shard_state's device_put makes the live state just
        # as committed as the restored one. np.array(copy) then jnp.array
        # (copy=True): BOTH hops must copy — on CPU np.asarray/jnp.asarray
        # are zero-copy views of the XLA buffer, and donating a
        # buffer-sharing state into the step fn frees memory the orbax
        # array still owns (heap corruption).
        # every leaf is a plain-dtype array here — _state_pytree saves
        # dropout_rng as raw key_data, and the template comes from the
        # same function
        restored = jax.tree.map(
            lambda leaf: jnp.array(np.array(leaf), copy=True), restored
        )
    else:
        # fresh XLA-owned buffers, same shardings: orbax's CPU restore can
        # hand back shards that alias one host allocation — the step fn
        # donates the state, and donating aliased buffers frees that
        # allocation piecewise (heap corruption). Copy INSIDE jit (no
        # donation, so outputs are newly allocated buffers): an eager
        # per-leaf copy would reject pod restores, whose global arrays are
        # not fully addressable by one process. Noise next to restore I/O.
        restored = jax.jit(
            lambda tree: jax.tree.map(jnp.copy, tree)
        )(restored)
    if resharded:
        logger.info(
            "restored checkpoint saved on mesh %s onto %s (PartitionSpecs "
            "re-bound; arrays resharded at load)",
            saved_mesh_shape,
            dict(mesh.shape) if mesh is not None else "a single device",
        )
    dropout_rng = restored["dropout_rng"]
    if jax.dtypes.issubdtype(state.dropout_rng.dtype, jax.dtypes.prng_key):
        # re-wrap with the template's impl: key-data shape differs between
        # threefry ([2] uint32) and rbg ([4] uint32) keys
        dropout_rng = jax.random.wrap_key_data(
            dropout_rng, impl=jax.random.key_impl(state.dropout_rng)
        )
    new_state = state.replace(
        params=restored["params"],
        opt_state=restored["opt_state"],
        dropout_rng=dropout_rng,
        # int32 array, not a weak Python int: a weak-typed step would trace
        # one extra jit-cache entry on the first post-resume step (see
        # create_train_state) and overflow the bucketed recompile budget
        step=jnp.asarray(int(restored["step"]), jnp.int32),
    )
    # a later same-step re-save of this state (preempted resume) may take
    # the in-place sidecar path against this dir
    _SAME_RUN_PATHS.add(path)
    slot = "best" if os.path.basename(path).startswith("step_") else "last"
    return RestoredCheckpoint(
        state=new_state,
        meta=saved_meta,
        slot=slot,
        path=path,
        resharded=resharded,
        saved_mesh_shape=saved_mesh_shape,
    )
