"""Hyperparameter search — the Optuna-HPO equivalent, self-contained.

The reference drives ``optuna.create_study(MedianPruner()).optimize``
(main.py:429-488) with per-epoch ``trial.report(1 - f1)`` + pruning
(main.py:207-211). Optuna is not available in this image, so this module
implements the same surface natively:

- a :class:`Study` sampling over the same distributions the reference's
  objective draws from (main.py:447-449, 477-483): ``encode_size`` log-int
  100..300, ``dropout_prob`` 0.5..0.9, ``batch_size`` log-int 256..2048,
  Adam ``lr`` log 1e-5..1e-1 and ``weight_decay`` log 1e-10..1e-3;
- a :class:`TPESampler` — optuna's default sampler
  (``optuna.create_study`` with no sampler argument is TPE, main.py:460)
  re-implemented from the published algorithm (Bergstra et al., NeurIPS
  2011): per-parameter Parzen estimators over the best/rest split, with
  candidate selection by the l(x)/g(x) density ratio. A
  :class:`RandomSampler` remains as the fallback (``sampler="random"``);
- a :class:`MedianPruner` with optuna's semantics: after
  ``n_startup_trials`` finished trials, prune when the trial's best
  intermediate value so far is worse than the median of prior trials'
  intermediate values at the same step;
- :func:`find_optimal_hyperparams`, the ``main.py --find_hyperparams``
  entry: objective = ``1 - best_f1`` (minimized), pruning wired into the
  train loop through its ``report_fn`` hook (which raises
  :class:`~code2vec_tpu.train.loop.StopTraining`).

The corpus is loaded ONCE and shared across trials, matching the
reference's reader/builder reuse (main.py:431-441). Each trial still
traces/compiles its own train step — trial dims change model shapes, so
jit caches cannot be shared; XLA's compilation cache softens repeats.
"""

from __future__ import annotations

import logging
import math
from dataclasses import dataclass, field
from typing import Callable

import numpy as np

logger = logging.getLogger(__name__)


class TrialPruned(Exception):
    """Raised inside an objective to mark the running trial pruned."""


@dataclass
class FrozenTrial:
    """Completed/pruned trial record (optuna's FrozenTrial analogue)."""

    number: int
    params: dict[str, float | int]
    intermediates: dict[int, float] = field(default_factory=dict)
    value: float | None = None
    state: str = "running"  # running | complete | pruned | failed


class MedianPruner:
    """Prune when the trial's best intermediate so far is worse (for
    minimization: greater) than the median of previous finished trials'
    intermediate values at the same step.

    ``n_startup_trials`` trials run unpruned first; steps below
    ``n_warmup_steps`` never prune. Matches optuna's defaults (5 / 0).
    """

    def __init__(self, n_startup_trials: int = 5, n_warmup_steps: int = 0):
        self.n_startup_trials = n_startup_trials
        self.n_warmup_steps = n_warmup_steps

    def should_prune(self, study: "Study", trial: FrozenTrial) -> bool:
        if not trial.intermediates:
            return False
        step = max(trial.intermediates)
        if step < self.n_warmup_steps:
            return False
        # optuna parity: only COMPLETE trials gate startup and feed the
        # median (pruned trials' bad tails would skew it), and each prior
        # trial contributes its BEST intermediate up to this step, not the
        # raw value at the step (a trial that regressed late still counts
        # by its early best)
        finished = [
            t for t in study.trials
            if t.number != trial.number and t.state == "complete"
        ]
        if len(finished) < self.n_startup_trials:
            return False
        at_step = [
            min(v for s, v in t.intermediates.items() if s <= step)
            for t in finished
            if step in t.intermediates
        ]
        if not at_step:
            return False
        best_so_far = min(trial.intermediates.values())
        return best_so_far > float(np.median(at_step))


@dataclass(frozen=True)
class _Distribution:
    """Search-space descriptor for one parameter."""

    low: float
    high: float
    log: bool = False
    is_int: bool = False

    def to_internal(self, value: float) -> float:
        return math.log(value) if self.log else float(value)

    def from_internal(self, x: float) -> float | int:
        value = math.exp(x) if self.log else x
        if self.is_int:
            value = min(max(int(round(value)), int(self.low)), int(self.high))
        return value

    @property
    def internal_low(self) -> float:
        return math.log(self.low) if self.log else self.low

    @property
    def internal_high(self) -> float:
        return math.log(self.high) if self.log else self.high


class RandomSampler:
    """Independent uniform (or log-uniform) draws — the pre-TPE behavior."""

    def __init__(self, seed: int = 0):
        self._rng = np.random.default_rng(seed)

    def suggest(self, study: "Study", trial: FrozenTrial, name: str,
                dist: _Distribution) -> float | int:
        x = self._rng.uniform(dist.internal_low, dist.internal_high)
        return dist.from_internal(x)


class _ParzenEstimator:
    """1-D mixture of truncated Gaussians over the internal domain
    (Bergstra et al. 2011 §4: per-point bandwidths from neighbor spacing,
    plus a wide uniform-ish prior component at the domain midpoint)."""

    def __init__(self, xs: np.ndarray, low: float, high: float):
        span = max(high - low, 1e-12)
        mid = 0.5 * (low + high)
        mus = np.sort(np.append(xs, mid))
        if len(mus) > 1:
            neighbor = np.empty_like(mus)
            gaps = np.diff(mus)
            neighbor[0] = gaps[0]
            neighbor[-1] = gaps[-1]
            if len(mus) > 2:
                neighbor[1:-1] = np.maximum(gaps[:-1], gaps[1:])
            sigmas = np.clip(neighbor, span / min(100.0, len(mus) + 1.0), span)
        else:
            sigmas = np.full_like(mus, span)
        # the prior component (at mid) always keeps full-range bandwidth
        sigmas[np.argmin(np.abs(mus - mid))] = span
        self.mus, self.sigmas = mus, sigmas
        self.low, self.high = low, high
        # truncation mass of each component on [low, high]
        self._z = self._cdf((high - mus) / sigmas) - self._cdf((low - mus) / sigmas)
        self._z = np.maximum(self._z, 1e-12)

    @staticmethod
    def _cdf(z: np.ndarray) -> np.ndarray:
        # vectorized standard-normal CDF via erf (math.erf is scalar-only)
        return 0.5 * (1.0 + np.vectorize(math.erf)(z / math.sqrt(2.0)))

    def sample(self, rng: np.random.Generator, n: int) -> np.ndarray:
        idx = rng.integers(0, len(self.mus), n)
        draws = rng.normal(self.mus[idx], self.sigmas[idx])
        return np.clip(draws, self.low, self.high)

    def log_pdf(self, xs: np.ndarray) -> np.ndarray:
        z = (xs[:, None] - self.mus[None, :]) / self.sigmas[None, :]
        comp = (
            np.exp(-0.5 * z**2)
            / (self.sigmas[None, :] * math.sqrt(2.0 * math.pi))
            / self._z[None, :]
        )
        return np.log(np.maximum(comp.mean(axis=1), 1e-300))


class TPESampler:
    """Tree-structured Parzen Estimator, sampling each parameter
    independently (optuna's default mode): split prior trials into the
    gamma-best ("good") and the rest ("bad"), fit Parzen estimators l(x)
    and g(x), draw ``n_ei_candidates`` from l and keep the candidate
    maximizing l(x)/g(x). Falls back to random until ``n_startup_trials``
    scored trials exist (optuna defaults: 10 startup, 24 candidates,
    gamma(n) = min(ceil(0.1 n), 25))."""

    def __init__(self, n_startup_trials: int = 10, n_ei_candidates: int = 24,
                 seed: int = 0):
        self.n_startup_trials = n_startup_trials
        self.n_ei_candidates = n_ei_candidates
        self._rng = np.random.default_rng(seed)

    @staticmethod
    def _gamma(n: int) -> int:
        return min(int(np.ceil(0.1 * n)), 25)

    def _scored_observations(
        self, study: "Study", trial: FrozenTrial, name: str
    ) -> list[tuple[float, float]]:
        """(objective value, param value) for prior trials that drew
        ``name``; pruned trials count by their best intermediate, like
        optuna's TPE does."""
        out = []
        for t in study.trials:
            if t.number == trial.number or name not in t.params:
                continue
            if t.state == "complete" and t.value is not None:
                out.append((t.value, t.params[name]))
            elif t.state == "pruned" and t.intermediates:
                out.append((min(t.intermediates.values()), t.params[name]))
        return out

    def suggest(self, study: "Study", trial: FrozenTrial, name: str,
                dist: _Distribution) -> float | int:
        obs = self._scored_observations(study, trial, name)
        if len(obs) < self.n_startup_trials:
            x = self._rng.uniform(dist.internal_low, dist.internal_high)
            return dist.from_internal(x)

        obs.sort(key=lambda pair: pair[0])
        n_good = self._gamma(len(obs))
        xs = np.array([dist.to_internal(v) for _, v in obs])
        good = _ParzenEstimator(
            xs[:n_good], dist.internal_low, dist.internal_high
        )
        bad = _ParzenEstimator(
            xs[n_good:], dist.internal_low, dist.internal_high
        )
        candidates = good.sample(self._rng, self.n_ei_candidates)
        score = good.log_pdf(candidates) - bad.log_pdf(candidates)
        return dist.from_internal(float(candidates[int(np.argmax(score))]))


class Trial:
    """Sampling + reporting handle passed to the objective."""

    def __init__(self, study: "Study", record: FrozenTrial):
        self._study = study
        self._record = record

    @property
    def number(self) -> int:
        return self._record.number

    @property
    def params(self) -> dict[str, float | int]:
        return self._record.params

    def _suggest(self, name: str, dist: _Distribution) -> float | int:
        value = self._study.sampler.suggest(self._study, self._record, name, dist)
        self._record.params[name] = value
        return value

    def suggest_float(self, name: str, low: float, high: float,
                      log: bool = False) -> float:
        return float(self._suggest(name, _Distribution(low, high, log=log)))

    def suggest_int(self, name: str, low: int, high: int,
                    log: bool = False) -> int:
        return int(self._suggest(
            name, _Distribution(low, high, log=log, is_int=True)
        ))

    def report(self, value: float, step: int) -> None:
        self._record.intermediates[step] = float(value)

    def should_prune(self) -> bool:
        return self._study.pruner.should_prune(self._study, self._record)


class Study:
    """Minimizing study with pruning; TPE sampling by default (the
    reference's ``optuna.create_study`` default, main.py:460)."""

    def __init__(self, pruner: MedianPruner | None = None, seed: int = 0,
                 sampler: "TPESampler | RandomSampler | str | None" = None):
        self.pruner = pruner if pruner is not None else MedianPruner()
        if sampler is None or sampler == "tpe":
            sampler = TPESampler(seed=seed)
        elif sampler == "random":
            sampler = RandomSampler(seed=seed)
        self.sampler = sampler
        self.trials: list[FrozenTrial] = []

    def optimize(self, objective: Callable[[Trial], float],
                 n_trials: int) -> None:
        for _ in range(n_trials):
            record = FrozenTrial(number=len(self.trials), params={})
            self.trials.append(record)
            trial = Trial(self, record)
            try:
                record.value = float(objective(trial))
                record.state = "complete"
            except TrialPruned:
                # a pruned trial still scores: its best intermediate
                record.value = (
                    min(record.intermediates.values())
                    if record.intermediates else None
                )
                record.state = "pruned"
                logger.info("trial %d pruned at step %s", record.number,
                            max(record.intermediates, default=None))
            logger.info("trial %d %s value=%s params=%s", record.number,
                        record.state, record.value, record.params)

    @property
    def best_trial(self) -> FrozenTrial:
        scored = [t for t in self.trials
                  if t.state == "complete" and t.value is not None]
        if not scored:
            raise ValueError("no completed trials")
        return min(scored, key=lambda t: t.value)

    @property
    def best_value(self) -> float:
        return self.best_trial.value

    @property
    def best_params(self) -> dict[str, float | int]:
        return self.best_trial.params


def sample_train_config(trial: Trial, base_config):
    """Draw the reference's search space into a TrainConfig
    (main.py:447-449 for dims, 477-483 for Adam)."""
    return base_config.with_updates(
        encode_size=trial.suggest_int("encode_size", 100, 300, log=True),
        dropout_prob=trial.suggest_float("dropout_prob", 0.5, 0.9),
        batch_size=trial.suggest_int("batch_size", 256, 2048, log=True),
        lr=trial.suggest_float("adam_lr", 1e-5, 1e-1, log=True),
        weight_decay=trial.suggest_float(
            "adam_weight_decay", 1e-10, 1e-3, log=True),
    )


def find_optimal_hyperparams(
    data,
    base_config,
    n_trials: int = 100,
    seed: int = 0,
    pruner: MedianPruner | None = None,
    sampler: TPESampler | RandomSampler | str | None = None,
    events=None,
) -> Study:
    """The ``--find_hyperparams`` entry (reference: main.py:429-488).

    Each trial trains with the sampled config; per-epoch ``1 - f1`` is
    reported for median pruning (reference: main.py:207-211), and the
    objective value is ``1 - best_f1``. Checkpoint/vector export is
    suppressed during search, as in the reference (``trial is not None``
    guards, main.py:226-231).

    ``events``: a shared ``obs.events.EventLog`` for the whole search.
    The manifest is written once, up front, with the BASE config; each
    trial then opens with a ``trial`` event carrying its number and
    sampled params — events are strictly ordered, so everything between
    one ``trial`` marker and the next belongs to that trial — and closes
    with a ``trial_result`` event (state + objective value).
    """
    from code2vec_tpu.train.loop import StopTraining, train

    if events is not None:
        events.write_manifest(
            config=base_config, search={"n_trials": n_trials, "seed": seed}
        )

    def objective(trial: Trial) -> float:
        config = sample_train_config(trial, base_config)
        logger.info("trial %d config: %s", trial.number, trial.params)
        if events is not None:
            events.emit("trial", number=trial.number, params=dict(trial.params))
        pruned = False

        def report_fn(epoch: int, f1: float) -> None:
            nonlocal pruned
            trial.report(1.0 - f1, epoch)
            if trial.should_prune():
                pruned = True
                raise StopTraining  # caught by the train loop; ends the run

        result = train(config, data, report_fn=report_fn, events=events)
        if events is not None:
            events.emit(
                "trial_result",
                number=trial.number,
                state="pruned" if pruned else "complete",
                value=1.0 - result.best_f1,
            )
        if pruned:
            raise TrialPruned
        return 1.0 - result.best_f1

    study = Study(pruner=pruner, seed=seed, sampler=sampler)
    study.optimize(objective, n_trials)
    best = study.best_trial
    logger.info("best trial: #%d value=%s params=%s", best.number, best.value,
                best.params)
    return study
