"""Hyperparameter search — the Optuna-HPO equivalent, self-contained.

The reference drives ``optuna.create_study(MedianPruner()).optimize``
(main.py:429-488) with per-epoch ``trial.report(1 - f1)`` + pruning
(main.py:207-211). Optuna is not available in this image, so this module
implements the same surface natively:

- a :class:`Study` with random sampling over the same distributions the
  reference's objective draws from (main.py:447-449, 477-483):
  ``encode_size`` log-int 100..300, ``dropout_prob`` 0.5..0.9,
  ``batch_size`` log-int 256..2048, Adam ``lr`` log 1e-5..1e-1 and
  ``weight_decay`` log 1e-10..1e-3;
- a :class:`MedianPruner` with optuna's semantics: after
  ``n_startup_trials`` finished trials, prune when the trial's best
  intermediate value so far is worse than the median of prior trials'
  intermediate values at the same step;
- :func:`find_optimal_hyperparams`, the ``main.py --find_hyperparams``
  entry: objective = ``1 - best_f1`` (minimized), pruning wired into the
  train loop through its ``report_fn`` hook (which raises
  :class:`~code2vec_tpu.train.loop.StopTraining`).

The corpus is loaded ONCE and shared across trials, matching the
reference's reader/builder reuse (main.py:431-441). Each trial still
traces/compiles its own train step — trial dims change model shapes, so
jit caches cannot be shared; XLA's compilation cache softens repeats.
"""

from __future__ import annotations

import logging
import math
from dataclasses import dataclass, field
from typing import Callable

import numpy as np

logger = logging.getLogger(__name__)


class TrialPruned(Exception):
    """Raised inside an objective to mark the running trial pruned."""


@dataclass
class FrozenTrial:
    """Completed/pruned trial record (optuna's FrozenTrial analogue)."""

    number: int
    params: dict[str, float | int]
    intermediates: dict[int, float] = field(default_factory=dict)
    value: float | None = None
    state: str = "running"  # running | complete | pruned | failed


class MedianPruner:
    """Prune when the trial's best intermediate so far is worse (for
    minimization: greater) than the median of previous finished trials'
    intermediate values at the same step.

    ``n_startup_trials`` trials run unpruned first; steps below
    ``n_warmup_steps`` never prune. Matches optuna's defaults (5 / 0).
    """

    def __init__(self, n_startup_trials: int = 5, n_warmup_steps: int = 0):
        self.n_startup_trials = n_startup_trials
        self.n_warmup_steps = n_warmup_steps

    def should_prune(self, study: "Study", trial: FrozenTrial) -> bool:
        if not trial.intermediates:
            return False
        step = max(trial.intermediates)
        if step < self.n_warmup_steps:
            return False
        # optuna parity: only COMPLETE trials gate startup and feed the
        # median (pruned trials' bad tails would skew it), and each prior
        # trial contributes its BEST intermediate up to this step, not the
        # raw value at the step (a trial that regressed late still counts
        # by its early best)
        finished = [
            t for t in study.trials
            if t.number != trial.number and t.state == "complete"
        ]
        if len(finished) < self.n_startup_trials:
            return False
        at_step = [
            min(v for s, v in t.intermediates.items() if s <= step)
            for t in finished
            if step in t.intermediates
        ]
        if not at_step:
            return False
        best_so_far = min(trial.intermediates.values())
        return best_so_far > float(np.median(at_step))


class Trial:
    """Sampling + reporting handle passed to the objective."""

    def __init__(self, study: "Study", record: FrozenTrial,
                 rng: np.random.Generator):
        self._study = study
        self._record = record
        self._rng = rng

    @property
    def number(self) -> int:
        return self._record.number

    @property
    def params(self) -> dict[str, float | int]:
        return self._record.params

    def suggest_float(self, name: str, low: float, high: float,
                      log: bool = False) -> float:
        if log:
            value = math.exp(self._rng.uniform(math.log(low), math.log(high)))
        else:
            value = float(self._rng.uniform(low, high))
        self._record.params[name] = value
        return value

    def suggest_int(self, name: str, low: int, high: int,
                    log: bool = False) -> int:
        if log:
            value = int(round(math.exp(
                self._rng.uniform(math.log(low), math.log(high)))))
            value = min(max(value, low), high)
        else:
            value = int(self._rng.integers(low, high + 1))
        self._record.params[name] = value
        return value

    def report(self, value: float, step: int) -> None:
        self._record.intermediates[step] = float(value)

    def should_prune(self) -> bool:
        return self._study.pruner.should_prune(self._study, self._record)


class Study:
    """Minimizing random-search study with pruning."""

    def __init__(self, pruner: MedianPruner | None = None, seed: int = 0):
        self.pruner = pruner if pruner is not None else MedianPruner()
        self.trials: list[FrozenTrial] = []
        self._rng = np.random.default_rng(seed)

    def optimize(self, objective: Callable[[Trial], float],
                 n_trials: int) -> None:
        for _ in range(n_trials):
            record = FrozenTrial(number=len(self.trials), params={})
            self.trials.append(record)
            trial = Trial(self, record, self._rng)
            try:
                record.value = float(objective(trial))
                record.state = "complete"
            except TrialPruned:
                # a pruned trial still scores: its best intermediate
                record.value = (
                    min(record.intermediates.values())
                    if record.intermediates else None
                )
                record.state = "pruned"
                logger.info("trial %d pruned at step %s", record.number,
                            max(record.intermediates, default=None))
            logger.info("trial %d %s value=%s params=%s", record.number,
                        record.state, record.value, record.params)

    @property
    def best_trial(self) -> FrozenTrial:
        scored = [t for t in self.trials
                  if t.state == "complete" and t.value is not None]
        if not scored:
            raise ValueError("no completed trials")
        return min(scored, key=lambda t: t.value)

    @property
    def best_value(self) -> float:
        return self.best_trial.value

    @property
    def best_params(self) -> dict[str, float | int]:
        return self.best_trial.params


def sample_train_config(trial: Trial, base_config):
    """Draw the reference's search space into a TrainConfig
    (main.py:447-449 for dims, 477-483 for Adam)."""
    return base_config.with_updates(
        encode_size=trial.suggest_int("encode_size", 100, 300, log=True),
        dropout_prob=trial.suggest_float("dropout_prob", 0.5, 0.9),
        batch_size=trial.suggest_int("batch_size", 256, 2048, log=True),
        lr=trial.suggest_float("adam_lr", 1e-5, 1e-1, log=True),
        weight_decay=trial.suggest_float(
            "adam_weight_decay", 1e-10, 1e-3, log=True),
    )


def find_optimal_hyperparams(
    data,
    base_config,
    n_trials: int = 100,
    seed: int = 0,
    pruner: MedianPruner | None = None,
) -> Study:
    """The ``--find_hyperparams`` entry (reference: main.py:429-488).

    Each trial trains with the sampled config; per-epoch ``1 - f1`` is
    reported for median pruning (reference: main.py:207-211), and the
    objective value is ``1 - best_f1``. Checkpoint/vector export is
    suppressed during search, as in the reference (``trial is not None``
    guards, main.py:226-231).
    """
    from code2vec_tpu.train.loop import StopTraining, train

    def objective(trial: Trial) -> float:
        config = sample_train_config(trial, base_config)
        logger.info("trial %d config: %s", trial.number, trial.params)
        pruned = False

        def report_fn(epoch: int, f1: float) -> None:
            nonlocal pruned
            trial.report(1.0 - f1, epoch)
            if trial.should_prune():
                pruned = True
                raise StopTraining  # caught by the train loop; ends the run

        result = train(config, data, report_fn=report_fn)
        if pruned:
            raise TrialPruned
        return 1.0 - result.best_f1

    study = Study(pruner=pruner, seed=seed)
    study.optimize(objective, n_trials)
    best = study.best_trial
    logger.info("best trial: #%d value=%s params=%s", best.number, best.value,
                best.params)
    return study
