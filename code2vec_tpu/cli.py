"""The experiment-driver CLI — flag-for-flag parity with the reference's
entry point (reference: main.py:37-81,494-502) plus the TPU-native knobs.

Usage mirrors the reference README:

    python -m code2vec_tpu --corpus_path d/corpus.txt \
        --path_idx_path d/path_idxs.txt --terminal_idx_path d/terminal_idxs.txt

Reference flags kept verbatim: seeds, corpus paths, model dims, optimizer,
dropout, output paths, ``--env`` (tensorboard|floyd), eval/print cycles,
HPO (``--find_hyperparams`` / ``--num_trials``), angular-margin head, task
selection. ``--no_cuda`` keeps its reference meaning — don't use the
accelerator — by pinning the CPU backend. The remaining CUDA-machinery
flags (``--gpu``, ``--num_workers``) are accepted for drop-in compatibility
but are no-ops: device placement is JAX's job and the input pipeline is
vectorized host-side (no worker pool to size).

TPU-native additions (no reference counterpart): ``--compute_dtype``,
``--use_pallas``, mesh axes (``--data_axis``/``--model_axis``/
``--context_axis``), ``--resume``, ``--profile_dir``,
``--class_weighting``.
"""

from __future__ import annotations

import argparse
import logging
import os

logger = logging.getLogger(__name__)


def _strtobool(value: str) -> bool:
    """The reference parses bool flags via distutils ``strtobool``
    (main.py:77-79); distutils is gone in py3.12, so re-state the rule."""
    lowered = value.strip().lower()
    if lowered in ("y", "yes", "t", "true", "on", "1"):
        return True
    if lowered in ("n", "no", "f", "false", "off", "0"):
        return False
    raise argparse.ArgumentTypeError(f"invalid truth value {value!r}")


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="code2vec_tpu",
        description="TPU-native code2vec: train, search, export",
    )
    # reproducibility (main.py:38) — also seeds the train/test split here
    parser.add_argument("--random_seed", type=int, default=123)

    # dataset artifacts (main.py:40-42)
    parser.add_argument("--corpus_path", type=str, default="./dataset/corpus.txt")
    parser.add_argument("--path_idx_path", type=str, default="./dataset/path_idxs.txt")
    parser.add_argument("--terminal_idx_path", type=str,
                        default="./dataset/terminal_idxs.txt")
    parser.add_argument("--synthetic", type=str, default=None,
                        metavar="SPEC",
                        help="ignore the corpus flags and train on a generated "
                             "corpus (tiny|small|top11) — smoke runs/benchmarks")

    # model dims (main.py:44-48)
    parser.add_argument("--batch_size", type=int, default=32)
    parser.add_argument("--terminal_embed_size", type=int, default=100)
    parser.add_argument("--path_embed_size", type=int, default=100)
    parser.add_argument("--encode_size", type=int, default=300)
    parser.add_argument("--max_path_length", type=int, default=200)

    # outputs (main.py:50-52)
    parser.add_argument("--model_path", type=str, default="./output")
    parser.add_argument("--vectors_path", type=str, default="./output/code.vec")
    parser.add_argument("--test_result_path", type=str, default=None)

    # optimizer (main.py:54-58)
    parser.add_argument("--max_epoch", type=int, default=40)
    parser.add_argument("--lr", type=float, default=0.01)
    parser.add_argument("--beta_min", type=float, default=0.9)
    parser.add_argument("--beta_max", type=float, default=0.999)
    parser.add_argument("--weight_decay", type=float, default=0.0)
    parser.add_argument("--dropout_prob", type=float, default=0.25)

    # device flags accepted for drop-in compatibility, no-ops under JAX
    # (main.py:62-64)
    parser.add_argument("--no_cuda", action="store_true", default=False,
                        help="run on CPU (pins the cpu JAX backend)")
    parser.add_argument("--gpu", type=str, default=None,
                        help="no-op (JAX owns device placement)")
    parser.add_argument("--num_workers", type=int, default=None,
                        help="no-op (vectorized host pipeline)")

    # observability + eval control (main.py:66-68)
    parser.add_argument("--env", type=str, default=None,
                        choices=(None, "tensorboard", "floyd"),
                        help="extra metric sink: tensorboard | floyd")
    parser.add_argument("--print_sample_cycle", type=int, default=10)
    parser.add_argument("--eval_method", type=str, default="subtoken",
                        choices=("exact", "subtoken", "ave_subtoken"))

    # HPO (main.py:70-71)
    parser.add_argument("--find_hyperparams", action="store_true", default=False)
    parser.add_argument("--hpo_sampler", type=str, default="tpe",
                        choices=("tpe", "random"),
                        help="hyperparameter search sampler (tpe matches "
                             "the reference's optuna default)")
    parser.add_argument("--num_trials", type=int, default=100)

    # angular-margin head (main.py:73-75)
    parser.add_argument("--angular_margin_loss", action="store_true", default=False)
    parser.add_argument("--angular_margin", type=float, default=0.5)
    parser.add_argument("--inverse_temp", type=float, default=30.0)

    # task selection (main.py:77-79)
    parser.add_argument("--infer_method_name", type=_strtobool, default=True)
    parser.add_argument("--infer_variable_name", type=_strtobool, default=False)
    parser.add_argument("--shuffle_variable_indexes", type=_strtobool, default=False)

    # ---- TPU-native flags (no reference counterpart) ----
    parser.add_argument("--compute_dtype", type=str, default="float32",
                        choices=("float32", "bfloat16"),
                        help="matmul/activation dtype; bfloat16 for TPU MXU")
    parser.add_argument("--use_pallas", action="store_true", default=False,
                        help="Pallas kernels on the aggregation hot path "
                             "(composes with data/model mesh axes)")
    parser.add_argument("--pallas_block_b", type=int, default=8,
                        help="batch-tile size of the Pallas kernels")
    parser.add_argument("--pallas_impl", type=str, default="pool_only",
                        choices=("pool_only", "gather_split", "fused", "auto"),
                        help="which kernel serves the forward: pool-only "
                             "fusion, XLA-gather + fused encode/attend/pool, "
                             "the fully-fused in-kernel-gather chain, or "
                             "'auto' (consult the autotuned schedule cache "
                             "per traced shape — ops/autotune.py)")
    parser.add_argument("--pallas_dma_depth", type=int, default=2,
                        help="fused-kernel gather double-buffer slots")
    parser.add_argument("--pallas_chunk_l", type=int, default=128,
                        help="fused-kernel bag-chunk lane tile")
    parser.add_argument("--table_dtype", type=str, default="f32",
                        choices=("f32", "bf16", "int8"),
                        help="embedding-table storage for SERVING/EVAL "
                             "forwards (int8 = per-row scale, dequant on "
                             "load — ops/quant.py); training rejects "
                             "anything but f32 (master weights)")
    parser.add_argument("--autotune_cache", type=str, default="",
                        help="kernel-schedule cache path for --pallas_impl "
                             "auto (default $C2V_AUTOTUNE_CACHE or "
                             "~/.cache/code2vec_tpu/autotune_schedules.json; "
                             "populate it via python -m "
                             "code2vec_tpu.ops.autotune)")
    parser.add_argument("--attn_impl", type=str, default="xla",
                        choices=("xla", "streaming"),
                        help="attention-pool lowering: jax.nn.softmax chain "
                             "or the explicit streaming exp/sum decomposition "
                             "(same math; --use_pallas overrides)")
    parser.add_argument("--encoder_impl", type=str, default="concat",
                        choices=("concat", "split"),
                        help="context-encoder lowering: one [3E,H] matmul on "
                             "the concat, or the same kernel as three sliced "
                             "matmuls summed (same math and params)")
    parser.add_argument("--sample_prefetch", type=_strtobool, default=False,
                        help="device-epoch chunks sample batch i+1 while "
                             "stepping on batch i (double-buffering; same "
                             "batches, losses equal up to float reassociation)")
    from code2vec_tpu.ops.embed import GRAD_MODES

    parser.add_argument("--embed_grad", type=str, default="dense",
                        choices=GRAD_MODES,
                        help="embedding-table backward formulation (ops.embed)")
    parser.add_argument("--data_axis", type=int, default=1,
                        help="mesh data-parallel axis size")
    parser.add_argument("--model_axis", type=int, default=1,
                        help="mesh model-parallel axis size (shards vocab tables)")
    parser.add_argument("--context_axis", type=int, default=1,
                        help="mesh context-parallel axis size (shards the bag)")
    parser.add_argument("--device_epoch", action="store_true", default=False,
                        help="stage the corpus in device memory and run "
                        "scanned chunks of batches per dispatch (method "
                        "and/or variable task; composes with the mesh axes)")
    parser.add_argument("--export_only", action="store_true", default=False,
                        help="skip training: restore the checkpoint in "
                        "--model_path and rewrite --vectors_path (+ the "
                        "test TSV). The post-hoc export pass for "
                        "host-sharded pod runs")
    parser.add_argument("--host_shard_corpus", action="store_true",
                        default=False,
                        help="each process loads only its round-robin share "
                        "of the corpus (multi-host pods; context arrays "
                        "are held 1/n_hosts per host)")
    parser.add_argument("--bucketed", action="store_true", default=False,
                        help="length-aware bucketed batching: partition "
                        "each epoch by real context count into a static "
                        "ladder of bag widths and run [B, L_b] batches per "
                        "bucket — stops paying embedding/attention/HBM "
                        "cost for PAD slots on skewed corpora (exactly "
                        "len(ladder) step compiles)")
    parser.add_argument("--bucket_ladder", type=str, default="",
                        help="comma list of bag widths ending at "
                        "--max_path_length (e.g. 25,50,100,200); empty = "
                        "derive a geometric ladder from the corpus length "
                        "histogram (see tools/corpus_stats.py)")
    parser.add_argument("--max_contexts", type=int, default=-1,
                        help="per-example context cap: -1 = follow "
                        "--max_path_length (long bags subsample down, the "
                        "historical behavior); 0 = UNBOUNDED (requires "
                        "--bucketed): nothing is truncated — the ladder "
                        "grows longbag rungs above the top width and those "
                        "shapes stream through the fused kernel's chunked "
                        "softmax in bounded VMEM")
    parser.add_argument("--pallas_softmax", type=str, default="auto",
                        choices=("auto", "materialize", "online", "two_pass"),
                        help="bag-softmax numerics of the fused Pallas "
                        "kernel: materialize = VMEM-resident encoded bag; "
                        "online/two_pass = flash-style chunked softmax "
                        "(bounded VMEM at any bag length); auto = "
                        "materialize at base ladder widths, online above "
                        "(longbag rungs)")
    parser.add_argument("--corpus_format", type=str, default="auto",
                        choices=("auto", "text", "csr"),
                        help="corpus file format: text (L1 corpus.txt), "
                        "csr (memory-mapped binary container from "
                        "tools/corpus_convert.py — feeds training through "
                        "mmap views in bounded host RSS), or auto-detect "
                        "by magic (default)")
    parser.add_argument("--stream_chunk_items", type=int, default=0,
                        help="stream epochs in chunks of this many rows "
                        "instead of materializing [N, L] tensors (bounds "
                        "host RSS at java-large scale; 0 = materialize)")
    parser.add_argument("--prefetch_batches", type=int, default=0,
                        help="host-epoch input pipeline: build + transfer "
                        "this many batches ahead of compute on a background "
                        "thread (0 = synchronous; identical batches in "
                        "identical order)")
    parser.add_argument("--feed_workers", type=int, default=0,
                        help="parallel host ingest: execute each epoch's "
                        "batch plan on this many forked worker processes "
                        "(RNG stays on the coordinator — feed order, loss "
                        "history, and resume cursors are bitwise identical "
                        "to 0 = build on the coordinator). Method-task "
                        "host pipeline only; composes with bucketed/"
                        "streaming/mmap and --prefetch_batches")
    parser.add_argument("--profile_steps", type=int, default=0,
                        help="fence the first N train steps of each epoch "
                        "and log the host-build / H2D / feed-wait / "
                        "compute wall-time split (0 = off)")
    parser.add_argument("--device_chunk_batches", type=int, default=16,
                        help="batches per device-epoch dispatch")
    parser.add_argument("--shard_staged_corpus", action="store_true",
                        default=False,
                        help="partition the staged train corpus over the "
                        "data axis instead of replicating it (per-device "
                        "HBM ~1/data_axis; method and/or variable task, "
                        "ctx_axis 1)")
    parser.add_argument("--class_weighting", type=str, default="reference",
                        choices=("reference", "occurrence", "none"))
    parser.add_argument("--no_corpus_cache", action="store_true", default=False,
                        help="disable the <corpus>.cache.npz sidecar that "
                             "makes repeat startups fast at top11 scale")
    parser.add_argument("--rng_impl", type=str, default="threefry2x32",
                        choices=("threefry2x32", "rbg", "unsafe_rbg"),
                        help="dropout-stream PRNG (rbg/unsafe_rbg are "
                             "faster on TPU)")
    parser.add_argument("--adam_mu_dtype", type=str, default="float32",
                        choices=("float32", "bfloat16"),
                        help="Adam first-moment storage dtype (bfloat16 "
                             "trims HBM traffic on the memory-bound step; "
                             "float32 keeps torch parity)")
    parser.add_argument("--table_update", type=str, default="dense",
                        choices=("dense", "lazy"),
                        help="embedding-table optimizer: dense = "
                             "torch.optim.Adam parity; lazy = touched-rows "
                             "updates (torch.optim.SparseAdam semantics) — "
                             "skips the full-table gradient + Adam RMW, "
                             "the win growing with vocab size")
    parser.add_argument("--vocab_pad_multiple", type=int, default=0,
                        help="pad vocab/label table dims to this multiple "
                             "for even model-axis sharding (0 = follow "
                             "--model_axis); pin it to resume a checkpoint "
                             "under a different mesh")
    parser.add_argument("--checkpoint_cycle", type=int, default=0,
                        help="also checkpoint every N epochs (0 = best-F1 "
                             "only) — preemption safety for pod runs")
    parser.add_argument("--async_checkpoint", action="store_true",
                        default=False,
                        help="async checkpointing: the loop blocks only for "
                             "the device-to-host snapshot; the disk write "
                             "overlaps the next steps on a background thread "
                             "(single-process; pods fall back to sync saves)")
    parser.add_argument("--checkpoint_every_steps", type=int, default=0,
                        help="also save the last slot every N train steps "
                             "with a mid-epoch data cursor so --resume "
                             "restarts inside the epoch (0 = epoch-boundary "
                             "saves only)")
    parser.add_argument("--fault_plan", type=str, default="",
                        help="deterministic fault injection for recovery "
                             "drills (code2vec_tpu/faultinject.py), e.g. "
                             "'train_step@10:sigterm,mid_save@1:raise' — "
                             "crashes the process ON PURPOSE")
    parser.add_argument("--resume", action="store_true", default=False,
                        help="resume from the checkpoint in --model_path")
    parser.add_argument("--profile_dir", type=str, default=None,
                        help="write a jax.profiler trace of epoch 2 here")
    parser.add_argument("--tensorboard_dir", type=str, default="runs",
                        help="scalar log dir for --env tensorboard")
    parser.add_argument("--events_dir", type=str, default=None,
                        help="write a per-process JSONL event log here "
                             "(run manifest first, then typed epoch/"
                             "step_sample/checkpoint/eval/recompile/error "
                             "events — obs/events.py)")
    parser.add_argument("--trace_dir", type=str, default=None,
                        help="write a Chrome trace_event JSON here (spans "
                             "from the extractor, input pipeline, prefetch "
                             "producer, train/eval/checkpoint phases; view "
                             "in Perfetto — obs/trace.py)")
    return parser


def config_from_args(args: argparse.Namespace):
    from code2vec_tpu.train.config import TrainConfig

    return TrainConfig(
        random_seed=args.random_seed,
        terminal_embed_size=args.terminal_embed_size,
        path_embed_size=args.path_embed_size,
        encode_size=args.encode_size,
        max_path_length=args.max_path_length,
        batch_size=args.batch_size,
        max_epoch=args.max_epoch,
        lr=args.lr,
        beta_min=args.beta_min,
        beta_max=args.beta_max,
        weight_decay=args.weight_decay,
        dropout_prob=args.dropout_prob,
        angular_margin_loss=args.angular_margin_loss,
        angular_margin=args.angular_margin,
        inverse_temp=args.inverse_temp,
        infer_method_name=args.infer_method_name,
        infer_variable_name=args.infer_variable_name,
        shuffle_variable_indexes=args.shuffle_variable_indexes,
        eval_method=args.eval_method,
        print_sample_cycle=args.print_sample_cycle,
        class_weighting=args.class_weighting,
        compute_dtype=args.compute_dtype,
        data_axis=args.data_axis,
        model_axis=args.model_axis,
        context_axis=args.context_axis,
        use_pallas=args.use_pallas,
        pallas_block_b=args.pallas_block_b,
        pallas_impl=args.pallas_impl,
        pallas_dma_depth=args.pallas_dma_depth,
        pallas_chunk_l=args.pallas_chunk_l,
        table_dtype=args.table_dtype,
        autotune_cache=args.autotune_cache,
        attn_impl=args.attn_impl,
        encoder_impl=args.encoder_impl,
        sample_prefetch=args.sample_prefetch,
        embed_grad=args.embed_grad,
        rng_impl=args.rng_impl,
        adam_mu_dtype=args.adam_mu_dtype,
        table_update=args.table_update,
        vocab_pad_multiple=args.vocab_pad_multiple,
        resume=args.resume,
        checkpoint_cycle=args.checkpoint_cycle,
        async_checkpoint=args.async_checkpoint,
        checkpoint_every_steps=args.checkpoint_every_steps,
        fault_plan=args.fault_plan,
        device_epoch=args.device_epoch,
        shard_staged_corpus=args.shard_staged_corpus,
        bucketed=args.bucketed,
        bucket_ladder=args.bucket_ladder,
        max_contexts=args.max_contexts,
        pallas_softmax=args.pallas_softmax,
        stream_chunk_items=args.stream_chunk_items,
        device_chunk_batches=args.device_chunk_batches,
        prefetch_batches=args.prefetch_batches,
        feed_workers=args.feed_workers,
        profile_steps=args.profile_steps,
    )


def sinks_from_args(args: argparse.Namespace):
    from code2vec_tpu.sinks import floyd_sink, logging_sink, tensorboard_sink

    sinks = [logging_sink]
    if args.env == "floyd":
        sinks.append(floyd_sink)
    elif args.env == "tensorboard":
        sinks.append(tensorboard_sink(args.tensorboard_dir))
    return tuple(sinks)


def pin_platform(no_cuda: bool) -> None:
    """Honor --no_cuda / JAX_PLATFORMS through the config API: experimental
    device plugins can pre-empt the env var, so the env route alone is
    unreliable. --no_cuda keeps the reference's semantics (main.py:62,83 —
    don't use the accelerator) by pinning the CPU backend. Works as long as
    no backend is initialized yet. Shared with the predict CLI."""
    if not (no_cuda or os.environ.get("JAX_PLATFORMS", "").strip()):
        return
    import jax

    platforms = "cpu" if no_cuda else os.environ["JAX_PLATFORMS"]
    # no public API answers "is any backend initialized yet?" without
    # initializing one; prefer the named probe, fall back to the older
    # private dict if a future jax renames it
    from jax._src import xla_bridge as _xb

    _initialized = getattr(
        _xb,
        "backends_are_initialized",
        lambda: bool(getattr(_xb, "_backends", None)),
    )()
    if not _initialized:
        jax.config.update("jax_platforms", platforms)
    else:
        requested = {p.strip() for p in platforms.split(",") if p.strip()}
        if "cuda" in requested or "rocm" in requested:
            requested.add("gpu")  # default_backend() reports the alias
        if jax.default_backend() not in requested:
            logger.warning(
                "cannot honor platform request %r: the %s backend is "
                "already initialized", platforms, jax.default_backend())


def main(argv: list[str] | None = None) -> None:
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s: %(message)s",
                        datefmt="%m/%d/%Y %I:%M:%S %p")
    args = build_parser().parse_args(argv)
    pin_platform(args.no_cuda)
    if args.gpu is not None or args.num_workers is not None:
        logger.info("--gpu/--num_workers are no-ops on this framework: "
                    "JAX selects the device (current: %s)", _backend_name())

    config = config_from_args(args)
    if args.synthetic is not None:
        import atexit
        import shutil
        import tempfile

        from code2vec_tpu.data.synth import SPECS, generate_corpus_files

        if args.synthetic not in SPECS:
            raise SystemExit(
                f"--synthetic must be one of {sorted(SPECS)}, "
                f"got {args.synthetic!r}")
        synth_dir = tempfile.mkdtemp(prefix="c2v_synth_")
        # the corpus must outlive this function (training reads it for the
        # whole run), so reclaim the temp dir at process exit
        atexit.register(shutil.rmtree, synth_dir, ignore_errors=True)
        logger.info("generating %r synthetic corpus in %s", args.synthetic,
                    synth_dir)
        paths = generate_corpus_files(synth_dir, SPECS[args.synthetic])
        args.corpus_path = paths["corpus"]
        args.path_idx_path = paths["path_idx"]
        args.terminal_idx_path = paths["terminal_idx"]

    # telemetry (code2vec_tpu.obs): installed BEFORE corpus load so the
    # data-layer spans (native parse, epoch builds) land in the trace; the
    # CLI owns the lifecycle (train() writes the manifest, we export/close)
    events, tracer = _telemetry_from_args(args)
    try:
        _run(args, config, events, tracer)
    finally:
        # best-effort: a failing export/close must neither mask the real
        # exception unwinding through here nor skip the remaining cleanup
        if tracer is not None:
            from code2vec_tpu.obs.trace import set_tracer

            set_tracer(None)  # back to the inert NullTracer
            try:
                path = tracer.export_dir(args.trace_dir)
                logger.info(
                    "chrome trace written to %s — open in Perfetto "
                    "(ui.perfetto.dev) or chrome://tracing", path)
            except Exception:
                logger.warning(
                    "could not write chrome trace to %s", args.trace_dir,
                    exc_info=True)
        if events is not None:
            if events.path is not None:
                logger.info("event log written to %s", events.path)
            try:
                events.close()
            except Exception:
                logger.warning("could not close event log", exc_info=True)


def _telemetry_from_args(args: argparse.Namespace):
    """(EventLog | None, Tracer | None) from --events_dir / --trace_dir.
    The Tracer is also installed process-wide (obs.trace.set_tracer) so
    instrumented layers pick it up via get_tracer()."""
    # neither constructor touches the JAX backend (process indices resolve
    # lazily at first write/export) — multi-host runs must reach
    # jax.distributed.initialize with the backend still uninitialized
    events = tracer = None
    if args.events_dir:
        from code2vec_tpu.obs.events import EventLog

        events = EventLog(args.events_dir)
    if args.trace_dir:
        from code2vec_tpu.obs.trace import Tracer, set_tracer

        tracer = Tracer()
        set_tracer(tracer)
    return events, tracer


def _run(args: argparse.Namespace, config, events, tracer) -> None:
    from code2vec_tpu.data.reader import load_corpus

    shard = None
    if args.host_shard_corpus:
        import jax

        # form the process group first (no-op without coordinator env vars)
        # — otherwise process_count() is 1 and sharding silently degrades
        # to every host loading the full corpus
        from code2vec_tpu.parallel.distributed import initialize_from_env

        initialize_from_env()
        if jax.process_count() == 1:
            logger.warning(
                "--host_shard_corpus with a single process: set "
                "COORDINATOR_ADDRESS/NUM_PROCESSES/PROCESS_ID (or "
                "JAX_AUTO_DISTRIBUTED=1 on a TPU pod) to form the process "
                "group; loading the full corpus"
            )
        # shard by FEED GROUP (processes covering the same data-axis
        # coords), not by process index: with a model/ctx axis spanning
        # processes — or a permuted device mesh — the two differ, and
        # train() validates the shard against feed_groups(mesh)
        if (
            jax.process_count() > 1
            and args.data_axis * args.model_axis * args.context_axis <= 1
        ):
            raise SystemExit(
                "--host_shard_corpus requires mesh axes (--data_axis / "
                "--model_axis / --context_axis)"
            )
        from code2vec_tpu.parallel.distributed import feed_groups
        from code2vec_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(
            data=args.data_axis, model=args.model_axis, ctx=args.context_axis
        )
        shard = feed_groups(mesh)
        logger.info("loading corpus shard %d/%d", shard[0], shard[1])
    if getattr(args, "corpus_format", "auto") != "auto":
        # load_corpus dispatches by magic; the explicit flag exists to fail
        # LOUDLY when the file is not what the operator believes it is
        # (e.g. a text path after the corpus was converted, silently
        # falling back to full-RAM parsing on a memory-budgeted host)
        from code2vec_tpu.formats.corpus_io import is_csr_corpus

        actual = "csr" if is_csr_corpus(args.corpus_path) else "text"
        if actual != args.corpus_format:
            raise SystemExit(
                f"--corpus_format {args.corpus_format} but {args.corpus_path!r} "
                f"is a {actual} corpus; convert with tools/corpus_convert.py "
                "or fix the flag"
            )
    data = load_corpus(
        args.corpus_path,
        args.path_idx_path,
        args.terminal_idx_path,
        infer_method=args.infer_method_name,
        infer_variable=args.infer_variable_name,
        cache=not args.no_corpus_cache,
        shard=shard,
    )

    if args.find_hyperparams:
        from code2vec_tpu.hpo import find_optimal_hyperparams

        study = find_optimal_hyperparams(
            data, config, n_trials=args.num_trials, seed=args.random_seed,
            sampler=args.hpo_sampler, events=events)
        best = study.best_trial
        logger.info("Number of finished trials: %d", len(study.trials))
        logger.info("Best trial value: %s", best.value)
        for key, value in best.params.items():
            logger.info("    %s: %s", key, value)
        return

    from code2vec_tpu.train.loop import train

    os.makedirs(args.model_path, exist_ok=True)
    for out_file in (args.vectors_path, args.test_result_path):
        if out_file and os.path.dirname(out_file):
            os.makedirs(os.path.dirname(out_file), exist_ok=True)
    if args.export_only:
        from code2vec_tpu.export import export_from_checkpoint

        if not args.vectors_path:
            raise SystemExit("--export_only requires --vectors_path")
        f1 = export_from_checkpoint(
            config, data, args.model_path, args.vectors_path,
            args.test_result_path,
        )
        logger.info("done: exported (test f1=%s)", f1)
        return
    result = train(
        config,
        data,
        out_dir=args.model_path,
        vectors_path=args.vectors_path,
        test_result_path=args.test_result_path,
        sinks=sinks_from_args(args),
        profile_dir=args.profile_dir,
        events=events,
        tracer=tracer,
    )
    logger.info("done: best_f1=%s after %d epochs", result.best_f1,
                result.epochs_run)


def _backend_name() -> str:
    try:
        import jax

        return jax.default_backend()
    except Exception:  # pragma: no cover - jax always present here
        return "unknown"


if __name__ == "__main__":
    main()
