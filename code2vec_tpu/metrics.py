"""Evaluation metrics: the reference's three matchers (main.py:291-359).

All three consume label *ids* plus the label vocab's subtoken table and run
host-side on numpy — they are string-set metrics, not device math.
"""

from __future__ import annotations

import numpy as np

from code2vec_tpu.data.vocab import Vocab


def exact_match(
    expected: np.ndarray, actual: np.ndarray
) -> tuple[float, float, float, float]:
    """Accuracy + weighted P/R/F1 on raw label ids (reference:
    main.py:300-305, via sklearn)."""
    from sklearn.metrics import accuracy_score, precision_recall_fscore_support

    precision, recall, f1, _ = precision_recall_fscore_support(
        expected, actual, average="weighted", zero_division=0
    )
    accuracy = accuracy_score(expected, actual)
    return float(accuracy), float(precision), float(recall), float(f1)


def subtoken_match(
    expected: np.ndarray, actual: np.ndarray, label_vocab: Vocab
) -> tuple[float, float, float, float]:
    """Corpus-pooled subtoken overlap — the code2vec-paper-style metric and
    the reference default (main.py:339-359).

    A predicted subtoken counts as a match if it appears in the expected
    name's subtoken list (membership, not multiset intersection — parity
    with the reference's ``in`` loop).
    """
    match = expected_count = actual_count = 0.0
    itosubtokens = label_vocab.itosubtokens
    for exp, act in zip(expected.tolist(), actual.tolist()):
        exp_subtokens = itosubtokens[int(exp)]
        act_subtokens = itosubtokens[int(act)]
        for subtoken in exp_subtokens:
            if subtoken in act_subtokens:
                match += 1
        expected_count += len(exp_subtokens)
        actual_count += len(act_subtokens)

    denom = expected_count + actual_count - match
    accuracy = match / denom if denom else 0.0
    precision = match / actual_count if actual_count else 0.0
    recall = match / expected_count if expected_count else 0.0
    f1 = (
        2.0 * precision * recall / (precision + recall)
        if precision + recall > 0
        else 0.0
    )
    return accuracy, precision, recall, f1


def averaged_subtoken_match(
    expected: np.ndarray, actual: np.ndarray, label_vocab: Vocab
) -> tuple[float, float, float, float]:
    """Per-example Jaccard-style subtoken metrics, then arithmetic mean
    (reference: main.py:308-336)."""
    accs, precs, recs, f1s = [], [], [], []
    itosubtokens = label_vocab.itosubtokens
    for exp, act in zip(expected.tolist(), actual.tolist()):
        exp_subtokens = itosubtokens[int(exp)]
        act_subtokens = itosubtokens[int(act)]
        match = sum(1 for s in exp_subtokens if s in act_subtokens)
        acc = match / float(len(exp_subtokens) + len(act_subtokens) - match)
        rec = match / float(len(exp_subtokens))
        prec = match / float(len(act_subtokens))
        f1 = 2.0 * prec * rec / (prec + rec) if prec + rec > 0 else 0.0
        accs.append(acc)
        precs.append(prec)
        recs.append(rec)
        f1s.append(f1)
    return (
        float(np.average(accs)),
        float(np.average(precs)),
        float(np.average(recs)),
        float(np.average(f1s)),
    )


def evaluate(
    eval_method: str,
    expected: np.ndarray,
    actual: np.ndarray,
    label_vocab: Vocab,
) -> tuple[float, float, float, float]:
    """Dispatch mirroring main.py:291-296. Returns
    (accuracy, precision, recall, f1)."""
    if len(expected) == 0:
        # empty eval split (tiny corpus): all-zero metrics instead of a
        # sklearn ValueError (exact) or NaN (ave_subtoken)
        return 0.0, 0.0, 0.0, 0.0
    if eval_method == "exact":
        return exact_match(expected, actual)
    if eval_method == "subtoken":
        return subtoken_match(expected, actual, label_vocab)
    if eval_method == "ave_subtoken":
        return averaged_subtoken_match(expected, actual, label_vocab)
    raise ValueError(f"unknown eval_method: {eval_method!r}")
