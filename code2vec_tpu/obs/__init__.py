"""Run-level telemetry: structured events, span tracing, runtime health.

Three small, stdlib-only layers (no accelerator coupling — safe to import
before a backend exists):

- :mod:`code2vec_tpu.obs.events` — a per-process JSONL event log opened with
  a run manifest, followed by typed events (``epoch``, ``step_sample``,
  ``checkpoint_saved``, ``eval``, ``recompile``, ``error``). The metric
  sinks (``code2vec_tpu.sinks``) are consumers of the SAME stream, so the
  epoch metrics a sink reports and the event log records cannot disagree.
- :mod:`code2vec_tpu.obs.trace` — a thread-safe span API
  (``with tracer.span("host_build"): ...``) exportable as a Chrome
  ``trace_event`` JSON viewable in Perfetto / ``chrome://tracing``, with
  per-process tracks for multi-host runs.
- :mod:`code2vec_tpu.obs.runtime` — a counters/gauges registry, a
  ``jax.jit`` recompile detector, and a host/device memory sampler.

Surfaced as ``--events_dir`` / ``--trace_dir`` on the training CLI and
``BENCH_TRACE_DIR`` on the benchmark.
"""

from code2vec_tpu.obs.events import EventLog, metric_record, run_manifest, sink_consumer
from code2vec_tpu.obs.runtime import (
    RecompileDetector,
    RuntimeHealth,
    host_rss_bytes,
    memory_snapshot,
)
from code2vec_tpu.obs.trace import NullTracer, Tracer, get_tracer, set_tracer

__all__ = [
    "EventLog",
    "metric_record",
    "run_manifest",
    "sink_consumer",
    "NullTracer",
    "Tracer",
    "get_tracer",
    "set_tracer",
    "RecompileDetector",
    "RuntimeHealth",
    "host_rss_bytes",
    "memory_snapshot",
]
