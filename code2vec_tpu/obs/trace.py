"""Span tracing with Chrome ``trace_event`` export.

``with tracer.span("host_build", queue_depth=2): ...`` records a complete
("X"-phase) event — name, start, duration, process/thread track, args —
into an in-memory buffer; :meth:`Tracer.export_dir` writes the standard
Chrome trace JSON (``{"traceEvents": [...]}``), loadable in Perfetto or
``chrome://tracing``. Multi-host runs write one file per process
(``trace-p<i>.json``) whose events carry ``pid = process_index`` plus a
``process_name`` metadata event, so merged traces keep one track per host.

Instrumented layers fetch the process-wide tracer via :func:`get_tracer`
(the extractor, ``data/pipeline.py``, the prefetch producer thread,
``train/loop.py``, ``bench.py``); with no tracer installed they get the
:class:`NullTracer`, whose ``span`` returns a shared ``nullcontext`` —
cheap enough for per-batch call sites.

Thread-safe: spans may close concurrently on any thread (the prefetch
producer records ``host_build``/``h2d`` while the main thread records
``train_step``); each thread gets its own trace row (``tid``), named after
``threading.Thread.name`` via ``thread_name`` metadata events.

**Cross-process request tracing** (the fleet observability plane): a
:class:`TraceContext` rides an optional ``"trace"`` field in every serve
protocol request dict — the fleet router stamps one at admission (or
honors a client-supplied one, :func:`ensure_trace`), the replica pipe
forwards the dict verbatim, and the worker's resolver installs it so the
batcher-coalesce / engine-device-call / retrieval spans it triggers carry
the originating ``trace_id`` as a span arg. Per-request cost is O(1) dict
work — one 32-hex id, no locks, no allocation bursts. The per-process
trace files (already unix-epoch-anchored) then merge into one fleet-wide
view with ``tools/trace_stitch.py``, which indexes spans by trace id —
including the coalesce-aware link: a batched device span records the N
trace ids it served as ``trace_ids``.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
import uuid
from dataclasses import dataclass

from code2vec_tpu.obs.events import sanitize

__all__ = [
    "NullTracer",
    "TraceContext",
    "Tracer",
    "current_trace_scope",
    "ensure_trace",
    "get_tracer",
    "new_trace_id",
    "set_tracer",
    "trace_scope",
]


def new_trace_id() -> str:
    """A fresh 32-hex request trace id (uuid4; no coordination needed)."""
    return uuid.uuid4().hex


@dataclass
class TraceContext:
    """One request's trace identity as it crosses process boundaries.

    ``trace_id`` correlates every span the request touches — router
    admission, the replica worker's resolver, the micro-batcher's
    coalesced device call, retrieval — across separate trace files.
    ``parent_span_id`` names the span that forwarded the context (the
    router's request span), so a stitched trace can draw the handoff
    edge; it is optional and purely informational.
    """

    trace_id: str
    parent_span_id: str | None = None

    WIRE_KEY = "trace"

    @classmethod
    def from_request(cls, request: dict) -> "TraceContext | None":
        """Parse the optional ``"trace"`` field off a protocol request
        dict; malformed values are ignored (None), never fatal — a
        garbage trace field must not break serving."""
        raw = request.get(cls.WIRE_KEY)
        if not isinstance(raw, dict):
            return None
        trace_id = raw.get("trace_id")
        if not isinstance(trace_id, str) or not trace_id:
            return None
        parent = raw.get("parent_span_id")
        return cls(
            trace_id=trace_id[:64],
            parent_span_id=parent[:64] if isinstance(parent, str) else None,
        )

    def to_wire(self) -> dict:
        wire = {"trace_id": self.trace_id}
        if self.parent_span_id:
            wire["parent_span_id"] = self.parent_span_id
        return wire


def ensure_trace(request: dict, parent_span_id: str | None = None) -> TraceContext:
    """The admission hook: honor a client-supplied trace context or stamp
    a fresh one INTO ``request`` (the same dict then crosses the replica
    pipe, so downstream processes see the id without any extra wiring).
    O(1) per request."""
    ctx = TraceContext.from_request(request)
    if ctx is None:
        ctx = TraceContext(
            trace_id=new_trace_id(), parent_span_id=parent_span_id
        )
        request[TraceContext.WIRE_KEY] = ctx.to_wire()
    return ctx


# thread-local span tags: lets a caller scope trace ids over a callee's
# spans WITHOUT widening the callee's signature (the batcher wraps the
# engine's device call; duck-typed fake engines in tests keep their
# 3-arg run()). The batcher thread calls the engine synchronously, so
# thread-locality is exactly the right propagation boundary.
_scope = threading.local()


@contextlib.contextmanager
def trace_scope(**tags):
    """Attach ``tags`` (e.g. ``trace_ids=[...]``) to every span the
    wrapped block records via :func:`current_trace_scope` readers."""
    previous = getattr(_scope, "tags", None)
    _scope.tags = {**(previous or {}), **tags}
    try:
        yield
    finally:
        _scope.tags = previous


def current_trace_scope() -> dict:
    """The active :func:`trace_scope` tags for this thread ({} outside)."""
    tags = getattr(_scope, "tags", None)
    return dict(tags) if tags else {}


class Tracer:
    """Collect spans; export once at end of run.

    ``process_index=None`` (the default) defers resolution to export time
    (``jax.process_index()``): a tracer is per-process so the pid is one
    value, and resolving it lazily means constructing a tracer never
    initializes the JAX backend — which must not happen before
    ``jax.distributed.initialize`` on multi-host runs.

    ``max_events`` bounds memory on very long runs (a java-large epoch is
    ~16k steps; per-batch producer spans add up). Overflow is counted, not
    silent: the exported JSON carries ``dropped_events`` metadata.
    """

    enabled = True

    def __init__(
        self,
        process_index: int | None = None,
        process_name: str | None = None,
        max_events: int = 1_000_000,
    ):
        self.process_index = process_index
        self.process_name = process_name
        self.max_events = int(max_events)
        self._events: list[dict] = []
        # (os thread ident, thread name) -> synthetic trace tid. CPython
        # reuses idents as soon as a thread dies — and the prefetcher
        # spawns a fresh producer per epoch — so the raw ident would let a
        # later thread inherit a dead stranger's track label; keying by
        # (ident, name) gives every distinctly-named occupant its own row
        self._tids: dict[tuple[int, str], int] = {}
        self._dropped = 0
        # plain on purpose: hottest leaf lock in the process (every span);
        # never held across another acquire, so tracing it buys nothing
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()
        # wall-clock anchor for the monotonic span clock: exported ts are
        # µs since the unix epoch, so per-host trace files land on one
        # shared time axis (aligned up to NTP skew) when merged
        self._wall_t0_us = time.time() * 1e6

    def _resolve_process_index(self) -> int:
        if self.process_index is None:
            from code2vec_tpu.obs.events import resolve_process_index

            self.process_index = resolve_process_index()
        return int(self.process_index)

    def _now_us(self) -> float:
        return (time.perf_counter() - self._t0) * 1e6

    @contextlib.contextmanager
    def span(self, name: str, category: str = "run", **args):
        """Record the wrapped block as a complete trace event. Args are
        evaluated at entry (e.g. queue depth at enqueue time)."""
        ts = self._now_us()
        try:
            yield self
        finally:
            self._record(name, category, ts, self._now_us() - ts, args)

    def instant(self, name: str, category: str = "run", **args) -> None:
        """A zero-duration mark (Chrome "i" phase) — e.g. a recompile."""
        self._record(name, category, self._now_us(), None, args)

    def span_complete(
        self,
        name: str,
        category: str = "run",
        start_s: float = 0.0,
        end_s: float = 0.0,
        track: str | None = None,
        **args,
    ) -> None:
        """Record a span measured OUTSIDE this thread — e.g. inside a feed
        worker process. ``start_s``/``end_s`` are ``time.perf_counter()``
        stamps (CLOCK_MONOTONIC is system-wide on Linux, so a forked
        child's stamps share this process's span clock). ``track`` names
        the trace row the span lands on (its own tid, e.g.
        ``feed-worker-3``) instead of the recording thread's."""
        ts = (start_s - self._t0) * 1e6
        self._record(
            name, category, ts, max((end_s - start_s) * 1e6, 0.0), args,
            track=track,
        )

    def _record(self, name, category, ts, dur, args, track=None) -> None:
        if track is not None:
            # synthetic per-track row: the key shape matches the thread
            # keys ((unique, display-name)) so naming metadata just works
            thread_key = (f"__track__{track}", track)
        else:
            thread_key = (
                threading.get_ident(), threading.current_thread().name
            )
        # pid is stamped at export (one tracer = one process) so recording
        # never has to resolve the process index
        event = {
            "name": name,
            "cat": category,
            "ph": "X" if dur is not None else "i",
            "ts": round(ts, 3),
        }
        if dur is not None:
            event["dur"] = round(dur, 3)
        else:
            event["s"] = "t"
        if args:
            event["args"] = sanitize(args)
        with self._lock:
            tid = self._tids.get(thread_key)
            if tid is None:
                tid = len(self._tids)
                self._tids[thread_key] = tid
            event["tid"] = tid
            if len(self._events) < self.max_events:
                self._events.append(event)
            else:
                self._dropped += 1

    # ---- export --------------------------------------------------------
    def chrome_trace(self) -> dict:
        """The full Chrome trace object: per-process / per-thread naming
        metadata first, then the recorded events in timestamp order."""
        pid = self._resolve_process_index()
        with self._lock:
            # epoch-anchored integer µs: whole-µs resolution is plenty (the
            # cross-host alignment bound is NTP skew), and it keeps the
            # offset exact in float64 JSON numbers
            events = [
                dict(e, pid=pid, ts=round(self._wall_t0_us + e["ts"]))
                for e in self._events
            ]
            thread_names = {tid: key[1] for key, tid in self._tids.items()}
            dropped = self._dropped
        events.sort(key=lambda e: e["ts"])
        meta = [
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "args": {"name": self.process_name or f"process {pid}"},
            }
        ]
        for tid, tname in thread_names.items():
            meta.append(
                {
                    "name": "thread_name",
                    "ph": "M",
                    "pid": pid,
                    "tid": tid,
                    "args": {"name": tname},
                }
            )
        trace = {"traceEvents": meta + events, "displayTimeUnit": "ms"}
        if dropped:
            trace["dropped_events"] = dropped
        return trace

    def export(self, path: str) -> str:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(self.chrome_trace(), f)
        return path

    def export_dir(self, trace_dir: str) -> str:
        """Write ``<trace_dir>/trace-p<process_index>.json`` (one file per
        process on multi-host runs)."""
        os.makedirs(trace_dir, exist_ok=True)
        return self.export(
            os.path.join(
                trace_dir, f"trace-p{self._resolve_process_index()}.json"
            )
        )


class NullTracer:
    """The no-tracing default: ``span`` hands back one shared reusable
    ``nullcontext`` — per-batch call sites pay a method call, nothing
    else."""

    enabled = False
    _NULL = contextlib.nullcontext()

    def span(self, name: str, category: str = "run", **args):
        return self._NULL

    def instant(self, name: str, category: str = "run", **args) -> None:
        return None

    def span_complete(
        self,
        name: str,
        category: str = "run",
        start_s: float = 0.0,
        end_s: float = 0.0,
        track: str | None = None,
        **args,
    ) -> None:
        return None


NULL_TRACER = NullTracer()
_current: Tracer | NullTracer = NULL_TRACER


def get_tracer() -> Tracer | NullTracer:
    """The process-wide tracer (NullTracer unless :func:`set_tracer` ran)."""
    return _current


def set_tracer(tracer: Tracer | NullTracer | None):
    """Install ``tracer`` (None restores the NullTracer); returns the
    previous tracer so tests/tools can restore it."""
    global _current
    previous = _current
    _current = tracer if tracer is not None else NULL_TRACER
    return previous
