"""Traced synchronization primitives: the runtime half of the lock sanitizer.

Every lock in the serving/training stacks is built through one factory —
``make_lock(name)`` / ``make_rlock(name)`` / ``make_condition(name)`` —
so one switch turns the whole process's locking observable:

- **Default (``C2V_SYNC_DEBUG`` unset): plain ``threading`` primitives.**
  The factory returns the exact objects ``threading.Lock()`` etc. return —
  no wrapper, no extra attributes, zero hot-path cost. The contract is
  pinned by tests: production serving never pays for the sanitizer.
- **``--sync_debug`` / ``C2V_SYNC_DEBUG=1``: traced wrappers.** Each
  acquire/release maintains a per-thread held-lock stack; every *blocking*
  acquire taken while other locks are held adds ``held -> acquiring``
  edges to a process-global acquisition-order graph and checks for a
  cycle **at acquire time** — an inversion is reported the first time the
  orders disagree, not the one unlucky schedule where they actually
  deadlock. A detected inversion emits a ``lock_order_violation`` event
  carrying both threads' acquisition stacks and lock names, bumps the
  ``lock.order_violations`` counter, and is kept in an in-process list
  (:func:`violations`) that tests and the worker health payload read.

Accounting (debug mode only) rides the existing obs registry
(:func:`code2vec_tpu.obs.runtime.global_health`): ``lock.hold_ms`` and
``lock.wait_ms`` latency histograms and a ``lock.contended`` counter,
which the Prometheus exporter surfaces as ``c2v_lock_hold_ms`` /
``c2v_lock_wait_ms`` summaries and ``c2v_lock_contended_total``.

Scope notes:

- Non-blocking ``acquire(blocking=False)`` never adds graph edges — a
  trylock cannot participate in a deadlock (and ``Condition``'s internal
  ``_is_owned`` probe uses exactly that pattern).
- A reentrant re-acquire of a :class:`TracedRLock` the thread already
  owns adds no edge and no stack entry — RLock reentrancy is not an
  inversion.
- The leaf locks inside ``obs.runtime`` itself (``Counter``,
  ``LatencyHistogram``, the registry) stay plain ``threading`` locks:
  they are the sanitizer's own recording substrate (routing them through
  the factory would recurse) and they guard single dict/list operations
  with no nested acquisition by construction.

:func:`guard_fork_safety` is the runtime twin of the static CX005 rule:
call it immediately before requesting a ``fork`` start method, and it
reports (warning log + ``error`` event) any live non-daemon threads
whose held locks a forked child would inherit frozen.
"""

from __future__ import annotations

import logging
import os
import threading
import time
import traceback

logger = logging.getLogger(__name__)

__all__ = [
    "SYNC_DEBUG_ENV",
    "TracedCondition",
    "TracedLock",
    "TracedRLock",
    "guard_fork_safety",
    "make_condition",
    "make_lock",
    "make_rlock",
    "register_event_log",
    "reset_sync_state",
    "sync_debug_enabled",
    "sync_snapshot",
    "violations",
]

SYNC_DEBUG_ENV = "C2V_SYNC_DEBUG"

_FALSY = {"", "0", "false", "no", "off"}


def sync_debug_enabled() -> bool:
    """Read the switch at call time (not import time) so tests and the
    ``--sync_debug`` CLI flag can flip it before constructing locks."""
    return os.environ.get(SYNC_DEBUG_ENV, "").strip().lower() not in _FALSY


# ---------------------------------------------------------------------------
# global sanitizer state (touched only in debug mode)
# ---------------------------------------------------------------------------

# guards the order graph, the violation list, and event-log registration;
# deliberately a PLAIN lock — it is the sanitizer's own substrate
_state_lock = threading.Lock()

# src lock name -> {dst lock name: provenance of the first src->dst edge}
_edges: dict[str, dict[str, dict]] = {}
_violations: list[dict] = []
_violation_pairs: set[tuple[str, str]] = set()
_event_logs: list = []

_tls = threading.local()


def _held_stack() -> list:
    """This thread's stack of currently-held traced locks (innermost last);
    entries are ``[lock, t_acquired]``."""
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def register_event_log(events) -> None:
    """Attach an :class:`~code2vec_tpu.obs.events.EventLog`; detected
    inversions emit ``lock_order_violation`` events into every registered
    log (best-effort — a closed log never breaks an acquire)."""
    with _state_lock:
        if events not in _event_logs:
            _event_logs.append(events)


def reset_sync_state() -> None:
    """Drop the acquisition graph, recorded violations, and registered
    event logs (tests; also sensible after ``os.fork``)."""
    with _state_lock:
        _edges.clear()
        _violations.clear()
        _violation_pairs.clear()
        _event_logs.clear()


def violations() -> list[dict]:
    """Recorded lock-order violations (copies), oldest first."""
    with _state_lock:
        return [dict(v) for v in _violations]


def sync_snapshot() -> dict:
    """Health-payload block: sanitizer mode plus graph/violation sizes."""
    with _state_lock:
        return {
            "enabled": sync_debug_enabled(),
            "order_violations": len(_violations),
            "locks_tracked": len(
                {n for n in _edges} | {d for ds in _edges.values() for d in ds}
            ),
            "order_edges": sum(len(d) for d in _edges.values()),
        }


def _health():
    from code2vec_tpu.obs.runtime import global_health

    return global_health()


def _path_exists(src: str, dst: str) -> bool:
    """Is there a path src ->* dst in the (small) acquisition graph?
    Caller holds ``_state_lock``."""
    seen = {src}
    frontier = [src]
    while frontier:
        node = frontier.pop()
        if node == dst:
            return True
        for nxt in _edges.get(node, ()):  # noqa: jaxlint ok - dict iteration
            if nxt not in seen:
                seen.add(nxt)
                frontier.append(nxt)
    return False


def _note_blocking_acquire(lock: "_TracedBase") -> None:
    """Record ``held -> lock`` order edges and detect inversions. Runs
    BEFORE the acquire blocks, so a cycle is reported even on schedules
    that happen not to deadlock."""
    held = [entry[0] for entry in _held_stack()]
    if not held:
        return
    me = threading.current_thread().name
    stack_text = "".join(traceback.format_stack(limit=12)[:-2])
    held_names = [h.name for h in held]
    reported: list[dict] = []
    with _state_lock:
        for h in held:
            if h.name == lock.name:
                continue  # same-name locks (e.g. per-instance) never self-edge
            if _path_exists(lock.name, h.name):
                pair = (h.name, lock.name)
                if pair in _violation_pairs:
                    continue
                _violation_pairs.add(pair)
                # provenance of the recorded reverse edge lock -> h (or,
                # for longer cycles, the first hop out of `lock`)
                reverse = _edges.get(lock.name, {})
                other = reverse.get(h.name) or next(iter(reverse.values()), {})
                record = {
                    "lock": lock.name,
                    "held": list(held_names),
                    "thread": me,
                    "stack": stack_text,
                    "other_thread": other.get("thread"),
                    "other_held": other.get("held"),
                    "other_stack": other.get("stack"),
                }
                _violations.append(record)
                reported.append(record)
            else:
                _edges.setdefault(h.name, {}).setdefault(
                    lock.name,
                    {
                        "thread": me,
                        "held": list(held_names),
                        "stack": stack_text,
                    },
                )
        logs = list(_event_logs)
    # report outside the state lock: EventLog.emit and the health counter
    # take their own leaf locks
    for record in reported:
        _health().counter("lock.order_violations").inc()
        logger.error(
            "lock-order violation: thread %r acquires %r while holding %r, "
            "but the reverse order %r -> %r is already on record "
            "(thread %r) — potential deadlock",
            record["thread"], record["lock"], record["held"],
            record["lock"], record["held"][-1], record["other_thread"],
        )
        for ev in logs:
            try:
                ev.emit("lock_order_violation", **record)
            except Exception:  # pragma: no cover - closed log
                logger.warning(
                    "could not emit lock_order_violation", exc_info=True
                )


class _TracedBase:
    """Shared acquire/release instrumentation for traced locks."""

    def __init__(self, name: str, inner) -> None:
        self.name = str(name)
        self._inner = inner

    # -- context manager ------------------------------------------------
    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc_info) -> None:
        self.release()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<{type(self).__name__} {self.name!r}>"

    # -- instrumentation hooks ------------------------------------------
    def _owned_count(self) -> int:
        return sum(1 for entry in _held_stack() if entry[0] is self)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        reentrant = self._reentrant and self._owned_count() > 0
        if blocking and not reentrant:
            _note_blocking_acquire(self)
        got = self._inner.acquire(False)
        if not got:
            if not blocking:
                # not counted as contention: trylock probes (Condition's
                # _is_owned) fail by design and never wait
                return False
            _health().counter("lock.contended").inc()
            t0 = time.perf_counter()
            got = self._inner.acquire(True, timeout)
            _health().latency("lock.wait_ms").record(
                (time.perf_counter() - t0) * 1e3
            )
        if got:
            _held_stack().append([self, time.perf_counter()])
        return got

    def release(self) -> None:
        stack = _held_stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][0] is self:
                _, t_acq = stack.pop(i)
                # hold time of the outermost hold only would need pairing;
                # each acquire/release pair records its own span
                _health().latency("lock.hold_ms").record(
                    (time.perf_counter() - t_acq) * 1e3
                )
                break
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()


class TracedLock(_TracedBase):
    """``threading.Lock`` with held-stack + acquisition-order tracing."""

    _reentrant = False

    def __init__(self, name: str) -> None:
        super().__init__(name, threading.Lock())


class TracedRLock(_TracedBase):
    """``threading.RLock`` with tracing; reentrant re-acquires add no
    order edges (reentrancy is not an inversion)."""

    _reentrant = True

    def __init__(self, name: str) -> None:
        super().__init__(name, threading.RLock())

    def locked(self) -> bool:  # RLock has no .locked() before 3.12
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True


class TracedCondition:
    """``threading.Condition`` over a :class:`TracedLock`: waiting releases
    the traced lock (popping it off the held stack — a waiter holds
    nothing) and re-acquires it through the traced path on wake."""

    def __init__(self, name: str, lock: _TracedBase | None = None) -> None:
        self.name = str(name)
        self._lock = lock if lock is not None else TracedLock(name)
        self._cond = threading.Condition(self._lock)

    def acquire(self, *args, **kwargs):
        return self._lock.acquire(*args, **kwargs)

    def release(self) -> None:
        self._lock.release()

    def __enter__(self):
        self._lock.acquire()
        return self

    def __exit__(self, *exc_info) -> None:
        self._lock.release()

    def wait(self, timeout: float | None = None) -> bool:
        return self._cond.wait(timeout)

    def wait_for(self, predicate, timeout: float | None = None):
        return self._cond.wait_for(predicate, timeout)

    def notify(self, n: int = 1) -> None:
        self._cond.notify(n)

    def notify_all(self) -> None:
        self._cond.notify_all()


# ---------------------------------------------------------------------------
# the factory
# ---------------------------------------------------------------------------


def make_lock(name: str):
    """A mutex named for diagnostics: plain ``threading.Lock()`` unless
    ``C2V_SYNC_DEBUG`` is set, then a :class:`TracedLock`."""
    if sync_debug_enabled():
        return TracedLock(name)
    return threading.Lock()


def make_rlock(name: str):
    """Reentrant variant of :func:`make_lock`."""
    if sync_debug_enabled():
        return TracedRLock(name)
    return threading.RLock()


def make_condition(name: str, lock=None):
    """Condition variant of :func:`make_lock`; ``lock`` may be a traced
    lock (debug mode) or any plain lock (default mode)."""
    if sync_debug_enabled():
        traced = lock if isinstance(lock, _TracedBase) else None
        return TracedCondition(name, traced)
    return threading.Condition(lock)


# ---------------------------------------------------------------------------
# fork safety (runtime twin of the static CX005 rule)
# ---------------------------------------------------------------------------


def guard_fork_safety(where: str, events=None) -> list[str]:
    """Report live non-daemon threads (other than the caller) right before
    a ``fork`` start method is requested: a forked child inherits every
    lock those threads hold, permanently locked, with no owner to release
    them. Returns the offending thread names; warns via the log and an
    ``error`` event rather than refusing — the caller may know its
    threads hold nothing (and says so at its call site)."""
    offenders = sorted(
        t.name
        for t in threading.enumerate()
        if t.is_alive()
        and not t.daemon
        and t is not threading.current_thread()
    )
    if offenders:
        message = (
            f"{where}: fork start-method requested while non-daemon "
            f"threads are alive ({', '.join(offenders)}); forked children "
            "inherit any locks those threads hold, permanently frozen — "
            "start worker pools before serving/training threads"
        )
        logger.warning(message)
        if events is not None:
            try:
                events.emit(
                    "error",
                    where=where,
                    kind="fork_after_threads",
                    message=message,
                    threads=offenders,
                )
            except Exception:  # pragma: no cover - closed log
                logger.warning("could not emit fork guard event", exc_info=True)
    return offenders
