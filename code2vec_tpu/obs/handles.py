"""Runtime handle ledger: the runtime half of the resource-lifecycle analyzer.

Every lifecycle-bearing object in the serving/training stacks — prefetch
producer threads, micro-batchers, forked feed pools, replica subprocesses,
swap generations, the async checkpoint writer, mmap CSR/ANN readers, event
logs, the flight recorder — registers with one ledger through
``track(obj, kind)`` at acquisition and ``untrack(obj)`` at release:

- **Default (``C2V_HANDLE_DEBUG`` unset): a zero-cost no-op.**
  ``track`` returns its argument unchanged (``track(x, k) is x``), adds no
  attributes, takes no locks, and leaves module state empty. The contract
  is pinned by tests the same way ``obs/sync.py`` pins its plain-primitive
  contract: production serving never pays for the ledger.
- **``--handle_debug`` / ``C2V_HANDLE_DEBUG=1``: a live open-handle
  ledger.** Each tracked object gets a record carrying its kind, a
  human-readable name, and the *creation-site* stack captured at
  ``track`` time. ``untrack`` removes the record; whatever is left is, by
  definition, an open handle.

Accounting (debug mode only) rides the existing obs registry
(:func:`code2vec_tpu.obs.runtime.global_health`): per-kind
``handles.open.<kind>`` gauges (Prometheus: ``c2v_handles_open_<kind>``)
plus ``handles.opened`` / ``handles.closed`` / ``handles.leaked``
counters. The worker health payload carries a ``handles`` block
(:func:`handles_snapshot`) that the fleet router relays per-replica into
fleet health — so a replica leaking one fd per swap is visible from the
router *before* it dies, and :mod:`~code2vec_tpu.serve.fleet.router`
stamps the dead incarnation's last-known open-handle count into
``fleet_replica_evicted`` events.

At shutdown, :func:`report_leaks` emits one ``handle_leak`` event per
still-open handle, naming the creation site — the runtime twin of the
static RS rules in :mod:`code2vec_tpu.analysis.lifecycle`, sharing their
vocabulary of lifecycle owners.

The ledger keys records by ``id(obj)`` and never holds a strong reference
to the tracked object itself, so tracking cannot extend an object's
lifetime or break GC cycles. ``_state_lock`` is deliberately a PLAIN
``threading.Lock`` (not ``make_lock``): the ledger is observability
substrate, same tier as the metric primitives the lock sanitizer refuses
to trace.
"""

from __future__ import annotations

import logging
import os
import threading
import time
import traceback

logger = logging.getLogger(__name__)

__all__ = [
    "HANDLE_DEBUG_ENV",
    "handle_debug_enabled",
    "handles_snapshot",
    "open_handles",
    "register_event_log",
    "report_leaks",
    "reset_handle_state",
    "track",
    "untrack",
]

HANDLE_DEBUG_ENV = "C2V_HANDLE_DEBUG"

_FALSY = {"", "0", "false", "no", "off"}


def handle_debug_enabled() -> bool:
    """Read the switch at call time (not import time) so tests and the
    ``--handle_debug`` CLI flag can flip it before constructing owners."""
    return os.environ.get(HANDLE_DEBUG_ENV, "").strip().lower() not in _FALSY


# ---------------------------------------------------------------------------
# global ledger state (touched only in debug mode)
# ---------------------------------------------------------------------------

# guards the open-handle table and event-log registration; deliberately a
# PLAIN lock — the ledger is observability substrate (see module docstring)
_state_lock = threading.Lock()

# id(obj) -> open-handle record (no strong ref to obj; see module docstring)
_open: dict[int, dict] = {}
_leaked: int = 0
_seq: int = 0
_event_logs: list = []


def register_event_log(events) -> None:
    """Attach an :class:`~code2vec_tpu.obs.events.EventLog`; leak reports
    emit ``handle_leak`` events into every registered log (best-effort —
    a closed log never breaks a report)."""
    with _state_lock:
        if events not in _event_logs:
            _event_logs.append(events)


def reset_handle_state() -> None:
    """Drop all ledger state (tests)."""
    global _leaked, _seq
    with _state_lock:
        _open.clear()
        _event_logs.clear()
        _leaked = 0
        _seq = 0


def _health():
    # lazy: obs.runtime is stdlib-only but keeping the import out of module
    # scope keeps this module importable from anywhere without cycles
    from code2vec_tpu.obs.runtime import global_health

    return global_health()


def _creation_site(skip: int = 2) -> str:
    """Trimmed stack text ending at the caller of ``track`` — the site the
    leak report names. ``skip`` drops this helper + the track frame."""
    frames = traceback.format_stack()
    return "".join(frames[max(0, len(frames) - 8 - skip) : len(frames) - skip])


def track(obj, kind: str, name: str | None = None):
    """Register ``obj`` as an open handle of ``kind``; ALWAYS returns
    ``obj`` itself (identity — callers can write
    ``self._proc = track(Popen(...), "replica")`` unconditionally).

    Off: returns immediately, no state touched. On: records
    {kind, name, creation site, open time} keyed by ``id(obj)`` and bumps
    the per-kind open gauge. Re-tracking an id (a dead object's id reused
    by a new allocation) replaces the stale record.
    """
    if not handle_debug_enabled():
        return obj
    global _seq
    now = time.time()
    site = _creation_site()
    record = {
        "kind": kind,
        "name": name if name is not None else type(obj).__name__,
        "site": site,
        "opened_unix": now,
        "thread": threading.current_thread().name,
    }
    with _state_lock:
        _seq += 1
        record["token"] = _seq
        stale = _open.pop(id(obj), None)
        _open[id(obj)] = record
    health = _health()
    if stale is not None:
        _gauge_delta(health, stale["kind"], -1)
    _gauge_delta(health, kind, +1)
    health.counter("handles.opened").inc()
    return obj


def untrack(obj) -> bool:
    """Mark ``obj`` closed. Returns True if it was ledger-open. Safe to
    call twice (idempotent close paths) and when the ledger is off."""
    if not handle_debug_enabled():
        return False
    with _state_lock:
        record = _open.pop(id(obj), None)
    if record is None:
        return False
    health = _health()
    _gauge_delta(health, record["kind"], -1)
    health.counter("handles.closed").inc()
    return True


def _gauge_delta(health, kind: str, delta: int) -> None:
    gauge = health.gauge(f"handles.open.{kind}")
    gauge.set((gauge.value or 0) + delta)


def open_handles(kind: str | None = None) -> list[dict]:
    """Copies of the currently-open records (optionally one kind), ordered
    by open time. Each carries ``token`` — a monotone per-process open
    sequence number the zero-leak pytest fixture diffs across a test."""
    with _state_lock:
        records = [dict(r) for r in _open.values()]
    if kind is not None:
        records = [r for r in records if r["kind"] == kind]
    records.sort(key=lambda r: r["token"])
    return records


def handles_snapshot() -> dict:
    """Health-payload block: enabled flag + open counts per kind. Cheap
    enough to ride every health probe."""
    if not handle_debug_enabled():
        return {"enabled": False}
    by_kind: dict[str, int] = {}
    with _state_lock:
        for record in _open.values():
            by_kind[record["kind"]] = by_kind.get(record["kind"], 0) + 1
        leaked = _leaked
    return {
        "enabled": True,
        "open_total": sum(by_kind.values()),
        "open": dict(sorted(by_kind.items())),
        "leaked": leaked,
    }


def report_leaks(where: str, events=None, exclude: tuple = ()) -> list[dict]:
    """Shutdown leak report: every handle still open is a leak. Emits one
    ``handle_leak`` event per leaked record (kind, name, age, creation
    site) into ``events`` plus every registered log, bumps the
    ``handles.leaked`` counter, and returns the records.

    ``exclude`` lists objects legitimately still open at report time —
    typically the event log the report itself writes into. Records are
    reported once: a second ``report_leaks`` call (e.g. two teardown
    paths racing) skips already-reported entries. The ledger is NOT
    cleared — post-report assertions still see the leaks.
    """
    global _leaked
    if not handle_debug_enabled():
        return []
    exclude_ids = {id(o) for o in exclude}
    fresh: list[dict] = []
    with _state_lock:
        for obj_id, record in _open.items():
            if obj_id in exclude_ids or record.get("reported"):
                continue
            record["reported"] = True
            fresh.append(dict(record))
        logs = list(_event_logs)
        _leaked += len(fresh)
    if not fresh:
        return []
    fresh.sort(key=lambda r: r["token"])
    now = time.time()
    health = _health()
    health.counter("handles.leaked").inc(len(fresh))
    if events is not None and events not in logs:
        logs.append(events)
    for record in fresh:
        logger.warning(
            "handle leak at %s: %s '%s' open %.1fs, created at\n%s",
            where,
            record["kind"],
            record["name"],
            now - record["opened_unix"],
            record["site"],
        )
        for log in logs:
            try:
                log.emit(
                    "handle_leak",
                    where=where,
                    kind=record["kind"],
                    name=record["name"],
                    age_s=round(now - record["opened_unix"], 3),
                    site=record["site"],
                )
            except Exception:  # pragma: no cover - closed/broken log
                pass
    logger.warning(
        "handle leak report at %s: %d leaked handle(s)", where, len(fresh)
    )
    return fresh
