"""Runtime health: counters/gauges, recompile detection, memory sampling.

Two detectors wired into the train loop (``train/loop.py``):

- :class:`RecompileDetector` — reads each tracked jitted step function's
  ``jax.jit`` cache size (``fn._cache_size()``) at epoch boundaries. The
  first observation is the warmup baseline (the expected initial compile);
  any later growth means batch-shape/dtype churn recompiled the step —
  counted, logged as a warning, and emitted as a ``recompile`` event.
  Steady-shape runs report 0 recompiles after warmup.
- :func:`memory_snapshot` — host RSS (``/proc/self/statm``; peak via
  ``resource``) always, plus ``device.memory_stats()`` where the backend
  implements it (TPU/GPU; CPU returns None). Recorded into the ``epoch``
  event and ``bench.py``'s detail JSON.
"""

from __future__ import annotations

import logging
import os
import threading

logger = logging.getLogger(__name__)

__all__ = [
    "Counter",
    "Gauge",
    "LatencyHistogram",
    "NamespacedHealth",
    "RuntimeHealth",
    "RecompileDetector",
    "global_health",
    "host_cpu_fingerprint",
    "host_rss_bytes",
    "device_memory_stats",
    "memory_snapshot",
]


class Counter:
    """A monotonically increasing count (thread-safe)."""

    def __init__(self) -> None:
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        return self._value


class Gauge:
    """A last-written-wins measurement (thread-safe by assignment)."""

    def __init__(self) -> None:
        self._value: float | None = None

    def set(self, value) -> None:
        self._value = value

    @property
    def value(self):
        return self._value


class LatencyHistogram:
    """Latency samples with percentile summaries (thread-safe).

    The serving layer records one sample per request per phase
    (queue_wait / pad / device / postprocess plus end-to-end), and
    ``bench.py --serve`` reports the p50/p99 the ISSUE's acceptance
    criteria name. Exact samples, not buckets: serving test runs are
    10^3-10^5 requests, where a sorted copy per summary is cheap and
    bucket-boundary error would dominate a p99 over so few samples.
    ``max_samples`` bounds memory on long-lived servers: past the cap the
    buffer becomes a sliding window over the most recent samples (the
    regime a live server's percentiles should reflect anyway); ``count``
    keeps the true total.
    """

    def __init__(self, max_samples: int = 200_000) -> None:
        self._samples: list[float] = []
        self._count = 0
        self._max = int(max_samples)
        self._lock = threading.Lock()

    def record(self, value_ms: float) -> None:
        with self._lock:
            self._count += 1
            if len(self._samples) < self._max:
                self._samples.append(float(value_ms))
            else:
                # count is post-increment: sample #i lives at (i-1) % max,
                # so the overwrite must use the same 0-based index or the
                # oldest sample survives a full extra window
                self._samples[(self._count - 1) % self._max] = float(value_ms)

    @property
    def count(self) -> int:
        return self._count

    def summary(self) -> dict | None:
        # copy under the lock, sort OUTSIDE it: sorting 200k floats while
        # holding the lock would stall the batcher thread's record() calls
        # for the duration of every health poll
        with self._lock:
            samples = list(self._samples)
            count = self._count
        if not samples:
            return None
        ordered = sorted(samples)

        def at(q: float) -> float:
            rank = min(
                len(ordered) - 1,
                max(0, int(round(q / 100.0 * (len(ordered) - 1)))),
            )
            return round(ordered[rank], 3)

        return {
            "count": count,
            "p50_ms": at(50),
            "p90_ms": at(90),
            "p99_ms": at(99),
            "max_ms": round(ordered[-1], 3),
            "mean_ms": round(sum(ordered) / len(ordered), 3),
        }


class RuntimeHealth:
    """Named counters/gauges/latency-histograms registry; one per run,
    snapshot on demand."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: dict[str, Counter] = {}
        self._gauges: dict[str, Gauge] = {}
        self._latencies: dict[str, LatencyHistogram] = {}

    def counter(self, name: str) -> Counter:
        with self._lock:
            return self._counters.setdefault(name, Counter())

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            return self._gauges.setdefault(name, Gauge())

    def latency(self, name: str) -> LatencyHistogram:
        with self._lock:
            return self._latencies.setdefault(name, LatencyHistogram())

    def namespaced(self, prefix: str) -> "NamespacedHealth":
        """A view of this registry that prefixes every metric name with
        ``prefix`` + '.'. One registry, one snapshot, one schema — but
        subsystems that exist N times per process (fleet replica slots,
        SLO classes) get distinct, greppable metric names instead of
        aliasing one counter."""
        return NamespacedHealth(self, prefix)

    def snapshot(self) -> dict:
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            latencies = dict(self._latencies)
        return {
            "counters": {k: c.value for k, c in counters.items()},
            "gauges": {k: g.value for k, g in gauges.items()},
            **(
                {
                    "latencies_ms": {
                        k: h.summary() for k, h in latencies.items()
                    }
                }
                if latencies
                else {}
            ),
        }


class NamespacedHealth:
    """Name-prefixing facade over a :class:`RuntimeHealth` (see
    :meth:`RuntimeHealth.namespaced`); metrics land in the PARENT registry
    under ``<prefix>.<name>`` so its snapshot carries them all."""

    def __init__(self, parent: RuntimeHealth, prefix: str) -> None:
        self._parent = parent
        self.prefix = str(prefix)

    def _name(self, name: str) -> str:
        return f"{self.prefix}.{name}"

    def counter(self, name: str) -> Counter:
        return self._parent.counter(self._name(name))

    def gauge(self, name: str) -> Gauge:
        return self._parent.gauge(self._name(name))

    def latency(self, name: str) -> LatencyHistogram:
        return self._parent.latency(self._name(name))

    def namespaced(self, prefix: str) -> "NamespacedHealth":
        return NamespacedHealth(self._parent, self._name(prefix))

    def snapshot(self) -> dict:
        return self._parent.snapshot()


_global_health: RuntimeHealth | None = None
_global_health_lock = threading.Lock()


def global_health() -> RuntimeHealth:
    """Process-wide counter/gauge registry for subsystems that outlive any
    one run (the kernel-schedule autotune cache counts its hits/misses/
    timing runs here so callers can assert 'second run did zero search').
    The train loop keeps its own per-run :class:`RuntimeHealth`; this one
    is never reset."""
    global _global_health
    with _global_health_lock:
        if _global_health is None:
            _global_health = RuntimeHealth()
        return _global_health


def _lint_hints() -> dict[str, str]:
    """jaxlint rule ids whose defect class surfaces as silent jit-cache
    growth, so the `recompile` warning/event links runtime telemetry back
    to the static pass. Guarded: obs must stay usable even if the analysis
    package is stripped from a deployment."""
    try:
        from code2vec_tpu.analysis.jaxlint import RECOMPILE_HINT_RULES

        return dict(RECOMPILE_HINT_RULES)
    except Exception:  # pragma: no cover - partial install
        return {}


class RecompileDetector:
    """Count post-warmup ``jax.jit`` cache misses per tracked step function.

    The jitted train/eval steps are traced once per (shape, dtype)
    signature; static batch shapes are the suite's invariant (SURVEY §7).
    A growing cache after the first observation means something is feeding
    shape-churned batches — each growth is a silent recompile costing
    seconds. ``track`` ignores functions without a ``_cache_size`` probe
    (injected non-jitted steps), so wiring is unconditional.

    ``expected_compiles``: a per-function compile BUDGET for functions that
    legitimately serve several static shapes — length-aware bucketed
    batching compiles the step once per ladder width. Cache growth up to
    the budget counts as warmup and stays silent at every check (not just
    the first); only growth beyond ``max(budget, observed)`` fires the
    ``recompile`` warning/event. Without it the first observation is the
    baseline, as before.
    """

    def __init__(self, events=None, health: RuntimeHealth | None = None):
        self._events = events
        self._counter = (
            health.counter("recompiles") if health is not None else Counter()
        )
        # name -> [fn, last observed cache size or None (pre-warmup)];
        # budgeted fns start at their budget instead of None — the ladder's
        # compiles are expected whenever they happen, so there is no
        # first-observation grace to confuse with real churn
        self._tracked: dict[str, list] = {}

    def track(self, name: str, fn, expected_compiles: int | None = None):
        if callable(getattr(fn, "_cache_size", None)):
            baseline = None
            if expected_compiles is not None:
                if expected_compiles < 1:
                    raise ValueError(
                        f"expected_compiles must be >= 1, got {expected_compiles}"
                    )
                baseline = int(expected_compiles)
            self._tracked[name] = [fn, baseline]
        return fn

    @property
    def recompile_count(self) -> int:
        return self._counter.value

    def check(self, epoch: int | None = None) -> int:
        """Observe every tracked function once; returns the number of NEW
        post-warmup compiles found this check."""
        new = 0
        for name, slot in self._tracked.items():
            fn, last = slot
            try:
                size = int(fn._cache_size())
            except Exception:  # pragma: no cover - probe API drift
                continue
            if last is None:
                slot[1] = size  # warmup: the expected initial compile(s)
                continue
            if size > last:
                delta = size - last
                new += delta
                self._counter.inc(delta)
                # also a zero-duration mark on the trace timeline, so the
                # recompile is visible next to the step spans it stalled
                from code2vec_tpu.obs.trace import get_tracer

                get_tracer().instant(
                    "recompile", category="health", fn=name, delta=delta
                )
                hints = _lint_hints()
                hint_suffix = (
                    " Likely static causes: "
                    + "; ".join(
                        f"{rid}: {why}" for rid, why in hints.items()
                    )
                    + " — run `python -m code2vec_tpu.analysis` to locate"
                    if hints
                    else ""
                )
                logger.warning(
                    "recompile detected: %s jit cache grew %d -> %d "
                    "(batch shape/dtype churn?); each recompile stalls the "
                    "step for the full XLA compile.%s",
                    name,
                    last,
                    size,
                    hint_suffix,
                )
                if self._events is not None:
                    fields = {"fn": name, "cache_size": size, "delta": delta,
                              "lint_hints": sorted(hints)}
                    if epoch is not None:
                        fields["epoch"] = epoch
                    self._events.emit("recompile", **fields)
                slot[1] = size
        return new


def host_cpu_fingerprint() -> str:
    """8-hex digest of the host's CPU feature set (ISA flags + arch).

    XLA's persistent compile cache stores machine code specialized to the
    compiling host's CPU features; reusing one cache dir across hosts with
    different feature sets logs ``machine features mismatch ... could lead
    to SIGILL`` (seen in BENCH_r05) and can crash outright. Consumers
    (tests/conftest.py, bench.py) key their cache dirs by this fingerprint
    so each CPU population gets its own cache. Stdlib-only, stable within
    a host across runs."""
    import hashlib
    import platform

    parts = [platform.machine()]
    try:
        with open("/proc/cpuinfo", encoding="utf-8") as f:
            for line in f:
                # x86 exposes "flags", arm64 "Features"; sort so kernel
                # ordering changes don't churn the digest
                if line.startswith(("flags", "Features")):
                    parts.append(
                        " ".join(sorted(line.split(":", 1)[1].split()))
                    )
                    break
    except OSError:
        parts.append(platform.processor() or "")
    return hashlib.sha1("|".join(parts).encode()).hexdigest()[:8]


def host_rss_bytes() -> int | None:
    """Current resident set size, or None off-Linux."""
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return pages * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):  # pragma: no cover - non-Linux
        return None


def _host_peak_rss_bytes() -> int | None:
    try:
        import resource
        import sys

        peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        # linux reports ru_maxrss in KiB; macOS/BSD report bytes
        return peak * 1024 if sys.platform.startswith("linux") else peak
    except Exception:  # pragma: no cover - platform without resource
        return None


def device_memory_stats() -> dict | None:
    """Aggregate ``memory_stats()`` over local devices; None when the
    backend doesn't report (CPU) or jax isn't up yet."""
    try:
        import jax

        devices = jax.local_devices()
        if not devices:
            return None
        # inside the guard: some backends raise (UNIMPLEMENTED) instead of
        # returning None, and the per-epoch sampler must never kill a run
        stats = [d.memory_stats() for d in devices]
    except Exception:
        return None
    if any(s is None for s in stats):
        return None
    out = {
        "device_kind": devices[0].device_kind,
        "n_devices": len(devices),
    }
    for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
        values = [s.get(key) for s in stats]
        if all(v is not None for v in values):
            out[key] = int(sum(values))
    return out


def memory_snapshot(health: RuntimeHealth | None = None) -> dict:
    """One host+device memory sample; mirrors into ``health`` gauges when
    given. Called at epoch boundaries and from bench.py's detail block."""
    snap: dict = {
        "host_rss_bytes": host_rss_bytes(),
        "host_peak_rss_bytes": _host_peak_rss_bytes(),
    }
    device = device_memory_stats()
    if device is not None:
        snap["device"] = device
    if health is not None:
        for key in ("host_rss_bytes", "host_peak_rss_bytes"):
            if snap[key] is not None:
                health.gauge(key).set(snap[key])
        if device is not None and "bytes_in_use" in device:
            health.gauge("device_bytes_in_use").set(device["bytes_in_use"])
    return snap
