"""Runtime health: counters/gauges, recompile detection, memory sampling.

Two detectors wired into the train loop (``train/loop.py``):

- :class:`RecompileDetector` — reads each tracked jitted step function's
  ``jax.jit`` cache size (``fn._cache_size()``) at epoch boundaries. The
  first observation is the warmup baseline (the expected initial compile);
  any later growth means batch-shape/dtype churn recompiled the step —
  counted, logged as a warning, and emitted as a ``recompile`` event.
  Steady-shape runs report 0 recompiles after warmup.
- :func:`memory_snapshot` — host RSS (``/proc/self/statm``; peak via
  ``resource``) always, plus ``device.memory_stats()`` where the backend
  implements it (TPU/GPU; CPU returns None). Recorded into the ``epoch``
  event and ``bench.py``'s detail JSON.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import re
import threading
import time

# no cycle: obs.sync reaches back into this module only lazily (inside its
# metric-recording path), so the factory import is safe at module top
from code2vec_tpu.obs import handles
from code2vec_tpu.obs.sync import make_lock

logger = logging.getLogger(__name__)

__all__ = [
    "Counter",
    "FlightRecorder",
    "Gauge",
    "LatencyHistogram",
    "NamespacedHealth",
    "RuntimeHealth",
    "RecompileDetector",
    "build_info",
    "build_info_text",
    "global_health",
    "host_cpu_fingerprint",
    "host_rss_bytes",
    "device_memory_stats",
    "memory_snapshot",
    "parse_prometheus_text",
    "prometheus_metric_name",
    "prometheus_text",
]


class Counter:
    """A monotonically increasing count (thread-safe)."""

    def __init__(self) -> None:
        self._value = 0
        # plain on purpose: metric primitives are the lock sanitizer's own
        # recording substrate — tracing them would recurse
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        return self._value


class Gauge:
    """A last-written-wins measurement (thread-safe by assignment)."""

    def __init__(self) -> None:
        self._value: float | None = None

    def set(self, value) -> None:
        self._value = value

    @property
    def value(self):
        return self._value


class LatencyHistogram:
    """Latency samples with percentile summaries (thread-safe).

    The serving layer records one sample per request per phase
    (queue_wait / pad / device / postprocess plus end-to-end), and
    ``bench.py --serve`` reports the p50/p99 the ISSUE's acceptance
    criteria name. Exact samples, not buckets: serving test runs are
    10^3-10^5 requests, where a sorted copy per summary is cheap and
    bucket-boundary error would dominate a p99 over so few samples.
    ``max_samples`` bounds memory on long-lived servers: past the cap the
    buffer becomes a sliding window over the most recent samples (the
    regime a live server's percentiles should reflect anyway); ``count``
    keeps the true total.
    """

    def __init__(self, max_samples: int = 200_000) -> None:
        self._samples: list[float] = []
        self._count = 0
        self._sum = 0.0  # over ALL samples ever (Prometheus summary _sum)
        self._max = int(max_samples)
        self._lock = threading.Lock()  # plain on purpose: sanitizer substrate

    def record(self, value_ms: float) -> None:
        with self._lock:
            self._count += 1
            self._sum += float(value_ms)
            if len(self._samples) < self._max:
                self._samples.append(float(value_ms))
            else:
                # count is post-increment: sample #i lives at (i-1) % max,
                # so the overwrite must use the same 0-based index or the
                # oldest sample survives a full extra window
                self._samples[(self._count - 1) % self._max] = float(value_ms)

    @property
    def count(self) -> int:
        return self._count

    def summary(self) -> dict | None:
        # copy under the lock, sort OUTSIDE it: sorting 200k floats while
        # holding the lock would stall the batcher thread's record() calls
        # for the duration of every health poll
        with self._lock:
            samples = list(self._samples)
            count = self._count
            total = self._sum
        if not samples:
            return None
        ordered = sorted(samples)

        def at(q: float) -> float:
            rank = min(
                len(ordered) - 1,
                max(0, int(round(q / 100.0 * (len(ordered) - 1)))),
            )
            return round(ordered[rank], 3)

        return {
            "count": count,
            "p50_ms": at(50),
            "p90_ms": at(90),
            "p99_ms": at(99),
            "max_ms": round(ordered[-1], 3),
            "mean_ms": round(sum(ordered) / len(ordered), 3),
            # all-time sum (not just the window): with count it lets two
            # /metrics scrapes compute an honest rate — the Prometheus
            # summary contract (_sum/_count)
            "sum_ms": round(total, 3),
        }


class RuntimeHealth:
    """Named counters/gauges/latency-histograms registry; one per run,
    snapshot on demand."""

    def __init__(self) -> None:
        # plain on purpose: the registry hands out the sanitizer's metrics
        self._lock = threading.Lock()
        self._counters: dict[str, Counter] = {}
        self._gauges: dict[str, Gauge] = {}
        self._latencies: dict[str, LatencyHistogram] = {}
        # identity fields every snapshot carries: process start time and a
        # strictly increasing snapshot sequence number. Two /metrics
        # scrapes (or two health polls) can then compute honest rates and
        # DETECT a counter reset — a respawned replica restarts both at
        # zero, which otherwise reads as a huge negative rate.
        self._started_unix = time.time()
        self._snapshot_seq = 0

    def counter(self, name: str) -> Counter:
        with self._lock:
            return self._counters.setdefault(name, Counter())

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            return self._gauges.setdefault(name, Gauge())

    def latency(self, name: str) -> LatencyHistogram:
        with self._lock:
            return self._latencies.setdefault(name, LatencyHistogram())

    def namespaced(self, prefix: str) -> "NamespacedHealth":
        """A view of this registry that prefixes every metric name with
        ``prefix`` + '.'. One registry, one snapshot, one schema — but
        subsystems that exist N times per process (fleet replica slots,
        SLO classes) get distinct, greppable metric names instead of
        aliasing one counter."""
        return NamespacedHealth(self, prefix)

    def snapshot(self) -> dict:
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            latencies = dict(self._latencies)
            self._snapshot_seq += 1
            seq = self._snapshot_seq
        return {
            "started_unix": self._started_unix,
            "snapshot_seq": seq,
            "counters": {k: c.value for k, c in counters.items()},
            "gauges": {k: g.value for k, g in gauges.items()},
            **(
                {
                    "latencies_ms": {
                        k: h.summary() for k, h in latencies.items()
                    }
                }
                if latencies
                else {}
            ),
        }


class NamespacedHealth:
    """Name-prefixing facade over a :class:`RuntimeHealth` (see
    :meth:`RuntimeHealth.namespaced`); metrics land in the PARENT registry
    under ``<prefix>.<name>`` so its snapshot carries them all."""

    def __init__(self, parent: RuntimeHealth, prefix: str) -> None:
        self._parent = parent
        self.prefix = str(prefix)

    def _name(self, name: str) -> str:
        return f"{self.prefix}.{name}"

    def counter(self, name: str) -> Counter:
        return self._parent.counter(self._name(name))

    def gauge(self, name: str) -> Gauge:
        return self._parent.gauge(self._name(name))

    def latency(self, name: str) -> LatencyHistogram:
        return self._parent.latency(self._name(name))

    def namespaced(self, prefix: str) -> "NamespacedHealth":
        return NamespacedHealth(self._parent, self._name(prefix))

    def snapshot(self) -> dict:
        return self._parent.snapshot()


# ---------------------------------------------------------------------------
# Prometheus text exposition (text/plain; version=0.0.4)
# ---------------------------------------------------------------------------

_PROM_INVALID = re.compile(r"[^a-zA-Z0-9_:]")


def prometheus_metric_name(dotted: str, prefix: str = "c2v_") -> str:
    """Sanitize one of the registry's dotted metric names into a legal
    Prometheus metric name: ``serve.op.embed.e2e_ms`` ->
    ``c2v_serve_op_embed_e2e_ms``. The prefix namespaces the whole
    exporter; a leading digit after sanitization gets an underscore."""
    name = _PROM_INVALID.sub("_", str(dotted))
    name = prefix + name
    if not re.match(r"[a-zA-Z_:]", name):  # pragma: no cover - empty prefix
        name = "_" + name
    return name


def _prom_label_str(labels: dict) -> str:
    if not labels:
        return ""
    parts = []
    for key, value in sorted(labels.items()):
        # the exposition format's three label escapes: backslash, quote,
        # newline (an unescaped newline would split the sample line)
        value = (
            str(value)
            .replace("\\", r"\\")
            .replace('"', r"\"")
            .replace("\n", r"\n")
        )
        parts.append(f'{key}="{value}"')
    return "{" + ",".join(parts) + "}"


def _prom_number(value) -> str:
    # integers stay exact; floats use repr (full precision, strict JSON
    # numbers are valid Prometheus values)
    if isinstance(value, bool):  # pragma: no cover - gauges never store bools
        return "1" if value else "0"
    if isinstance(value, int):
        return str(value)
    return repr(float(value))


def prometheus_text(
    sources, prefix: str = "c2v_"
) -> str:
    """Serialize health snapshots as Prometheus text exposition 0.0.4.

    ``sources``: iterable of ``(labels_dict, snapshot_dict)`` pairs — one
    pair for a single process, one per replica (``{"replica": "r0"}``)
    for the fleet router's aggregated view. Snapshots are the plain
    dicts :meth:`RuntimeHealth.snapshot` returns (or the same block
    embedded in a replica's ``health`` payload), so serialization never
    touches live registries, locks, or device state — the lock-light
    scrape contract.

    Counters export as ``counter``, numeric gauges as ``gauge``
    (non-numeric gauges — e.g. the transport name — are skipped), and
    latency histograms as ``summary`` series: ``quantile`` labels for
    p50/p90/p99 plus ``_sum``/``_count``. ``started_unix`` becomes the
    conventional ``process_start_time_seconds`` and ``snapshot_seq`` a
    gauge, so scrapers can compute honest rates and detect counter
    resets across replica respawns.
    """
    # metric name -> {"type": t, "samples": [(labels, value)]}; insertion
    # order preserved so the output groups each metric's series under ONE
    # # TYPE header (the exposition format requires it)
    series: dict[str, dict] = {}

    def add(name: str, mtype: str, labels: dict, value) -> None:
        entry = series.setdefault(name, {"type": mtype, "samples": []})
        entry["samples"].append((labels, value))

    for labels, snapshot in sources:
        labels = dict(labels or {})
        started = snapshot.get("started_unix")
        if isinstance(started, (int, float)):
            add(
                prometheus_metric_name("process_start_time_seconds", prefix),
                "gauge", labels, float(started),
            )
        seq = snapshot.get("snapshot_seq")
        if isinstance(seq, (int, float)):
            add(
                prometheus_metric_name("health_snapshot_seq", prefix),
                "gauge", labels, seq,
            )
        for key, value in (snapshot.get("counters") or {}).items():
            if isinstance(value, (int, float)) and not isinstance(value, bool):
                add(
                    prometheus_metric_name(key, prefix) + "_total",
                    "counter", labels, value,
                )
        for key, value in (snapshot.get("gauges") or {}).items():
            if isinstance(value, (int, float)) and not isinstance(value, bool):
                add(prometheus_metric_name(key, prefix), "gauge", labels, value)
        for key, summary in (snapshot.get("latencies_ms") or {}).items():
            if not isinstance(summary, dict):
                continue
            base = prometheus_metric_name(key, prefix)
            for quantile, field in (
                ("0.5", "p50_ms"), ("0.9", "p90_ms"), ("0.99", "p99_ms"),
            ):
                if isinstance(summary.get(field), (int, float)):
                    add(
                        base, "summary",
                        {**labels, "quantile": quantile}, summary[field],
                    )
            if isinstance(summary.get("sum_ms"), (int, float)):
                add(base + "_sum", "summary:sum", labels, summary["sum_ms"])
            if isinstance(summary.get("count"), (int, float)):
                add(base + "_count", "summary:count", labels, summary["count"])

    lines = []
    emitted_type: set[str] = set()
    for name, entry in series.items():
        mtype = entry["type"]
        # _sum/_count ride their summary's TYPE header, not their own
        base = name
        if mtype.startswith("summary:"):
            base = name[: -len("_sum")] if mtype == "summary:sum" else (
                name[: -len("_count")]
            )
            mtype = "summary"
        if base not in emitted_type:
            lines.append(f"# TYPE {base} {mtype}")
            emitted_type.add(base)
        for labels, value in entry["samples"]:
            lines.append(f"{name}{_prom_label_str(labels)} {_prom_number(value)}")
    return "\n".join(lines) + ("\n" if lines else "")


def build_info(extra: dict | None = None) -> dict:
    """Build/runtime identity labels for the ``c2v_build_info`` gauge.

    jax's version comes from package metadata (no import), so a jax-free
    process — the fleet router — can report it without dragging in the
    backend; ``backend``/``device_kind`` appear only when the caller's
    process already initialized jax (workers, the train loop).
    """
    import platform

    info = {"python_version": platform.python_version()}
    try:
        import code2vec_tpu

        info["package_version"] = getattr(code2vec_tpu, "__version__", "unknown")
    except Exception:  # pragma: no cover - package always importable in-tree
        info["package_version"] = "unknown"
    try:
        from importlib import metadata as _im

        info["jax_version"] = _im.version("jax")
    except Exception:
        info["jax_version"] = "absent"
    import sys as _sys

    jax = _sys.modules.get("jax")
    if jax is not None:
        try:
            info["backend"] = str(jax.default_backend())
            info["device_kind"] = str(jax.devices()[0].device_kind)
        except Exception:  # pragma: no cover - backend init races
            pass
    if extra:
        info.update({k: str(v) for k, v in extra.items()})
    return info


def build_info_text(extra: dict | None = None, prefix: str = "c2v_") -> str:
    """The conventional Prometheus info-gauge: constant 1, identity in
    labels. Prepend to an exposition body (workers and the router both
    do) so every scrape carries version/backend provenance."""
    name = prometheus_metric_name("build_info", prefix)
    labels = _prom_label_str(build_info(extra))
    return f"# TYPE {name} gauge\n{name}{labels} 1\n"


_PROM_SAMPLE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?"
    r"\s+(?P<value>[^\s]+)\s*$"
)
# label values may contain escaped quotes/backslashes/newlines — match
# escape pairs atomically so \" does not terminate the value early
_PROM_LABEL = re.compile(
    r'(?P<key>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<value>(?:[^"\\]|\\.)*)"'
)


_PROM_ESCAPES = {"n": "\n", '"': '"', "\\": "\\"}


def _prom_unescape(value: str) -> str:
    # left-to-right over escape PAIRS: sequential str.replace would turn
    # the escaped-backslash-then-n sequence into a spurious newline
    return re.sub(
        r"\\(.)",
        lambda m: _PROM_ESCAPES.get(m.group(1), m.group(0)),
        value,
    )


def parse_prometheus_text(text: str) -> dict:
    """Parse exposition text back into
    ``{metric_name: [{"labels": {...}, "value": float}, ...]}`` plus a
    ``"# types"`` entry mapping metric -> declared type. Strict enough to
    catch a malformed exporter (tests and ``bench.py --serve``'s mid-load
    scrape use it); raises ``ValueError`` on an unparseable line."""
    metrics: dict = {}
    types: dict[str, str] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3]
            continue
        match = _PROM_SAMPLE.match(line)
        if match is None:
            raise ValueError(f"bad exposition line {lineno}: {line!r}")
        labels = {
            m.group("key"): _prom_unescape(m.group("value"))
            for m in _PROM_LABEL.finditer(match.group("labels") or "")
        }
        try:
            value = float(match.group("value"))
        except ValueError:
            raise ValueError(
                f"bad sample value on line {lineno}: {line!r}"
            ) from None
        metrics.setdefault(match.group("name"), []).append(
            {"labels": labels, "value": value}
        )
    metrics["# types"] = types
    return metrics


# ---------------------------------------------------------------------------
# slow-request flight recorder
# ---------------------------------------------------------------------------


class FlightRecorder:
    """Bounded reservoir of full per-request span breakdowns for the tail.

    A latency histogram says *that* p99 spiked; a tail-latency incident
    needs to know *where one slow request spent its time*. The batcher
    and the fleet router feed every finished request's breakdown
    (queue-wait / pad / device / postprocess, queue depths at admission,
    trace id) through :meth:`observe`; a request is CAPTURED when its
    end-to-end latency exceeds ``threshold_ms`` (when set) or the
    recorder's own rolling p99 estimate — so roughly the worst ~1% of
    requests always leave a concrete per-request timeline behind.

    O(1) per request on the hot path: one deque append plus comparisons;
    the p99 estimate re-sorts a small recent-latency window only every
    ``_REFRESH`` observations (amortized O(1)). Captured records land in
    a bounded deque (oldest evicted), are emitted as ``flight`` events
    when an event log is attached, and :meth:`dump` writes them as
    ``flight_<seq>.json`` files for offline forensics.
    """

    _REFRESH = 64  # re-estimate p99 every this many observations
    _MIN_SAMPLES = 100  # p99 sampling stays off until this many seen

    def __init__(
        self,
        capacity: int = 256,
        threshold_ms: float | None = None,
        p99_window: int = 512,
        events=None,
        health: RuntimeHealth | None = None,
    ) -> None:
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.threshold_ms = (
            float(threshold_ms) if threshold_ms is not None else None
        )
        self._records: collections.deque[dict] = collections.deque(
            maxlen=int(capacity)
        )
        self._recent: collections.deque[float] = collections.deque(
            maxlen=int(p99_window)
        )
        self._p99: float | None = None
        self._since_refresh = 0
        self._seen = 0
        self._capture_seq = 0
        self._events = events
        self._captured = (
            health.counter("flight.recorded") if health is not None else Counter()
        )
        self._lock = make_lock("obs.flight_recorder")
        handles.track(self, "flight_recorder")

    @property
    def count(self) -> int:
        """How many requests have been captured (all-time, not capacity)."""
        return self._captured.value

    @property
    def seen(self) -> int:
        return self._seen

    def observe(self, e2e_ms: float, record: dict) -> bool:
        """Feed one finished request; returns True when it was captured.
        ``record`` is the caller-built span breakdown (shallow-copied on
        capture, untouched otherwise)."""
        e2e_ms = float(e2e_ms)
        with self._lock:
            self._seen += 1
            self._recent.append(e2e_ms)
            self._since_refresh += 1
            if self._since_refresh >= self._REFRESH or (
                self._p99 is None and self._seen >= self._MIN_SAMPLES
            ):
                ordered = sorted(self._recent)
                rank = min(
                    len(ordered) - 1, int(round(0.99 * (len(ordered) - 1)))
                )
                self._p99 = ordered[rank]
                self._since_refresh = 0
            capture = (
                self.threshold_ms is not None and e2e_ms >= self.threshold_ms
            ) or (
                self._p99 is not None
                and self._seen >= self._MIN_SAMPLES
                and e2e_ms >= self._p99
            )
            if not capture:
                return False
            captured = {
                "flight_seq": self._capture_seq,
                "e2e_ms": round(e2e_ms, 3),
                **record,
            }
            self._capture_seq += 1
            self._records.append(captured)
        self._captured.inc()
        if self._events is not None:
            try:
                self._events.emit("flight", **captured)
            except Exception:  # pragma: no cover - closed log
                logger.warning("could not emit flight event", exc_info=True)
        return True

    def snapshot(self) -> list[dict]:
        """The captured records currently in the reservoir (oldest first)."""
        with self._lock:
            return [dict(r) for r in self._records]

    def dump(self, out_dir: str) -> list[str]:
        """Write every resident record as ``<out_dir>/flight_<seq>.json``;
        returns the paths (the ``flight_*.json`` artifacts a tail-latency
        incident is debugged from)."""
        records = self.snapshot()
        os.makedirs(out_dir, exist_ok=True)
        from code2vec_tpu.obs.events import sanitize

        paths = []
        for record in records:
            path = os.path.join(
                out_dir, f"flight_{record['flight_seq']:06d}.json"
            )
            with open(path, "w", encoding="utf-8") as f:
                json.dump(sanitize(record), f, indent=1)
            paths.append(path)
        return paths

    def close(self) -> None:
        """Retire the recorder from the handle ledger. Resident records
        stay readable (``dump`` after close is fine — the teardown paths
        dump last); idempotent."""
        handles.untrack(self)


_global_health: RuntimeHealth | None = None
_global_health_lock = threading.Lock()  # plain on purpose: sanitizer substrate


def global_health() -> RuntimeHealth:
    """Process-wide counter/gauge registry for subsystems that outlive any
    one run (the kernel-schedule autotune cache counts its hits/misses/
    timing runs here so callers can assert 'second run did zero search').
    The train loop keeps its own per-run :class:`RuntimeHealth`; this one
    is never reset."""
    global _global_health
    with _global_health_lock:
        if _global_health is None:
            _global_health = RuntimeHealth()
        return _global_health


def _lint_hints() -> dict[str, str]:
    """jaxlint rule ids whose defect class surfaces as silent jit-cache
    growth, so the `recompile` warning/event links runtime telemetry back
    to the static pass. Guarded: obs must stay usable even if the analysis
    package is stripped from a deployment."""
    try:
        from code2vec_tpu.analysis.jaxlint import RECOMPILE_HINT_RULES

        return dict(RECOMPILE_HINT_RULES)
    except Exception:  # pragma: no cover - partial install
        return {}


class RecompileDetector:
    """Count post-warmup ``jax.jit`` cache misses per tracked step function.

    The jitted train/eval steps are traced once per (shape, dtype)
    signature; static batch shapes are the suite's invariant (SURVEY §7).
    A growing cache after the first observation means something is feeding
    shape-churned batches — each growth is a silent recompile costing
    seconds. ``track`` ignores functions without a ``_cache_size`` probe
    (injected non-jitted steps), so wiring is unconditional.

    ``expected_compiles``: a per-function compile BUDGET for functions that
    legitimately serve several static shapes — length-aware bucketed
    batching compiles the step once per ladder width. Cache growth up to
    the budget counts as warmup and stays silent at every check (not just
    the first); only growth beyond ``max(budget, observed)`` fires the
    ``recompile`` warning/event. Without it the first observation is the
    baseline, as before.
    """

    def __init__(self, events=None, health: RuntimeHealth | None = None):
        self._events = events
        self._counter = (
            health.counter("recompiles") if health is not None else Counter()
        )
        # name -> [fn, last observed cache size or None (pre-warmup)];
        # budgeted fns start at their budget instead of None — the ladder's
        # compiles are expected whenever they happen, so there is no
        # first-observation grace to confuse with real churn
        self._tracked: dict[str, list] = {}

    def track(self, name: str, fn, expected_compiles: int | None = None):
        if callable(getattr(fn, "_cache_size", None)):
            baseline = None
            if expected_compiles is not None:
                if expected_compiles < 1:
                    raise ValueError(
                        f"expected_compiles must be >= 1, got {expected_compiles}"
                    )
                baseline = int(expected_compiles)
            self._tracked[name] = [fn, baseline]
        return fn

    @property
    def recompile_count(self) -> int:
        return self._counter.value

    def check(self, epoch: int | None = None) -> int:
        """Observe every tracked function once; returns the number of NEW
        post-warmup compiles found this check."""
        new = 0
        for name, slot in self._tracked.items():
            fn, last = slot
            try:
                size = int(fn._cache_size())
            except Exception:  # pragma: no cover - probe API drift
                continue
            if last is None:
                slot[1] = size  # warmup: the expected initial compile(s)
                continue
            if size > last:
                delta = size - last
                new += delta
                self._counter.inc(delta)
                # also a zero-duration mark on the trace timeline, so the
                # recompile is visible next to the step spans it stalled
                from code2vec_tpu.obs.trace import get_tracer

                get_tracer().instant(
                    "recompile", category="health", fn=name, delta=delta
                )
                hints = _lint_hints()
                hint_suffix = (
                    " Likely static causes: "
                    + "; ".join(
                        f"{rid}: {why}" for rid, why in hints.items()
                    )
                    + " — run `python -m code2vec_tpu.analysis` to locate"
                    if hints
                    else ""
                )
                logger.warning(
                    "recompile detected: %s jit cache grew %d -> %d "
                    "(batch shape/dtype churn?); each recompile stalls the "
                    "step for the full XLA compile.%s",
                    name,
                    last,
                    size,
                    hint_suffix,
                )
                if self._events is not None:
                    fields = {"fn": name, "cache_size": size, "delta": delta,
                              "lint_hints": sorted(hints)}
                    if epoch is not None:
                        fields["epoch"] = epoch
                    self._events.emit("recompile", **fields)
                slot[1] = size
        return new


def host_cpu_fingerprint() -> str:
    """8-hex digest of the host's CPU feature set (ISA flags + arch).

    XLA's persistent compile cache stores machine code specialized to the
    compiling host's CPU features; reusing one cache dir across hosts with
    different feature sets logs ``machine features mismatch ... could lead
    to SIGILL`` (seen in BENCH_r05) and can crash outright. Consumers
    (tests/conftest.py, bench.py) key their cache dirs by this fingerprint
    so each CPU population gets its own cache. Stdlib-only, stable within
    a host across runs."""
    import hashlib
    import platform

    parts = [platform.machine()]
    try:
        with open("/proc/cpuinfo", encoding="utf-8") as f:
            for line in f:
                # x86 exposes "flags", arm64 "Features"; sort so kernel
                # ordering changes don't churn the digest
                if line.startswith(("flags", "Features")):
                    parts.append(
                        " ".join(sorted(line.split(":", 1)[1].split()))
                    )
                    break
    except OSError:
        parts.append(platform.processor() or "")
    return hashlib.sha1("|".join(parts).encode()).hexdigest()[:8]


def host_rss_bytes() -> int | None:
    """Current resident set size, or None off-Linux."""
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return pages * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):  # pragma: no cover - non-Linux
        return None


def _host_peak_rss_bytes() -> int | None:
    try:
        import resource
        import sys

        peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        # linux reports ru_maxrss in KiB; macOS/BSD report bytes
        return peak * 1024 if sys.platform.startswith("linux") else peak
    except Exception:  # pragma: no cover - platform without resource
        return None


def device_memory_stats() -> dict | None:
    """Aggregate ``memory_stats()`` over local devices; None when the
    backend doesn't report (CPU) or jax isn't up yet."""
    try:
        import jax

        devices = jax.local_devices()
        if not devices:
            return None
        # inside the guard: some backends raise (UNIMPLEMENTED) instead of
        # returning None, and the per-epoch sampler must never kill a run
        stats = [d.memory_stats() for d in devices]
    except Exception:
        return None
    if any(s is None for s in stats):
        return None
    out = {
        "device_kind": devices[0].device_kind,
        "n_devices": len(devices),
    }
    for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
        values = [s.get(key) for s in stats]
        if all(v is not None for v in values):
            out[key] = int(sum(values))
    return out


def memory_snapshot(health: RuntimeHealth | None = None) -> dict:
    """One host+device memory sample; mirrors into ``health`` gauges when
    given. Called at epoch boundaries and from bench.py's detail block."""
    snap: dict = {
        "host_rss_bytes": host_rss_bytes(),
        "host_peak_rss_bytes": _host_peak_rss_bytes(),
    }
    device = device_memory_stats()
    if device is not None:
        snap["device"] = device
    if health is not None:
        for key in ("host_rss_bytes", "host_peak_rss_bytes"):
            if snap[key] is not None:
                health.gauge(key).set(snap[key])
        if device is not None and "bytes_in_use" in device:
            health.gauge("device_bytes_in_use").set(device["bytes_in_use"])
    return snap
