"""Static & dynamic cost accounting: FLOPs, bytes, device time, MFU, capacity.

The rest of the obs plane answers *what happened* (traces, counters, SLO
burn); this module answers *how efficiently the hardware ran* and *how much
headroom is left*.  Three layers, deliberately cheap:

**Static costs** — at compile time every AOT executable gets a cost record:
FLOPs, bytes accessed, and arithmetic intensity.  The primary source is
XLA's ``compiled.cost_analysis()``; because backends are allowed to return
``None``, partial dicts, or per-primitive lists, :func:`executable_cost`
normalizes all of those and falls back to :func:`analytic_forward_cost`,
a closed-form model of the fused gather→encode→attend→pool forward that
agrees with XLA within a few percent on CPU (calibrated; see the perfobs
tests).  Every record carries ``cost_source: "xla" | "analytic"`` so
provenance never lies about where a number came from.

**Dynamic accounting** — :class:`CostAccountant` accumulates device-ms per
executable, riding the *existing* fenced timings (the serve batcher's
``device_ms`` span, the train loop's sampled ``compute_ms``).  Each
``record()`` is O(1) dict arithmetic — no device syncs, no new timers —
and folds static FLOPs into achieved-FLOP/s, MFU against a per-device-kind
peak table, and a busy fraction, exported as ``perf.*`` gauges
(``c2v_perf_*`` in Prometheus exposition).

**Capacity** — :func:`fleet_capacity` turns per-replica perf snapshots
into the max-sustainable-QPS estimate ROADMAP item 3's autoscaler needs:
per-rung device-ms/request, mix-weighted into a per-replica serial-device
throughput bound, times alive replicas.

The peak table is *generous* on purpose: MFU is only meaningful as a
ratio trend, and the acceptance invariant ``achieved ≤ peak`` must hold
even on turbo-clocked CI hosts.  Override with ``C2V_PEAK_FLOPS`` (an
absolute per-device FLOP/s number) when you know your hardware.

This module is jax-free at import time (routers stay jax-free);
:func:`detect_device_kind` only touches jax when the caller already
initialized it.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Any, Callable

from code2vec_tpu.obs.sync import make_lock

__all__ = [
    "PEAK_FLOPS",
    "CostAccountant",
    "analytic_forward_cost",
    "detect_device_kind",
    "executable_cost",
    "extract_cost",
    "fleet_capacity",
    "peak_flops",
    "train_step_cost",
]

# Per-device-kind peak FLOP/s (dense, the precision the matmuls actually
# run in — bf16 on TPU/GPU tensor units, f32 SIMD on CPU).  Matched by
# lowercase substring against ``device_kind``; first hit wins, so keep
# more specific names earlier.  Extend by adding a row here or exporting
# C2V_PEAK_FLOPS — see docs/ARCHITECTURE.md "Performance observability".
PEAK_FLOPS: dict[str, float] = {
    # TPUs (per chip, bf16).
    "tpu v6": 918e12,
    "tpu v5p": 459e12,
    "tpu v5e": 197e12,
    "tpu v5 lite": 197e12,  # what jax actually reports for v5e
    "tpu v5": 459e12,
    "tpu v4": 275e12,
    "tpu v3": 123e12,
    "tpu v2": 46e12,
    # GPUs (per device, bf16 tensor core, dense).
    "h100": 990e12,
    "h200": 990e12,
    "a100": 312e12,
    "l4": 121e12,
    "v100": 125e12,
    "t4": 65e12,
}

# Generous per-core f32 peak for unrecognized CPUs: 2×FMA × 16-lane
# AVX-512 × ~4 GHz ≈ 256 GFLOP/s/core.  Real sustained throughput is far
# lower, which is exactly what keeps measured MFU ≤ 1 on any host.
_CPU_PEAK_PER_CORE = 256e9

_PEAK_ENV = "C2V_PEAK_FLOPS"


def peak_flops(device_kind: str | None) -> float:
    """Peak FLOP/s for a device kind string (``C2V_PEAK_FLOPS`` wins)."""
    env = os.environ.get(_PEAK_ENV)
    if env:
        try:
            value = float(env)
            if value > 0:
                return value
        except ValueError:
            pass
    kind = (device_kind or "").lower()
    for needle, value in PEAK_FLOPS.items():
        if needle in kind:
            return value
    return _CPU_PEAK_PER_CORE * float(os.cpu_count() or 1)


def detect_device_kind() -> str:
    """Device kind of the default jax device, or ``"unknown"``.

    Only consults jax if the caller's process already imported it — never
    drags the backend into a jax-free process (the fleet router).
    """
    jax = sys.modules.get("jax")
    if jax is None:
        return "unknown"
    try:
        return str(jax.devices()[0].device_kind)
    except Exception:
        return "unknown"


# ---------------------------------------------------------------------------
# static costs


def analytic_forward_cost(
    batch: int,
    width: int,
    *,
    terminal_embed: int,
    path_embed: int,
    encode: int,
    labels: int,
    table_dtype: str = "f32",
) -> dict[str, Any]:
    """Closed-form cost of the fused code2vec forward at one (batch, width).

    FLOP terms (B = batch, L = width/bag, E = encode size, calibrated
    against XLA ``cost_analysis()`` on CPU to within ~2.5%):

    - encode matmul: ``2·B·L·(2·te+pe)·E`` (Dense, no bias)
    - label head:    ``2·B·E·labels``
    - attention:     ``2·B·L·E`` (context · attention vector)
    - pool:          ``2·B·L·E`` (weighted sum)
    - layernorm:     ``10·B·L·E`` (f32 mean/var/normalize/affine)
    - tanh:          ``B·L·E``
    - softmax:       ``5·B·L`` (max, sub, exp, sum, div over the bag)

    Bytes are a roofline-style estimate (embedding-gather reads + weight
    reads + activation traffic) — good enough for arithmetic intensity,
    not a bus-accurate model.
    """
    b, l = float(batch), float(width)
    concat = 2.0 * terminal_embed + path_embed
    flops = (
        2.0 * b * l * concat * encode  # encode matmul
        + 2.0 * b * encode * labels  # label head
        + 2.0 * b * l * encode  # attention logits
        + 2.0 * b * l * encode  # attention-weighted pool
        + 10.0 * b * l * encode  # layernorm (f32)
        + 1.0 * b * l * encode  # tanh
        + 5.0 * b * l  # masked softmax over the bag
    )
    table_bytes = {"int8": 1.0, "bf16": 2.0}.get(table_dtype, 4.0)
    bytes_accessed = (
        b * l * concat * table_bytes  # embedding gathers
        + (concat * encode + encode * labels + encode) * 4.0  # weights
        + 3.0 * b * l * encode * 4.0  # encoded/ln/tanh activations
        + b * l * concat * 4.0  # concat activation
        + (b * encode + b * labels) * 4.0  # pooled vector + logits
        + b * l * 3.0 * 4.0  # int32 token ids
    )
    return {
        "flops": flops,
        "bytes_accessed": bytes_accessed,
        "arithmetic_intensity": flops / bytes_accessed if bytes_accessed else None,
        "cost_source": "analytic",
    }


def train_step_cost(forward_cost: dict[str, Any], multiplier: float = 3.0) -> dict[str, Any]:
    """Train-step cost from a forward cost (fwd + bwd ≈ 3× forward FLOPs)."""
    flops = forward_cost.get("flops")
    bytes_accessed = forward_cost.get("bytes_accessed")
    flops = flops * multiplier if flops else None
    bytes_accessed = bytes_accessed * multiplier if bytes_accessed else None
    intensity = flops / bytes_accessed if flops and bytes_accessed else None
    return {
        "flops": flops,
        "bytes_accessed": bytes_accessed,
        "arithmetic_intensity": intensity,
        "cost_source": "analytic",
    }


def _coerce_flops(value: Any) -> float | None:
    try:
        value = float(value)
    except (TypeError, ValueError):
        return None
    if value != value or value <= 0 or value == float("inf"):  # NaN/neg/inf
        return None
    return value


def extract_cost(raw: Any) -> dict[str, Any] | None:
    """Normalize whatever ``compiled.cost_analysis()`` returned.

    Backends disagree on shape: CPU returns a list with one properties
    dict, TPU historically a bare dict, some return per-primitive dicts,
    and backends are allowed to return ``None`` or omit keys entirely.
    Returns ``{"flops": float, "bytes_accessed": float|None}`` or ``None``
    when nothing usable came back.  Never raises.
    """
    if raw is None:
        return None
    entries: list[dict] = []
    if isinstance(raw, dict):
        entries = [raw]
    elif isinstance(raw, (list, tuple)):
        entries = [e for e in raw if isinstance(e, dict)]
    if not entries:
        return None
    flops_total = 0.0
    bytes_total = 0.0
    saw_flops = saw_bytes = False
    for entry in entries:
        flops = _coerce_flops(entry.get("flops"))
        if flops is not None:
            flops_total += flops
            saw_flops = True
        for key in ("bytes accessed", "bytes_accessed"):
            b = _coerce_flops(entry.get(key))
            if b is not None:
                bytes_total += b
                saw_bytes = True
                break
    if not saw_flops:
        return None
    return {
        "flops": flops_total,
        "bytes_accessed": bytes_total if saw_bytes else None,
    }


def executable_cost(
    compiled: Any, analytic: dict[str, Any] | None = None
) -> dict[str, Any]:
    """Cost record for one compiled executable: XLA first, analytic fallback.

    Never raises — a backend without ``cost_analysis()`` (or one that
    throws) degrades to the analytic model, and with neither available the
    record is explicit about knowing nothing (``cost_source: None``).
    """
    xla = None
    if compiled is not None:
        try:
            fn = getattr(compiled, "cost_analysis", None)
            xla = extract_cost(fn()) if callable(fn) else None
        except Exception:
            xla = None
    if xla is not None:
        flops = xla["flops"]
        bytes_accessed = xla["bytes_accessed"]
        if bytes_accessed is None and analytic:
            bytes_accessed = analytic.get("bytes_accessed")
        source = "xla"
    elif analytic:
        flops = analytic.get("flops")
        bytes_accessed = analytic.get("bytes_accessed")
        source = "analytic" if flops else None
    else:
        flops = bytes_accessed = source = None
    intensity = flops / bytes_accessed if flops and bytes_accessed else None
    return {
        "flops": flops,
        "bytes_accessed": bytes_accessed,
        "arithmetic_intensity": intensity,
        "cost_source": source,
    }


# ---------------------------------------------------------------------------
# dynamic accounting


def _exec_key(key: Any) -> str:
    if isinstance(key, tuple):
        return "b{}w{}".format(*key) if len(key) == 2 else "_".join(map(str, key))
    return str(key)


class CostAccountant:
    """Per-executable device-time → achieved-FLOP/s → MFU accumulator.

    ``record()`` is the hot-path entry: a handful of dict additions and
    (optionally) gauge sets under one lock — O(1), no device interaction.
    Static costs arrive via ``register()`` at compile time; executables
    that record time without a registered cost still get device-ms
    accounting (their FLOPs just don't contribute to MFU).

    Gauges land in the supplied health registry under ``perf.*`` — i.e.
    ``c2v_perf_mfu``, ``c2v_perf_achieved_flops_per_s``,
    ``c2v_perf_busy_fraction``, ``c2v_perf_device_ms_total``,
    ``c2v_perf_peak_flops_per_s`` in the /metrics exposition.  With
    hot-swap, accountants of co-resident engine generations share the
    process registry (last writer wins, same as the other serve gauges);
    per-generation truth lives in each engine's ``perf_summary()``.
    """

    def __init__(
        self,
        device_kind: str | None = None,
        *,
        peak: float | None = None,
        health: Any = None,
        clock: Callable[[], float] = time.perf_counter,
    ) -> None:
        self.device_kind = device_kind or "unknown"
        self.peak = float(peak) if peak else peak_flops(self.device_kind)
        self._health = health
        self._clock = clock
        self._t0 = clock()
        self._lock = make_lock("obs.costs")
        self._execs: dict[str, dict[str, Any]] = {}
        self._device_ms = 0.0
        self._flops_done = 0.0
        self._calls = 0
        self._requests = 0
        if health is not None:
            health.gauge("perf.peak_flops_per_s").set(self.peak)
            health.gauge("perf.device_kind").set(self.device_kind)

    def register(self, key: Any, cost: dict[str, Any] | None) -> None:
        """Attach a static cost record to an executable key."""
        with self._lock:
            entry = self._execs.setdefault(_exec_key(key), self._fresh_entry())
            if cost:
                entry["flops"] = cost.get("flops")
                entry["bytes_accessed"] = cost.get("bytes_accessed")
                entry["arithmetic_intensity"] = cost.get("arithmetic_intensity")
                entry["cost_source"] = cost.get("cost_source")

    @staticmethod
    def _fresh_entry() -> dict[str, Any]:
        return {
            "flops": None,
            "bytes_accessed": None,
            "arithmetic_intensity": None,
            "cost_source": None,
            "device_ms": 0.0,
            "calls": 0,
            "requests": 0,
        }

    def record(self, key: Any, device_ms: float, requests: int = 1) -> None:
        """Fold one fenced device span into the accounting.  O(1)."""
        if device_ms < 0:
            return
        with self._lock:
            entry = self._execs.setdefault(_exec_key(key), self._fresh_entry())
            entry["device_ms"] += device_ms
            entry["calls"] += 1
            entry["requests"] += int(requests)
            self._device_ms += device_ms
            self._calls += 1
            self._requests += int(requests)
            if entry["flops"]:
                self._flops_done += entry["flops"]
            achieved, mfu, busy = self._derived_locked()
        health = self._health
        if health is not None:
            health.gauge("perf.device_ms_total").set(round(self._device_ms, 3))
            health.gauge("perf.busy_fraction").set(busy)
            if achieved is not None:
                health.gauge("perf.achieved_flops_per_s").set(achieved)
                health.gauge("perf.mfu").set(mfu)

    def _derived_locked(self) -> tuple[float | None, float | None, float]:
        device_s = self._device_ms / 1e3
        wall_s = max(self._clock() - self._t0, 1e-9)
        busy = round(min(device_s / wall_s, 1.0), 6)
        if device_s <= 0 or self._flops_done <= 0:
            return None, None, busy
        achieved = self._flops_done / device_s
        return round(achieved, 3), round(achieved / self.peak, 9), busy

    def snapshot(self) -> dict[str, Any]:
        """Perf block: totals + per-executable breakdown (JSON-safe)."""
        with self._lock:
            achieved, mfu, busy = self._derived_locked()
            per_exec = {}
            for key, entry in self._execs.items():
                rec = dict(entry)
                rec["device_ms"] = round(rec["device_ms"], 3)
                if rec["requests"] > 0:
                    rec["device_ms_per_request"] = round(
                        entry["device_ms"] / entry["requests"], 4
                    )
                else:
                    rec["device_ms_per_request"] = None
                if entry["flops"] and entry["device_ms"] > 0 and entry["calls"] > 0:
                    exec_achieved = entry["flops"] * entry["calls"] / (
                        entry["device_ms"] / 1e3
                    )
                    rec["mfu"] = round(exec_achieved / self.peak, 9)
                else:
                    rec["mfu"] = None
                per_exec[key] = rec
            return {
                "device_kind": self.device_kind,
                "peak_flops_per_s": self.peak,
                "device_ms": round(self._device_ms, 3),
                "device_calls": self._calls,
                "requests": self._requests,
                "flops_total": round(self._flops_done, 1),
                "achieved_flops_per_s": achieved,
                "mfu": mfu,
                "busy_fraction": busy,
                "per_executable": per_exec,
            }


# ---------------------------------------------------------------------------
# fleet capacity


def fleet_capacity(
    replica_perfs: list[dict[str, Any] | None], alive: int | None = None
) -> dict[str, Any] | None:
    """Max-sustainable-QPS estimate from per-replica perf snapshots.

    Device work inside one replica is serial (one engine lock, one
    device), so a replica saturates when the mix-weighted device time per
    request fills a second of device time:

        qps_replica = 1 / Σ_rung share_rung · device_s_per_request_rung

    where ``share`` is the observed arrival mix (requests per rung).  The
    fleet bound is that times the number of alive replicas — an upper
    bound that ignores host-side overhead (padding, transport), which is
    the right shape for a scale-up control signal: when observed QPS
    approaches ``max_qps_fleet``, there is no headroom left to absorb it.

    Returns ``None`` until some replica has recorded device time.
    """
    rungs: dict[str, dict[str, float]] = {}
    observed = 0
    for perf in replica_perfs:
        if not perf:
            continue
        for key, entry in (perf.get("per_executable") or {}).items():
            try:
                requests = int(entry.get("requests") or 0)
                device_ms = float(entry.get("device_ms") or 0.0)
            except (TypeError, ValueError):
                continue
            if requests <= 0 or device_ms <= 0:
                continue
            agg = rungs.setdefault(key, {"requests": 0.0, "device_ms": 0.0})
            agg["requests"] += requests
            agg["device_ms"] += device_ms
            observed += requests
    if not rungs or observed <= 0:
        return None
    if alive is None:
        alive = sum(1 for perf in replica_perfs if perf)
    weighted_s_per_request = 0.0
    per_rung = []
    for key in sorted(rungs):
        agg = rungs[key]
        per_request_ms = agg["device_ms"] / agg["requests"]
        share = agg["requests"] / observed
        weighted_s_per_request += share * per_request_ms / 1e3
        per_rung.append(
            {
                "rung": key,
                "requests": int(agg["requests"]),
                "share": round(share, 4),
                "device_ms_per_request": round(per_request_ms, 4),
                "max_qps_per_replica": round(1e3 / per_request_ms, 2),
            }
        )
    qps_replica = 1.0 / max(weighted_s_per_request, 1e-12)
    return {
        "alive_replicas": int(alive),
        "requests_observed": int(observed),
        "device_ms_per_request": round(weighted_s_per_request * 1e3, 4),
        "max_qps_per_replica": round(qps_replica, 2),
        "max_qps_fleet": round(qps_replica * max(int(alive), 0), 2),
        "per_rung": per_rung,
    }
