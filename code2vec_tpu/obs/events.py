"""Structured run-level event log: one JSONL file per process.

The log opens with a **run manifest** (run id, config dict, process
index/count, mesh shape, device kind, package version) and then carries
typed events with monotonic timestamps. Schema of every line::

    {"event": <type>, "seq": N, "t_ms": <monotonic ms since log open>,
     "unix_time": <wall clock>, ...event fields...}

``seq`` is strictly increasing per process — emitters on other threads
(the prefetch producer, HPO callbacks) serialize through one lock, so the
file order IS the emission order.

Event types written by the train loop (``train/loop.py``): ``manifest``,
``epoch`` (the full per-epoch metrics dict + a memory snapshot),
``best_f1``, ``step_sample`` (per profiled step: host-build / H2D /
compute ms), ``eval``, ``checkpoint_saved`` (slot/path/step + whether the
persist ran async), ``checkpoint_restored`` (slot/path/step, the save- and
restore-time mesh shapes, and whether the arrays were resharded onto a new
topology), ``preempted`` (clean SIGTERM exit), ``recompile``
(obs.runtime.RecompileDetector), ``error``.

The serving side (``serve/``) writes ``serve_executable`` (one per AOT
compile, with schedule provenance and the model version), the hot-swap
state machine's ``swap_started`` / ``swap_committed`` / ``swap_failed`` /
``rollback`` / ``generation_retired`` (serve/swap.py — build/validate
timings and the golden-validation report ride the commit event), and the
fleet router's ``fleet_replica_spawned`` / ``fleet_replica_evicted`` /
``fleet_swap_started`` / ``fleet_swap_committed`` / ``fleet_swap_failed``
/ ``fleet_rollback`` (serve/fleet/router.py). Run manifests carry the
serve/fleet topology blocks next to the config.

The fleet observability plane (PR 15) adds two more:

- ``flight`` (obs.runtime.FlightRecorder) — one slow/tail request's full
  span breakdown: ``flight_seq``, ``e2e_ms``, ``trace_id``, and per-kind
  fields (worker ``kind: "serve"``: queue_wait/pad/device/postprocess ms,
  batch/width/coalesced, queue_depth_at_admission; router ``kind:
  "router"``: op, slo_class, outcome, dispatch_wait_ms, replica_slot,
  attempts, queue_depth_at_admission). The same records dump as
  ``flight_<seq>.json`` files at process exit.
- ``slo_budget_exhausted`` (serve/fleet/slo.SloBurnTracker) —
  edge-triggered once per exhaustion episode: ``slo_class``,
  ``burn_rate``, ``objective``, ``window_s``, window ``good``/``bad``.

The opt-in debug planes add ``lock_order_violation`` (obs.sync, under
``C2V_SYNC_DEBUG``) and ``handle_leak`` (obs.handles, under
``C2V_HANDLE_DEBUG``) — one per handle still open at the shutdown leak
report: ``where``, ``kind``, ``name``, ``age_s``, and the creation-site
``site`` stack captured when the handle was tracked.

Health snapshots embedded in ``epoch``/``health`` payloads additionally
carry ``started_unix`` + ``snapshot_seq`` (obs.runtime.RuntimeHealth),
so consumers can compute rates and detect counter resets across replica
respawns.

**Sinks are consumers of this stream**: ``sink_consumer`` adapts the
``(epoch, metrics)`` metric sinks (``code2vec_tpu.sinks``) into an event
consumer, and the train loop emits metrics ONLY as events — so the sink
output and the event log derive from the same dict and cannot disagree.
Consumers receive the raw (unsanitized) event; the file gets the
strict-JSON form: non-finite floats serialize as ``null`` plus a sibling
``<key>_raw`` string (see :func:`metric_record` for the per-metric-line
shape the sinks use).
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
import uuid
from typing import Callable

from code2vec_tpu.obs import handles

__all__ = ["EventLog", "metric_record", "run_manifest", "sink_consumer"]


def resolve_process_index() -> int:
    """This process's index in the pod, 0 when no backend is available.
    THE one lazy probe shared by the event-log filename, the tracer's pid
    track, and the manifest fallback — so the three artifacts can never
    disagree on which process they label."""
    try:
        import jax

        return jax.process_index()
    except Exception:
        return 0


def _scalar(value):
    """Unwrap numpy scalars (``np.float32`` etc.) to Python scalars; pass
    everything else through."""
    if hasattr(value, "item") and not isinstance(
        value, (str, bytes, dict, list, tuple)
    ):
        try:
            return value.item()
        except (TypeError, ValueError):
            return value
    return value


def sanitize(obj):
    """Recursively convert ``obj`` into strict-JSON values.

    ``json.dumps`` happily prints bare ``NaN``/``Infinity`` — tokens the
    JSON grammar does not have, which strict parsers reject. Non-finite
    floats become ``null``; inside dicts a sibling ``<key>_raw`` string
    (``"nan"`` / ``"inf"`` / ``"-inf"``) preserves the original value.
    Unknown objects fall back to ``str``.
    """
    if isinstance(obj, dict):
        out = {}
        for key, value in obj.items():
            key = str(key)
            value = _scalar(value)
            if isinstance(value, float) and not math.isfinite(value):
                out[key] = None
                out[key + "_raw"] = repr(value)
            else:
                out[key] = sanitize(value)
        return out
    if isinstance(obj, (list, tuple)):
        return [sanitize(v) for v in obj]
    obj = _scalar(obj)
    if obj is None or isinstance(obj, (str, int, bool)):
        return obj
    if isinstance(obj, float):
        return obj if math.isfinite(obj) else None
    return str(obj)


def metric_record(name: str, value) -> dict:
    """The ``{"metric": name, "value": value}`` line shape the floyd and
    logging sinks emit, made strict-JSON: a non-finite value serializes as
    ``null`` with the original preserved in a string ``"raw"`` field."""
    value = _scalar(value)
    record = {"metric": name, "value": value}
    if isinstance(value, float) and not math.isfinite(value):
        record["value"] = None
        record["raw"] = repr(value)
    return record


def _shared_run_id(process_count: int) -> str:
    """One run id for the whole run. ``C2V_RUN_ID`` pins it; otherwise a
    timestamped random id — BROADCAST from process 0 on multi-host runs
    (clock skew and per-process uuids would otherwise give one pod run N
    uncorrelatable ids across its per-process logs/traces). Safe as a
    collective: every process writes its manifest at the same point of
    train(). Falls back to a local id if the broadcast fails."""
    pinned = os.environ.get("C2V_RUN_ID")
    if pinned:
        return pinned
    run_id = f"{time.strftime('%Y%m%d-%H%M%S')}-{uuid.uuid4().hex[:8]}"
    if process_count > 1:
        try:
            import numpy as np
            from jax.experimental import multihost_utils

            raw = np.frombuffer(
                run_id.encode("ascii").ljust(32, b" ")[:32], dtype=np.uint8
            )
            raw = np.asarray(multihost_utils.broadcast_one_to_all(raw))
            run_id = raw.tobytes().decode("ascii").strip()
        except Exception:  # pragma: no cover - exotic backend
            pass
    return run_id


def run_manifest(config=None, mesh=None, **extra) -> dict:
    """Collect the run manifest: package version, process identity, device
    kind, mesh shape, and the config as a plain dict.

    Imports jax lazily — by the time anything writes a manifest the
    backend is up (the caller is the train loop / bench), and keeping the
    import out of module scope lets tests build logs without a backend.
    """
    import dataclasses

    import code2vec_tpu

    manifest = {
        "package": "code2vec-tpu",
        "package_version": code2vec_tpu.__version__,
        "started_unix": time.time(),
    }
    try:
        from code2vec_tpu.parallel.distributed import process_info

        manifest.update(process_info())
    except Exception:  # pragma: no cover - no backend available
        manifest.update(
            {"process_index": resolve_process_index(), "process_count": 1}
        )
    manifest["run_id"] = _shared_run_id(manifest["process_count"])
    if mesh is not None:
        manifest["mesh_shape"] = dict(mesh.shape)
    else:
        manifest["mesh_shape"] = None
    if config is not None:
        if dataclasses.is_dataclass(config):
            config = dataclasses.asdict(config)
        manifest["config"] = dict(config)
    manifest.update(extra)
    return manifest


class EventLog:
    """Thread-safe JSONL event log + in-process event dispatcher.

    ``events_dir=None`` builds a dispatch-only log (no file): the train
    loop always emits through an EventLog so sinks stay consumers of the
    event stream whether or not ``--events_dir`` was given.

    The file opens lazily on the first emit, in APPEND mode: constructing
    a log never touches the JAX backend (the lazy ``process_index``
    resolution must not pre-empt ``jax.distributed.initialize`` on
    multi-host runs), and a ``--resume``d run extends the previous run's
    log — its new manifest line marks the new segment — instead of
    truncating the recorded history.
    """

    def __init__(
        self,
        events_dir: str | None = None,
        process_index: int | None = None,
        run_id: str | None = None,
    ):
        self.process_index = process_index
        self.run_id = run_id
        self.path: str | None = None
        self._events_dir = events_dir
        self._file = None
        self._closed = False
        # RLock: a consumer may emit follow-up events from inside dispatch.
        # Plain on purpose: the sanitizer reports violations THROUGH event
        # logs, so a traced lock here would re-enter the reporter
        self._lock = threading.RLock()
        self._consumers: list[Callable[[dict], None]] = []
        self._seq = 0
        self._t0 = time.monotonic()
        self._manifest_written = False

    def _ensure_open(self):
        """Open the per-process JSONL on first use (append mode)."""
        if self._events_dir is None or self._closed or self._file is not None:
            return self._file
        if self.process_index is None:
            self.process_index = resolve_process_index()
        os.makedirs(self._events_dir, exist_ok=True)
        self.path = os.path.join(
            self._events_dir, f"events-p{self.process_index}.jsonl"
        )
        self._file = open(self.path, "a", encoding="utf-8")
        handles.track(self, "event_log", name=self.path)
        return self._file

    @property
    def observed(self) -> bool:
        """Whether emissions go anywhere — a backing file or at least one
        consumer. The train loop skips manifest construction (which
        includes a cross-host run-id broadcast on pods) when nobody would
        see it."""
        return self._events_dir is not None or bool(self._consumers)

    # ---- consumers -----------------------------------------------------
    def subscribe(self, consumer: Callable[[dict], None]) -> Callable:
        """Register ``consumer(event_dict)``; returns it for unsubscribe."""
        with self._lock:
            self._consumers.append(consumer)
        return consumer

    def unsubscribe(self, consumer: Callable[[dict], None]) -> None:
        with self._lock:
            if consumer in self._consumers:
                self._consumers.remove(consumer)

    # ---- emission ------------------------------------------------------
    def emit(self, event: str, **fields) -> dict:
        """Append one typed event; dispatch the RAW record to consumers,
        write the sanitized strict-JSON form to the file. Serialized under
        one lock so file order == emission order across threads."""
        with self._lock:
            record = {
                "event": event,
                "seq": self._seq,
                "t_ms": round((time.monotonic() - self._t0) * 1e3, 3),
                "unix_time": time.time(),
                **fields,
            }
            self._seq += 1
            out = self._ensure_open()
            if out is not None:
                out.write(json.dumps(sanitize(record), allow_nan=False) + "\n")
                out.flush()  # events are low-rate; survive crashes
            for consumer in tuple(self._consumers):
                consumer(record)
        return record

    def write_manifest(self, config=None, mesh=None, **extra) -> dict | None:
        """Emit the run manifest as the log's first event (idempotent —
        only the first call writes)."""
        with self._lock:
            if self._manifest_written:
                return None
            self._manifest_written = True
            manifest = run_manifest(config=config, mesh=mesh, **extra)
            if self.run_id is None:
                self.run_id = manifest["run_id"]
            else:
                manifest["run_id"] = self.run_id
            return self.emit("manifest", **manifest)

    def close(self) -> None:
        with self._lock:
            self._closed = True
            if self._file is not None:
                self._file.close()
                self._file = None
        handles.untrack(self)

    def __enter__(self) -> "EventLog":
        return self

    def __exit__(self, *exc_info) -> None:
        self.close()


def sink_consumer(sinks) -> Callable[[dict], None]:
    """Adapt ``(epoch, metrics)`` metric sinks into an event consumer.

    ``epoch`` and ``best_f1`` events carry an ``epoch`` + ``metrics`` pair;
    each registered sink sees exactly the dict the event was emitted with
    (NaNs intact — strict-JSON handling is each sink's own concern)."""

    def consume(event: dict) -> None:
        if event.get("event") in ("epoch", "best_f1") and "metrics" in event:
            for sink in sinks:
                sink(event["epoch"], event["metrics"])

    return consume
