"""Two-way checkpoint interop with the reference implementation.

The reference trains a torch ``Code2Vec`` and persists
``torch.save(model.state_dict(), <model_path>/code2vec.model)`` on every
new best F1 (reference main.py:231). This module holds the lossless
tensor mapping between that state_dict and our flax param tree, plus the
torch-side oracle forward used to gate conversions — shared by
``tools/import_reference_checkpoint.py`` (theirs → ours) and
``tools/export_reference_checkpoint.py`` (ours → theirs).

Mapping (reference model/model.py:21-42 → models/code2vec.py):

    terminal_embedding.weight [T, dt]  ↔ terminal_embedding.embedding
    path_embedding.weight     [P, dp]  ↔ path_embedding.embedding
    input_linear.weight   [E, 2dt+dp]  ↔ input_dense.kernel (TRANSPOSED —
                                         torch Linear stores [out, in];
                                         concat order start|path|end is
                                         the same on both sides)
    input_layer_norm.weight/bias  [E]  ↔ input_layer_norm.scale/bias
    attention_parameter           [E]  ↔ attention
    output_linear.weight/bias (plain)  ↔ output_dense.kernel (T)/bias
    output_linear (margin Parameter)   ↔ output_margin_weight

Both directions assume ``vocab_pad_multiple == 1`` shapes (the reference
has no padding); exporting a padded checkpoint slices the pad rows off,
which is exact because pad rows never receive gradient (their indices
never occur in data).
"""

from __future__ import annotations

import os

import numpy as np

PLAIN_KEYS = {
    "terminal_embedding.weight",
    "path_embedding.weight",
    "input_linear.weight",
    "input_layer_norm.weight",
    "input_layer_norm.bias",
    "attention_parameter",
    "output_linear.weight",
    "output_linear.bias",
}
MARGIN_KEYS = (PLAIN_KEYS - {"output_linear.weight", "output_linear.bias"}) | {
    "output_linear"
}


def load_state_dict(path: str) -> dict[str, np.ndarray]:
    """torch.load the reference state_dict (cpu, weights_only) → numpy."""
    import torch

    if os.path.isdir(path):
        path = os.path.join(path, "code2vec.model")
    sd = torch.load(path, map_location="cpu", weights_only=True)
    arrays = {
        k: np.asarray(v.detach().cpu().numpy(), np.float32) for k, v in sd.items()
    }
    keys = set(arrays)
    if keys not in (PLAIN_KEYS, MARGIN_KEYS):
        raise SystemExit(
            f"unrecognized state_dict layout: {sorted(keys)}\n"
            "expected the reference Code2Vec model "
            "(model/model.py:21-42, plain or angular-margin head)"
        )
    return arrays


def save_state_dict(sd: dict[str, np.ndarray], path: str) -> str:
    """numpy → torch.save, the file the reference's load expects."""
    import torch

    if os.path.isdir(path):
        path = os.path.join(path, "code2vec.model")
    torch.save(
        {k: torch.from_numpy(np.array(v, np.float32)) for k, v in sd.items()},
        path,
    )
    return path


def infer_dims(sd: dict[str, np.ndarray]) -> dict:
    t_count, t_dim = sd["terminal_embedding.weight"].shape
    p_count, p_dim = sd["path_embedding.weight"].shape
    encode = sd["input_layer_norm.weight"].shape[0]
    margin = "output_linear.weight" not in sd
    head = sd["output_linear"] if margin else sd["output_linear.weight"]
    label_count = head.shape[0]
    expect_in = 2 * t_dim + p_dim
    got_out, got_in = sd["input_linear.weight"].shape
    if (got_out, got_in) != (encode, expect_in):
        raise SystemExit(
            f"input_linear.weight is {got_out}x{got_in}, expected "
            f"{encode}x{expect_in} (encode x 2*terminal_embed+path_embed)"
        )
    return {
        "terminal_count": t_count,
        "path_count": p_count,
        "label_count": label_count,
        "terminal_embed_size": t_dim,
        "path_embed_size": p_dim,
        "encode_size": encode,
        "angular_margin_loss": margin,
    }


def to_param_tree(sd: dict[str, np.ndarray], dims: dict) -> dict:
    """state_dict → the flax param tree for Code2Vec(vocab_pad_multiple=1)."""
    tree = {
        "terminal_embedding": {"embedding": sd["terminal_embedding.weight"]},
        "path_embedding": {"embedding": sd["path_embedding.weight"]},
        "input_dense": {"kernel": sd["input_linear.weight"].T.copy()},
        "input_layer_norm": {
            "scale": sd["input_layer_norm.weight"],
            "bias": sd["input_layer_norm.bias"],
        },
        "attention": sd["attention_parameter"],
    }
    if dims["angular_margin_loss"]:
        tree["output_margin_weight"] = sd["output_linear"]
    else:
        tree["output_dense"] = {
            "kernel": sd["output_linear.weight"].T.copy(),
            "bias": sd["output_linear.bias"],
        }
    return tree


def from_param_tree(params: dict, model_config) -> dict[str, np.ndarray]:
    """Flax param tree → state_dict, slicing off vocab-pad rows/columns.

    Inverse of :func:`to_param_tree` for unpadded models; for padded ones
    (``vocab_pad_multiple > 1``) the extra rows/head columns are dropped —
    exact, since pad ids never occur in data and their rows keep their
    init values without ever affecting a real logit.
    """
    c = model_config
    p = {k: np.asarray(v, np.float32) for k, v in _flatten(params).items()}
    sd = {
        "terminal_embedding.weight": p["terminal_embedding/embedding"][
            : c.terminal_count
        ],
        "path_embedding.weight": p["path_embedding/embedding"][: c.path_count],
        "input_linear.weight": p["input_dense/kernel"].T.copy(),
        "input_layer_norm.weight": p["input_layer_norm/scale"],
        "input_layer_norm.bias": p["input_layer_norm/bias"],
        "attention_parameter": p["attention"],
    }
    if c.angular_margin_loss:
        sd["output_linear"] = p["output_margin_weight"][: c.label_count]
    else:
        sd["output_linear.weight"] = p["output_dense/kernel"].T[: c.label_count].copy()
        sd["output_linear.bias"] = p["output_dense/bias"][: c.label_count]
    return sd


def _flatten(tree: dict, prefix: str = "") -> dict:
    out = {}
    for k, v in tree.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten(v, key + "/"))
        else:
            out[key] = v
    return out


def reference_forward(
    sd: dict[str, np.ndarray],
    dims: dict,
    starts: np.ndarray,
    paths: np.ndarray,
    ends: np.ndarray,
    labels: np.ndarray,
    angular_margin: float,
    inverse_temp: float,
) -> np.ndarray:
    """The reference forward (model/model.py:44-88) in torch, eval mode —
    the oracle a conversion must reproduce before it is written."""
    import math

    import torch
    import torch.nn.functional as F

    # np.array copies: orbax-restored arrays can be non-writable, which
    # torch.from_numpy warns about (it never writes here, but keep it clean)
    t = {k: torch.from_numpy(np.array(v)) for k, v in sd.items()}
    starts_t = torch.from_numpy(starts).long()
    paths_t = torch.from_numpy(paths).long()
    ends_t = torch.from_numpy(ends).long()
    ccv = torch.cat(
        (
            t["terminal_embedding.weight"][starts_t],
            t["path_embedding.weight"][paths_t],
            t["terminal_embedding.weight"][ends_t],
        ),
        dim=2,
    )
    ccv = ccv @ t["input_linear.weight"].T
    ccv = F.layer_norm(
        ccv, (dims["encode_size"],),
        t["input_layer_norm.weight"], t["input_layer_norm.bias"],
    )
    ccv = torch.tanh(ccv)
    mask = (starts_t > 0).float()
    ninf = -3.4e38
    attn = F.softmax(
        (ccv * t["attention_parameter"]).sum(-1) * mask + (1 - mask) * ninf,
        dim=1,
    )
    code_vector = (ccv * attn.unsqueeze(-1)).sum(1)
    if dims["angular_margin_loss"]:
        labels_t = torch.from_numpy(labels).long()
        cosine = F.normalize(code_vector) @ F.normalize(t["output_linear"]).T
        sine = torch.sqrt(torch.clamp(1.0 - cosine**2, min=0.0))
        phi = cosine * math.cos(angular_margin) - sine * math.sin(angular_margin)
        phi = torch.where(cosine > 0, phi, cosine)
        one_hot = torch.zeros_like(cosine)
        one_hot.scatter_(1, labels_t.view(-1, 1), 1)
        out = ((one_hot * phi) + ((1.0 - one_hot) * cosine)) * inverse_temp
    else:
        out = code_vector @ t["output_linear.weight"].T + t["output_linear.bias"]
    return out.numpy()
