"""Deterministic fault injection for elastic-training tests.

Preemption-recovery code is exactly the code a healthy run never executes:
without a way to *schedule* a crash, the mid-save crash window, the SIGTERM
drain path, and mid-epoch resume are only ever exercised by production
incidents. This module lets tests (and brave operators) declare a **fault
plan** — "at the Nth hit of this named point, do X" — that the training
stack honors at a handful of instrumented points.

Plan grammar (``--fault_plan`` / ``C2V_FAULT_PLAN``)::

    plan    := clause ("," clause)*
    clause  := point ["@" occurrence] ":" action
    action  := "raise" | "kill" | "sigterm" | "sleep" millis

``occurrence`` is 1-based and counts hits of that point since
:func:`install_plan` (default 1). Examples::

    train_step@10:sigterm        # graceful preemption after the 10th step
    train_step@10:kill           # SIGKILL — the unceremonious preemption
    mid_save@1:raise             # fail the first persist mid-write
    mid_save@1:sleep500          # slow the first persist by 500 ms
    prefetch_produce@3:raise     # fail the producer thread on batch 3

Instrumented points (grep ``fault_point(`` for the authoritative list):

- ``train_step`` — after each optimizer step's dispatch (train/loop.py)
- ``epoch_start`` — top of each epoch (train/loop.py)
- ``pre_save`` — checkpoint save requested, before any write (checkpoint.py)
- ``mid_save`` — inside persist: arrays written, not yet published
  (checkpoint.py — a ``kill`` here leaves the partial dir restore must skip)
- ``post_save`` — after the atomic publish (checkpoint.py)
- ``prefetch_produce`` — per batch built by the producer thread
  (train/prefetch.py)

Actions:

- ``raise``   — raise :class:`FaultInjected` at the point (exception paths)
- ``kill``    — ``SIGKILL`` the process (no cleanup runs; exit code -9)
- ``sigterm`` — send the process ``SIGTERM`` (exercises the graceful
  preemption handler, train/preempt.py)
- ``sleepN``  — sleep N milliseconds (widen overlap windows so tests can
  observe async behavior deterministically)

Counters are process-local and thread-safe (the producer and persist
threads hit points too). ``install_plan`` resets all counters, so each
``train()`` call replays the plan from scratch — occurrence numbers are
deterministic for a fixed config/seed.
"""

from __future__ import annotations

import logging
import os
import re
import signal
import threading
import time
from dataclasses import dataclass, field

logger = logging.getLogger(__name__)

__all__ = [
    "FaultInjected",
    "FaultPlan",
    "active_plan",
    "fault_point",
    "install_plan",
    "parse_plan",
]

ENV_VAR = "C2V_FAULT_PLAN"

_CLAUSE = re.compile(
    r"^(?P<point>[A-Za-z_][A-Za-z0-9_]*)"
    r"(?:@(?P<occurrence>[0-9]+))?"
    r":(?P<action>raise|kill|sigterm|sleep(?P<millis>[0-9]+))$"
)


class FaultInjected(RuntimeError):
    """Raised by a ``raise``-action clause at its fault point."""


@dataclass
class FaultPlan:
    """Parsed plan: ``(point, occurrence) -> action``, plus hit counters."""

    spec: str
    clauses: dict[tuple[str, int], str]
    _hits: dict[str, int] = field(default_factory=dict)
    _lock: threading.Lock = field(default_factory=threading.Lock)

    def fire(self, point: str, **context) -> None:
        """Count a hit of ``point``; perform the matching action if any."""
        with self._lock:
            self._hits[point] = self._hits.get(point, 0) + 1
            action = self.clauses.get((point, self._hits[point]))
        if action is None:
            return
        logger.warning(
            "fault plan: %s@%d -> %s %s",
            point, self._hits[point], action, context or "",
        )
        if action == "raise":
            raise FaultInjected(
                f"fault plan fired: {point}@{self._hits[point]} {context}"
            )
        if action == "kill":
            # the point of SIGKILL is that NOTHING runs after it — no
            # finally blocks, no atexit, no flush; recovery must work
            # from whatever already reached disk
            os.kill(os.getpid(), signal.SIGKILL)
        if action == "sigterm":
            os.kill(os.getpid(), signal.SIGTERM)
            return
        if action.startswith("sleep"):
            time.sleep(int(action[len("sleep"):]) / 1e3)

    def hits(self, point: str) -> int:
        with self._lock:
            return self._hits.get(point, 0)


def parse_plan(spec: str) -> FaultPlan:
    """Parse a plan string; raises ``ValueError`` on malformed clauses."""
    clauses: dict[tuple[str, int], str] = {}
    for raw in spec.split(","):
        raw = raw.strip()
        if not raw:
            continue
        m = _CLAUSE.match(raw)
        if m is None:
            raise ValueError(
                f"malformed fault-plan clause {raw!r}; expected "
                "point[@occurrence]:raise|kill|sigterm|sleep<ms> "
                "(e.g. train_step@10:sigterm)"
            )
        occurrence = int(m.group("occurrence") or 1)
        if occurrence < 1:
            raise ValueError(f"occurrence must be >= 1 in {raw!r}")
        key = (m.group("point"), occurrence)
        if key in clauses:
            raise ValueError(f"duplicate fault-plan clause for {raw!r}")
        clauses[key] = m.group("action")
    return FaultPlan(spec=spec, clauses=clauses)


_plan: FaultPlan | None = None


def install_plan(spec: str | None) -> FaultPlan | None:
    """Install (or clear, for falsy ``spec``) the process-wide plan.

    Resets hit counters: each installation replays the plan from zero.
    Returns the installed plan (None when cleared).
    """
    global _plan
    _plan = parse_plan(spec) if spec else None
    return _plan


def install_plan_from_env() -> FaultPlan | None:
    """Install the plan from ``C2V_FAULT_PLAN`` if set; else leave the
    current plan alone (subprocess harnesses set the env var)."""
    spec = os.environ.get(ENV_VAR, "").strip()
    return install_plan(spec) if spec else _plan


def active_plan() -> FaultPlan | None:
    return _plan


def fault_point(point: str, **context) -> None:
    """Mark a named fault point. No-op (one global read) without a plan —
    cheap enough for per-step and per-batch call sites."""
    plan = _plan
    if plan is not None:
        plan.fire(point, **context)
