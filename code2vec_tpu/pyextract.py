"""Python-language path-context extractor (the multi-language leg of
BASELINE config 5: "Java+Python merged AST vocab").

The Java pipeline is native C++ (``extractor/``, re-deriving the reference
Scala notebook ipynb cells 4-11). Python already ships a full-fidelity AST
in the standard library, so the Python leg walks ``ast`` directly and
re-applies the SAME conventions the C++ extractor uses, so the two
languages intern into one shared vocab space:

- anonymization env: parameters/locals -> ``@var_k``, the function's own
  name (and nested defs) -> ``@method_k``, encounter-ordered (ipynb cell6);
- literal normalization: str/bytes -> ``@string_literal``, float ->
  ``@double_literal``, int kept verbatim by default (ExtractConfig parity,
  extractor/src/extract.h);
- operator-suffixed node names (``BinOp:+`` like ``BinaryExpr:+``,
  extract.cc operator-suffixed nodes);
- leaf-pair path enumeration with the same length/width caps and the same
  ``↑``/``↓`` path-string format (extract.cc get_path / ipynb cell9);
- terminals lowercased at interning, vocabs 1-based insertion-ordered
  (extract.cc Vocabs);
- ignorable-method filter analogue (extract.cc is_ignorable_method):
  bodyless defs, dunder methods (the Object-method analogue), trivial
  property getters/setters.

``extract_python_dataset`` writes/extends the five corpus artifacts with
the exact text formats of ``extractor/src/main.cc``; in merge mode it
preloads the existing vocab files and appends records, which is how a
Java+Python corpus shares one vocab (see extractor.main, which routes
.java rows to the native CLI and .py rows here).
"""

from __future__ import annotations

import ast
import logging
import os
from dataclasses import dataclass, field

logger = logging.getLogger(__name__)

UP = "↑"  # ↑ — same arrows as extract.cc kUp/kDown
DOWN = "↓"

# the Object-method analogue of extract.cc kObjectMethods (toString/
# hashCode/equals/...): dunders carry no name signal to predict
_DUNDER_PREFIX = "__"

_BINOP_SYMBOL = {
    ast.Add: "+", ast.Sub: "-", ast.Mult: "*", ast.Div: "/",
    ast.FloorDiv: "//", ast.Mod: "%", ast.Pow: "**", ast.LShift: "<<",
    ast.RShift: ">>", ast.BitOr: "|", ast.BitXor: "^", ast.BitAnd: "&",
    ast.MatMult: "@",
}
_UNARYOP_SYMBOL = {
    ast.UAdd: "+", ast.USub: "-", ast.Not: "not", ast.Invert: "~",
}
_CMPOP_SYMBOL = {
    ast.Eq: "==", ast.NotEq: "!=", ast.Lt: "<", ast.LtE: "<=",
    ast.Gt: ">", ast.GtE: ">=", ast.Is: "is", ast.IsNot: "is not",
    ast.In: "in", ast.NotIn: "not in",
}
_BOOLOP_SYMBOL = {ast.And: "and", ast.Or: "or"}


@dataclass
class PyExtractConfig:
    """Mirrors extractor/src/extract.h ExtractConfig."""

    normalize_string_literal: bool = True
    normalize_char_literal: bool = True  # no-op for Python; params.txt parity
    normalize_int_literal: bool = False
    normalize_double_literal: bool = True
    max_length: int = 8
    max_width: int = 3


@dataclass
class _ENode:
    """Normalized AST node (extract.cc ENode)."""

    name: str
    terminal: str | None = None
    children: list["_ENode"] = field(default_factory=list)


@dataclass
class PyMethod:
    label: str  # original def name (the prediction target)
    contexts: list[tuple[str, str, str]]  # (start, path-string, end)
    variables: list[tuple[str, str]]  # (original, @var_k) encounter order
    methods: list[tuple[str, str]]  # (original, @method_k) encounter order
    source: str | None = None


class _Env:
    """Anonymization environment (extract.cc Env): encounter-ordered
    ``@<space>_k`` aliases."""

    def __init__(self, space: str):
        self.space = space
        self.order: list[tuple[str, str]] = []  # (original, alias)
        self.by_name: dict[str, str] = {}

    def fresh(self, original: str) -> str:
        alias = f"@{self.space}_{len(self.order)}"
        self.order.append((original, alias))
        self.by_name[original] = alias
        return alias

    def lookup(self, name: str) -> str | None:
        return self.by_name.get(name)


class _MethodExtractor(ast.NodeVisitor):
    """One FunctionDef -> normalized _ENode tree.

    Scoping follows the Java extractor's spirit: a name binds to a fresh
    ``@var_k`` at its first binding occurrence (params, assignment targets,
    for/with/except/comprehension targets), and every later reference
    resolves through the env; unbound names (globals, builtins, attribute
    roots of other objects) keep their original text, like Java field/type
    names do.
    """

    def __init__(self, config: PyExtractConfig, vars_env: _Env, methods_env: _Env):
        self.config = config
        self.vars = vars_env
        self.methods = methods_env

    # -- helpers ---------------------------------------------------------

    def node(self, name: str, *children) -> _ENode:
        out = _ENode(name)
        out.children = [c for c in children if c is not None]
        return out

    def term(self, name: str, terminal: str) -> _ENode:
        return _ENode(name, terminal=terminal)

    def walk(self, n) -> _ENode | None:
        if n is None:
            return None
        method = getattr(self, f"x_{type(n).__name__}", None)
        if method is not None:
            return method(n)
        return self.generic(n)

    def walk_all(self, nodes) -> list[_ENode]:
        return [e for e in (self.walk(c) for c in nodes) if e is not None]

    def generic(self, n) -> _ENode:
        out = _ENode(type(n).__name__)
        for child in ast.iter_child_nodes(n):
            e = self.walk(child)
            if e is not None:
                out.children.append(e)
        if not out.children and not isinstance(n, (ast.expr_context, ast.operator, ast.unaryop, ast.cmpop, ast.boolop)):
            # leaf statement/expr with no operands (pass, break, ...)
            out.terminal = type(n).__name__.lower()
        if isinstance(n, (ast.expr_context, ast.operator, ast.unaryop, ast.cmpop, ast.boolop)):
            return None  # operator tokens are folded into parent names
        return out

    # -- binding forms ---------------------------------------------------

    def bind_target(self, target) -> _ENode | None:
        """Anonymize a binding occurrence (Store context)."""
        if isinstance(target, ast.Name):
            alias = self.vars.lookup(target.id) or self.vars.fresh(target.id)
            return self.term("Name", alias)
        if isinstance(target, (ast.Tuple, ast.List)):
            out = _ENode(type(target).__name__)
            out.children = [
                e for e in (self.bind_target(t) for t in target.elts)
                if e is not None
            ]
            return out
        if isinstance(target, ast.Starred):
            out = _ENode("Starred")
            inner = self.bind_target(target.value)
            if inner is not None:
                out.children.append(inner)
            return out
        return self.walk(target)  # Attribute/Subscript targets: references

    # -- visitors --------------------------------------------------------

    def x_Name(self, n: ast.Name) -> _ENode:
        if isinstance(n.ctx, ast.Store):
            return self.bind_target(n)
        # vars first, then enclosing def names (so recursive calls resolve
        # to @method_k — the Java extractor's method-space lookup)
        alias = self.vars.lookup(n.id) or self.methods.lookup(n.id)
        return self.term("Name", alias if alias is not None else n.id)

    def x_arg(self, n: ast.arg) -> _ENode:
        alias = self.vars.fresh(n.arg)
        out = self.node("arg", self.term("Name", alias))
        if n.annotation is not None:
            out.children.append(self.walk(n.annotation))
        return out

    def x_Constant(self, n: ast.Constant) -> _ENode:
        v = n.value
        if isinstance(v, bool) or v is None or v is Ellipsis:
            return self.term("Constant", str(v))
        if isinstance(v, (str, bytes)):
            if self.config.normalize_string_literal:
                return self.term("Constant", "@string_literal")
            return self.term("Constant", str(v))
        if isinstance(v, int):
            if self.config.normalize_int_literal:
                return self.term("Constant", "@int_literal")
            return self.term("Constant", str(v))
        if isinstance(v, (float, complex)):
            if self.config.normalize_double_literal:
                return self.term("Constant", "@double_literal")
            return self.term("Constant", str(v))
        return self.term("Constant", str(v))

    def x_Attribute(self, n: ast.Attribute) -> _ENode:
        return self.node(
            "Attribute", self.walk(n.value), self.term("attr", n.attr)
        )

    def x_keyword(self, n: ast.keyword) -> _ENode:
        name = self.term("arg", n.arg) if n.arg else None
        return self.node("keyword", name, self.walk(n.value))

    def x_BinOp(self, n: ast.BinOp) -> _ENode:
        return self.node(
            f"BinOp:{_BINOP_SYMBOL.get(type(n.op), '?')}",
            self.walk(n.left), self.walk(n.right),
        )

    def x_UnaryOp(self, n: ast.UnaryOp) -> _ENode:
        return self.node(
            f"UnaryOp:{_UNARYOP_SYMBOL.get(type(n.op), '?')}",
            self.walk(n.operand),
        )

    def x_AugAssign(self, n: ast.AugAssign) -> _ENode:
        return self.node(
            f"AugAssign:{_BINOP_SYMBOL.get(type(n.op), '?')}=",
            self.bind_target(n.target), self.walk(n.value),
        )

    def x_BoolOp(self, n: ast.BoolOp) -> _ENode:
        out = _ENode(f"BoolOp:{_BOOLOP_SYMBOL.get(type(n.op), '?')}")
        out.children = self.walk_all(n.values)
        return out

    def x_Compare(self, n: ast.Compare) -> _ENode:
        # name carries the operator chain, like BinaryExpr:<op>
        ops = ",".join(_CMPOP_SYMBOL.get(type(o), "?") for o in n.ops)
        out = _ENode(f"Compare:{ops}")
        out.children = [self.walk(n.left)] + self.walk_all(n.comparators)
        return out

    def x_Assign(self, n: ast.Assign) -> _ENode:
        # value first (its references see pre-assignment bindings), then
        # targets bind — Python evaluation order
        value = self.walk(n.value)
        targets = [self.bind_target(t) for t in n.targets]
        out = _ENode("Assign")
        out.children = [t for t in targets if t is not None] + (
            [value] if value is not None else []
        )
        return out

    def x_AnnAssign(self, n: ast.AnnAssign) -> _ENode:
        value = self.walk(n.value) if n.value is not None else None
        return self.node(
            "AnnAssign", self.bind_target(n.target),
            self.walk(n.annotation), value,
        )

    def x_NamedExpr(self, n: ast.NamedExpr) -> _ENode:
        value = self.walk(n.value)
        return self.node("NamedExpr", self.bind_target(n.target), value)

    def x_For(self, n: ast.For) -> _ENode:
        return self._for(n, "For")

    def x_AsyncFor(self, n: ast.AsyncFor) -> _ENode:
        return self._for(n, "AsyncFor")

    def _for(self, n, name: str) -> _ENode:
        it = self.walk(n.iter)
        target = self.bind_target(n.target)
        out = _ENode(name)
        out.children = [target, it] + self.walk_all(n.body) + self.walk_all(
            n.orelse
        )
        out.children = [c for c in out.children if c is not None]
        return out

    def x_withitem(self, n: ast.withitem) -> _ENode:
        ctx = self.walk(n.context_expr)
        opt = (
            self.bind_target(n.optional_vars)
            if n.optional_vars is not None
            else None
        )
        return self.node("withitem", ctx, opt)

    def x_ExceptHandler(self, n: ast.ExceptHandler) -> _ENode:
        ty = self.walk(n.type) if n.type is not None else None
        name = self.term("Name", self.vars.fresh(n.name)) if n.name else None
        out = _ENode("ExceptHandler")
        out.children = [c for c in (ty, name) if c is not None]
        out.children += self.walk_all(n.body)
        return out

    def x_comprehension(self, n: ast.comprehension) -> _ENode:
        # target binds BEFORE iter/ifs are walked (they reference it)
        target = self.bind_target(n.target)
        out = _ENode("comprehension")
        out.children = [target, self.walk(n.iter)] + self.walk_all(n.ifs)
        out.children = [c for c in out.children if c is not None]
        return out

    def _comp(self, n, name: str) -> _ENode:
        out = _ENode(name)
        gens = self.walk_all(n.generators)
        if isinstance(n, ast.DictComp):
            elems = [self.walk(n.key), self.walk(n.value)]
        else:
            elems = [self.walk(n.elt)]
        out.children = gens + [e for e in elems if e is not None]
        return out

    def x_ListComp(self, n):
        return self._comp(n, "ListComp")

    def x_SetComp(self, n):
        return self._comp(n, "SetComp")

    def x_DictComp(self, n):
        return self._comp(n, "DictComp")

    def x_GeneratorExp(self, n):
        return self._comp(n, "GeneratorExp")

    def x_Lambda(self, n: ast.Lambda) -> _ENode:
        args = self.walk(n.args)
        return self.node("Lambda", args, self.walk(n.body))

    def x_Global(self, n: ast.Global) -> _ENode:
        out = _ENode("Global")
        out.children = [self.term("Name", name) for name in n.names]
        return out

    def x_Nonlocal(self, n: ast.Nonlocal) -> _ENode:
        out = _ENode("Nonlocal")
        out.children = [self.term("Name", name) for name in n.names]
        return out

    def x_FunctionDef(self, n) -> _ENode:
        alias = self.methods.fresh(n.name)
        out = _ENode(type(n).__name__)
        out.children.append(self.term("Name", alias))
        out.children.append(self.walk(n.args))
        out.children += self.walk_all(n.body)
        if n.returns is not None:
            out.children.append(self.walk(n.returns))
        for d in n.decorator_list:
            out.children.append(self.walk(d))
        return out

    x_AsyncFunctionDef = x_FunctionDef

    def x_alias(self, n: ast.alias) -> _ENode:
        shown = n.asname or n.name
        if n.asname:
            self.vars.fresh(n.asname)
            shown = self.vars.lookup(n.asname)
        return self.term("alias", shown)


def _is_ignorable(fn) -> bool:
    """extract.cc is_ignorable_method analogue for Python defs."""
    name = fn.name
    body = [
        s for s in fn.body
        if not (
            isinstance(s, ast.Expr)
            and isinstance(s.value, ast.Constant)
            and isinstance(s.value.value, str)
        )  # docstrings don't count as body
    ]
    if not body or all(isinstance(s, ast.Pass) for s in body):
        return True  # abstract/bodyless
    if name.startswith(_DUNDER_PREFIX) and name.endswith(_DUNDER_PREFIX):
        return True  # the Object-methods analogue
    if len(body) == 1:
        only = body[0]
        # trivial getter: get*/is* returning an attribute or name (the C++
        # filter's name-prefix condition applies here too — a one-line
        # return in an arbitrary def is NOT ignorable)
        if (
            (name.startswith("get") or name.startswith("is"))
            and isinstance(only, ast.Return)
            and isinstance(only.value, (ast.Attribute, ast.Name))
        ):
            return True
        # trivial setter: set* with a single self.<attr> = <param>
        if (
            name.startswith("set")
            and isinstance(only, ast.Assign)
            and len(only.targets) == 1
            and isinstance(only.targets[0], ast.Attribute)
            and isinstance(only.value, ast.Name)
        ):
            return True
    return False


def _find_terminals(root: _ENode):
    """(node, path-from-root as [(node, child_index), ...]) per terminal —
    extract.cc find_terminals."""
    out = []
    path = [(root, 0)]

    def rec(n: _ENode):
        if n.terminal is not None:
            out.append((n, list(path)))
            return
        for i, c in enumerate(n.children):
            path.append((c, i))
            rec(c)
            path.pop()

    rec(root)
    return out


def _get_path(a, b, max_length: int, max_width: int) -> str | None:
    """extract.cc get_path: shared-prefix strip, width/length caps, the
    ↑/↓ join. ``a``/``b`` are path-from-root lists."""
    i = 1
    hinge = a[0][0]
    while i < len(a) and i < len(b) and a[i][0] is b[i][0]:
        hinge = a[i][0]
        i += 1
    width = a[i][1] - b[i][1]
    if abs(width) > max_width:
        return None
    up_len = len(a) - i
    down_len = len(b) - i
    if up_len + down_len + 1 > max_length:
        return None
    parts = []
    for k in range(len(a) - 1, i - 1, -1):
        parts.append(a[k][0].name)
        parts.append(UP)
    parts.append(hinge.name)
    parts.append(DOWN)
    for k in range(i, len(b) - 1):
        parts.append(b[k][0].name)
        parts.append(DOWN)
    parts.append(b[-1][0].name)
    return "".join(parts)


def _collect_defs(tree):
    """All function defs, recursively (extract.cc collect_methods)."""
    out = []
    for n in ast.walk(tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.append(n)
    return out


def extract_python_source(
    source: str,
    method_name: str = "*",
    config: PyExtractConfig | None = None,
) -> list[PyMethod]:
    """Parse Python source and extract path-contexts per function def."""
    config = config or PyExtractConfig()
    tree = ast.parse(source)
    methods: list[PyMethod] = []
    for fn in _collect_defs(tree):
        if method_name != "*" and fn.name != method_name:
            continue
        if _is_ignorable(fn):
            continue
        vars_env = _Env("var")
        methods_env = _Env("method")
        extractor = _MethodExtractor(config, vars_env, methods_env)
        enode = extractor.walk(fn)
        terminals = _find_terminals(enode)
        contexts: list[tuple[str, str, str]] = []
        for x in range(len(terminals)):
            for y in range(x + 1, len(terminals)):
                path = _get_path(
                    terminals[x][1], terminals[y][1],
                    config.max_length, config.max_width,
                )
                if path is not None:
                    contexts.append(
                        (terminals[x][0].terminal, path, terminals[y][0].terminal)
                    )
        if not contexts:
            continue
        methods.append(
            PyMethod(
                label=fn.name,
                contexts=contexts,
                variables=list(vars_env.order),
                methods=list(methods_env.order),
                source=ast.get_source_segment(source, fn),
            )
        )
    return methods


# ---------------------------------------------------------------------------
# dataset writing (main.cc artifact formats, with merge/append support)


class PyVocabs:
    """1-based insertion-ordered interner (extract.cc Vocabs), optionally
    preloaded from existing terminal_idxs.txt/path_idxs.txt so Python
    records extend a Java corpus's vocab space."""

    def __init__(self):
        self.terminals: dict[str, int] = {}
        self.paths: dict[str, int] = {}

    @staticmethod
    def _load(path: str) -> dict[str, int]:
        out: dict[str, int] = {}
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.rstrip("\n")
                if not line:
                    continue
                idx, name = line.split("\t", 1)
                if name == "<PAD/>":
                    continue  # the writers re-emit row 0
                out[name] = int(idx)
        return out

    @classmethod
    def preloaded(cls, dataset_dir: str) -> "PyVocabs":
        v = cls()
        v.terminals = cls._load(os.path.join(dataset_dir, "terminal_idxs.txt"))
        v.paths = cls._load(os.path.join(dataset_dir, "path_idxs.txt"))
        return v

    def terminal_index(self, name: str) -> int:
        name = name.lower()  # vocab-size reduction (ipynb cell7)
        # unlike Java, Python string literals can contain raw newlines and
        # tabs (triple-quoted strings); with --no-normalize-string those
        # become terminal NAMES, which would corrupt the line/tab-delimited
        # terminal_idxs.txt — escape the delimiters before interning
        name = (
            name.replace("\\", "\\\\")
            .replace("\n", "\\n")
            .replace("\r", "\\r")
            .replace("\t", "\\t")
        )
        if name not in self.terminals:
            self.terminals[name] = len(self.terminals) + 1
        return self.terminals[name]

    def path_index(self, name: str) -> int:
        if name not in self.paths:
            self.paths[name] = len(self.paths) + 1
        return self.paths[name]


def _write_vocab(path: str, entries: dict[str, int]) -> None:
    with open(path, "w", encoding="utf-8") as f:
        f.write("0\t<PAD/>\n")
        for name, idx in sorted(entries.items(), key=lambda kv: kv[1]):
            f.write(f"{idx}\t{name}\n")


def extract_python_dataset(
    dataset_dir: str,
    source_dir: str,
    rows: list[tuple[str, str]],
    config: PyExtractConfig | None = None,
    merge: bool = False,
    start_id: int = 0,
    method_declarations: str | None = None,
) -> tuple[int, PyVocabs]:
    """Extract ``rows`` of (py_file, method_name) into the five artifacts.

    ``merge=True`` preloads the existing vocab files and APPENDS to
    corpus.txt/actual_methods.txt (the Java+Python merged-vocab flow);
    otherwise the artifacts are created fresh. Per-row failures (missing
    file, bad encoding, syntax error) warn and continue, like the C++ leg.
    Returns (next_id, vocabs).
    """
    config = config or PyExtractConfig()
    vocabs = PyVocabs.preloaded(dataset_dir) if merge else PyVocabs()
    mode = "a" if merge else "w"
    id_counter = start_id
    method_names: set[str] = set()
    if merge:
        # seed with the Java leg's names so method_name_vocab_count stays a
        # true distinct count across both languages (main.cc method_names)
        actual_path = os.path.join(dataset_dir, "actual_methods.txt")
        if os.path.exists(actual_path):
            with open(actual_path, encoding="utf-8") as f:
                for line in f:
                    parts = line.rstrip("\n").split("\t")
                    if len(parts) >= 2:
                        method_names.add(parts[1])

    corpus = open(os.path.join(dataset_dir, "corpus.txt"), mode, encoding="utf-8")
    actual = open(
        os.path.join(dataset_dir, "actual_methods.txt"), mode, encoding="utf-8"
    )
    declarations = None
    if method_declarations:
        declarations = open(
            os.path.join(dataset_dir, method_declarations), mode,
            encoding="utf-8",
        )
    try:
        last_file, methods_cache = None, []
        for py_file, method_name in rows:
            try:
                if py_file != last_file:
                    with open(
                        os.path.join(source_dir, py_file), encoding="utf-8"
                    ) as f:
                        methods_cache = extract_python_source(
                            f.read(), "*", config
                        )
                    last_file = py_file
                selected = [
                    m for m in methods_cache
                    if method_name == "*" or m.label == method_name
                ]
                if not selected and method_name != "*":
                    logger.warning("method not found: %s\t%s", py_file, method_name)
                for m in selected:
                    corpus_id = id_counter
                    id_counter += 1
                    corpus.write(f"#{corpus_id}\n")
                    corpus.write(f"label:{m.label}\n")
                    corpus.write(f"class:{py_file}\n")
                    corpus.write("paths:\n")
                    for start, path, end in m.contexts:
                        corpus.write(
                            f"{vocabs.terminal_index(start)}\t"
                            f"{vocabs.path_index(path)}\t"
                            f"{vocabs.terminal_index(end)}\n"
                        )
                    corpus.write("vars:\n")
                    for original, alias in m.variables:
                        corpus.write(f"{original}\t{alias}\n")
                    corpus.write("\n")
                    actual.write(
                        f"{py_file}\t{m.label}\t{corpus_id}\t{len(m.contexts)}\n"
                    )
                    if declarations is not None and m.source:
                        # main.cc method_declarations format
                        declarations.write(
                            f"#{corpus_id}\t{py_file}#{m.label}\n{m.source}\n\n"
                        )
                    method_names.add(m.label)
            except (SyntaxError, OSError, UnicodeDecodeError, ValueError) as e:
                # warn-and-continue, matching the C++ leg's per-row policy
                # (main.cc catch blocks): one bad file must not abort the
                # run mid-write and orphan already-appended records
                logger.error("parse error: %s (%s)", py_file, e)
                last_file, methods_cache = None, []
    finally:
        corpus.close()
        actual.close()
        if declarations is not None:
            declarations.close()

    _write_vocab(os.path.join(dataset_dir, "terminal_idxs.txt"), vocabs.terminals)
    _write_vocab(os.path.join(dataset_dir, "path_idxs.txt"), vocabs.paths)
    with open(os.path.join(dataset_dir, "params.txt"), "w", encoding="utf-8") as f:
        f.write(
            f"max_length:{config.max_length}\n"
            f"max_width:{config.max_width}\n"
            f"nomalize_string_literal:{'true' if config.normalize_string_literal else 'false'}\n"
            f"nomalize_char_literal:{'true' if config.normalize_char_literal else 'false'}\n"
            f"nomalize_int_literal:{'true' if config.normalize_int_literal else 'false'}\n"
            f"nomalize_double_literal:{'true' if config.normalize_double_literal else 'false'}\n"
            f"terminal_vocab_count:{len(vocabs.terminals)}\n"
            f"path_vocab_count:{len(vocabs.paths)}\n"
            f"method_count:{id_counter}\n"
            f"method_name_vocab_count:{len(method_names)}\n"
        )
    return id_counter, vocabs
