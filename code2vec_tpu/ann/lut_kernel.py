"""Fused LUT-gather-accumulate scoring for IVF-PQ search.

Given the per-query LUT ``[M, 256]`` of subspace inner products and the
index's cell-major storage (codes ``[n_list, C, M]`` uint8, per-row scales
and pad bias ``[n_list, C]`` f32), score every row of every probed cell::

    out[q, p, c] = scales[cell, c] * sum_m LUT[q, m, codes[cell, c, m]]
                   + bias[cell, c]          where cell = probed[q, p]

Two implementations with pinned parity (tests/test_ann.py):

- ``xla``    — ``jnp.take`` over a flattened per-query LUT. XLA's gather
  lowering is the right tool on CPU (and the reference semantics).
- ``pallas`` — one kernel program per (query, probed cell): the cell's
  codes/scales/bias are DMA'd from HBM into VMEM in ``chunk_c``-row chunks
  (``dma_depth``-buffered — chunk c+1's copy overlaps chunk c's compute,
  the PR-8 double-buffer pattern), the LUT stays VMEM-resident, and the
  gather is formulated as a one-hot contraction per subspace: TPU has no
  fast vector gather, but ``[chunk_c, 256] x [256]`` compare-and-reduce is
  pure VPU work. ``interpret=True`` runs the same kernel on CPU.

A third formulation, ``gpu_lut_score_cells``, lowers through Pallas's
Triton backend: XLA pre-gathers the probed cells' slabs and a portable
kernel body (no DMA/scratch/TPU memory spaces) runs the same one-hot
contraction per (query, cell). ``lut_score_cells`` picks between the
three via the shared backend resolver (``ops/backend.py``) — on the
resolved ``cpu`` strategy every impl serves the compiled ``xla``
formulation, so CPU serving never enters the Pallas interpreter.

Pad rows (beyond a cell's real count) carry scale 0 and bias ``-inf``, so
they score ``-inf`` and can never surface in the shortlist.

The (``chunk_c`` x ``dma_depth`` x impl) space is the LUT kernel's variant
axis in ``ops/autotune.py`` (``LutSchedule``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from code2vec_tpu.analysis.contracts import shape_contract, spec
from code2vec_tpu.ops.backend import resolve as resolve_backend

LUT_IMPLS = ("xla", "pallas")
_LANE = 128


def xla_lut_score_cells(lut, probed, codes, scales, bias):
    """The ``take``-based reference: gather probed cells' codes, index the
    flattened per-query LUT, reduce over subspaces."""
    q, m, entries = lut.shape
    gathered = codes[probed].astype(jnp.int32)  # [Q, P, C, M]
    offsets = gathered + jnp.arange(m, dtype=jnp.int32) * entries
    flat = lut.reshape(q, m * entries)
    vals = jax.vmap(lambda table, idx: table[idx])(flat, offsets)
    sums = jnp.sum(vals, axis=-1)  # [Q, P, C]
    return scales[probed] * sums + bias[probed]


def _make_kernel(m: int, entries: int, cap: int, cc: int, depth: int):
    n_chunks = cap // cc

    def _kernel(
        probed_ref, lut_ref, codes_ref, scales_ref, bias_ref, out_ref,
        code_buf, scale_buf, bias_buf, sems,
    ):
        cell = probed_ref[0, 0]

        def _copies(slot, c):
            """The chunk's three DMAs as (src, dst) pairs, rebuilt
            identically at issue and wait time (the double-buffer
            pattern, ops/fused_encode_pool.py)."""
            base = c * cc
            pairs = (
                (codes_ref.at[cell, pl.ds(base, cc)], code_buf.at[slot]),
                (scales_ref.at[cell, pl.ds(base, cc)], scale_buf.at[slot]),
                (bias_ref.at[cell, pl.ds(base, cc)], bias_buf.at[slot]),
            )

            def run(op):
                for src, dst in pairs:
                    op(pltpu.make_async_copy(src, dst, sems.at[slot]))

            return run

        def issue_chunk(slot, c):
            _copies(slot, c)(lambda d: d.start())

        def wait_chunk(slot, c):
            _copies(slot, c)(lambda d: d.wait())

        def compute_chunk(slot, c):
            codes_c = code_buf[slot].astype(jnp.int32)  # [cc, M]
            col = jax.lax.broadcasted_iota(jnp.int32, (cc, entries), 1)
            acc = jnp.zeros((cc,), jnp.float32)
            # static loop over subspaces; the gather is a one-hot
            # compare-and-reduce (VPU form — no vector gather on TPU)
            for sub in range(m):
                onehot = (codes_c[:, sub][:, None] == col).astype(jnp.float32)
                acc = acc + jnp.sum(
                    onehot * lut_ref[0, sub][None, :], axis=1
                )
            out_ref[0, 0, pl.ds(c * cc, cc)] = (
                acc * scale_buf[slot] + bias_buf[slot]
            )

        zero = jnp.int32(0)
        if depth <= 1:

            def serial_body(c, x):
                issue_chunk(0, c)
                wait_chunk(0, c)
                compute_chunk(0, c)
                return x

            jax.lax.fori_loop(0, n_chunks, serial_body, zero)
        else:
            issue_chunk(0, 0)

            def pipe_body(c, x):
                slot = jax.lax.rem(c, depth)

                @pl.when(c + 1 < n_chunks)
                def _():
                    issue_chunk(jax.lax.rem(c + 1, depth), c + 1)

                wait_chunk(slot, c)
                compute_chunk(slot, c)
                return x

            jax.lax.fori_loop(0, n_chunks, pipe_body, zero)

    return _kernel


def _make_gpu_kernel(m: int, entries: int):
    """The GPU (Triton-lowered) formulation: XLA pre-gathers the probed
    cells' codes/scales/bias, one kernel program per (query, probed cell)
    runs the same one-hot LUT contraction as the TPU kernel's
    ``compute_chunk`` over the whole cell — no DMA, no scratch, no TPU
    memory spaces, so the body lowers through Pallas's Triton backend
    (and runs under the interpreter for off-GPU validation)."""

    def _kernel(lut_ref, codes_ref, scales_ref, bias_ref, out_ref):
        codes_c = codes_ref[0, 0].astype(jnp.int32)  # [C, M]
        cap = codes_c.shape[0]
        col = jax.lax.broadcasted_iota(jnp.int32, (cap, entries), 1)
        acc = jnp.zeros((cap,), jnp.float32)
        for sub in range(m):
            onehot = (codes_c[:, sub][:, None] == col).astype(jnp.float32)
            acc = acc + jnp.sum(onehot * lut_ref[0, sub][None, :], axis=1)
        out_ref[0, 0] = acc * scales_ref[0, 0] + bias_ref[0, 0]

    return _kernel


def gpu_lut_score_cells(
    lut, probed, codes, scales, bias, *, interpret: bool = False
):
    """Score probed cells with the GPU kernel formulation (see
    ``_make_gpu_kernel``). Same output contract as the other impls."""
    q, m, entries = lut.shape
    p = probed.shape[1]
    cap = codes.shape[1]
    g_codes = codes[probed]  # [Q, P, C, M] — XLA-side gather
    g_scales = scales[probed]
    g_bias = bias[probed]
    return pl.pallas_call(
        _make_gpu_kernel(m, entries),
        grid=(q, p),
        in_specs=[
            pl.BlockSpec((1, m, entries), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, 1, cap, m), lambda i, j: (i, j, 0, 0)),
            pl.BlockSpec((1, 1, cap), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, 1, cap), lambda i, j: (i, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, cap), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((q, p, cap), jnp.float32),
        interpret=interpret,
    )(lut, g_codes, g_scales, g_bias)


def pallas_lut_score_cells(
    lut, probed, codes, scales, bias, *, chunk_c: int = _LANE,
    dma_depth: int = 2, interpret: bool | None = None,
):
    if interpret is None:
        # route through the shared resolver (ops/backend.py) — this TPU
        # formulation compiles only on TPU, so any other resolution means
        # the interpreter (callers wanting compiled-off-TPU use
        # lut_score_cells, which picks a non-TPU strategy instead)
        bs = resolve_backend()
        interpret = True if bs.strategy != "pallas_tpu" else bs.interpret
    q, m, entries = lut.shape
    p = probed.shape[1]
    n_list, cap, _ = codes.shape
    cc = int(chunk_c)
    if cc <= 0 or cc > cap or cap % cc:
        cc = _LANE if cap % _LANE == 0 else cap
    depth = max(int(dma_depth), 1)

    grid = (q, p)
    out = pl.pallas_call(
        _make_kernel(m, entries, cap, cc, depth),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1), lambda i, j: (i, j), memory_space=pltpu.VMEM),
            pl.BlockSpec(
                (1, m, entries), lambda i, j: (i, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, cap), lambda i, j: (i, j, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((q, p, cap), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((depth, cc, m), codes.dtype),
            pltpu.VMEM((depth, cc), jnp.float32),
            pltpu.VMEM((depth, cc), jnp.float32),
            pltpu.SemaphoreType.DMA((depth,)),
        ],
        interpret=interpret,
    )(probed, lut, codes, scales, bias)
    return out


LUT_CONTRACT = {
    "lut": spec("Q,M,J", "float"),
    "probed": spec("Q,P", "int"),
    "codes": spec("N,C,M", "int"),
    "scales": spec("N,C", "float"),
    "bias": spec("N,C", "float"),
}


@shape_contract(**LUT_CONTRACT)
def _check_contract(lut, probed, codes, scales, bias):
    return None


def lut_score_cells(
    lut: jnp.ndarray,  # [Q, M, 256] f32 per-query subspace LUT
    probed: jnp.ndarray,  # [Q, P] int32 probed cell ids
    codes: jnp.ndarray,  # [n_list, C, M] uint8 cell-major PQ codes
    scales: jnp.ndarray,  # [n_list, C] f32 per-row scale (0 on pad rows)
    bias: jnp.ndarray,  # [n_list, C] f32 (0 real, -inf pad)
    *,
    impl: str = "xla",
    chunk_c: int = _LANE,
    dma_depth: int = 2,
    interpret: bool | None = None,
    backend: str | None = None,
) -> jnp.ndarray:
    """Score every row of every probed cell; returns f32 ``[Q, P, C]``.

    Not jitted here: the searcher's query fn (and the autotuner's timing
    harness) jit the enclosing computation, and the impl knobs are plain
    Python — compile-time by construction.

    ``backend``/``interpret`` route through the shared resolver
    (``ops/backend.py``). ``impl="pallas"`` under the resolved ``cpu``
    strategy runs the compiled ``xla`` formulation (the reference
    semantics — there is no CPU Pallas lowering, and the serving path
    must never pay the interpreter); under ``pallas_gpu`` it runs the
    Triton-shaped kernel; an explicit ``interpret=True`` keeps its
    legacy meaning and pins the TPU formulation under the interpreter."""
    if impl not in LUT_IMPLS:
        raise ValueError(f"impl must be one of {LUT_IMPLS}, got {impl!r}")
    bs = resolve_backend(backend=backend, interpret=interpret)
    _check_contract(lut, probed, codes, scales, bias)
    if impl == "xla" or bs.strategy == "cpu":
        return xla_lut_score_cells(lut, probed, codes, scales, bias)
    if bs.strategy == "pallas_gpu":
        return gpu_lut_score_cells(
            lut, probed, codes, scales, bias, interpret=bs.interpret
        )
    return pallas_lut_score_cells(
        lut, probed, codes, scales, bias, chunk_c=int(chunk_c),
        dma_depth=int(dma_depth), interpret=bs.interpret,
    )
