"""Product quantization of the coarse residuals.

Each residual row (vector minus its cell centroid) is split into ``M``
subspaces of ``dsub = E / M`` dims; each subspace gets a 256-entry codebook
trained by the same k-means core as the coarse quantizer, and a row stores
one uint8 codebook id per subspace — ``E * 4`` bytes of f32 become ``M``
bytes of codes.

Rows are normalized by their **per-row absmax** before encoding
(``ops/quant.py:row_absmax`` — the same scale primitive the int8 tables
use), and the scale is stored per row: codebooks learn residual *shape* on
a unit-magnitude cloud while the scale carries magnitude, so one 256-entry
codebook is not spent modelling the residual-norm distribution. An
all-zero residual keeps scale 0 and reconstructs to exact zeros, mirroring
the int8 table contract.

Asymmetric scoring (``index.py``): for a unit query ``q``,
``q . x_n  ~=  q . c_cell  +  s_n * sum_m  <q_m, cb[m, code_{n,m}]>`` —
the per-query ``[M, 256]`` table of ``<q_m, cb[m, j]>`` is the LUT the
scoring kernel gathers from.
"""

from __future__ import annotations

import numpy as np

from code2vec_tpu.ann.kmeans import assign_cells, kmeans_fit

__all__ = ["PQ_ENTRIES", "train_codebooks", "encode", "decode"]

PQ_ENTRIES = 256  # one uint8 per subspace


def _row_scales(residuals: np.ndarray) -> np.ndarray:
    """Per-row absmax scale ``[N]`` via the shared ops/quant primitive."""
    import jax

    from code2vec_tpu.ops.quant import row_absmax

    with jax.default_device(jax.devices("cpu")[0]):
        return np.asarray(row_absmax(residuals)).reshape(-1)


def _unit_rows(residuals: np.ndarray, scales: np.ndarray) -> np.ndarray:
    safe = np.where(scales > 0, scales, 1.0).astype(np.float32)
    return (residuals.astype(np.float32) / safe[:, None]).astype(np.float32)


def _split(m: int, dim: int) -> int:
    if m < 1 or dim % m:
        raise ValueError(f"m must divide dim; got m={m}, dim={dim}")
    return dim // m


def train_codebooks(
    residuals: np.ndarray,
    m: int,
    *,
    seed: int = 0,
    iters: int = 15,
    batch_size: int | None = None,
    mesh=None,
) -> tuple[np.ndarray, np.ndarray]:
    """Train per-subspace codebooks on absmax-normalized residuals.

    Returns ``(codebooks f32 [M, 256, dsub], scales f32 [N])``. With fewer
    than 256 samples the trailing codebook entries duplicate entry 0 —
    the assignment argmin resolves ties to the first index, so duplicated
    entries are never emitted as codes."""
    n, dim = residuals.shape
    dsub = _split(m, dim)
    scales = _row_scales(residuals)
    unit = _unit_rows(residuals, scales)
    k_eff = min(PQ_ENTRIES, n)
    codebooks = np.zeros((m, PQ_ENTRIES, dsub), np.float32)
    for sub in range(m):
        block = unit[:, sub * dsub : (sub + 1) * dsub]
        cb = kmeans_fit(
            block, k_eff, seed=seed + sub, iters=iters,
            batch_size=batch_size, mesh=mesh,
        )
        codebooks[sub, :k_eff] = cb
        if k_eff < PQ_ENTRIES:
            codebooks[sub, k_eff:] = cb[0]
    return codebooks, scales


def encode(
    residuals: np.ndarray,
    codebooks: np.ndarray,
    scales: np.ndarray,
    *,
    batch_size: int | None = None,
    mesh=None,
) -> np.ndarray:
    """uint8 codes ``[N, M]``: nearest codebook entry per subspace of each
    absmax-normalized residual row."""
    m, entries, dsub = codebooks.shape
    unit = _unit_rows(residuals, scales)
    codes = np.empty((unit.shape[0], m), np.uint8)
    for sub in range(m):
        block = unit[:, sub * dsub : (sub + 1) * dsub]
        codes[:, sub] = assign_cells(
            block, codebooks[sub], batch_size=batch_size, mesh=mesh
        ).astype(np.uint8)
    return codes


def decode(
    codes: np.ndarray, codebooks: np.ndarray, scales: np.ndarray
) -> np.ndarray:
    """Reconstruct approximate residuals ``[N, E]`` (tests / error
    analysis; the query path never materializes this)."""
    m, _, dsub = codebooks.shape
    parts = [
        codebooks[sub][codes[:, sub].astype(np.int64)] for sub in range(m)
    ]
    unit = np.concatenate(parts, axis=1)
    return unit * scales.astype(np.float32)[:, None]
