"""Approximate-nearest-neighbor retrieval: IVF-PQ trained and served in JAX.

Layout:

- ``kmeans.py``     mini-batch Lloyd's k-means (k-means++ seeding, seeded-
                    deterministic, assignment step mesh-sharded over ``data``)
- ``pq.py``         product quantization of coarse residuals (per-row absmax
                    scale shared with ``ops/quant.py``)
- ``lut_kernel.py`` the fused Pallas LUT-gather-accumulate scoring kernel +
                    its XLA ``take``-based reference (pinned parity)
- ``index.py``      the :class:`IvfPqIndex` pytree, build/save/load through
                    the ``formats/ann_io.py`` container, and the compiled
                    :class:`AnnSearcher` query path
"""

from code2vec_tpu.ann.index import (  # noqa: F401
    AnnSearcher,
    IvfPqIndex,
    build_index,
    load_index,
    save_index,
)
