"""The IVF-PQ index: build, (de)serialize, and the compiled query path.

Build (``build_index``): L2-normalize the exported vectors, k-means the
unit rows into ``n_list`` cells (the coarse quantizer), PQ-encode each
row's residual (``pq.py``), then lay the corpus out **cell-major**: every
cell's rows packed into a fixed ``capacity`` slab (max cell size rounded
to a lane multiple) so the search path is static-shaped — codes
``[n_list, C, M]`` uint8, per-row scales ``[n_list, C]`` f32, original row
ids ``[n_list, C]`` int32 (``-1`` on pad slots). Per query the search
scores cells against the centroids, probes the top ``n_probe``, builds the
``[M, 256]`` LUT once, scores the probed slabs with the fused kernel
(``lut_kernel.py``), and returns a ``shortlist`` of candidate row ids for
exact f32 re-ranking — O(n_probe * C * M + shortlist * E) per query
instead of the exact path's O(N * E).

The index is a registered pytree (arrays as children, geometry as static
aux data), and serializes through the ``formats/ann_io.py`` container
together with the unit rows (the exact-rerank matrix, mmap-loaded) and the
method labels.

Query-path compile discipline (the PR-9 contract): ``AnnSearcher`` holds
one jitted function per power-of-two query-batch bucket — ``n_probe`` and
``shortlist`` are static per searcher, the client's ``k`` only enters the
host-side re-rank — and exposes the ``_cache_size`` probe so the obs
``RecompileDetector`` tracks it like the serving engine's executable
table.
"""

from __future__ import annotations

import dataclasses
import logging

import numpy as np

logger = logging.getLogger(__name__)

__all__ = [
    "IvfPqIndex",
    "build_index",
    "save_index",
    "load_index",
    "AnnSearcher",
    "normalize_rows",
    "pow2_bucket",
]

_LANE = 128


def normalize_rows(rows: np.ndarray) -> np.ndarray:
    """L2-normalize ``[N, E]`` rows (the exact index's rule: cosine
    becomes a plain dot product)."""
    rows = np.ascontiguousarray(rows, np.float32)
    norms = np.linalg.norm(rows, axis=1, keepdims=True)
    return rows / np.maximum(norms, 1e-12)


@dataclasses.dataclass
class IvfPqIndex:
    """The trained index. Arrays are pytree children; ``meta`` (geometry +
    provenance) is static aux data, so the whole index flows through
    jit/device_put unchanged."""

    centroids: np.ndarray  # f32 [n_list, E]
    codebooks: np.ndarray  # f32 [M, 256, dsub]
    codes: np.ndarray  # uint8 [n_list, C, M]
    scales: np.ndarray  # f32 [n_list, C] (0 on pad slots)
    ids: np.ndarray  # int32 [n_list, C] (-1 on pad slots)
    cell_counts: np.ndarray  # int32 [n_list] real rows per cell
    meta: dict

    def tree_flatten(self):
        import json

        children = (
            self.centroids, self.codebooks, self.codes, self.scales,
            self.ids, self.cell_counts,
        )
        # aux data must be hashable; meta (which may nest dicts, e.g. the
        # container's serving defaults) rides as its canonical JSON string
        return children, json.dumps(self.meta, sort_keys=True)

    @classmethod
    def tree_unflatten(cls, aux, children):
        import json

        return cls(*children, meta=json.loads(aux))


def _register_pytree() -> None:
    import jax

    try:
        jax.tree_util.register_pytree_node(
            IvfPqIndex,
            lambda idx: idx.tree_flatten(),
            IvfPqIndex.tree_unflatten,
        )
    except ValueError:  # pragma: no cover - double import guard
        pass


_register_pytree()


def build_index(
    rows: np.ndarray,
    *,
    n_list: int,
    m: int,
    seed: int = 0,
    kmeans_iters: int = 25,
    pq_iters: int = 15,
    batch_size: int | None = None,
    capacity: int | None = None,
    mesh=None,
) -> tuple[IvfPqIndex, np.ndarray]:
    """Train an index over ``rows [N, E]``; returns ``(index, unit_rows)``
    (the L2-normalized matrix the exact re-rank scores against).

    Seeded-deterministic end to end: k-means and PQ training consume one
    ``seed`` lineage and fold on the host (``kmeans.py``), and rows keep
    their original relative order inside each cell (stable sort)."""
    from code2vec_tpu.ann import pq
    from code2vec_tpu.ann.kmeans import assign_cells, kmeans_fit

    unit = normalize_rows(rows)
    n, dim = unit.shape
    n_list = max(min(int(n_list), n), 1)
    if dim % m:
        raise ValueError(f"m={m} must divide dim={dim}")

    centroids = kmeans_fit(
        unit, n_list, seed=seed, iters=kmeans_iters, batch_size=batch_size,
        mesh=mesh,
    )
    assign = assign_cells(unit, centroids, mesh=mesh)
    residuals = unit - centroids[assign]
    codebooks, row_scales = pq.train_codebooks(
        residuals, m, seed=seed + 1, iters=pq_iters, batch_size=batch_size,
        mesh=mesh,
    )
    row_codes = pq.encode(residuals, codebooks, row_scales, mesh=mesh)

    counts = np.bincount(assign, minlength=n_list).astype(np.int32)
    cap = int(capacity) if capacity else int(counts.max())
    cap = max(-(-cap // _LANE) * _LANE, _LANE)
    if counts.max() > cap:
        raise ValueError(
            f"capacity {cap} < largest cell ({int(counts.max())} rows); "
            "raise capacity or n_list"
        )

    codes = np.zeros((n_list, cap, m), np.uint8)
    scales = np.zeros((n_list, cap), np.float32)
    ids = np.full((n_list, cap), -1, np.int32)
    order = np.argsort(assign, kind="stable")
    sorted_cells = assign[order]
    starts = np.searchsorted(sorted_cells, np.arange(n_list))
    for cell in range(n_list):
        lo = int(starts[cell])
        cnt = int(counts[cell])
        sel = order[lo : lo + cnt]
        codes[cell, :cnt] = row_codes[sel]
        scales[cell, :cnt] = row_scales[sel]
        ids[cell, :cnt] = sel.astype(np.int32)

    meta = {
        "version": 1,
        "n": int(n),
        "dim": int(dim),
        "n_list": int(n_list),
        "m": int(m),
        "dsub": int(dim // m),
        "capacity": int(cap),
        "seed": int(seed),
    }
    index = IvfPqIndex(
        centroids=centroids, codebooks=codebooks, codes=codes,
        scales=scales, ids=ids, cell_counts=counts, meta=meta,
    )
    return index, unit


# ---------------------------------------------------------------------------
# container save/load (formats/ann_io.py conventions)
# ---------------------------------------------------------------------------


def save_index(
    path: str,
    index: IvfPqIndex,
    unit_rows: np.ndarray,
    labels: list[str],
    defaults: dict | None = None,
) -> None:
    """Serialize index + re-rank rows + labels into one container.
    ``defaults`` (e.g. ``{"n_probe": 8, "shortlist": 128}``) ride in the
    header meta so a server can start without per-deploy tuning flags."""
    from code2vec_tpu.formats.ann_io import write_ann_container

    n = index.meta["n"]
    if len(labels) != n or unit_rows.shape[0] != n:
        raise ValueError(
            f"labels ({len(labels)}) and rows ({unit_rows.shape[0]}) must "
            f"match the index size ({n})"
        )
    blob = bytearray()
    offsets = np.zeros(n + 1, np.int64)
    for i, label in enumerate(labels):
        blob.extend(label.encode("utf-8"))
        offsets[i + 1] = len(blob)
    arrays = {
        "centroids": index.centroids,
        "codebooks": index.codebooks,
        "codes": index.codes,
        "scales": index.scales,
        "ids": index.ids,
        "cell_counts": index.cell_counts,
        "label_offsets": offsets,
        "label_blob": np.frombuffer(bytes(blob), np.uint8)
        if blob
        else np.zeros(0, np.uint8),
        "rows": np.ascontiguousarray(unit_rows, np.float32),
    }
    meta = dict(index.meta)
    meta["defaults"] = dict(defaults or {})
    write_ann_container(path, arrays, meta)


def load_index(path: str) -> tuple[IvfPqIndex, np.ndarray, list[str]]:
    """Open a container: ``(index, unit_rows, labels)``. The big sections
    (``rows``, ``codes``) stay mmap views until touched; labels decode to
    an in-RAM list (the serving responses need the strings anyway)."""
    from code2vec_tpu.formats.ann_io import read_ann_container

    arrays, meta = read_ann_container(path)
    offsets = arrays["label_offsets"]
    blob = bytes(arrays["label_blob"])
    labels = [
        blob[int(offsets[i]) : int(offsets[i + 1])].decode("utf-8")
        for i in range(len(offsets) - 1)
    ]
    index = IvfPqIndex(
        centroids=arrays["centroids"],
        codebooks=arrays["codebooks"],
        codes=arrays["codes"],
        scales=arrays["scales"],
        ids=arrays["ids"],
        cell_counts=np.asarray(arrays["cell_counts"], np.int32),
        meta={k: v for k, v in meta.items() if k != "defaults"},
    )
    index.meta["defaults"] = dict(meta.get("defaults", {}))
    return index, arrays["rows"], labels


# ---------------------------------------------------------------------------
# the compiled query path
# ---------------------------------------------------------------------------


def pow2_bucket(n: int, cap: int | None = None) -> int:
    """Round up to a power of two, optionally capped — THE executable-
    table keying rule, shared by the ANN searcher and both serving
    retrieval backends (``serve/retrieval.py``): one definition, so the
    bounded-table contract every ``_cache_size`` probe asserts cannot
    drift between backends."""
    bucket = 1
    while bucket < n:
        bucket *= 2
    return min(bucket, cap) if cap is not None else bucket


class AnnSearcher:
    """Device-resident IVF-PQ search with a bounded executable table.

    ``n_probe``/``shortlist`` are static (one searcher per configuration —
    the serving deployment model); query batches bucket to powers of two,
    so the jit cache is bounded by log2(max Q) entries regardless of
    client batching. On a mesh the cell-major arrays shard over ``model``
    per ``parallel/shardings.ann_shardings`` (``n_list`` padded with
    ``-inf`` coarse bias so pad cells are never probed) and the scoring
    runs the XLA formulation — the Pallas kernel carries no partitioning
    rule, so it engages on the single-device/per-shard path only.
    """

    def __init__(
        self,
        index: IvfPqIndex,
        *,
        n_probe: int = 8,
        shortlist: int = 128,
        mesh=None,
        schedule=None,
        cache=None,
        interpret: bool | None = None,
    ) -> None:
        import jax
        import jax.numpy as jnp

        from code2vec_tpu.ops.autotune import lookup_lut_schedule

        meta = index.meta
        self.meta = meta
        self._mesh = mesh
        self.capacity = int(meta["capacity"])
        self.dim = int(meta["dim"])
        self.m = int(meta["m"])
        n_list = int(meta["n_list"])
        counts = np.asarray(index.cell_counts, np.int64)
        non_empty = int((counts > 0).sum())
        self.n_probe = max(min(int(n_probe), non_empty), 1)
        self.shortlist = max(
            min(int(shortlist), self.n_probe * self.capacity), 1
        )
        self.schedule = schedule or lookup_lut_schedule(
            self.m, n_list, self.capacity, self.shortlist, cache=cache
        )
        self._interpret = interpret
        self._counts = counts

        # pad n_list so the model axis shards the cell dim evenly; pad
        # cells (and empty real cells) get -inf coarse bias: never probed
        pad_to = 1
        if mesh is not None:
            from code2vec_tpu.parallel.mesh import AXIS_MODEL

            pad_to = max(int(mesh.shape[AXIS_MODEL]), 1)
        nl_pad = -(-n_list // pad_to) * pad_to
        self.n_list = n_list

        def pad_cells(x):
            if x.shape[0] == nl_pad:
                return x
            pad = np.zeros((nl_pad - x.shape[0],) + x.shape[1:], x.dtype)
            return np.concatenate([x, pad])

        centroids = pad_cells(np.ascontiguousarray(index.centroids, np.float32))
        codes = pad_cells(np.ascontiguousarray(index.codes))
        scales = pad_cells(np.ascontiguousarray(index.scales, np.float32))
        ids = np.concatenate(
            [
                np.ascontiguousarray(index.ids, np.int32),
                np.full(
                    (nl_pad - n_list, self.capacity), -1, np.int32
                ),
            ]
        ) if nl_pad != n_list else np.ascontiguousarray(index.ids, np.int32)
        bias = np.where(ids < 0, -np.inf, 0.0).astype(np.float32)
        cell_bias = np.zeros(nl_pad, np.float32)
        cell_bias[np.concatenate([counts, np.zeros(nl_pad - n_list)]) == 0] = (
            -np.inf
        )

        if mesh is not None:
            from code2vec_tpu.parallel.shardings import ann_shardings

            sh = ann_shardings(mesh)
            put = jax.device_put
            self._centroids = put(centroids, sh["centroids"])
            self._codebooks = put(
                np.ascontiguousarray(index.codebooks, np.float32),
                sh["codebooks"],
            )
            self._codes = put(codes, sh["codes"])
            self._scales = put(scales, sh["scales"])
            self._bias = put(bias, sh["bias"])
            self._ids = put(ids, sh["ids"])
            self._cell_bias = put(cell_bias, sh["cell_bias"])
            self._query_sharding = sh["query"]
        else:
            self._centroids = jnp.asarray(centroids)
            self._codebooks = jnp.asarray(
                np.ascontiguousarray(index.codebooks, np.float32)
            )
            self._codes = jnp.asarray(codes)
            self._scales = jnp.asarray(scales)
            self._bias = jnp.asarray(bias)
            self._ids = jnp.asarray(ids)
            self._cell_bias = jnp.asarray(cell_bias)
            self._query_sharding = None
        self._fns: dict[int, object] = {}  # q bucket -> jitted search fn

    # ---- accounting -----------------------------------------------------
    def _cache_size(self) -> int:
        """Compiled search-fn count (obs RecompileDetector probe)."""
        return len(self._fns)

    def probed_fraction(self, queries: np.ndarray) -> float:
        """Mean fraction of REAL index rows inside the probed cells — the
        honest probed-work accounting ``bench.py --ann-ab`` reports (pad
        slots are scored but cost only the padded slab, not the corpus).
        Applies the same ``-inf`` empty-cell bias as the compiled query
        path, so the counted cell set IS the probed cell set."""
        q = normalize_rows(np.asarray(queries, np.float32).reshape(-1, self.dim))
        sims = q @ np.asarray(self._centroids[: self.n_list]).T
        sims[:, self._counts == 0] = -np.inf  # never probed (cell_bias)
        order = np.argsort(-sims, axis=1)[:, : self.n_probe]
        probed = self._counts[order].sum(axis=1)
        return float(probed.mean() / max(self._counts.sum(), 1))

    def describe(self) -> dict:
        return {
            "n_list": int(self.n_list),
            "n_probe": int(self.n_probe),
            "shortlist": int(self.shortlist),
            "m": int(self.m),
            "capacity": int(self.capacity),
            "schedule": self.schedule.to_dict(),
            "impl_effective": self._impl_effective(),
            "kernel_backend": self._backend_label(),
            "search_executables": self._cache_size(),
        }

    def _impl_effective(self) -> str:
        return "xla" if self._mesh is not None else self.schedule.impl

    def _backend_label(self) -> str:
        """Resolved lowering-strategy label (ops/backend.py) the score
        kernel will actually use — provenance for serving describe()."""
        from code2vec_tpu.ops.backend import resolve as resolve_backend

        sched = self.schedule
        return resolve_backend(
            backend=None if sched.backend == "auto" else sched.backend,
            interpret=self._interpret,
        ).label

    # ---- query ----------------------------------------------------------
    def _fn(self, qb: int):
        fn = self._fns.get(qb)
        if fn is None:
            import jax
            import jax.numpy as jnp

            from code2vec_tpu.ann.lut_kernel import lut_score_cells

            centroids, codebooks = self._centroids, self._codebooks
            codes, scales, bias = self._codes, self._scales, self._bias
            ids, cell_bias = self._ids, self._cell_bias
            n_probe, shortlist = self.n_probe, self.shortlist
            cap, m, dsub = self.capacity, self.m, self.dim // self.m
            impl = self._impl_effective()
            sched = self.schedule
            interpret = self._interpret

            def ann_query(q):  # [qb, E] unit queries
                cell_scores = q @ centroids.T + cell_bias[None, :]
                coarse, probed = jax.lax.top_k(cell_scores, n_probe)
                qm = q.reshape(qb, m, dsub)
                lut = jnp.einsum("qmd,mjd->qmj", qm, codebooks)
                adc = lut_score_cells(
                    lut, probed.astype(jnp.int32), codes, scales, bias,
                    impl=impl, chunk_c=sched.chunk_c,
                    dma_depth=sched.dma_depth, interpret=interpret,
                    backend=(
                        None if sched.backend == "auto" else sched.backend
                    ),
                )
                scores = adc + coarse[:, :, None]  # + q . centroid term
                flat = scores.reshape(qb, n_probe * cap)
                top, flat_idx = jax.lax.top_k(flat, shortlist)
                p_idx = flat_idx // cap
                c_idx = flat_idx - p_idx * cap
                cells = jnp.take_along_axis(probed, p_idx, axis=1)
                return top, ids[cells, c_idx]

            if self._mesh is not None:
                fn = jax.jit(
                    ann_query,
                    in_shardings=self._query_sharding,
                    out_shardings=(
                        self._query_sharding, self._query_sharding,
                    ),
                )
            else:
                fn = jax.jit(ann_query)
            self._fns[qb] = fn
        return fn

    def search(
        self, queries: np.ndarray
    ) -> tuple[np.ndarray, np.ndarray]:
        """ANN shortlist for ``queries [Q, E]`` (normalized internally):
        ``(adc_scores [Q, S] f32, row_ids [Q, S] int32, -1 = pad slot)``.
        Scores are the approximate (ADC) values — callers re-rank the ids
        against the exact rows."""
        q = normalize_rows(
            np.asarray(queries, np.float32).reshape(-1, self.dim)
        )
        n = q.shape[0]
        qb = pow2_bucket(max(n, 1))
        if n < qb:
            q = np.concatenate([q, np.zeros((qb - n, self.dim), np.float32)])
        top, rows = self._fn(qb)(q)
        return np.asarray(top)[:n], np.asarray(rows)[:n]
