"""Mini-batch Lloyd's k-means: the coarse quantizer (and PQ codebook) core.

Split of labor, chosen for determinism:

- the O(batch * k * E) **assignment** step — the only term that grows with
  corpus and cluster count — runs as one jitted matmul+argmin on the
  device(s), optionally sharded over the mesh ``data`` axis (rows are
  embarrassingly parallel; the reduction over E stays within a shard, so
  assignments are bitwise identical on any topology);
- the O(batch * E) **centroid update** folds on the host in float64 in
  fixed row order (the Sculley running-average form: each cluster's
  centroid is the exact mean of every sample ever assigned to it).

Because every floating-point *accumulation* happens on the host in a fixed
order, the same seed produces BITWISE-identical centroids on one device and
on an 8-device mesh — the parity contract tests/test_ann.py pins. Seeding
is standard k-means++ (D² sampling) from one ``np.random.default_rng``.
"""

from __future__ import annotations

import numpy as np

__all__ = ["kmeans_pp_init", "kmeans_fit", "assign_cells"]


def _l2_sq_to(x: np.ndarray, c: np.ndarray) -> np.ndarray:
    """||x_i - c||^2 per row, float64 (host; k-means++ D² weights)."""
    d = x.astype(np.float64) - c.astype(np.float64)[None, :]
    return np.einsum("ne,ne->n", d, d)


def kmeans_pp_init(
    x: np.ndarray, k: int, rng: np.random.Generator
) -> np.ndarray:
    """k-means++ seeding: first center uniform, each next sampled with
    probability proportional to the squared distance to the nearest center
    chosen so far. Incremental min-distance update keeps it O(k * N * E).
    With fewer distinct points than ``k`` the D² mass hits zero and the
    remaining centers draw uniformly (duplicates are acceptable — the
    assignment argmin resolves ties to the first index)."""
    n = x.shape[0]
    if k < 1:
        raise ValueError(f"k must be >= 1, got {k}")
    centers = np.empty((k, x.shape[1]), np.float64)
    first = int(rng.integers(n))
    centers[0] = x[first]
    d2 = _l2_sq_to(x, centers[0])
    for i in range(1, k):
        total = float(d2.sum())
        if total > 0.0:
            idx = int(rng.choice(n, p=d2 / total))
        else:
            idx = int(rng.integers(n))
        centers[i] = x[idx]
        np.minimum(d2, _l2_sq_to(x, centers[i]), out=d2)
    return centers.astype(np.float32)


class _Assigner:
    """One jitted nearest-centroid assignment, compiled per (B, k, E) —
    the host loop pads the final short batch to the fixed B, so a full fit
    costs exactly one compile. On a mesh the batch rows shard over the
    ``data`` axis; centroids replicate (they are tiny at any scale)."""

    def __init__(self, batch: int, mesh=None):
        import jax
        import jax.numpy as jnp

        self.batch = int(batch)
        self._mesh = mesh

        def nearest(xb, cents):  # [B, E], [K, E] -> int32 [B]
            cross = xb @ cents.T
            c2 = jnp.sum(cents * cents, axis=1)
            return jnp.argmin(c2[None, :] - 2.0 * cross, axis=1).astype(
                jnp.int32
            )

        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from code2vec_tpu.parallel.mesh import AXIS_DATA

            data_axis = AXIS_DATA if mesh.shape[AXIS_DATA] > 1 else None
            self._fn = jax.jit(
                nearest,
                in_shardings=(
                    NamedSharding(mesh, P(data_axis, None)),
                    NamedSharding(mesh, P()),
                ),
                out_shardings=NamedSharding(mesh, P(data_axis)),
            )
        else:
            self._fn = jax.jit(nearest)

    def __call__(self, xb: np.ndarray, cents: np.ndarray) -> np.ndarray:
        n = xb.shape[0]
        if n < self.batch:  # pad the tail batch to the compiled shape
            xb = np.concatenate(
                [xb, np.zeros((self.batch - n, xb.shape[1]), xb.dtype)]
            )
        out = np.asarray(self._fn(xb, cents))
        return out[:n]


def _draw_size(n: int, batch_size: int | None) -> int:
    """Rows SAMPLED per mini-batch — a pure function of (n, batch_size),
    never of the mesh, so the rng consumes identically on any topology
    (the bitwise-parity contract)."""
    batch = int(batch_size) if batch_size else min(n, 16384)
    return max(min(batch, n), 1)


def _compiled_batch(draw: int, mesh=None) -> int:
    """The assigner's COMPILED batch shape: the draw size rounded up so
    the data axis shards it evenly. Padding to this shape happens inside
    the assigner (zero rows, sliced off before any fold), so mesh
    divisibility changes the compiled shape only — never the samples."""
    if mesh is not None:
        from code2vec_tpu.parallel.mesh import AXIS_DATA

        axis = max(int(mesh.shape[AXIS_DATA]), 1)
        return -(-draw // axis) * axis
    return draw


def kmeans_fit(
    x: np.ndarray,
    k: int,
    *,
    seed: int = 0,
    iters: int = 25,
    batch_size: int | None = None,
    mesh=None,
) -> np.ndarray:
    """Fit ``k`` centroids over ``x [N, E]``; returns f32 ``[k, E]``.

    Mini-batch Lloyd's: per iteration a seeded sample is assigned on the
    device and folded into the running per-cluster means on the host
    (float64, fixed order — the determinism contract). Clusters that never
    receive a sample keep their k-means++ seed point."""
    x = np.ascontiguousarray(x, np.float32)
    n = x.shape[0]
    rng = np.random.default_rng(seed)
    centers = kmeans_pp_init(x, k, rng).astype(np.float64)
    counts = np.zeros(k, np.int64)
    draw = _draw_size(n, batch_size)
    assigner = _Assigner(_compiled_batch(draw, mesh), mesh=mesh)
    for _ in range(max(int(iters), 0)):
        idx = (
            rng.choice(n, size=draw, replace=False)
            if draw < n
            else np.arange(n)
        )
        xb = x[idx]
        a = assigner(xb, centers.astype(np.float32))
        sums = np.zeros_like(centers)
        np.add.at(sums, a, xb.astype(np.float64))
        bc = np.bincount(a, minlength=k).astype(np.int64)
        touched = bc > 0
        total = counts[touched] + bc[touched]
        centers[touched] = (
            centers[touched] * counts[touched, None] + sums[touched]
        ) / total[:, None]
        counts[touched] = total
    return centers.astype(np.float32)


def assign_cells(
    x: np.ndarray,
    centroids: np.ndarray,
    *,
    batch_size: int | None = None,
    mesh=None,
) -> np.ndarray:
    """Full nearest-centroid assignment pass: int32 ``[N]``. Same jitted
    step as the fit (one compile; tail batch padded)."""
    x = np.ascontiguousarray(x, np.float32)
    n = x.shape[0]
    draw = _draw_size(n, batch_size or 65536)
    assigner = _Assigner(_compiled_batch(draw, mesh), mesh=mesh)
    cents = np.ascontiguousarray(centroids, np.float32)
    out = np.empty(n, np.int32)
    for lo in range(0, n, draw):
        hi = min(lo + draw, n)
        out[lo:hi] = assigner(x[lo:hi], cents)
    return out
