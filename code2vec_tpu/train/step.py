"""Jitted train/eval/forward steps.

Everything under ``jax.jit`` here is traced once per (shape, config): batches
are static ``[B, L]`` (pipeline pads the remainder batch and supplies an
example mask), so one compilation serves the whole run.

Optimizer parity: torch.optim.Adam applies weight decay as coupled L2 added
to the gradient *before* the moment updates (reference: main.py:138), so the
optax chain is add_decayed_weights -> scale_by_adam -> scale(-lr) — not
decoupled AdamW.

Loss parity: log_softmax + class-weighted NLL with mean reduction
``sum(w_i * nll_i) / sum(w_i)`` (reference: main.py:129-130,251-264 and
torch NLLLoss weighted-mean semantics), extended with the example mask for
padded rows.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from flax.training import train_state

from code2vec_tpu.analysis.contracts import shape_contract, spec
from code2vec_tpu.models.code2vec import Code2Vec, Code2VecConfig
from code2vec_tpu.train.config import TrainConfig

# trace-time contract on every jitted step's inputs (analysis/contracts.py):
# validated once per trace — zero steady-state cost — so a weak-typed
# `step` (the PR-4 double-compile bug) or a shape-skewed batch fails AT
# TRACE TIME with an attributable error instead of silently recompiling.
# Symbols bind per trace: bucketed runs validate each ladder width's
# [B, L_b] trace independently.
STEP_STATE_CONTRACT = {
    "step": spec("", jnp.int32),
    # training master weights are f32, full stop: quantized (int8/bf16)
    # tables are a SERVING/EVAL storage mode (ops/quant.py) — an optimizer
    # step over quantized storage would silently train on dequant noise,
    # so the contract rejects it at trace time on every step path
    "params": {
        "terminal_embedding": {"embedding": spec(None, jnp.float32)},
        "path_embedding": {"embedding": spec(None, jnp.float32)},
    },
}
STEP_BATCH_CONTRACT = {
    "starts": spec("B,L", "int"),
    "paths": spec("B,L", "int"),
    "ends": spec("B,L", "int"),
    "labels": spec("B", "int"),
    "example_mask": spec("B", "float"),
}


def contract_step(fn):
    """Apply the shared state/batch contract to a raw ``(state, batch)``
    step function; used by the single-chip, mesh-sharded, and
    device-epoch jit wrappers so the four paths can't drift."""
    return shape_contract(
        state=STEP_STATE_CONTRACT, batch=STEP_BATCH_CONTRACT
    )(fn)


class TrainState(train_state.TrainState):
    """TrainState carrying the dropout RNG so steps are fully functional."""

    dropout_rng: jax.Array

    def apply_gradients(self, *, grads, **kwargs):
        """Sparse-aware: the touched-rows table optimizer hands table
        grads as SparseTableGrad leaves and returns SparseRowUpdate
        leaves, which ``optax.apply_updates`` cannot apply; dense grads
        take flax's path unchanged (train/table_opt.py)."""
        from code2vec_tpu.train.table_opt import (
            apply_updates_sparse,
            has_sparse_grads,
        )

        if not has_sparse_grads(grads):
            return super().apply_gradients(grads=grads, **kwargs)
        updates, new_opt_state = self.tx.update(
            grads, self.opt_state, self.params
        )
        return self.replace(
            step=self.step + 1,
            params=apply_updates_sparse(self.params, updates),
            opt_state=new_opt_state,
            **kwargs,
        )


def torch_style_adam(
    lr: float,
    b1: float,
    b2: float,
    weight_decay: float,
    mu_dtype: str | None = None,
) -> optax.GradientTransformation:
    """Adam with coupled L2 (torch semantics), see module docstring.

    ``mu_dtype="bfloat16"`` stores the FIRST moment in bf16 — an opt-in
    HBM-traffic lever for the memory-bound step (the moment buffers are
    read-modify-written every step; at top11 scale mu is ~280 MB). The
    second moment stays f32: optax updates nu in the params dtype, and
    its magnitude spread makes bf16 storage genuinely lossy. Off by
    default — torch parity (and the train-step differential test) holds
    only for f32 moments.
    """
    steps = []
    if weight_decay:
        steps.append(optax.add_decayed_weights(weight_decay))
    steps.append(
        optax.scale_by_adam(
            b1=b1,
            b2=b2,
            eps=1e-8,
            mu_dtype=None if mu_dtype in (None, "float32") else mu_dtype,
        )
    )
    steps.append(optax.scale(-lr))
    return optax.chain(*steps)


def create_train_state(
    config: TrainConfig,
    model_config: Code2VecConfig,
    rng: jax.Array,
    example_batch: dict[str, Any],
) -> TrainState:
    model = Code2Vec(model_config)
    params_rng, dropout_rng = jax.random.split(rng)
    if config.rng_impl != "threefry2x32":
        # cheaper per-step bit generation for the dropout stream (threefry
        # costs ~1ms/step at [1024, 200, 100] on TPU v5e); params_rng stays
        # threefry so init is impl-independent
        seed = jax.random.randint(dropout_rng, (), 0, jnp.iinfo(jnp.int32).max)
        dropout_rng = jax.random.key(seed, impl=config.rng_impl)
    params = model.init(
        {"params": params_rng},
        example_batch["starts"],
        example_batch["paths"],
        example_batch["ends"],
        labels=example_batch["labels"],
        deterministic=True,
    )["params"]
    table_update = getattr(config, "table_update", "dense")
    if table_update == "lazy":
        from code2vec_tpu.train.table_opt import mixed_table_adam

        make_tx = mixed_table_adam
    elif table_update == "dense":
        make_tx = torch_style_adam
    else:  # fail loudly before the (possibly GB-scale) state is built
        raise ValueError(
            f"table_update must be 'dense' or 'lazy', got {table_update!r}"
        )
    tx = make_tx(
        config.lr,
        config.beta_min,
        config.beta_max,
        config.weight_decay,
        mu_dtype=config.adam_mu_dtype,
    )
    state = TrainState.create(
        apply_fn=model.apply, params=params, tx=tx, dropout_rng=dropout_rng
    )
    # flax initializes `step` as a weak-typed Python int while the step
    # returned by apply_gradients is a strong int32 array — so every jitted
    # step function silently compiled TWICE per batch shape (once for the
    # fresh state, once for every state after it). Normalize at creation:
    # one compile per shape, and the recompile detector's per-shape budget
    # (bucketed runs: one compile per ladder width) is exact.
    return state.replace(step=jnp.asarray(state.step, jnp.int32))


def weighted_nll(
    logits: jnp.ndarray,  # [B, C] f32
    labels: jnp.ndarray,  # [B] int
    class_weights: jnp.ndarray,  # [C] f32
    example_mask: jnp.ndarray,  # [B] f32
) -> jnp.ndarray:
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
    w = class_weights[labels] * example_mask
    return jnp.sum(nll * w) / jnp.maximum(jnp.sum(w), 1e-12)


def build_train_step_fn(
    model_config: Code2VecConfig,
    class_weights: jnp.ndarray,
    table_update: str = "dense",
) -> Callable[[TrainState, dict[str, jnp.ndarray]], tuple[TrainState, jnp.ndarray]]:
    """The raw (unjitted) SGD step; the single-chip and mesh-sharded
    variants jit this same function with different sharding annotations.

    ``table_update="lazy"`` pairs with a state built by
    ``create_train_state`` under ``TrainConfig.table_update="lazy"``: the
    step differentiates w.r.t. zero offsets on the gathered embeddings
    (never forming the dense table gradient) and hands the optimizer
    per-slot grads + ids as SparseTableGrad leaves (train/table_opt.py).
    """

    needs_labels = model_config.angular_margin_loss

    def loss_fn(params, apply_fn, batch, dropout_rng):
        logits, _, _ = apply_fn(
            {"params": params},
            batch["starts"],
            batch["paths"],
            batch["ends"],
            labels=batch["labels"] if needs_labels else None,
            deterministic=False,
            rngs={"dropout": dropout_rng},
        )
        return weighted_nll(
            logits, batch["labels"], class_weights, batch["example_mask"]
        )

    def train_step(state: TrainState, batch):
        dropout_rng, next_rng = jax.random.split(state.dropout_rng)
        loss, grads = jax.value_and_grad(loss_fn)(
            state.params, state.apply_fn, batch, dropout_rng
        )
        state = state.apply_gradients(grads=grads, dropout_rng=next_rng)
        return state, loss

    if table_update == "dense":
        return train_step
    if table_update != "lazy":
        raise ValueError(
            f"table_update must be 'dense' or 'lazy', got {table_update!r}"
        )

    from code2vec_tpu.train.table_opt import TABLE_KEYS, SparseTableGrad

    def lazy_loss_fn(diff, tables, apply_fn, batch, dropout_rng):
        nontable, offsets = diff
        logits, _, _ = apply_fn(
            {"params": {**nontable, **tables}},
            batch["starts"],
            batch["paths"],
            batch["ends"],
            labels=batch["labels"] if needs_labels else None,
            deterministic=False,
            rngs={"dropout": dropout_rng},
            embed_offsets=offsets,
        )
        return weighted_nll(
            logits, batch["labels"], class_weights, batch["example_mask"]
        )

    def lazy_train_step(state: TrainState, batch):
        dropout_rng, next_rng = jax.random.split(state.dropout_rng)
        tables = {k: state.params[k] for k in TABLE_KEYS}
        nontable = {
            k: v for k, v in state.params.items() if k not in TABLE_KEYS
        }
        b, l = batch["starts"].shape
        off_se = jnp.zeros(
            (b, 2 * l, model_config.terminal_embed_size), model_config.dtype
        )
        off_p = jnp.zeros(
            (b, l, model_config.path_embed_size), model_config.dtype
        )
        # diff args only — the tables enter as constants, so autodiff
        # never builds the [vocab, dim] scatter-add backward for them
        loss, (g_nontable, (g_se, g_p)) = jax.value_and_grad(lazy_loss_fn)(
            (nontable, (off_se, off_p)), tables, state.apply_fn, batch,
            dropout_rng,
        )
        term_ids = jnp.concatenate(
            [batch["starts"], batch["ends"]], axis=1
        ).reshape(-1)
        grads = {
            **g_nontable,
            "terminal_embedding": {
                "embedding": SparseTableGrad(
                    ids=term_ids.astype(jnp.int32),
                    slots=g_se.reshape(-1, g_se.shape[-1]).astype(
                        jnp.float32
                    ),
                )
            },
            "path_embedding": {
                "embedding": SparseTableGrad(
                    ids=batch["paths"].reshape(-1).astype(jnp.int32),
                    slots=g_p.reshape(-1, g_p.shape[-1]).astype(jnp.float32),
                )
            },
        }
        state = state.apply_gradients(grads=grads, dropout_rng=next_rng)
        return state, loss

    return lazy_train_step


def build_eval_step_fn(
    model_config: Code2VecConfig,
    class_weights: jnp.ndarray,
    quant_tables: tuple | None = None,
):
    """Raw eval step: batch-mean loss (the reference accumulates per-batch
    means, main.py:283-284), argmax predictions, and the max logit (what the
    reference reports as the prediction 'prob', main.py:411).

    ``quant_tables``: pre-quantized ``(terminal, path)`` QuantTable pair for
    ``table_dtype != "f32"`` configs — quantize ONCE at the call site
    (export/serving) instead of re-deriving the quantized storage from the
    f32 master inside every traced eval call."""

    needs_labels = model_config.angular_margin_loss

    def eval_step(state: TrainState, batch):
        logits, code_vector, attention = state.apply_fn(
            {"params": state.params},
            batch["starts"],
            batch["paths"],
            batch["ends"],
            labels=batch["labels"] if needs_labels else None,
            deterministic=True,
            quant_tables=quant_tables,
        )
        loss = weighted_nll(
            logits, batch["labels"], class_weights, batch["example_mask"]
        )
        preds = jnp.argmax(logits, axis=-1)
        max_logit = jnp.max(logits, axis=-1)
        return {
            "loss": loss,
            "preds": preds,
            "max_logit": max_logit,
            "code_vector": code_vector,
            "attention": attention,
        }

    return eval_step


def make_train_step(
    model_config: Code2VecConfig,
    class_weights: jnp.ndarray,
    table_update: str = "dense",
):
    """Single-device jitted train step (contract-checked at trace time)."""
    return jax.jit(
        contract_step(
            build_train_step_fn(model_config, class_weights, table_update)
        ),
        donate_argnums=(0,),
    )


def make_eval_step(
    model_config: Code2VecConfig,
    class_weights: jnp.ndarray,
    quant_tables: tuple | None = None,
):
    """Single-device jitted eval step (contract-checked at trace time)."""
    return jax.jit(
        contract_step(
            build_eval_step_fn(model_config, class_weights, quant_tables)
        )
    )
