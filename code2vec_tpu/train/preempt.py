"""Graceful-preemption coordination: the SIGTERM contract.

TPU pods signal preemption with SIGTERM and grant a grace window before the
SIGKILL. The default Python disposition tears the process down mid-step —
losing every step since the last epoch-boundary save. This module turns
SIGTERM into a cooperative flag:

- :func:`install_sigterm_handler` (called by ``train()``) registers a
  handler that sets a process-wide :class:`PreemptionGuard`; the previous
  disposition is returned and restored by the loop's ``finally``.
- The train loop polls :meth:`PreemptionGuard.requested` after every step:
  it finishes the in-flight step, forces a cursor-bearing ``last``-slot
  save (checkpoint.py), and returns cleanly — the CLI exits 0.
- The prefetch producer thread (train/prefetch.py) polls the same guard and
  drains cleanly — it stops building batches nobody will consume and ends
  the stream instead of racing the consumer's shutdown.

The guard is also the lever the fault-injection harness pulls: a
``sigterm`` action (faultinject.py) delivers a real SIGTERM to the process,
so tests exercise the identical code path production preemption takes.

Signal handlers are a main-thread-only facility; when ``train()`` runs on
another thread (HPO workers), installation degrades to a no-op and SIGTERM
keeps its prior disposition — preemption safety then rests on periodic
saves alone.
"""

from __future__ import annotations

import logging
import signal
import threading

logger = logging.getLogger(__name__)

__all__ = [
    "PreemptionGuard",
    "PreemptionStop",
    "coordinated_stop",
    "install_sigterm_handler",
    "preemption_guard",
    "restore_sigterm_handler",
]


class PreemptionStop(Exception):
    """Raised inside the train loop once the preemption save is on disk:
    unwinds the epoch cleanly (prefetch producer joined, sinks closed) and
    train() returns normally — the graceful half of the SIGTERM contract."""


class PreemptionGuard:
    """A sticky, thread-safe "preemption requested" flag."""

    def __init__(self) -> None:
        self._event = threading.Event()
        self.reason: str | None = None

    def request(self, reason: str = "requested") -> None:
        """Mark preemption requested (signal handler, or tests)."""
        if not self._event.is_set():
            self.reason = reason
            self._event.set()

    def requested(self) -> bool:
        return self._event.is_set()

    def clear(self) -> None:
        """Reset for a fresh run (train() entry)."""
        self.reason = None
        self._event.clear()


_GUARD = PreemptionGuard()


def preemption_guard() -> PreemptionGuard:
    """The process-wide guard shared by the loop, the prefetch producer,
    and the signal handler."""
    return _GUARD


def coordinated_stop(guard: PreemptionGuard) -> bool:
    """Whether to act on the guard — process-collectively.

    Single-process: the local flag. Multi-process: the flag flips at
    *signal-delivery* time, which differs per process by whole steps, but
    the save it triggers is a collective orbax write — uncoordinated
    participants deadlock in the commit barrier. So processes agree on
    process 0's view via one tiny ``broadcast_one_to_all`` (a pod preempts
    every process, so process 0's flag is the group's). Call ONLY at
    deterministic points every process reaches at the same step (a
    periodic-save step, stream end, an epoch boundary) — the broadcast is
    itself a collective.
    """
    import jax

    if jax.process_count() == 1:
        return guard.requested()
    from jax.experimental import multihost_utils
    import numpy as np

    return bool(
        multihost_utils.broadcast_one_to_all(
            np.asarray(1 if guard.requested() else 0, np.int32)
        )
    )


def install_sigterm_handler():
    """Route SIGTERM into the guard; returns the previous handler (pass it
    to :func:`restore_sigterm_handler`), or None when installation is not
    possible (non-main thread)."""

    def _on_sigterm(signum, frame):  # noqa: ARG001 - signal signature
        logger.warning(
            "SIGTERM received: finishing the in-flight step, then saving "
            "and exiting cleanly"
        )
        _GUARD.request("SIGTERM")

    try:
        return signal.signal(signal.SIGTERM, _on_sigterm)
    except ValueError:  # signals are main-thread-only
        logger.debug(
            "not installing SIGTERM handler (train() is off the main "
            "thread); preemption safety rests on periodic saves"
        )
        return None


def restore_sigterm_handler(previous) -> None:
    """Undo :func:`install_sigterm_handler` (no-op for a None previous)."""
    if previous is None:
        return
    try:
        signal.signal(signal.SIGTERM, previous)
    except ValueError:
        pass
