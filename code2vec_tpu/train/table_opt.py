"""Touched-rows ("lazy") Adam for the embedding tables.

The dense step pays two full-table costs every step regardless of batch
content: materializing a ``[vocab, dim]`` gradient for each table (the
autodiff scatter-add over the reference's ``nn.Embedding`` twins,
model/model.py:21-22), and Adam's read-modify-write over every row of
param/mu/nu (the reference's torch.optim.Adam over the same tables,
main.py:138). At top11 scale that is ~2-3 GB/step of HBM traffic on a
bandwidth-bound step (docs/ARCHITECTURE.md roofline); at java-large scale
(multi-million-row vocabs) it is the difference between feasible and not —
a batch touches at most ``B x L`` slots no matter how big the vocab grows.

This module updates only the TOUCHED rows, with the exact semantics of
``torch.optim.SparseAdam`` (the torch-side answer to the same problem):

- duplicate ids in the batch are coalesced (summed) first, like torch's
  ``grad.coalesce()``;
- touched rows get the full Adam treatment (moment decay + bias-corrected
  update with the GLOBAL step count, ``step_size = lr * sqrt(1-b2^t) /
  (1-b1^t)``, ``denom = sqrt(nu) + eps`` — torch's eps placement);
- untouched rows are left entirely alone (params AND moments) — that is
  the one deliberate semantic difference from dense Adam, which keeps
  decaying/applying stale moments to rows with zero gradient.

TPU-first formulation, all static shapes under ``jit``:

  sort the ``[N]`` ids -> run-boundary segment ids -> ``segment_sum`` the
  per-slot grads into an ``[N, dim]`` unique-capacity buffer (sorted
  indices, so XLA lowers a collision-free accumulation instead of a
  duplicate-index scatter) -> gather param/mu/nu rows at the unique ids ->
  Adam on rows -> scatter rows back (distinct indices by construction;
  capacity padding carries an out-of-range sentinel id and is dropped by
  ``mode="drop"``).

The per-slot gradients come from the zero-offset hook in the model
(``Code2Vec.__call__(embed_offsets=...)``): the step differentiates w.r.t.
zero tensors added to the gathered embeddings instead of w.r.t. the tables
themselves, so the dense ``[vocab, dim]`` gradient is never formed.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax
from flax import struct

# top-level param-tree keys of the two big tables (models/code2vec.py)
TABLE_KEYS = ("terminal_embedding", "path_embedding")


@struct.dataclass
class SparseTableGrad:
    """Per-slot gradient of one embedding table: ``ids[i]`` is the row the
    ``i``-th gathered slot read, ``slots[i]`` is d(loss)/d(that gather).
    Stands in for the dense ``[vocab, dim]`` gradient leaf in the grads
    pytree handed to ``TrainState.apply_gradients``."""

    ids: jax.Array  # int32 [N]
    slots: jax.Array  # f32 [N, dim]


@struct.dataclass
class SparseRowUpdate:
    """Row-sparse param update: add ``rows[i]`` to ``param[uids[i]]``.
    ``uids`` holds DISTINCT real row ids at the front and an out-of-range
    sentinel (``vocab``) in the capacity padding, so a ``mode="drop"``
    scatter applies exactly the touched rows."""

    uids: jax.Array  # int32 [N]
    rows: jax.Array  # f32 [N, dim]


class LazyAdamState(NamedTuple):
    count: jax.Array  # int32 scalar, shared by all tables (global step t)
    mu: Any  # pytree mirroring the table subtree, [vocab, dim] in mu_dtype
    nu: Any  # pytree mirroring the table subtree, [vocab, dim] f32


class MixedTableOptState(NamedTuple):
    dense: Any  # torch_style_adam chain state over the non-table params
    lazy: LazyAdamState


def _is_sparse_grad(x) -> bool:
    return isinstance(x, SparseTableGrad)


def has_sparse_grads(grads) -> bool:
    return any(
        _is_sparse_grad(leaf)
        for leaf in jax.tree_util.tree_leaves(grads, is_leaf=_is_sparse_grad)
    )


def _dedupe_sorted(ids: jax.Array, slots: jax.Array, vocab: int):
    """Coalesce duplicate ids: returns (uids, gsum) of capacity N where the
    first K rows are the distinct touched ids with their summed grads and
    the rest carry the ``vocab`` sentinel / zero rows."""
    n = ids.shape[0]
    order = jnp.argsort(ids)
    sid = ids[order]
    sg = slots[order]
    is_start = jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), sid[1:] != sid[:-1]]
    )
    seg = jnp.cumsum(is_start.astype(jnp.int32)) - 1  # [N], sorted
    gsum = jax.ops.segment_sum(
        sg, seg, num_segments=n, indices_are_sorted=True
    )
    # place each segment's id at its segment index (duplicate writes within
    # a segment store the same value); capacity padding keeps the sentinel
    uids = jnp.full((n,), vocab, ids.dtype).at[seg].set(sid)
    return uids, gsum


def _lazy_rows(
    g: SparseTableGrad,
    mu: jax.Array,
    nu: jax.Array,
    count: jax.Array,
    *,
    lr: float,
    b1: float,
    b2: float,
    eps: float,
):
    vocab = mu.shape[0]
    uids, gsum = _dedupe_sorted(g.ids, g.slots.astype(jnp.float32), vocab)
    safe = jnp.minimum(uids, vocab - 1)
    mu_new = b1 * mu[safe].astype(jnp.float32) + (1.0 - b1) * gsum
    nu_new = b2 * nu[safe] + (1.0 - b2) * (gsum * gsum)
    t = count.astype(jnp.float32)
    step_size = lr * jnp.sqrt(1.0 - b2**t) / (1.0 - b1**t)
    rows = -step_size * mu_new / (jnp.sqrt(nu_new) + eps)
    new_mu = mu.at[uids].set(mu_new.astype(mu.dtype), mode="drop")
    new_nu = nu.at[uids].set(nu_new, mode="drop")
    return SparseRowUpdate(uids=uids, rows=rows), new_mu, new_nu


def _split(tree):
    tables = {k: tree[k] for k in TABLE_KEYS if k in tree}
    rest = {k: v for k, v in tree.items() if k not in TABLE_KEYS}
    return rest, tables


def mixed_table_adam(
    lr: float,
    b1: float,
    b2: float,
    weight_decay: float,
    mu_dtype: str | None = None,
    eps: float = 1e-8,
) -> optax.GradientTransformation:
    """torch-style Adam on the non-table params + touched-rows SparseAdam
    on the two embedding tables. Weight decay (coupled L2, reference
    main.py:60 default 0.0) applies to the non-table params only —
    torch.optim.SparseAdam has no decay either; a nonzero setting is
    honored dense-side and skipped table-side."""
    from code2vec_tpu.train.step import torch_style_adam

    dense_tx = torch_style_adam(lr, b1, b2, weight_decay, mu_dtype=mu_dtype)
    store_dtype = (
        jnp.float32 if mu_dtype in (None, "float32") else jnp.dtype(mu_dtype)
    )

    def init(params):
        rest, tables = _split(params)
        return MixedTableOptState(
            dense=dense_tx.init(rest),
            lazy=LazyAdamState(
                count=jnp.zeros((), jnp.int32),
                mu=jax.tree.map(
                    lambda p: jnp.zeros(p.shape, store_dtype), tables
                ),
                nu=jax.tree.map(lambda p: jnp.zeros_like(p), tables),
            ),
        )

    def update(grads, state, params=None):
        g_rest, g_tables = _split(grads)
        p_rest, _ = _split(params) if params is not None else (None, None)
        u_rest, dense_state = dense_tx.update(g_rest, state.dense, p_rest)
        count = state.lazy.count + 1
        updates_t, mu_t, nu_t = {}, {}, {}
        # each table subtree is {"embedding": leaf} (models/code2vec.py's
        # _EmbedTable layout) — walk it directly
        for key, g_sub in g_tables.items():
            u_sub, mu_sub, nu_sub = {}, {}, {}
            for name, g in g_sub.items():
                u_sub[name], mu_sub[name], nu_sub[name] = _lazy_rows(
                    g,
                    state.lazy.mu[key][name],
                    state.lazy.nu[key][name],
                    count,
                    lr=lr, b1=b1, b2=b2, eps=eps,
                )
            updates_t[key], mu_t[key], nu_t[key] = u_sub, mu_sub, nu_sub
        new_state = MixedTableOptState(
            dense=dense_state,
            lazy=LazyAdamState(count=count, mu=mu_t, nu=nu_t),
        )
        return {**u_rest, **updates_t}, new_state

    return optax.GradientTransformation(init, update)


def apply_updates_sparse(params, updates):
    """``optax.apply_updates`` extended with :class:`SparseRowUpdate`
    leaves: distinct-row scatter-add with the sentinel capacity rows
    dropped. Dense leaves follow optax semantics (cast to the param
    dtype)."""

    def leaf(u, p):
        if isinstance(u, SparseRowUpdate):
            return p.at[u.uids].add(u.rows.astype(p.dtype), mode="drop")
        return optax.apply_updates(p, u)

    return jax.tree.map(
        leaf, updates, params,
        is_leaf=lambda x: isinstance(x, SparseRowUpdate),
    )
